"""What-if counterfactuals: replay a journal against a modified world.

The Tesserae/BandPilot evaluation loop (PAPERS.md) made native: every
recorded solve wave is re-solved against an EDITED fleet (e.g. +1 rack) or
an overridden solver configuration (different portfolio width, different
score weights), and both the recorded and the counterfactual plans are
scored with the placement-quality report (`quality/report.py`) — admitted
ratio, mean placement score, preferred-domain fraction — plus the measured
wave solve latency. The aggregate deltas answer "what would this capacity /
policy change have bought us over this recorded window?".

Scope: each wave replays against its own RECORDED pre-solve allocated state
(per-decision counterfactual, the trace-replay evaluation idiom). Admissions
the counterfactual adds do not cascade into later waves' allocated state —
that would require re-simulating the whole control loop, which the sim
harness does; this tool scores the recorded decision points.

Config-override what-ifs (no fleet edit) route through the batched sweep
engine (grove_tpu/tuning/sweep.py): the N override variants AND the
incumbent config stack onto the solver's variant axis, so N counterfactuals
cost ~one replay instead of N — and the incumbent row, being diffed against
the journal, yields the replay-divergence count for free
(`replayDivergences` in the summary; `trace replay` exits 1 on divergence,
and a what-if over a diverging journal is measuring noise). Fleet-edit
what-ifs keep the per-wave re-solve (the edited snapshot cannot share the
recorded encode) and report `replayDivergences: null` — not measured.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from grove_tpu.quality.report import evaluate_placement
from grove_tpu.solver.core import SolverParams
from grove_tpu.solver.encode import next_pow2
from grove_tpu.state.cluster import Node
from grove_tpu.trace.replay import (
    nodes_from_fleet,
    snapshot_from_wave,
    solve_wave_record,
    topology_from_fleet,
)
from grove_tpu.utils import serde


def clone_racks(
    nodes: list[Node], topology, count: int = 1, *, tag: str = "whatif"
) -> list[Node]:
    """`nodes` + `count` cloned racks. The template is the rack of the LAST
    node (narrowest non-host level of `topology`); clones keep its
    capacity/labels/taints shape with a fresh rack label value and fresh
    hostnames, so the counterfactual asks "one more rack of the same SKU",
    not an arbitrary fleet. Works on any live Node list — the journal
    what-if path and the rollout surge pricer share this one definition of
    "+N racks"."""
    if count <= 0:
        return list(nodes)
    non_host = [
        lvl for lvl in topology.sorted_levels() if lvl.domain.value != "host"
    ]
    if not non_host or not nodes:
        raise ValueError("fleet has no non-host topology level to clone a rack in")
    rack_key = non_host[-1].node_label_key
    template_rack = nodes[-1].labels.get(rack_key)
    template = [n for n in nodes if n.labels.get(rack_key) == template_rack]
    if not template:
        template = [nodes[-1]]
    out = list(nodes)
    for i in range(count):
        for j, src in enumerate(template):
            labels = dict(src.labels)
            labels[rack_key] = f"{tag}-r{i}"
            out.append(
                Node(
                    name=f"{tag}{i}h{j}",
                    capacity=dict(src.capacity),
                    labels=labels,
                    schedulable=True,
                    taints=[dict(t) for t in src.taints],
                )
            )
    return out


def add_racks(fleet: dict, count: int = 1) -> list[Node]:
    """Recorded fleet + `count` cloned racks (see clone_racks)."""
    return clone_racks(nodes_from_fleet(fleet), topology_from_fleet(fleet), count)


@dataclass
class WhatIfWave:
    index: int
    recorded: dict  # quality-report doc of the recorded plan
    counterfactual: dict  # quality-report doc of the counterfactual plan
    recorded_solve_s: float
    counterfactual_solve_s: float

    def to_doc(self) -> dict:
        return {
            "index": self.index,
            "recorded": self.recorded,
            "counterfactual": self.counterfactual,
            "recordedSolveSeconds": round(self.recorded_solve_s, 4),
            "counterfactualSolveSeconds": round(self.counterfactual_solve_s, 4),
        }


@dataclass
class WhatIfReport:
    """Aggregate recorded-vs-counterfactual quality over the journal."""

    waves: list = field(default_factory=list)  # WhatIfWave
    edits: dict = field(default_factory=dict)  # what was changed

    def _agg(self, which: str) -> dict:
        gangs = sum(getattr(w, which)["gangs"] for w in self.waves)
        admitted = sum(getattr(w, which)["admitted"] for w in self.waves)
        scored = [
            getattr(w, which)["meanPlacementScore"]
            for w in self.waves
            if getattr(w, which)["admitted"]
        ]
        return {
            "gangs": gangs,
            "admitted": admitted,
            "admittedRatio": round(admitted / gangs, 4) if gangs else 0.0,
            "meanPlacementScore": round(float(np.mean(scored)), 4) if scored else 0.0,
        }

    def to_doc(self) -> dict:
        rec = self._agg("recorded")
        cf = self._agg("counterfactual")
        return {
            "edits": self.edits,
            "waves": len(self.waves),
            "recorded": rec,
            "counterfactual": cf,
            "delta": {
                "admitted": cf["admitted"] - rec["admitted"],
                "admittedRatio": round(
                    cf["admittedRatio"] - rec["admittedRatio"], 4
                ),
                "meanPlacementScore": round(
                    cf["meanPlacementScore"] - rec["meanPlacementScore"], 4
                ),
            },
            # Fleet-edit path: divergence is NOT measurable without an extra
            # replay (the counterfactual legitimately differs). The
            # config-override path (WhatIfConfigsReport) measures it free.
            "replayDivergences": None,
            "recordedSolveSeconds": round(
                sum(w.recorded_solve_s for w in self.waves), 4
            ),
            "counterfactualSolveSeconds": round(
                sum(w.counterfactual_solve_s for w in self.waves), 4
            ),
        }


@dataclass
class WhatIfConfigsReport:
    """Config-override what-if via the batched sweep: every variant scored
    from ONE replay pass, deltas against the incumbent (recorded-config)
    row, plus the incumbent row's journal divergence count."""

    waves: int
    incumbent: dict  # incumbent row's tally doc (tuning ConfigTally.to_doc)
    variants: list  # per-variant tally docs, sweep rank order
    replay_divergences: int
    solve_s: float

    def to_doc(self) -> dict:
        rec = self.incumbent

        def delta(v):
            return {
                "admitted": v["admitted"] - rec["admitted"],
                "admittedRatio": round(
                    v["admittedRatio"] - rec["admittedRatio"], 4
                ),
                "meanPlacementScore": round(
                    v["meanPlacementScore"] - rec["meanPlacementScore"], 4
                ),
            }

        return {
            "edits": {"variants": [v["config"] for v in self.variants]},
            "waves": self.waves,
            "recorded": {
                k: rec[k]
                for k in (
                    "gangs", "admitted", "admittedRatio", "meanPlacementScore",
                )
            },
            "variants": [dict(v, delta=delta(v)) for v in self.variants],
            "replayDivergences": self.replay_divergences,
            "solveSeconds": round(self.solve_s, 4),
        }


_WEIGHT_KEYS = {
    "wTight": "w_tight",
    "wPref": "w_pref",
    "wReuse": "w_reuse",
    "wReserve": "w_reserve",
    "wSpread": "w_spread",
}


def _variant_config(incumbent, spec: dict, index: int):
    """One override spec ({"weights": {...}, "portfolio": N,
    "escalatePortfolio": N, "name": s}) -> SweepConfig based on the
    incumbent; unknown keys are errors (the config-validation stance)."""
    from grove_tpu.solver.core import SolverParams
    from grove_tpu.tuning.sweep import SweepConfig

    allowed = {"weights", "portfolio", "escalatePortfolio", "name"}
    unknown = set(spec) - allowed
    if unknown:
        raise ValueError(f"variant {index}: unknown keys {sorted(unknown)}")
    weights = {
        f: float(w) for f, w in zip(SolverParams._fields, incumbent.weights)
    }
    for key, val in (spec.get("weights") or {}).items():
        snake = _WEIGHT_KEYS.get(key, key)
        if snake not in weights:
            raise ValueError(f"variant {index}: unknown weight {key!r}")
        weights[snake] = float(val)
    return SweepConfig(
        name=str(spec.get("name") or f"variant-{index}"),
        weights=tuple(weights[f] for f in SolverParams._fields),
        portfolio=int(spec.get("portfolio") or incumbent.portfolio),
        escalate_portfolio=int(
            spec.get("escalatePortfolio") or incumbent.escalate_portfolio
        ),
    )


def whatif_configs(
    records: list, variants: list, *, warm_path=None
) -> WhatIfConfigsReport:
    """Score N config-override variants against the recorded trace in ONE
    sweep pass (incumbent + variants stacked on the solver's variant axis).
    The incumbent row doubles as the replay-divergence probe."""
    from grove_tpu.tuning.sweep import incumbent_config, sweep_journal

    if not variants:
        raise ValueError("whatif_configs needs at least one variant")
    incumbent = incumbent_config(records)
    configs = [incumbent] + [
        _variant_config(incumbent, spec, i) for i, spec in enumerate(variants)
    ]
    names = [c.name for c in configs]
    if len(set(names)) != len(names):
        raise ValueError(f"duplicate variant names: {names}")
    engine = sweep_journal(records, configs, warm_path=warm_path)
    inc_tally = engine.tallies["incumbent"]
    variant_docs = [
        engine.tallies[c.name].to_doc() for c in configs[1:]
    ]
    return WhatIfConfigsReport(
        waves=engine.waves_seen,
        incumbent=inc_tally.to_doc(),
        variants=variant_docs,
        replay_divergences=inc_tally.divergences,
        solve_s=sum(t.solve_s for t in engine.tallies.values()),
    )


def whatif_journal(
    records: list[dict],
    *,
    add_rack_count: int = 0,
    params: SolverParams | None = None,
    portfolio: int | None = None,
    escalate_portfolio: int | None = None,
    variants: list | None = None,
    warm_path=None,
):
    """Score every recorded wave against the counterfactual world. At least
    one edit (fleet or solver config) should be given — with none this
    degenerates to a scored replay.

    Config-only edits (no fleet change) return a WhatIfConfigsReport from
    ONE batched sweep pass — `variants` carries N override specs at ~1x
    replay cost, and the single params/portfolio/escalate overrides are
    folded into one variant the same way. Fleet edits (add_rack_count > 0)
    keep the per-wave re-solve path and may combine with a config override
    (the counterfactual then changes both)."""
    from grove_tpu.solver.warm import WarmPath

    if variants is not None and add_rack_count:
        raise ValueError(
            "config-override variants cannot combine with fleet edits — "
            "the sweep shares the RECORDED encode across variants"
        )
    if add_rack_count == 0:
        specs = list(variants or [])
        if not specs and (
            params is not None
            or portfolio is not None
            or escalate_portfolio is not None
        ):
            spec: dict = {}
            if params is not None:
                spec["weights"] = {
                    f: float(w)
                    for f, w in zip(SolverParams._fields, params)
                }
            if portfolio is not None:
                spec["portfolio"] = int(portfolio)
            if escalate_portfolio is not None:
                spec["escalatePortfolio"] = int(escalate_portfolio)
            specs = [spec]
        if specs:
            return whatif_configs(records, specs, warm_path=warm_path)

    warm = warm_path if warm_path is not None else WarmPath()
    fleets: dict[str, dict] = {}
    cf_nodes_cache: dict[str, list[Node]] = {}
    report = WhatIfReport(
        edits={
            "addRacks": add_rack_count,
            "portfolio": portfolio,
            "escalatePortfolio": escalate_portfolio,
            "weights": None if params is None else [float(w) for w in params],
        }
    )
    index = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "fleet":
            fleets[rec["digest"]] = rec
            continue
        if kind != "wave":
            continue
        fleet = fleets.get(rec["fleet"])
        if fleet is None:
            raise ValueError(
                f"wave {index} references fleet {rec['fleet']!r} missing from "
                "this journal — cannot evaluate"
            )
        gangs = [serde.decode(d) for d in rec["gangs"]]
        pods = {n: serde.decode(d) for n, d in rec["pods"].items()}

        # Recorded side: the plan as journaled, scored on the recorded fleet.
        rec_snap = snapshot_from_wave(rec, fleet)
        rec_report = evaluate_placement(gangs, pods, rec_snap, rec["plan"])

        # Counterfactual side: edited fleet (node pad grows with the fleet)
        # and/or overridden solver config, re-solved then scored.
        if rec["fleet"] not in cf_nodes_cache:
            cf_nodes_cache[rec["fleet"]] = add_racks(fleet, add_rack_count)
        cf_nodes = cf_nodes_cache[rec["fleet"]]
        cf_wave = dict(rec)
        cf_wave["padNodesTo"] = max(rec["padNodesTo"], next_pow2(len(cf_nodes)))
        cf_snap = snapshot_from_wave(cf_wave, fleet, nodes=cf_nodes)
        cf_plan, _cf_ok, _cf_scores, cf_s = solve_wave_record(
            cf_wave,
            cf_snap,
            warm=warm,
            params=params,
            portfolio=portfolio,
            escalate_portfolio=escalate_portfolio,
        )
        cf_report = evaluate_placement(gangs, pods, cf_snap, cf_plan)
        report.waves.append(
            WhatIfWave(
                index=index,
                recorded=rec_report.to_doc(),
                counterfactual=cf_report.to_doc(),
                recorded_solve_s=float(rec.get("solveSeconds", 0.0)),
                counterfactual_solve_s=cf_s,
            )
        )
        index += 1
    return report
