"""What-if counterfactuals: replay a journal against a modified world.

The Tesserae/BandPilot evaluation loop (PAPERS.md) made native: every
recorded solve wave is re-solved against an EDITED fleet (e.g. +1 rack) or
an overridden solver configuration (different portfolio width, different
score weights), and both the recorded and the counterfactual plans are
scored with the placement-quality report (`quality/report.py`) — admitted
ratio, mean placement score, preferred-domain fraction — plus the measured
wave solve latency. The aggregate deltas answer "what would this capacity /
policy change have bought us over this recorded window?".

Scope: each wave replays against its own RECORDED pre-solve allocated state
(per-decision counterfactual, the trace-replay evaluation idiom). Admissions
the counterfactual adds do not cascade into later waves' allocated state —
that would require re-simulating the whole control loop, which the sim
harness does; this tool scores the recorded decision points.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from grove_tpu.quality.report import evaluate_placement
from grove_tpu.solver.core import SolverParams
from grove_tpu.solver.encode import next_pow2
from grove_tpu.state.cluster import Node
from grove_tpu.trace.replay import (
    nodes_from_fleet,
    snapshot_from_wave,
    solve_wave_record,
    topology_from_fleet,
)
from grove_tpu.utils import serde


def add_racks(fleet: dict, count: int = 1) -> list[Node]:
    """Recorded fleet + `count` cloned racks. The template is the rack of
    the LAST recorded node (narrowest non-host level of the recorded
    topology); clones keep its capacity/labels/taints shape with a fresh
    rack label value and fresh hostnames, so the counterfactual asks "one
    more rack of the same SKU", not an arbitrary fleet."""
    nodes = nodes_from_fleet(fleet)
    if count <= 0:
        return nodes
    topo = topology_from_fleet(fleet)
    non_host = [
        lvl for lvl in topo.sorted_levels() if lvl.domain.value != "host"
    ]
    if not non_host or not nodes:
        raise ValueError("fleet has no non-host topology level to clone a rack in")
    rack_key = non_host[-1].node_label_key
    template_rack = nodes[-1].labels.get(rack_key)
    template = [n for n in nodes if n.labels.get(rack_key) == template_rack]
    if not template:
        template = [nodes[-1]]
    out = list(nodes)
    for i in range(count):
        for j, src in enumerate(template):
            labels = dict(src.labels)
            labels[rack_key] = f"whatif-r{i}"
            out.append(
                Node(
                    name=f"whatif{i}h{j}",
                    capacity=dict(src.capacity),
                    labels=labels,
                    schedulable=True,
                    taints=[dict(t) for t in src.taints],
                )
            )
    return out


@dataclass
class WhatIfWave:
    index: int
    recorded: dict  # quality-report doc of the recorded plan
    counterfactual: dict  # quality-report doc of the counterfactual plan
    recorded_solve_s: float
    counterfactual_solve_s: float

    def to_doc(self) -> dict:
        return {
            "index": self.index,
            "recorded": self.recorded,
            "counterfactual": self.counterfactual,
            "recordedSolveSeconds": round(self.recorded_solve_s, 4),
            "counterfactualSolveSeconds": round(self.counterfactual_solve_s, 4),
        }


@dataclass
class WhatIfReport:
    """Aggregate recorded-vs-counterfactual quality over the journal."""

    waves: list = field(default_factory=list)  # WhatIfWave
    edits: dict = field(default_factory=dict)  # what was changed

    def _agg(self, which: str) -> dict:
        gangs = sum(getattr(w, which)["gangs"] for w in self.waves)
        admitted = sum(getattr(w, which)["admitted"] for w in self.waves)
        scored = [
            getattr(w, which)["meanPlacementScore"]
            for w in self.waves
            if getattr(w, which)["admitted"]
        ]
        return {
            "gangs": gangs,
            "admitted": admitted,
            "admittedRatio": round(admitted / gangs, 4) if gangs else 0.0,
            "meanPlacementScore": round(float(np.mean(scored)), 4) if scored else 0.0,
        }

    def to_doc(self) -> dict:
        rec = self._agg("recorded")
        cf = self._agg("counterfactual")
        return {
            "edits": self.edits,
            "waves": len(self.waves),
            "recorded": rec,
            "counterfactual": cf,
            "delta": {
                "admitted": cf["admitted"] - rec["admitted"],
                "admittedRatio": round(
                    cf["admittedRatio"] - rec["admittedRatio"], 4
                ),
                "meanPlacementScore": round(
                    cf["meanPlacementScore"] - rec["meanPlacementScore"], 4
                ),
            },
            "recordedSolveSeconds": round(
                sum(w.recorded_solve_s for w in self.waves), 4
            ),
            "counterfactualSolveSeconds": round(
                sum(w.counterfactual_solve_s for w in self.waves), 4
            ),
        }


def whatif_journal(
    records: list[dict],
    *,
    add_rack_count: int = 0,
    params: SolverParams | None = None,
    portfolio: int | None = None,
    escalate_portfolio: int | None = None,
    warm_path=None,
) -> WhatIfReport:
    """Score every recorded wave against the counterfactual world. At least
    one edit (fleet or solver config) should be given — with none this
    degenerates to a scored replay."""
    from grove_tpu.solver.warm import WarmPath

    warm = warm_path if warm_path is not None else WarmPath()
    fleets: dict[str, dict] = {}
    cf_nodes_cache: dict[str, list[Node]] = {}
    report = WhatIfReport(
        edits={
            "addRacks": add_rack_count,
            "portfolio": portfolio,
            "escalatePortfolio": escalate_portfolio,
            "weights": None if params is None else [float(w) for w in params],
        }
    )
    index = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "fleet":
            fleets[rec["digest"]] = rec
            continue
        if kind != "wave":
            continue
        fleet = fleets.get(rec["fleet"])
        if fleet is None:
            raise ValueError(
                f"wave {index} references fleet {rec['fleet']!r} missing from "
                "this journal — cannot evaluate"
            )
        gangs = [serde.decode(d) for d in rec["gangs"]]
        pods = {n: serde.decode(d) for n, d in rec["pods"].items()}

        # Recorded side: the plan as journaled, scored on the recorded fleet.
        rec_snap = snapshot_from_wave(rec, fleet)
        rec_report = evaluate_placement(gangs, pods, rec_snap, rec["plan"])

        # Counterfactual side: edited fleet (node pad grows with the fleet)
        # and/or overridden solver config, re-solved then scored.
        if rec["fleet"] not in cf_nodes_cache:
            cf_nodes_cache[rec["fleet"]] = add_racks(fleet, add_rack_count)
        cf_nodes = cf_nodes_cache[rec["fleet"]]
        cf_wave = dict(rec)
        cf_wave["padNodesTo"] = max(rec["padNodesTo"], next_pow2(len(cf_nodes)))
        cf_snap = snapshot_from_wave(cf_wave, fleet, nodes=cf_nodes)
        cf_plan, _cf_ok, _cf_scores, cf_s = solve_wave_record(
            cf_wave,
            cf_snap,
            warm=warm,
            params=params,
            portfolio=portfolio,
            escalate_portfolio=escalate_portfolio,
        )
        cf_report = evaluate_placement(gangs, pods, cf_snap, cf_plan)
        report.waves.append(
            WhatIfWave(
                index=index,
                recorded=rec_report.to_doc(),
                counterfactual=cf_report.to_doc(),
                recorded_solve_s=float(rec.get("solveSeconds", 0.0)),
                counterfactual_solve_s=cf_s,
            )
        )
        index += 1
    return report
