"""The decision flight recorder: journal every solve wave off the hot path.

The journal is a directory of SELF-CONTAINED segment files, each an atomic
JSON document `{"version": N, "records": [...]}` written via the shared
temp-file+rename primitive (`utils/fsio.atomic_write_json`) — readers never
see a torn segment, and rotation/pruning cannot corrupt older ones. Two
record kinds matter to replay:

  fleet   the cluster fleet at one instant (nodes + topology), content-
          addressed by digest and deduplicated — a wave references its fleet
          by digest instead of re-serializing 5k nodes per tick. The writer
          re-emits the referenced fleet record into every segment so each
          segment replays standalone even after older segments are pruned.
  wave    one solve wave: the exact encode inputs (serde-encoded sub-gangs
          and their referenced pods, allocated rows, bound/reuse/spread
          seeds, bucketing pads), the solver config fingerprint (weights,
          portfolio, effective escalation width), the resulting plan with
          per-gang verdicts/scores/rejection reasons, and timings.

Everything else (`action` records: preemption, reclaim, defrag migration,
rolling updates, gang termination, sim chaos) is narrative for `trace info`
and incident forensics — replay re-solves wave records only.

Hot-path discipline: `capture_wave` runs on the reconcile thread but only
serde-encodes (a deep copy into plain JSON types — the pods mutate right
after the solve, so the copy must be synchronous); file I/O happens on the
bounded-queue writer thread. A full queue DROPS the record and counts it
(`dropped`) rather than blocking a solve.
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import queue
import threading
from typing import Any, Optional

from grove_tpu.api import pod as pod_mod
from grove_tpu.api import podgang as podgang_mod
from grove_tpu.api import types as types_mod
from grove_tpu.state import cluster as state_mod
from grove_tpu.utils import serde
from grove_tpu.utils.fsio import atomic_write_json

# Journal schema. The replayer refuses a mismatched version outright: a
# silent best-effort parse of an old journal would "replay" different solver
# inputs and report fake divergence (or fake equivalence).
SCHEMA_VERSION = 1

_SEGMENT_GLOB = "segment-*.json"
_MANIFEST = "manifest.json"

for _m in (types_mod, pod_mod, podgang_mod, state_mod):
    serde.register_module(_m)


class TraceSchemaError(ValueError):
    """Journal version does not match this build's SCHEMA_VERSION."""


def _jsonable(x: Any) -> Any:
    """Coerce numpy scalars riding in verdict/score maps to plain JSON."""
    if hasattr(x, "item"):
        return x.item()
    return x


def fleet_payload(snapshot) -> dict:
    """Fleet record body derived from the snapshot itself (the padded rows
    are excluded — padding is re-derived at replay from `padNodesTo`)."""
    nodes = []
    for i, name in enumerate(snapshot.node_names):
        cap = {
            res: float(snapshot.capacity[i, j])
            for j, res in enumerate(snapshot.resource_names)
            if float(snapshot.capacity[i, j])
        }
        nodes.append(
            {
                "name": name,
                "capacity": cap,
                "labels": dict(snapshot.node_labels[i]),
                "taints": list(snapshot.node_taints[i]),
                "schedulable": bool(snapshot.schedulable[i]),
            }
        )
    return {
        "kind": "fleet",
        "topology": snapshot.topology.levels_doc(),
        "nodes": nodes,
    }


def fleet_digest_of(snapshot) -> tuple[str, dict]:
    """(digest, payload) for the snapshot's fleet; memoized on the snapshot
    object (immutable for its lifetime — defrag mutates only `allocated`,
    which the fleet payload excludes)."""
    cached = getattr(snapshot, "_trace_fleet", None)
    if cached is not None:
        return cached
    payload = fleet_payload(snapshot)
    digest = hashlib.blake2b(
        json.dumps(payload, sort_keys=True).encode(), digest_size=16
    ).hexdigest()
    payload["digest"] = digest
    snapshot._trace_fleet = (digest, payload)
    return digest, payload


class TraceRecorder:
    """Bounded-queue journal writer with atomic segment rotation."""

    def __init__(
        self,
        path: str,
        *,
        max_records_per_file: int = 256,
        max_files: int = 16,
        queue_size: int = 2048,
        flush_interval_seconds: float = 1.0,
    ) -> None:
        self.path = path
        self.max_records_per_file = max(1, int(max_records_per_file))
        self.max_files = max(1, int(max_files))
        self.flush_interval_seconds = float(flush_interval_seconds)
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, int(queue_size)))
        self._stop = threading.Event()
        self._flush_now = threading.Event()
        self._flush_done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (observability: /statusz "trace", grove_trace_* metrics)
        self.recorded = 0
        self.dropped = 0
        self.segments_written = 0
        self.waves = 0
        self.actions = 0
        # Write-failure survival (ENOSPC, torn disk, injected recorder.write
        # faults): the writer thread NEVER dies on an OSError — it drops the
        # segment, counts every record in it as dropped, latches `degraded`,
        # and keeps consuming the queue so the hot path stays unblocked. A
        # later successful write clears `degraded` (disk recovered) but the
        # cumulative write_errors counter persists — and is stamped into
        # every subsequent segment so `trace info` can see the episode
        # offline.
        self.write_errors = 0
        self.degraded = False
        self._last_write_error: Optional[str] = None
        # Segment manifest bookkeeping: the writer maintains manifest.json
        # beside the segments (atomic like them) so tail replay can find its
        # resume point — last journaled wave id, per-segment wave ranges,
        # fleet digests — without opening every segment. Derived data: a
        # failed manifest write is counted but never degrades the journal.
        self.manifest_writes = 0
        self.manifest_write_errors = 0
        # Rotation-pruning ledger, cumulative across writer lives (seeded
        # back from the manifest on restart): once > 0 the journal's oldest
        # waves are GONE, so a reader rebuilding state from it (cell
        # recovery) is working from an incomplete tail and must say so
        # (`journal_truncated`, RecoveryReport.truncated).
        self.pruned_segments = 0
        self.pruned_waves = 0
        # fleet digests already enqueued this process (the writer re-emits
        # per segment from its own payload cache).
        self._announced: set[str] = set()

    # ---- lifecycle ---------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        os.makedirs(self.path, exist_ok=True)
        # Non-daemon: stop() joins; a daemon killed mid-rename could strand
        # a temp file (harmless) but a clean join never does.
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._writer, name="grove-trace-writer", daemon=False
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join()
        self._thread = None

    def request_flush(self) -> None:
        """Ask the writer to persist pending records now (the manager's
        trace flow step calls this each reconcile, bounding journal staleness
        by the reconcile cadence instead of the flush interval)."""
        self._flush_now.set()

    def flush(self, timeout: float = 5.0) -> bool:
        """Synchronous flush: block until the writer has drained what was
        enqueued before this call and persisted it (replay_verify and tests
        read the journal right after). False when no writer is running or
        the wait timed out."""
        if self._thread is None:
            return False
        self._flush_done.clear()
        self._flush_now.set()
        return self._flush_done.wait(timeout)

    # ---- capture (reconcile thread) ----------------------------------------------

    def record(self, rec: dict) -> bool:
        """Enqueue one journal record; False (and counted) when full."""
        try:
            self._queue.put_nowait(rec)
        except queue.Full:
            self.dropped += 1
            return False
        self.recorded += 1
        if rec.get("kind") == "wave":
            self.waves += 1
        elif rec.get("kind") == "action":
            self.actions += 1
        return True

    def capture_action(self, now: float, action: str, obj: str, **fields) -> bool:
        """Journal one control-plane decision action (preemption, reclaim,
        defrag migration, rolling update, gang termination, sim chaos)."""
        return self.record(
            {"kind": "action", "now": float(now), "action": action,
             "object": obj, **fields}
        )

    def capture_wave(
        self,
        *,
        now: float,
        wave: str,
        snapshot,
        gangs: list,
        pods_by_name: dict,
        scheduled_names,
        bound_nodes: dict,
        reuse_nodes: dict,
        spread_avoid: dict,
        max_groups,
        max_sets,
        max_pods,
        pad_gangs_to,
        params,
        portfolio: int,
        escalate_portfolio: int,
        pruning=None,  # solver.pruning.PruningConfig (or None = dense)
        plan: dict,
        ok_by_name: dict,
        valid_by_name: dict,
        scores: dict,
        solve_seconds: float,
        allocated_override=None,  # np [N, R]: allocation ENTERING this wave
        free_rows: dict | None = None,  # node -> exact entering free row
        candidates: list | None = None,  # pruned waves: fixed candidate list
        mesh: dict | None = None,  # mesh fingerprint {portfolio, node} | None
    ) -> bool:
        """Journal one solve wave — the full encode+solve input closure plus
        the resulting plan. Serde-encoding here IS the synchronous deep copy;
        the pods mutate (bind) immediately after the solve.

        The pipelined drain (solver/drain._WavePipeline) journals waves whose
        entering state is NOT the snapshot: `allocated_override` is the
        running allocation table at the wave's commit point, `free_rows` the
        exact device-chained free carry (fetched bitwise — f32 round-trips
        JSON exactly), and `candidates` the fixed candidate-node list its
        plan was cut with (plans are cut against the INITIAL free, so replay
        must not re-cut them against the wave's entering free). Replay
        (trace/replay.py) prefers these over the snapshot-derived state."""
        digest, payload = fleet_digest_of(snapshot)
        if digest not in self._announced:
            if self.record(payload):
                self._announced.add(digest)
            else:
                return False  # fleet dropped: a wave referencing it is unreplayable
        names = {g.name for g in gangs}
        ref_names = {
            r.name
            for g in gangs
            for grp in g.spec.pod_groups
            for r in grp.pod_references
            if r.name in pods_by_name
        }
        allocated = {}
        n_real = len(snapshot.node_names)
        alloc_src = (
            snapshot.allocated if allocated_override is None else allocated_override
        )
        for i in range(n_real):
            row = alloc_src[i]
            if row.any():
                allocated[snapshot.node_names[i]] = [float(v) for v in row]
        rejections = {}
        for name in names:
            if _jsonable(ok_by_name.get(name, False)):
                continue
            rejections[name] = (
                "rejected (capacity/constraints)"
                if _jsonable(valid_by_name.get(name, False))
                else "not solver-valid (gated base or unresolvable constraint)"
            )
        rec = {
            "kind": "wave",
            "now": float(now),
            "wave": wave,
            "fleet": digest,
            "padNodesTo": int(snapshot.capacity.shape[0]),
            "resources": list(snapshot.resource_names),
            "allocated": allocated,
            "gangs": [serde.encode(g) for g in gangs],
            "pods": {n: serde.encode(pods_by_name[n]) for n in sorted(ref_names)},
            "scheduled": sorted(scheduled_names),
            "boundNodes": {
                g: {grp: list(map(int, idx)) for grp, idx in per.items()}
                for g, per in bound_nodes.items()
                if g in names
            },
            "reuseNodes": {
                g: list(map(int, idx))
                for g, idx in reuse_nodes.items()
                if g in names
            },
            "spreadAvoid": {
                g: list(map(int, idx))
                for g, idx in spread_avoid.items()
                if g in names
            },
            "maxGroups": max_groups,
            "maxSets": max_sets,
            "maxPods": max_pods,
            "padGangsTo": pad_gangs_to,
            "solver": {
                "params": [float(w) for w in params],
                "portfolio": int(portfolio),
                "escalatePortfolio": int(escalate_portfolio),
                # Mesh fingerprint (parallel/mesh.SolveLayout.fingerprint):
                # the device-mesh layout the recorded solve ran under. The
                # sharded solve is bitwise-equal to the unsharded one, so a
                # replay host with fewer devices (a 1-device mesh replaying
                # an 8-device plan) still replays bitwise — but the pruning
                # candidate pad is negotiated mesh-divisible, so replay
                # needs the recorded node-axis size to rebuild the exact
                # executable shape (trace/replay.py).
                "mesh": None
                if not mesh or int(mesh.get("node", 1)) <= 1
                else {
                    "portfolio": int(mesh.get("portfolio", 1)),
                    "node": int(mesh["node"]),
                },
                # Candidate-pruning fingerprint: replay must route through
                # the same pruned path (pruned placements legitimately
                # differ from dense ones) for bitwise equivalence.
                "pruning": None
                if pruning is None or not getattr(pruning, "enabled", False)
                else {
                    "enabled": True,
                    "maxCandidates": int(pruning.max_candidates),
                    "padLadder": [int(x) for x in pruning.pad_ladder],
                    "minPad": int(pruning.min_pad),
                    "minFleet": int(pruning.min_fleet),
                },
            },
            "plan": {g: dict(b) for g, b in plan.items()},
            "ok": {n: bool(_jsonable(ok_by_name.get(n, False))) for n in sorted(names)},
            "valid": {
                n: bool(_jsonable(valid_by_name.get(n, False))) for n in sorted(names)
            },
            "scores": {
                n: float(_jsonable(scores.get(n, 0.0))) for n in sorted(names)
            },
            "rejections": rejections,
            "solveSeconds": float(solve_seconds),
        }
        if free_rows:
            rec["freeRows"] = {
                str(n): [float(v) for v in row] for n, row in free_rows.items()
            }
        if candidates is not None:
            rec["candidates"] = [int(i) for i in candidates]
        return self.record(rec)

    # ---- writer thread -----------------------------------------------------------

    def _writer(self) -> None:
        seq = self._next_seq()
        segment: list[dict] = []
        seg_digests: set[str] = set()
        fleets: dict[str, dict] = {}  # every fleet payload seen this process
        dirty = False
        import time as _time

        last_flush = _time.monotonic()
        # seq -> manifest entry for every segment currently on disk, seeded
        # from the prior process's manifest (entries for pruned files drop;
        # unmanifested segments — written before the manifest existed — are
        # summarized once from disk here, never again per write).
        manifest = self._seed_manifest()
        self._write_manifest(manifest)

        def write_segment() -> None:
            nonlocal dirty, last_flush, segment, seg_digests
            if segment:
                try:
                    from grove_tpu import faults as faults_mod

                    faults_mod.active().maybe_raise(
                        "recorder.write", records=len(segment)
                    )
                    atomic_write_json(
                        os.path.join(self.path, f"segment-{seq:06d}.json"),
                        {
                            "version": SCHEMA_VERSION,
                            "records": segment,
                            # Recorder-state counters AT WRITE TIME (cumulative
                            # for this process): lets an offline reader
                            # (`grove-tpu trace info`, the tuning sweep) tell a
                            # truncated journal — records dropped under queue
                            # pressure — from a genuinely quiet day. Additive
                            # field: replay ignores it, old segments read as 0.
                            "recorderDropped": self.dropped,
                            "recorderRecorded": self.recorded,
                            # Counting-drops mode ledger: segments the writer
                            # could NOT persist (ENOSPC et al). > 0 tells an
                            # offline reader the journal has a HOLE even when
                            # the queue never overflowed.
                            "recorderWriteErrors": self.write_errors,
                        },
                    )
                    self.segments_written += 1
                    self.degraded = False
                    manifest[seq] = _manifest_entry(seq, segment)
                    self._write_manifest(manifest)
                except OSError as e:
                    # Counting-drops mode: the journal is observability, the
                    # solve loop is the product — a full disk must cost a
                    # SEGMENT of records (counted), never the writer thread
                    # (whose death would silently drop everything after) and
                    # never a blocked solve. The segment buffer is released
                    # so memory stays bounded while the disk is sick.
                    self.write_errors += 1
                    self.degraded = True
                    self.dropped += len(segment)
                    self._last_write_error = str(e)
                    segment = []
                    seg_digests = set()
            dirty = False
            last_flush = _time.monotonic()

        def rotate() -> None:
            nonlocal seq, segment, seg_digests
            write_segment()
            seq += 1
            segment = []
            seg_digests = set()
            if self._prune(manifest):
                self._write_manifest(manifest)

        while True:
            try:
                rec = self._queue.get(timeout=0.2)
            except queue.Empty:
                rec = None
            if rec is not None:
                if rec.get("kind") == "fleet":
                    fleets[rec["digest"]] = rec
                    # Emitted into a segment only when a wave references it.
                else:
                    d = rec.get("fleet")
                    if d and d not in seg_digests and d in fleets:
                        segment.append(fleets[d])
                        seg_digests.add(d)
                    segment.append(rec)
                    dirty = True
                if len(segment) >= self.max_records_per_file:
                    rotate()
                continue  # drain the queue before honoring flush/stop
            want_flush = self._flush_now.is_set()
            interval_due = (
                _time.monotonic() - last_flush >= self.flush_interval_seconds
            )
            if dirty and (want_flush or interval_due):
                write_segment()
            if want_flush:
                self._flush_now.clear()
                self._flush_done.set()  # flush(): everything enqueued before
                # the request is now on disk (the queue drained first).
            if self._stop.is_set() and self._queue.empty():
                break
        write_segment()
        if self._prune(manifest):
            self._write_manifest(manifest)

    def _segments(self) -> list[str]:
        return sorted(glob.glob(os.path.join(self.path, _SEGMENT_GLOB)))

    def _next_seq(self) -> int:
        seqs = []
        for p in self._segments():
            stem = os.path.basename(p)[len("segment-"):-len(".json")]
            try:
                seqs.append(int(stem))
            except ValueError:
                continue
        return max(seqs) + 1 if seqs else 0

    def _prune(self, manifest: dict | None = None) -> bool:
        files = self._segments()
        removed = False
        for p in files[: max(0, len(files) - self.max_files)]:
            try:
                os.unlink(p)
            except OSError:
                continue  # pruning is best-effort; the journal stays readable
            removed = True
            self.pruned_segments += 1
            if manifest is not None:
                stem = os.path.basename(p)[len("segment-"):-len(".json")]
                try:
                    entry = manifest.pop(int(stem), None)
                except ValueError:
                    entry = None
                if entry:
                    self.pruned_waves += int(entry.get("waves", 0) or 0)
        return removed

    # ---- segment manifest (writer thread) ------------------------------------------

    def _seed_manifest(self) -> dict[int, dict]:
        """Entries for every segment already on disk: reuse the previous
        process's manifest where its entries still match a file, summarize
        the rest by reading them once."""
        prior = {}
        doc = read_manifest(self.path)
        if doc:
            # Carry the pruning ledger across writer lives (max, not +=, so
            # a same-instance restart cannot double-count its own entries).
            self.pruned_segments = max(
                self.pruned_segments, int(doc.get("prunedSegments", 0) or 0)
            )
            self.pruned_waves = max(
                self.pruned_waves, int(doc.get("prunedWaves", 0) or 0)
            )
            for e in doc.get("segments", []):
                try:
                    prior[int(e["seq"])] = e
                except (KeyError, TypeError, ValueError):
                    continue
        manifest: dict[int, dict] = {}
        for p in self._segments():
            stem = os.path.basename(p)[len("segment-"):-len(".json")]
            try:
                seq = int(stem)
            except ValueError:
                continue
            got = prior.get(seq)
            if got is not None and got.get("file") == os.path.basename(p):
                manifest[seq] = got
                continue
            try:
                with open(p) as f:
                    records = json.load(f).get("records", [])
            except (OSError, ValueError):
                continue
            manifest[seq] = _manifest_entry(seq, records)
        return manifest

    def _write_manifest(self, manifest: dict[int, dict]) -> None:
        entries = [manifest[s] for s in sorted(manifest)]
        last_wave = None
        for e in entries:
            rng = e.get("waveRange")
            if rng:
                last_wave = rng[1]
        try:
            atomic_write_json(
                os.path.join(self.path, _MANIFEST),
                {
                    "version": SCHEMA_VERSION,
                    "segments": entries,
                    "lastWave": last_wave,
                    "waves": sum(int(e.get("waves", 0)) for e in entries),
                    # Pruning ledger: > 0 means the journal's oldest waves
                    # were rotated away — state rebuilt from this journal is
                    # incomplete (journal_truncated / recovery flags it).
                    "prunedSegments": self.pruned_segments,
                    "prunedWaves": self.pruned_waves,
                },
            )
            self.manifest_writes += 1
        except OSError:
            # Derived data: replay falls back to scanning segments; the
            # journal itself is NOT degraded by a missing manifest.
            self.manifest_write_errors += 1

    def stats(self) -> dict:
        """JSON-able recorder state for /statusz "trace" and the metrics."""
        doc = {
            "path": self.path,
            "recorded": self.recorded,
            "dropped": self.dropped,
            "waves": self.waves,
            "actions": self.actions,
            "segmentsWritten": self.segments_written,
            "queueDepth": self._queue.qsize(),
            "degraded": self.degraded,
            "writeErrors": self.write_errors,
            "manifestWrites": self.manifest_writes,
            "manifestWriteErrors": self.manifest_write_errors,
            "prunedSegments": self.pruned_segments,
            "prunedWaves": self.pruned_waves,
        }
        if self._last_write_error:
            doc["lastWriteError"] = self._last_write_error
        return doc


def _manifest_entry(seq: int, records: list[dict]) -> dict:
    """One segment's manifest row: id, record/wave counts, the wave-id range
    it covers (commit order — first and last wave record), and the fleet
    digests it re-emits (every segment replays standalone)."""
    waves = [r.get("wave", "?") for r in records if r.get("kind") == "wave"]
    return {
        "file": f"segment-{seq:06d}.json",
        "seq": seq,
        "records": len(records),
        "waves": len(waves),
        "waveRange": [waves[0], waves[-1]] if waves else None,
        "fleetDigests": sorted(
            {r["digest"] for r in records if r.get("kind") == "fleet"}
        ),
    }


def read_manifest(path: str) -> dict | None:
    """The journal's segment manifest ({"version", "segments", "lastWave",
    "waves"}), or None when absent/unreadable — callers fall back to
    scanning segment files (`read_journal`). A restarting cell uses
    `lastWave` as its resume point and the per-segment `waveRange` rows to
    pick the tail segments worth replaying."""
    try:
        with open(os.path.join(path, _MANIFEST)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def journal_truncated(path: str) -> bool:
    """True when the journal's oldest segments were rotation-pruned away —
    state rebuilt from it (cell recovery) is missing the pruned waves'
    admissions and therefore under-counts allocation. Primary signal is the
    manifest's pruning ledger (`prunedSegments`); the fallback — for a
    journal whose manifest is missing — is the surviving segment numbering
    (the writer numbers from 0, so a lowest seq > 0 means the head is
    gone)."""
    doc = read_manifest(path)
    if doc is not None and int(doc.get("prunedSegments", 0) or 0) > 0:
        return True
    files = [path] if os.path.isfile(path) else sorted(
        glob.glob(os.path.join(path, _SEGMENT_GLOB))
    )
    seqs = []
    for p in files:
        stem = os.path.basename(p)[len("segment-"):-len(".json")]
        try:
            seqs.append(int(stem))
        except ValueError:
            continue
    return bool(seqs) and min(seqs) > 0


def journal_stats(path: str) -> dict:
    """Writer-side counters recovered from the segment files themselves:
    {"dropped", "recorded", "segments", "writeErrors", "degraded"}.
    `dropped` > 0 means the journal is TRUNCATED — records were lost under
    queue pressure or to failed segment writes — which a sweep or replay
    consumer must surface (a wave referencing a dropped fleet fails replay
    outright, but dropped WAVES are silent without this). `writeErrors` > 0
    (stamped by the first segment successfully written AFTER a failed one)
    means the writer spent time in counting-drops mode — the journal has a
    hole even if the queue never overflowed; `degraded` mirrors it for
    `trace info`. Counters are cumulative per writer process, so the max
    across segments is the final count; segments written before the fields
    existed read as 0."""
    files = [path] if os.path.isfile(path) else sorted(
        glob.glob(os.path.join(path, _SEGMENT_GLOB))
    )
    dropped = 0
    recorded = 0
    write_errors = 0
    for p in files:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        dropped = max(dropped, int(doc.get("recorderDropped", 0) or 0))
        recorded = max(recorded, int(doc.get("recorderRecorded", 0) or 0))
        write_errors = max(
            write_errors, int(doc.get("recorderWriteErrors", 0) or 0)
        )
    return {
        "dropped": dropped,
        "recorded": recorded,
        "segments": len(files),
        "writeErrors": write_errors,
        "degraded": write_errors > 0,
    }


def read_journal(path: str) -> list[dict]:
    """Load a journal (directory of segments, or one segment file) into a
    record list, oldest first. Raises TraceSchemaError on a version mismatch
    — replaying a journal written by a different schema would rebuild
    different solver inputs and report meaningless (non-)divergence."""
    files = [path] if os.path.isfile(path) else sorted(
        glob.glob(os.path.join(path, _SEGMENT_GLOB))
    )
    if not files:
        raise FileNotFoundError(f"no trace journal at {path!r}")
    records: list[dict] = []
    for p in files:
        with open(p) as f:
            doc = json.load(f)
        version = doc.get("version")
        if version != SCHEMA_VERSION:
            raise TraceSchemaError(
                f"{p}: journal schema version {version!r} does not match this "
                f"build's {SCHEMA_VERSION} — re-record the journal with this "
                "build (or replay with the build that wrote it)"
            )
        records.extend(doc.get("records", []))
    return records
