"""Decision flight recorder, deterministic replay, what-if counterfactuals.

The control plane makes irreversible, hard-to-reproduce decisions (gang
placement, preemption, defrag migrations). This package journals every solve
wave off the hot path (`recorder.py`), rebuilds the solver inputs from a
journal and re-solves them asserting bitwise plan equivalence (`replay.py` —
any divergence is a solver-nondeterminism regression), and replays a journal
against a modified fleet or solver config to score counterfactual capacity /
policy changes with the placement-quality report (`whatif.py`).
"""

from grove_tpu.trace.recorder import (
    SCHEMA_VERSION,
    TraceRecorder,
    TraceSchemaError,
    read_journal,
)
from grove_tpu.trace.replay import ReplayReport, replay_journal
from grove_tpu.trace.whatif import WhatIfReport, whatif_journal

__all__ = [
    "SCHEMA_VERSION",
    "TraceRecorder",
    "TraceSchemaError",
    "read_journal",
    "ReplayReport",
    "replay_journal",
    "WhatIfReport",
    "whatif_journal",
]
