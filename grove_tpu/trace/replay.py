"""Deterministic replay: rebuild each journaled solve wave and re-solve it.

The batched solver is deterministic in its inputs (seeded portfolio
populations included), so re-encoding a wave's recorded input closure and
re-solving it through the warm-path AOT executable cache must reproduce the
recorded plan BITWISE — identical verdicts, identical pod→node bindings,
identical placement scores. Any divergence on the same platform is a
solver-nondeterminism regression (or journal corruption) and is reported as
a structured diff; the manager surfaces the count as
`grove_replay_divergence_total`.

Cross-platform note: replaying a TPU-recorded journal on CPU can diverge
legitimately (different aggregation path, float association). The regression
gate replays on the recording platform; cross-platform replay is a
conformance probe, not a correctness oracle.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from grove_tpu.api.types import ClusterTopology
from grove_tpu.solver.core import SolverParams, decode_assignments, solve
from grove_tpu.solver.encode import encode_gangs
from grove_tpu.state.cluster import ClusterSnapshot, Node, build_snapshot
from grove_tpu.utils import serde


@dataclass
class WaveReplay:
    """One wave's recorded-vs-replayed outcome."""

    index: int  # position among wave records in the journal
    wave: str  # floors | extras
    gangs: int
    recorded_admitted: int
    replayed_admitted: int
    recorded_solve_s: float
    replayed_solve_s: float
    divergences: list = field(default_factory=list)  # structured diffs

    def to_doc(self) -> dict:
        return {
            "index": self.index,
            "wave": self.wave,
            "gangs": self.gangs,
            "recordedAdmitted": self.recorded_admitted,
            "replayedAdmitted": self.replayed_admitted,
            "recordedSolveSeconds": round(self.recorded_solve_s, 4),
            "replayedSolveSeconds": round(self.replayed_solve_s, 4),
            "divergences": self.divergences,
        }


@dataclass
class ReplayReport:
    waves: list = field(default_factory=list)  # WaveReplay, journal order

    @property
    def divergence_count(self) -> int:
        return sum(len(w.divergences) for w in self.waves)

    @property
    def recorded_solve_s(self) -> float:
        return sum(w.recorded_solve_s for w in self.waves)

    @property
    def replayed_solve_s(self) -> float:
        return sum(w.replayed_solve_s for w in self.waves)

    def to_doc(self) -> dict:
        return {
            "waves": len(self.waves),
            "divergences": self.divergence_count,
            "recordedSolveSeconds": round(self.recorded_solve_s, 4),
            "replayedSolveSeconds": round(self.replayed_solve_s, 4),
            "diverged": [w.to_doc() for w in self.waves if w.divergences],
        }


def nodes_from_fleet(fleet: dict) -> list[Node]:
    """Fleet record -> Node objects, in recorded order (order IS identity:
    snapshot node indices derive from it)."""
    return [
        Node(
            name=nd["name"],
            capacity=dict(nd.get("capacity", {})),
            labels=dict(nd.get("labels", {})),
            schedulable=bool(nd.get("schedulable", True)),
            taints=list(nd.get("taints", [])),
        )
        for nd in fleet["nodes"]
    ]


def topology_from_fleet(fleet: dict) -> ClusterTopology:
    return ClusterTopology.from_dict({"name": "trace", "levels": fleet["topology"]})


def snapshot_from_wave(
    wave: dict, fleet: dict, nodes: list[Node] | None = None
) -> ClusterSnapshot:
    """Rebuild the wave's pre-solve snapshot: recorded fleet + recorded
    per-node allocated rows (float32 round-trips JSON exactly — every f32 is
    representable as a double)."""
    snap = build_snapshot(
        nodes if nodes is not None else nodes_from_fleet(fleet),
        topology_from_fleet(fleet),
        resource_names=tuple(wave["resources"]),
        pad_nodes_to=wave["padNodesTo"],
    )
    for name, row in wave.get("allocated", {}).items():
        if name in snap.node_index_map:
            snap.allocated[snap.node_index(name)] = np.asarray(row, np.float32)
    return snap


def solve_wave_record(
    wave: dict,
    snapshot: ClusterSnapshot,
    *,
    warm=None,
    params: SolverParams | None = None,
    portfolio: int | None = None,
    escalate_portfolio: int | None = None,
) -> tuple[dict, dict, dict, float]:
    """Re-encode + re-solve one wave record against `snapshot`; returns
    (plan, ok_by_name, scores_by_name, solve_seconds). The solver config
    defaults to the recorded fingerprint; the what-if path overrides it."""
    gangs = [serde.decode(d) for d in wave["gangs"]]
    pods = {n: serde.decode(d) for n, d in wave["pods"].items()}
    cfg = wave["solver"]
    # Recorded mesh fingerprint: rebuild the layout when this runtime can
    # host it (exercising the recorded sharded configuration); otherwise
    # replay unsharded — the sharded solve is bitwise-equal to the unsharded
    # one (tests/test_mesh.py), so a 1-device replay of an 8-device plan
    # still reproduces it bitwise. The fingerprint's node-axis size is
    # ALWAYS honored for the pruning candidate pad below (the executable
    # shape depends on it, devices or not).
    mesh_fp = cfg.get("mesh")
    mesh_layout = None
    if mesh_fp:
        from grove_tpu.parallel.mesh import layout_from_fingerprint

        mesh_layout = layout_from_fingerprint(
            mesh_fp, int(np.asarray(snapshot.capacity).shape[0])
        )
    pruning = None
    pr = cfg.get("pruning")
    if pr and pr.get("enabled"):
        # Recorded pruning fingerprint: the replay must take the SAME
        # candidate-pruned path (pruned placements legitimately differ from
        # dense ones — bitwise equivalence holds per configuration).
        from grove_tpu.solver.pruning import PruningConfig

        pruning = PruningConfig(
            enabled=True,
            max_candidates=int(pr.get("maxCandidates", 8191)),
            pad_ladder=tuple(pr.get("padLadder", ())),
            min_pad=int(pr.get("minPad", 64)),
            min_fleet=int(pr.get("minFleet", 256)),
        )
    t0 = time.perf_counter()
    batch, decode = encode_gangs(
        gangs,
        pods,
        snapshot,
        max_groups=wave.get("maxGroups"),
        max_sets=wave.get("maxSets"),
        max_pods=wave.get("maxPods"),
        pad_gangs_to=wave.get("padGangsTo"),
        scheduled_gangs=set(wave.get("scheduled", [])),
        bound_nodes_by_group=wave.get("boundNodes") or None,
        reuse_nodes_by_gang=wave.get("reuseNodes") or None,
        spread_avoid_by_gang=wave.get("spreadAvoid") or None,
    )
    # Pipelined-drain waves (solver/drain._WavePipeline) carry their exact
    # entering free rows: the device-chained carry fetched bitwise at journal
    # time. `capacity - allocated` recomputes the same values only when the
    # chain's float associations match the host's — the recorded rows make
    # replay independent of that. Rows absent from freeRows entered the wave
    # untouched (free == capacity bitwise).
    free_override = None
    if wave.get("freeRows"):
        free_override = np.array(snapshot.capacity, dtype=np.float32, copy=True)
        for name, row in wave["freeRows"].items():
            if name in snapshot.node_index_map:
                free_override[snapshot.node_index(name)] = np.asarray(
                    row, np.float32
                )
    candidates = wave.get("candidates")
    if candidates is not None and pruning is not None:
        # Pruned pipelined wave: the plan was cut against the drain's INITIAL
        # free (a superset of every later wave's eligible set), which the
        # record does not carry — rebuild the exact gather from the journaled
        # candidate list instead of re-cutting against the entering free.
        # Escalation is moot here: a wave whose dense re-solve changed a
        # verdict journaled AS dense (no candidates), so the recorded
        # verdicts equal the pruned solve's.
        import jax.numpy as jnp

        from grove_tpu.solver.core import (
            SolveResult,
            sharded_solve_fn,
            solve_batch,
        )
        from grove_tpu.solver.encode import GangBatch
        from grove_tpu.solver.pruning import plan_from_indices

        plan = plan_from_indices(
            snapshot,
            candidates,
            pruning,
            int(np.asarray(batch.gang_valid).shape[0]),
            # Recorded candidate pad: mesh-divisibility was negotiated into
            # the pad at record time, so the rebuilt plan must use the
            # RECORDED node-axis size even when replay itself runs
            # unsharded (executable shape identity).
            mesh_axis=int(mesh_fp.get("node", 1)) if mesh_fp else 1,
        )
        free_np = (
            free_override
            if free_override is not None
            else np.asarray(snapshot.free, np.float32)
        )
        jpbatch = GangBatch(
            *(
                None if x is None else jnp.asarray(x)
                for x in plan.gather_batch(batch)
            )
        )
        params_ = params if params is not None else SolverParams(*cfg["params"])
        pruned_args = (
            jnp.asarray(plan.gather_free(free_np)),
            jnp.asarray(plan.capacity),
            jnp.asarray(plan.schedulable),
            jnp.asarray(plan.node_domain_id),
            jpbatch,
        )
        if warm is not None:
            presult = warm.executables.solve(
                *pruned_args, params_, None,
                coarse_dmax=plan.coarse_dmax(), layout=mesh_layout,
            )
        elif mesh_layout is not None:
            f_s, c_s, s_s, nd_s, b_s, _ = mesh_layout.shard_solve_args(
                *pruned_args, None
            )
            presult = sharded_solve_fn(mesh_layout)(
                f_s, c_s, s_s, nd_s, b_s, params_, None,
                coarse_dmax=plan.coarse_dmax(),
            )
        else:
            presult = solve_batch(
                *pruned_args, params_, None, coarse_dmax=plan.coarse_dmax()
            )
        result = SolveResult(
            assigned=plan.remap_assigned(np.asarray(presult.assigned)),
            ok=presult.ok,
            placement_score=presult.placement_score,
            free_after=presult.free_after,
        )
    else:
        result = solve(
            snapshot,
            batch,
            params if params is not None else SolverParams(*cfg["params"]),
            free=free_override,
            portfolio=portfolio if portfolio is not None else cfg["portfolio"],
            escalate_portfolio=(
                escalate_portfolio
                if escalate_portfolio is not None
                else cfg["escalatePortfolio"]
            ),
            warm=warm,
            pruning=pruning,
            mesh=mesh_layout,
        )
    plan = decode_assignments(result, decode, snapshot)
    elapsed = time.perf_counter() - t0
    ok = dict(zip(decode.gang_names, (bool(x) for x in np.asarray(result.ok))))
    scores = dict(
        zip(decode.gang_names, (float(x) for x in np.asarray(result.placement_score)))
    )
    return plan, ok, scores, elapsed


def diff_wave(wave: dict, plan: dict, ok: dict, scores: dict) -> list[dict]:
    """Structured recorded-vs-replayed diff for one wave: verdict flips,
    binding differences, and (for admitted gangs) exact score mismatches."""
    divergences: list[dict] = []
    rec_ok = wave["ok"]
    rec_plan = wave["plan"]
    rec_scores = wave.get("scores", {})
    for gang in sorted(rec_ok):
        r_ok = bool(rec_ok[gang])
        p_ok = bool(ok.get(gang, False))
        if r_ok != p_ok:
            divergences.append(
                {"gang": gang, "type": "verdict", "recorded": r_ok, "replayed": p_ok}
            )
            continue
        if not r_ok:
            continue
        rb = rec_plan.get(gang, {})
        pb = plan.get(gang, {})
        if rb != pb:
            moved = {
                pod: [rb[pod], pb[pod]]
                for pod in rb.keys() & pb.keys()
                if rb[pod] != pb[pod]
            }
            divergences.append(
                {
                    "gang": gang,
                    "type": "bindings",
                    "moved": moved,
                    "missing": sorted(rb.keys() - pb.keys()),
                    "extra": sorted(pb.keys() - rb.keys()),
                }
            )
            continue
        r_score = rec_scores.get(gang)
        p_score = scores.get(gang)
        if r_score is not None and p_score is not None and r_score != p_score:
            divergences.append(
                {
                    "gang": gang,
                    "type": "score",
                    "recorded": r_score,
                    "replayed": p_score,
                }
            )
    return divergences


def replay_journal(records: list[dict], *, warm_path=None) -> ReplayReport:
    """Replay every wave record in `records` (as returned by
    `recorder.read_journal`), asserting bitwise plan equivalence. Raises
    KeyError-derived ValueError when a wave references a fleet digest the
    journal does not contain (dropped under queue pressure, or a hand-pruned
    segment set)."""
    from grove_tpu.solver.warm import WarmPath

    warm = warm_path if warm_path is not None else WarmPath()
    fleets: dict[str, dict] = {}
    report = ReplayReport()
    index = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "fleet":
            fleets[rec["digest"]] = rec
            continue
        if kind != "wave":
            continue
        fleet = fleets.get(rec["fleet"])
        if fleet is None:
            raise ValueError(
                f"wave {index} references fleet {rec['fleet']!r} which this "
                "journal does not contain (record dropped under queue "
                "pressure, or segments pruned apart) — cannot replay"
            )
        snapshot = snapshot_from_wave(rec, fleet)
        plan, ok, scores, elapsed = solve_wave_record(rec, snapshot, warm=warm)
        report.waves.append(
            WaveReplay(
                index=index,
                wave=rec.get("wave", "?"),
                gangs=len(rec["ok"]),
                recorded_admitted=sum(1 for v in rec["ok"].values() if v),
                replayed_admitted=sum(1 for v in ok.values() if v),
                recorded_solve_s=float(rec.get("solveSeconds", 0.0)),
                replayed_solve_s=elapsed,
                divergences=diff_wave(rec, plan, ok, scores),
            )
        )
        index += 1
    return report
