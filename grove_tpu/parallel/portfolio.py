"""Portfolio-parallel gang placement: the multi-chip solve.

The placement problem is combinatorial; the sequential-commit solver
(solver/core.py) is a greedy heuristic whose quality depends on its score
weights (SolverParams). Instead of one greedy pass, run P independent variants
— a *portfolio* of weight vectors — in parallel across the device mesh and
keep the best outcome (most gangs admitted, then highest placement quality).
This is the TPU-native replacement for the reference's single-threaded KAI
Filter/Score/Permit pipeline: quality comes from parallel search, throughput
from batching, and both ride the MXU/ICI instead of goroutines.

`tune_solve_step` goes one further: each call solves the portfolio, selects
the winner, and emits the next generation of weights (elite + deterministic
log-normal mutations) — a jittable evolutionary "training step" whose
parameters are the solver's score weights. That is this framework's analog of
a training loop, and the function `__graft_entry__.dryrun_multichip` shards
over a (portfolio, node) mesh.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from grove_tpu.parallel.mesh import (
    node_sharding,
    portfolio_sharding,
    replicated,
    solver_mesh,
    solver_mesh_for,
)
from grove_tpu.solver.core import SolveResult, SolverParams, solve_batch
from grove_tpu.solver.encode import GangBatch

_N_WEIGHTS = len(SolverParams._fields)

# Warm path: the population is deterministic in (p, base, spread, seed), so
# the per-solve RNG draw + device upload memoize away. The escalation path
# (solver.portfolioEscalation) otherwise re-derives the identical stack on
# EVERY escalated solve — measurable host time inside the serving loop.
_POPULATION_CACHE: dict[tuple, SolverParams] = {}


def params_population(p: int, base: SolverParams = SolverParams(), spread: float = 0.6,
                      seed: int = 0) -> SolverParams:
    """Stack P weight vectors: the base plus log-normal perturbations, with
    PACKING-POLARITY diversity — odd slots flip w_tight's sign (worst-fit).

    Magnitude noise alone cannot change which node wins an argmax whose
    ordering every positive scaling preserves; the measured failure it
    misses is the bin-packing trap where best-fit doubles small gangs onto
    one node and strands a later gang's floor, while worst-fit (negative
    tightness = spread-first) admits everything. Half the portfolio
    explores each polarity and the winner-select keeps whichever fits the
    batch; slot 0 is always the exact base, so the portfolio's admitted
    count can never fall below the base solver's.

    Deterministic for a given seed so portfolio solves are reproducible —
    which also makes the stack memoizable: repeat calls with scalar bases
    (every serving path) return the SAME device arrays instead of paying the
    RNG draw + host->device upload per solve.
    """
    try:
        key = (p, tuple(float(x) for x in base), spread, seed)
    except (TypeError, ValueError):
        key = None  # non-scalar base (already-stacked weights): no memo
    if key is not None:
        cached = _POPULATION_CACHE.get(key)
        if cached is not None:
            return cached
    rng = np.random.default_rng(seed)
    factors = np.exp(rng.normal(0.0, spread, size=(p, _N_WEIGHTS))).astype(np.float32)
    factors[0, :] = 1.0  # slot 0 is always the unperturbed base
    base_vec = np.asarray([float(x) for x in base], dtype=np.float32)
    stack = factors * base_vec[None, :]
    tight_i = SolverParams._fields.index("w_tight")
    stack[1::2, tight_i] *= -1.0  # odd slots: worst-fit members
    result = SolverParams(*(jnp.asarray(stack[:, i]) for i in range(_N_WEIGHTS)))
    if key is not None:
        if len(_POPULATION_CACHE) > 64:
            _POPULATION_CACHE.clear()  # tiny key space in practice; bound anyway
        _POPULATION_CACHE[key] = result
    return result


def _mutation_factors(p: int, spread: float = 0.35, seed: int = 7) -> np.ndarray:
    rng = np.random.default_rng(seed)
    factors = np.exp(rng.normal(0.0, spread, size=(p, _N_WEIGHTS))).astype(np.float32)
    factors[0, :] = 1.0  # elitism: slot 0 carries the winner unchanged
    return factors


def _objective(result: SolveResult) -> tuple[jax.Array, jax.Array]:
    """(gangs admitted, total placement quality) — compared lexicographically."""
    admitted = result.ok.sum(dtype=jnp.int32)
    quality = jnp.where(result.ok, result.placement_score, 0.0).sum()
    return admitted, quality


@partial(jax.jit, static_argnames=("coarse_dmax",))
def portfolio_solve_batch(
    free0: jax.Array,
    capacity: jax.Array,
    schedulable: jax.Array,
    node_domain_id: jax.Array,
    batch: GangBatch,
    params_stack: SolverParams,
    ok_global: jax.Array | None = None,  # cross-wave verdict bitmap [T]
    coarse_dmax: int | None = None,  # see solver/core.py coarse_dmax_of
) -> tuple[SolveResult, jax.Array, jax.Array]:
    """Solve the same batch under every weight vector; return the winner.

    Returns (best SolveResult, winner index, per-member objective [P]).
    The winner is chosen by exact lexicographic (admitted count, quality) —
    a two-stage argmax, NOT a packed float (which would quantize the quality
    tie-break away in f32 once admitted*1e6 dominates the mantissa).

    `ok_global` (the drain's cross-wave scaled-gang verdict bitmap) is
    shared by every member: wave chaining keeps only the WINNER's outcome,
    so each member must judge base-gang dependencies against that one
    committed history, not its own hypothetical.
    """
    vsolve = jax.vmap(
        lambda f, c, s, nd, b, p: solve_batch(
            f, c, s, nd, b, p, ok_global, coarse_dmax=coarse_dmax
        ),
        in_axes=(None, None, None, None, None, 0),
    )
    results = vsolve(free0, capacity, schedulable, node_domain_id, batch, params_stack)
    admitted, quality = jax.vmap(_objective)(results)
    max_admitted = admitted.max()
    winner = jnp.argmax(jnp.where(admitted == max_admitted, quality, -jnp.inf))
    best = jax.tree_util.tree_map(lambda x: x[winner], results)
    objectives = admitted.astype(jnp.float32) * 1e6 + quality  # display only
    return best, winner, objectives


@partial(jax.jit, static_argnames=("spread_seed", "coarse_dmax"))
def tune_solve_step(
    free0: jax.Array,
    capacity: jax.Array,
    schedulable: jax.Array,
    node_domain_id: jax.Array,
    batch: GangBatch,
    params_stack: SolverParams,
    spread_seed: int = 7,
    coarse_dmax: int | None = None,
) -> tuple[SolveResult, SolverParams, jax.Array]:
    """One evolutionary step: solve portfolio → pick winner → next generation.

    The next generation is the winner's weights broadcast through fixed
    log-normal mutation factors (slot 0 = elite copy). Fully jittable; calling
    it in a loop anneals the solver's score weights to the workload.
    """
    p = params_stack[0].shape[0]
    best, winner, objectives = portfolio_solve_batch(
        free0, capacity, schedulable, node_domain_id, batch, params_stack,
        coarse_dmax=coarse_dmax,
    )
    factors = jnp.asarray(_mutation_factors(p, seed=spread_seed))  # [P, W]
    winner_vec = jnp.stack([w[winner] for w in params_stack])  # [W]
    next_stack = SolverParams(*(factors[:, i] * winner_vec[i] for i in range(_N_WEIGHTS)))
    return best, next_stack, objectives


def shard_solver_inputs(
    mesh, free0, capacity, schedulable, node_domain_id, batch: GangBatch,
    params_stack: SolverParams,
):
    """Array-level mesh layout: node tensors sharded along NODE_AXIS, the
    weight stack along PORTFOLIO_AXIS, the gang batch replicated. The one
    place the sharding layout is defined — production solve (solver.core
    portfolio path), shard_inputs, and the driver dryrun all go through it.
    """
    rep = replicated(mesh)
    free0 = jax.device_put(jnp.asarray(free0), node_sharding(mesh, 0, 2))
    capacity = jax.device_put(jnp.asarray(capacity), node_sharding(mesh, 0, 2))
    schedulable = jax.device_put(jnp.asarray(schedulable), node_sharding(mesh, 0, 1))
    node_domain_id = jax.device_put(
        jnp.asarray(node_domain_id), node_sharding(mesh, 1, 2)
    )
    jbatch = GangBatch(
        *(None if x is None else jax.device_put(jnp.asarray(x), rep) for x in batch)
    )
    pstack = SolverParams(
        *(jax.device_put(jnp.asarray(x), portfolio_sharding(mesh)) for x in params_stack)
    )
    return free0, capacity, schedulable, node_domain_id, jbatch, pstack


def shard_inputs(mesh, snapshot, batch: GangBatch, params_stack: SolverParams):
    """Snapshot-level wrapper over shard_solver_inputs."""
    return shard_solver_inputs(
        mesh,
        snapshot.free,
        snapshot.capacity,
        snapshot.schedulable,
        snapshot.node_domain_id,
        batch,
        params_stack,
    )


_AUTO_MESH = object()  # sentinel: "compute the mesh here" (None = unsharded)


def portfolio_solve(
    free0,
    capacity,
    schedulable,
    node_domain_id,
    batch: GangBatch,
    base_params: SolverParams,
    portfolio: int,
    ok_global=None,
    coarse_dmax: int | None = None,
    *,
    pstack: SolverParams | None = None,
    mesh=_AUTO_MESH,
) -> SolveResult:
    """One-stop portfolio solve: population -> mesh layout (when the device
    count admits a valid (P, N)-divisible split, solver_mesh_for) -> winner.

    The single entry both serving paths use (solver.core.solve's portfolio
    branch and solver.drain's per-wave closure), so population seeding,
    sharding, and winner selection can never diverge between them.

    A wave-loop caller (the drain) hoists the invariants by passing
    `pstack` (the population) and `mesh` (None = stay unsharded) computed
    ONCE — re-running the RNG and the mesh search per wave would put host
    work back in the dispatch loop the drain exists to keep clean; the
    per-wave device_puts of unchanged statics are no-ops.
    """
    if pstack is None:
        pstack = params_population(portfolio, base=base_params)
    if mesh is _AUTO_MESH:
        mesh = solver_mesh_for(portfolio, int(free0.shape[0]))
    if mesh is not None:
        (free0, capacity, schedulable, node_domain_id, batch, pstack) = (
            shard_solver_inputs(
                mesh, free0, capacity, schedulable, node_domain_id, batch, pstack
            )
        )
        if ok_global is not None:
            ok_global = jax.device_put(jnp.asarray(ok_global), replicated(mesh))
    best, _winner, _objectives = portfolio_solve_batch(
        free0,
        capacity,
        schedulable,
        node_domain_id,
        batch,
        pstack,
        ok_global,
        coarse_dmax=coarse_dmax,
    )
    return best


def sharded_portfolio_solve(snapshot, batch: GangBatch, params_stack: SolverParams,
                            mesh=None) -> tuple[SolveResult, int, np.ndarray]:
    """Device-mesh entry point: portfolio axis data-parallel, node axis sharded.

    Places the P weight vectors across the mesh's portfolio axis and the node
    dimension of the capacity/score tensors across its node axis; XLA GSPMD
    inserts the collectives (per-domain reductions → psum over node shards,
    winner argmax → all-reduce over the portfolio axis).
    """
    mesh = mesh if mesh is not None else solver_mesh()
    from grove_tpu.solver.core import coarse_dmax_of

    best, winner, objectives = portfolio_solve_batch(
        *shard_inputs(mesh, snapshot, batch, params_stack),
        coarse_dmax=coarse_dmax_of(snapshot),
    )
    return best, int(winner), np.asarray(objectives)
