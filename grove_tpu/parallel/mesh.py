"""Device-mesh construction for the multi-chip solver.

The solver's parallelism axes (SURVEY.md §5.8 "TPU-native equivalent"):

  portfolio — data parallelism: independent solver variants (score-weight
              vectors / commit orderings) solved concurrently, winner kept.
              Rides ICI with zero communication until the final argmax.
  node      — model parallelism analog: the node axis of the [MG, N] / [N, R]
              score and capacity tensors is sharded across devices; XLA GSPMD
              inserts the psum/all-gather collectives for the per-domain
              segment reductions.

This mirrors how the reference scales: it has no multi-device math (pure Go
control plane, SURVEY.md §2.4), so the mesh here is new TPU-first design, not
a port — the analog of its horizontal scaling (ConcurrentSyncs workers,
operator/api/config/v1alpha1/types.go:180-208) done the XLA way.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PORTFOLIO_AXIS = "portfolio"
NODE_AXIS = "node"

logger = logging.getLogger(__name__)

# Shard-fallback ledger: every time layout negotiation declines to shard on a
# MULTI-device host (no divisible split, fleet under the floor, axis too
# small) the caller silently solves unsharded — correct, but one chip does
# all the work. The first fallback logs its reason; all of them count, and
# WarmPath.stats()/DrainStats surface the counter (/statusz warmPath
# shardFallbacks, `grove-tpu get solver`).
_FALLBACKS = 0
_FALLBACK_LOCK = threading.Lock()
_FALLBACK_LOGGED = False


def _note_fallback(reason: str) -> None:
    global _FALLBACKS, _FALLBACK_LOGGED
    with _FALLBACK_LOCK:
        _FALLBACKS += 1
        first = not _FALLBACK_LOGGED
        _FALLBACK_LOGGED = True
    if first:
        logger.warning(
            "solver mesh fallback: %s — solving unsharded on one device "
            "(counted as shardFallbacks; only the first fallback logs)",
            reason,
        )


def shard_fallbacks() -> int:
    """Process-wide count of mesh-negotiation fallbacks to unsharded."""
    with _FALLBACK_LOCK:
        return _FALLBACKS


def factor_devices(n: int) -> tuple[int, int]:
    """Factor n into (portfolio, node) — the most-square split, portfolio-major.

    Portfolio parallelism is communication-free so it gets the larger factor.
    """
    best = (n, 1)
    k = 1
    while k * k <= n:
        if n % k == 0:
            best = (n // k, k)
        k += 1
    return best


def solver_mesh(devices: list | None = None) -> Mesh:
    """Build the 2D (portfolio, node) mesh over all (or given) devices."""
    devices = devices if devices is not None else jax.devices()
    p, k = factor_devices(len(devices))
    return Mesh(np.asarray(devices).reshape(p, k), (PORTFOLIO_AXIS, NODE_AXIS))


def solver_mesh_for(
    portfolio: int, n_nodes: int, devices: list | None = None
) -> Mesh | None:
    """Largest valid (portfolio, node) mesh for the PROBLEM shape, or None.

    device_put with a NamedSharding needs each sharded dimension divisible
    by its axis size; an arbitrary (P, N) pair (P=2 variants, 6 nodes, 8
    devices) often can't use the most-square split. Prefer the largest
    portfolio axis that divides P with a node axis that divides N; None
    means no valid layout — the caller solves unsharded (vmap on the
    default device), which is always correct, just not distributed.
    """
    devices = devices if devices is not None else jax.devices()
    nd = len(devices)
    if nd <= 1:
        return None
    for k in range(1, nd + 1):
        if nd % k:
            continue
        pa = nd // k
        if portfolio % pa == 0 and n_nodes % k == 0:
            return Mesh(
                np.asarray(devices).reshape(pa, k), (PORTFOLIO_AXIS, NODE_AXIS)
            )
    _note_fallback(
        f"no (portfolio, node) split of {nd} devices divides "
        f"portfolio={portfolio}, nodes={n_nodes}"
    )
    return None


def portfolio_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis split across the portfolio axis, rest replicated."""
    return NamedSharding(mesh, PartitionSpec(PORTFOLIO_AXIS))


def node_sharding(mesh: Mesh, node_axis_index: int, ndim: int) -> NamedSharding:
    """Shard dimension `node_axis_index` of an ndim-array across NODE_AXIS."""
    spec = [None] * ndim
    spec[node_axis_index] = NODE_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def mesh_divisible_pad(pad: int, k: int) -> int:
    """Round `pad` up to the next multiple of `k` (identity for k <= 1).

    NamedSharding needs each sharded dimension divisible by its mesh axis;
    the pow2 pads the encode/pruning ladders produce are divisible by any
    pow2 device count already, so this only moves the pad on exotic axis
    sizes (6 devices -> node axis 3, say). Keeping the bump HERE — in the
    pad, not in the mesh search — is what lets `solve_layout_for` and
    `solver_mesh_for` succeed at bench scale instead of silently falling
    back to one device."""
    if k <= 1:
        return pad
    return ((pad + k - 1) // k) * k


@dataclass(frozen=True)
class SolveLayout:
    """One negotiated mesh layout for the single-variant production solve.

    The portfolio axis is size 1 here (weight-variant data parallelism rides
    `portfolio_solve`'s own mesh); the node axis carries the model-parallel
    split of every node-axis tensor: free/capacity [N, R], schedulable [N],
    node_domain_id [L, N], and the batch's node-seed fields. XLA GSPMD
    inserts the collectives for the per-domain segment reductions and the
    stage-2 top-k — the solver function itself is UNCHANGED.

    One instance = one executable family: `key()` feeds the AOT cache key
    (solver/warm.py) and the jitted-variant table, `fingerprint()` is what
    the flight recorder journals so replay can rebuild the same layout.
    """

    mesh: Mesh

    @property
    def node_devices(self) -> int:
        return int(self.mesh.shape[NODE_AXIS])

    @property
    def portfolio_devices(self) -> int:
        return int(self.mesh.shape[PORTFOLIO_AXIS])

    def key(self) -> tuple:
        """Hashable executable-cache identity: axis sizes + device ids (two
        same-shape meshes over different device subsets must not alias)."""
        return (
            self.portfolio_devices,
            self.node_devices,
            tuple(d.id for d in self.mesh.devices.flat),
        )

    def fingerprint(self) -> dict:
        """JSON-able journal record (trace/recorder.py wave records)."""
        return {"portfolio": self.portfolio_devices, "node": self.node_devices}

    # ---- shardings per solver argument --------------------------------------

    def replicated(self) -> NamedSharding:
        return replicated(self.mesh)

    def node_sharding(self, node_axis_index: int, ndim: int) -> NamedSharding:
        return node_sharding(self.mesh, node_axis_index, ndim)

    def free_sharding(self) -> NamedSharding:
        return self.node_sharding(0, 2)

    def batch_sharding(self, field: str, ndim: int) -> NamedSharding:
        """Sharding for one GangBatch field: node-seed fields shard their
        trailing node axis, everything else is replicated."""
        if field in ("reuse_nodes", "spread_avoid", "group_node_ok"):
            return self.node_sharding(ndim - 1, ndim)
        return self.replicated()

    def shard_solve_args(
        self, free0, capacity, schedulable, node_domain_id, batch, ok_global=None
    ):
        """device_put every solver input with its layout sharding (no-ops
        for arrays already resident with the right sharding — the drain's
        chained carry and the content-digest device cache stay zero-copy)."""
        rep = self.replicated()
        free0 = jax.device_put(free0, self.free_sharding())
        capacity = jax.device_put(capacity, self.free_sharding())
        schedulable = jax.device_put(schedulable, self.node_sharding(0, 1))
        node_domain_id = jax.device_put(node_domain_id, self.node_sharding(1, 2))
        batch = type(batch)(
            *(
                None
                if x is None
                else jax.device_put(x, self.batch_sharding(name, x.ndim))
                for name, x in zip(type(batch)._fields, batch)
            )
        )
        if ok_global is not None:
            ok_global = jax.device_put(ok_global, rep)
        return free0, capacity, schedulable, node_domain_id, batch, ok_global

    def gather_rows(self, free, padded_idx):
        """free [N, R] (node-sharded) -> rows [pad, R], node-sharded; pad
        rows (out-of-range idx) read as zero. The pruned drain's per-wave
        candidate gather, layout-stable by out_shardings."""
        import jax.numpy as jnp

        return _row_ops(self)[0](free, jnp.asarray(padded_idx))

    def scatter_rows(self, fleet_free, padded_idx, rows):
        """Write solved candidate rows back into the node-sharded fleet
        carry (pad rows drop via out-of-range scatter)."""
        import jax.numpy as jnp

        return _row_ops(self)[1](fleet_free, jnp.asarray(padded_idx), rows)


# Per-layout jitted gather/scatter for the pruned drain's device-chained
# fleet carry: out_shardings pin the result to the layout's node sharding,
# so gathering a wave's candidate rows out of the sharded fleet free (and
# scattering the solved rows back) keeps the chain sharded end to end — the
# pipeline never reshards between waves (eager .at[] ops would leave the
# output layout to GSPMD's whim and force a device_put per wave).
_ROW_OPS: dict[tuple, tuple] = {}
_ROW_OPS_LOCK = threading.Lock()


def _row_ops(layout: "SolveLayout") -> tuple:
    key = layout.key()
    with _ROW_OPS_LOCK:
        ops = _ROW_OPS.get(key)
    if ops is None:
        sh = layout.free_sharding()
        gather = jax.jit(
            lambda free, idx: free.at[idx].get(mode="fill", fill_value=0.0),
            out_shardings=sh,
        )
        scatter = jax.jit(
            lambda fleet, idx, rows: fleet.at[idx].set(
                rows, mode="drop", unique_indices=True
            ),
            out_shardings=sh,
        )
        with _ROW_OPS_LOCK:
            ops = _ROW_OPS.setdefault(key, (gather, scatter))
    return ops


def solve_layout_for(
    n_nodes: int,
    devices: list | None = None,
    *,
    max_devices: int = 0,
    min_nodes: int = 0,
    count_fallback: bool = True,
) -> SolveLayout | None:
    """Negotiate the (1, K) node-sharded layout for a single-variant solve.

    K is the largest device count <= the available devices (clamped by
    `max_devices` when > 0) that divides `n_nodes` — with pow2 node pads and
    pow2 device counts that is simply "all of them". None means stay
    unsharded: one device, a fleet below `min_nodes` (sharding overhead
    would dominate), or no K > 1 dividing the axis (counted + logged once
    via the shard-fallback ledger unless `count_fallback=False` — probes
    and status renders must not inflate the production counter)."""
    devices = list(devices if devices is not None else jax.devices())
    if max_devices > 0:
        devices = devices[:max_devices]
    nd = len(devices)
    if nd <= 1:
        return None
    if n_nodes < min_nodes:
        if count_fallback:
            _note_fallback(
                f"fleet axis {n_nodes} below solver.mesh.minNodes={min_nodes}"
            )
        return None
    for k in range(nd, 1, -1):
        if n_nodes % k == 0:
            return SolveLayout(
                mesh=Mesh(
                    np.asarray(devices[:k]).reshape(1, k),
                    (PORTFOLIO_AXIS, NODE_AXIS),
                )
            )
    if count_fallback:
        _note_fallback(
            f"no node-axis split: {n_nodes} nodes not divisible by any "
            f"k in 2..{nd}"
        )
    return None


@dataclass(frozen=True)
class MeshConfig:
    """`solver.mesh` config block (runtime/config.py validates the YAML
    shape; this is the solver-side value object)."""

    enabled: bool = False
    # Fleets whose padded node axis is below this stay unsharded — at small
    # sizes the collectives cost more than the split saves.
    min_nodes: int = 512
    # Devices the solve may occupy; 0 = every visible device.
    max_devices: int = 0

    def layout_for(self, n_nodes: int) -> SolveLayout | None:
        """Negotiated layout for a fleet axis (memoized — serving paths call
        this per solve); None when disabled or negotiation falls back."""
        if not self.enabled:
            return None
        key = (self, int(n_nodes))
        with _LAYOUT_MEMO_LOCK:
            if key in _LAYOUT_MEMO:
                return _LAYOUT_MEMO[key]
        layout = solve_layout_for(
            int(n_nodes), max_devices=self.max_devices, min_nodes=self.min_nodes
        )
        with _LAYOUT_MEMO_LOCK:
            if len(_LAYOUT_MEMO) > 64:
                _LAYOUT_MEMO.clear()  # tiny key space in practice; bound anyway
            _LAYOUT_MEMO[key] = layout
        return layout


_LAYOUT_MEMO: dict[tuple, SolveLayout | None] = {}
_LAYOUT_MEMO_LOCK = threading.Lock()


def resolve_layout(mesh, n_nodes: int) -> SolveLayout | None:
    """Normalize a caller-facing `mesh` argument (None | SolveLayout |
    MeshConfig) to a SolveLayout or None — the one sniffing point for the
    drain/stream/solve entries."""
    if mesh is None:
        return None
    if isinstance(mesh, SolveLayout):
        return mesh
    if isinstance(mesh, MeshConfig):
        return mesh.layout_for(n_nodes)
    raise TypeError(f"mesh must be None, SolveLayout, or MeshConfig; got {type(mesh)!r}")


def layout_from_fingerprint(fp: dict | None, n_nodes: int) -> SolveLayout | None:
    """Rebuild a journaled layout when this process can host it.

    Replay contract (trace/replay.py): the sharded solve is bitwise-equal to
    the unsharded solve (pinned by tests/test_mesh.py), so a plan recorded
    on an 8-device mesh replays bitwise on ANY device count — when the
    recorded mesh fits the current runtime we rebuild it (exercising the
    recorded configuration), otherwise replay solves unsharded. Returns
    None when fp is absent/1-device/unbuildable; never counts a fallback
    (replay is not the production path)."""
    if not fp:
        return None
    k = int(fp.get("node", 1))
    if k <= 1:
        return None
    devices = jax.devices()
    if len(devices) < k or n_nodes % k != 0:
        return None
    return SolveLayout(
        mesh=Mesh(
            np.asarray(devices[:k]).reshape(1, k), (PORTFOLIO_AXIS, NODE_AXIS)
        )
    )
