"""Device-mesh construction for the multi-chip solver.

The solver's parallelism axes (SURVEY.md §5.8 "TPU-native equivalent"):

  portfolio — data parallelism: independent solver variants (score-weight
              vectors / commit orderings) solved concurrently, winner kept.
              Rides ICI with zero communication until the final argmax.
  node      — model parallelism analog: the node axis of the [MG, N] / [N, R]
              score and capacity tensors is sharded across devices; XLA GSPMD
              inserts the psum/all-gather collectives for the per-domain
              segment reductions.

This mirrors how the reference scales: it has no multi-device math (pure Go
control plane, SURVEY.md §2.4), so the mesh here is new TPU-first design, not
a port — the analog of its horizontal scaling (ConcurrentSyncs workers,
operator/api/config/v1alpha1/types.go:180-208) done the XLA way.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PORTFOLIO_AXIS = "portfolio"
NODE_AXIS = "node"


def factor_devices(n: int) -> tuple[int, int]:
    """Factor n into (portfolio, node) — the most-square split, portfolio-major.

    Portfolio parallelism is communication-free so it gets the larger factor.
    """
    best = (n, 1)
    k = 1
    while k * k <= n:
        if n % k == 0:
            best = (n // k, k)
        k += 1
    return best


def solver_mesh(devices: list | None = None) -> Mesh:
    """Build the 2D (portfolio, node) mesh over all (or given) devices."""
    devices = devices if devices is not None else jax.devices()
    p, k = factor_devices(len(devices))
    return Mesh(np.asarray(devices).reshape(p, k), (PORTFOLIO_AXIS, NODE_AXIS))


def solver_mesh_for(
    portfolio: int, n_nodes: int, devices: list | None = None
) -> Mesh | None:
    """Largest valid (portfolio, node) mesh for the PROBLEM shape, or None.

    device_put with a NamedSharding needs each sharded dimension divisible
    by its axis size; an arbitrary (P, N) pair (P=2 variants, 6 nodes, 8
    devices) often can't use the most-square split. Prefer the largest
    portfolio axis that divides P with a node axis that divides N; None
    means no valid layout — the caller solves unsharded (vmap on the
    default device), which is always correct, just not distributed.
    """
    devices = devices if devices is not None else jax.devices()
    nd = len(devices)
    if nd <= 1:
        return None
    for k in range(1, nd + 1):
        if nd % k:
            continue
        pa = nd // k
        if portfolio % pa == 0 and n_nodes % k == 0:
            return Mesh(
                np.asarray(devices).reshape(pa, k), (PORTFOLIO_AXIS, NODE_AXIS)
            )
    return None


def portfolio_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis split across the portfolio axis, rest replicated."""
    return NamedSharding(mesh, PartitionSpec(PORTFOLIO_AXIS))


def node_sharding(mesh: Mesh, node_axis_index: int, ndim: int) -> NamedSharding:
    """Shard dimension `node_axis_index` of an ndim-array across NODE_AXIS."""
    spec = [None] * ndim
    spec[node_axis_index] = NODE_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
