"""Device-mesh construction for the multi-chip solver.

The solver's parallelism axes (SURVEY.md §5.8 "TPU-native equivalent"):

  portfolio — data parallelism: independent solver variants (score-weight
              vectors / commit orderings) solved concurrently, winner kept.
              Rides ICI with zero communication until the final argmax.
  node      — model parallelism analog: the node axis of the [MG, N] / [N, R]
              score and capacity tensors is sharded across devices; XLA GSPMD
              inserts the psum/all-gather collectives for the per-domain
              segment reductions.

This mirrors how the reference scales: it has no multi-device math (pure Go
control plane, SURVEY.md §2.4), so the mesh here is new TPU-first design, not
a port — the analog of its horizontal scaling (ConcurrentSyncs workers,
operator/api/config/v1alpha1/types.go:180-208) done the XLA way.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

PORTFOLIO_AXIS = "portfolio"
NODE_AXIS = "node"


def factor_devices(n: int) -> tuple[int, int]:
    """Factor n into (portfolio, node) — the most-square split, portfolio-major.

    Portfolio parallelism is communication-free so it gets the larger factor.
    """
    best = (n, 1)
    k = 1
    while k * k <= n:
        if n % k == 0:
            best = (n // k, k)
        k += 1
    return best


def solver_mesh(devices: list | None = None) -> Mesh:
    """Build the 2D (portfolio, node) mesh over all (or given) devices."""
    devices = devices if devices is not None else jax.devices()
    p, k = factor_devices(len(devices))
    return Mesh(np.asarray(devices).reshape(p, k), (PORTFOLIO_AXIS, NODE_AXIS))


def portfolio_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis split across the portfolio axis, rest replicated."""
    return NamedSharding(mesh, PartitionSpec(PORTFOLIO_AXIS))


def node_sharding(mesh: Mesh, node_axis_index: int, ndim: int) -> NamedSharding:
    """Shard dimension `node_axis_index` of an ndim-array across NODE_AXIS."""
    spec = [None] * ndim
    spec[node_axis_index] = NODE_AXIS
    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
