"""Multi-chip solve: device meshes and portfolio-parallel placement."""

from grove_tpu.parallel.mesh import (
    NODE_AXIS,
    PORTFOLIO_AXIS,
    factor_devices,
    solver_mesh,
)
from grove_tpu.parallel.portfolio import (
    params_population,
    portfolio_solve_batch,
    shard_inputs,
    sharded_portfolio_solve,
    tune_solve_step,
)

__all__ = [
    "NODE_AXIS",
    "PORTFOLIO_AXIS",
    "factor_devices",
    "solver_mesh",
    "params_population",
    "portfolio_solve_batch",
    "shard_inputs",
    "sharded_portfolio_solve",
    "tune_solve_step",
]
