"""Structured leveled logging — the `operator/internal/logger/logger.go` analog.

The reference builds a zap-backed logr with level {debug,info,error} and
format {json,text} from OperatorConfiguration. Here: stdlib logging with a
JSON or key=value formatter, level/format from the same config surface, and
logr-style key-value pairs (`log.info("msg", pcs="a", replica=2)`).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Any

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO, "error": logging.ERROR}


class _JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        doc.update(getattr(record, "kv", {}))
        return json.dumps(doc, default=str)


class _TextFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%H:%M:%S", time.localtime(record.created))
        kv = " ".join(
            f"{k}={v}" for k, v in getattr(record, "kv", {}).items()
        )
        base = f"{ts} {record.levelname[:4]} {record.name}: {record.getMessage()}"
        return f"{base} {kv}" if kv else base


class Logger:
    """logr-flavored wrapper: leveled, structured key-values, named children."""

    def __init__(self, inner: logging.Logger):
        self._inner = inner

    def with_name(self, name: str) -> "Logger":
        # Standalone child (not via the global registry): shares this
        # logger's handlers/level but cannot be reconfigured from outside.
        child = logging.Logger(f"{self._inner.name}.{name}", self._inner.level)
        child.handlers = self._inner.handlers
        child.propagate = False
        return Logger(child)

    def debug(self, msg: str, **kv: Any) -> None:
        self._inner.debug(msg, extra={"kv": kv})

    def info(self, msg: str, **kv: Any) -> None:
        self._inner.info(msg, extra={"kv": kv})

    def error(self, msg: str, **kv: Any) -> None:
        self._inner.error(msg, extra={"kv": kv})


def new_logger(
    level: str = "info", fmt: str = "text", name: str = "grove", stream=None
) -> Logger:
    """MustNewLogger analog. Unknown level/format raise ValueError (the
    reference treats bad log config as a boot failure)."""
    if level not in _LEVELS:
        raise ValueError(f"unknown log level {level!r} (want debug|info|error)")
    if fmt not in ("json", "text"):
        raise ValueError(f"unknown log format {fmt!r} (want json|text)")
    # Standalone instance, NOT logging.getLogger(name): two managers in one
    # process must not reconfigure each other's handlers through the global
    # logger registry.
    inner = logging.Logger(name, _LEVELS[level])
    inner.propagate = False
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(_JsonFormatter() if fmt == "json" else _TextFormatter())
    inner.handlers = [handler]
    return Logger(inner)
