"""Slow-start concurrent task runner.

Mirror of the reference's RunConcurrentlyWithSlowStart
(`operator/internal/utils/concurrent.go:72-96`): tasks run in batches of
doubling size (1, 2, 4, ...) so a systemic failure (apiserver throttling
there; a poisoned expansion or a broken downstream here) is detected after
one cheap task instead of a full-width burst. Within a batch, tasks run on a
bounded thread pool.

Used for work that is safe to parallelize: pure computation (workload
expansion) and external I/O (watch-driver event fan-out). The in-memory
store itself stays single-writer by design (SURVEY.md §5.2).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable, Sequence


@dataclass
class TaskResult:
    index: int
    value: Any = None
    error: BaseException | None = None


def run_concurrently_with_slow_start(
    tasks: Sequence[Callable[[], Any]],
    max_workers: int = 1,
    initial_batch: int = 1,
    stop_on_error: bool = True,
) -> list[TaskResult]:
    """Run `tasks`, doubling the batch size after each fully-successful batch.

    Returns one TaskResult per task that RAN, in task order. With
    `stop_on_error`, a failing batch records its own errors and the remaining
    tasks are never started — they simply have no TaskResult in the returned
    list (compare indices against range(len(tasks)) to find them).
    """
    results: list[TaskResult] = []
    max_workers = max(1, int(max_workers))
    batch = max(1, int(initial_batch))
    i = 0
    with ThreadPoolExecutor(max_workers=max_workers) as pool:
        while i < len(tasks):
            chunk = tasks[i : i + batch]

            def _run(idx_fn):
                idx, fn = idx_fn
                try:
                    return TaskResult(index=idx, value=fn())
                except BaseException as e:  # captured, not raised: batch policy
                    return TaskResult(index=idx, error=e)

            chunk_results = list(pool.map(_run, list(enumerate(chunk, start=i))))
            results.extend(chunk_results)
            if stop_on_error and any(r.error is not None for r in chunk_results):
                break
            i += len(chunk)
            batch *= 2  # slow start: 1, 2, 4, 8, ...
    return results
