"""Cross-cutting runtime utilities (platform hardening, logging, metrics,
errors, concurrency) — the analog of the reference's `operator/internal/utils`
+ `internal/logger` + `internal/errors` packages."""

from grove_tpu.utils.platform import (
    ensure_usable_backend,
    force_cpu,
    force_virtual_cpu_devices,
    probe_default_platform,
    scrubbed_cpu_env,
    wait_for_accelerator,
)

__all__ = [
    "ensure_usable_backend",
    "force_cpu",
    "force_virtual_cpu_devices",
    "probe_default_platform",
    "scrubbed_cpu_env",
    "wait_for_accelerator",
]
