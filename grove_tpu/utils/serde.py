"""Typed JSON codec for control-plane objects (persistence + wire format).

The reference persists all control-plane state in CR status through the
apiserver (rolling-update progress survives operator restarts,
`operator/api/core/v1alpha1/podcliqueset.go:96-118`). This stack has no
apiserver, so the store itself must round-trip: this codec turns the
dataclass object graph into plain JSON (with type tags) and back.

Encoding rules:
  dataclass -> {"!t": "<registered name>", <field>: <encoded>, ...}
  Enum      -> {"!e": "<registered name>", "v": <value>}
  set       -> {"!s": [..]}     tuple -> {"!u": [..]}
  dict with non-str keys -> {"!d": [[k, v], ..]}
  primitives/list/str-key dict pass through.

Only registered classes decode — an unknown tag is a hard error, not a
silent skip (state corruption must not truncate quietly).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any

_CLASSES: dict[str, type] = {}


def register(cls: type) -> type:
    """Register a dataclass/enum for decoding (idempotent; name-keyed)."""
    _CLASSES[cls.__name__] = cls
    return cls


def register_module(module) -> None:
    """Register every dataclass and Enum defined in `module`."""
    for name in dir(module):
        obj = getattr(module, name)
        if isinstance(obj, type) and obj.__module__ == module.__name__:
            if dataclasses.is_dataclass(obj) or issubclass(obj, enum.Enum):
                register(obj)


def encode(obj: Any) -> Any:
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return {"!e": type(obj).__name__, "v": obj.value}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        doc: dict[str, Any] = {"!t": type(obj).__name__}
        for f in dataclasses.fields(obj):
            doc[f.name] = encode(getattr(obj, f.name))
        return doc
    if isinstance(obj, (list,)):
        return [encode(x) for x in obj]
    if isinstance(obj, tuple):
        return {"!u": [encode(x) for x in obj]}
    if isinstance(obj, (set, frozenset)):
        return {"!s": sorted(encode(x) for x in obj)}
    if isinstance(obj, dict):
        if all(isinstance(k, str) for k in obj):
            return {k: encode(v) for k, v in obj.items()}
        return {"!d": [[encode(k), encode(v)] for k, v in obj.items()]}
    raise TypeError(f"cannot encode {type(obj).__name__}: {obj!r}")


def decode(doc: Any) -> Any:
    if doc is None or isinstance(doc, (bool, int, float, str)):
        return doc
    if isinstance(doc, list):
        return [decode(x) for x in doc]
    if isinstance(doc, dict):
        if "!e" in doc:
            cls = _lookup(doc["!e"])
            return cls(doc["v"])
        if "!t" in doc:
            cls = _lookup(doc["!t"])
            kwargs = {k: decode(v) for k, v in doc.items() if k != "!t"}
            field_names = {f.name for f in dataclasses.fields(cls) if f.init}
            no_init = {k: v for k, v in kwargs.items() if k not in field_names}
            obj = cls(**{k: v for k, v in kwargs.items() if k in field_names})
            for k, v in no_init.items():
                setattr(obj, k, v)
            return obj
        if "!s" in doc:
            return set(decode(x) for x in doc["!s"])
        if "!u" in doc:
            return tuple(decode(x) for x in doc["!u"])
        if "!d" in doc:
            return {decode(k): decode(v) for k, v in doc["!d"]}
        return {k: decode(v) for k, v in doc.items()}
    raise TypeError(f"cannot decode {type(doc).__name__}: {doc!r}")


def _lookup(name: str) -> type:
    cls = _CLASSES.get(name)
    if cls is None:
        raise KeyError(
            f"serde: type {name!r} not registered — state file from a newer "
            "schema, or register_module() missing for its module"
        )
    return cls


def _camel(name: str) -> str:
    parts = name.split("_")
    return parts[0] + "".join(w.capitalize() for w in parts[1:])


def to_k8s(obj: Any) -> Any:
    """Dataclass -> k8s-wire-shaped plain dict: camelCase keys, enum values,
    empty/None fields dropped (CR status subresource convention). Used by
    the kubernetes WatchSource to write PodCliqueSet status back to the CR
    — the reference persists exactly this through the apiserver
    (reconcilestatus.go)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out: dict[str, Any] = {}
        for f in dataclasses.fields(obj):
            v = to_k8s(getattr(obj, f.name))
            if v is None or v == [] or v == {}:
                continue
            out[_camel(f.name)] = v
        return out
    if isinstance(obj, (list, tuple)):
        return [to_k8s(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): to_k8s(v) for k, v in obj.items()}
    if isinstance(obj, (set, frozenset)):
        return sorted(to_k8s(x) for x in obj)
    raise TypeError(f"cannot render {type(obj).__name__} for the k8s wire")
