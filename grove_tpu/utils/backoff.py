"""Decorrelated-jitter backoff — the one retry-pacing policy for the stack.

Every retry loop that talks to something that can flake (the kube wire
client, the bind push, the watch resubscribe, the resilience ladder's bind
retry) shares THIS policy instead of growing its own fixed-sleep variant:

  sleep_n = min(cap, uniform(base, 3 * sleep_{n-1}))

— the "decorrelated jitter" scheme (AWS architecture blog): retries spread
out under contention (no thundering herd after an apiserver hiccup) while
the cap bounds the worst-case wait and `base` keeps the first retry fast.

Determinism contract: a Backoff seeded with the same `seed` yields the same
sleep sequence — chaos tests replay fault schedules bit-for-bit, so the
recovery timeline they assert on must be reproducible too. Callers that
want real entropy pass seed=None (system randomness).

Deadline awareness: `next_delay()` returns None once the (optional)
deadline would be exceeded — the caller stops retrying instead of sleeping
past its budget, and a sleep is clipped so the LAST retry still happens at
the deadline rather than overshooting it.
"""

from __future__ import annotations

import random
import time


class Backoff:
    """One retry episode's pacing state (not thread-safe; one per episode).

    >>> b = Backoff(base_s=0.1, cap_s=2.0, seed=7)
    >>> delay = b.next_delay()   # first retry: exactly base_s
    >>> delay = b.next_delay()   # then decorrelated jitter under cap_s
    """

    def __init__(
        self,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        *,
        deadline_s: float | None = None,  # absolute, on `clock`'s axis
        seed: int | None = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ) -> None:
        if base_s <= 0:
            raise ValueError(f"base_s must be > 0, got {base_s}")
        if cap_s < base_s:
            raise ValueError(f"cap_s must be >= base_s, got {cap_s} < {base_s}")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.deadline_s = deadline_s
        self.clock = clock
        self._sleep = sleep
        self._rng = random.Random(seed)
        self._prev = 0.0  # last delay handed out (0 = none yet)
        self.attempts = 0  # delays handed out so far

    def next_delay(self) -> float | None:
        """The next sleep in seconds, or None when the deadline is spent.

        The first delay is exactly `base_s` (deterministic fast retry);
        subsequent delays are uniform in [base_s, 3 * previous], capped at
        `cap_s`. A delay that would overshoot the deadline is CLIPPED to
        land on it — the final retry fires at the deadline, not past it."""
        if self._prev == 0.0:
            delay = self.base_s
        else:
            delay = min(self.cap_s, self._rng.uniform(self.base_s, 3.0 * self._prev))
        if self.deadline_s is not None:
            remaining = self.deadline_s - self.clock()
            if remaining <= 0:
                return None
            delay = min(delay, remaining)
        self._prev = delay
        self.attempts += 1
        return delay

    def sleep(self) -> bool:
        """Sleep the next delay; False when the deadline is spent (caller
        should stop retrying)."""
        delay = self.next_delay()
        if delay is None:
            return False
        self._sleep(delay)
        return True

    def reset(self) -> None:
        """Back to the fast first retry (call after a success so the NEXT
        episode starts fresh — long-lived loops like the watch reuse one
        Backoff across episodes)."""
        self._prev = 0.0
        self.attempts = 0


def retry(
    fn,
    *,
    attempts: int = 3,
    base_s: float = 0.05,
    cap_s: float = 2.0,
    deadline_s: float | None = None,
    seed: int | None = None,
    retry_on: tuple = (Exception,),
    clock=time.monotonic,
    sleep=time.sleep,
):
    """Call `fn()` up to `attempts` times with decorrelated-jitter pacing.

    Returns fn's value; re-raises the last exception when attempts (or the
    deadline) run out. `deadline_s` here is RELATIVE (a budget from now)."""
    abs_deadline = clock() + deadline_s if deadline_s is not None else None
    b = Backoff(
        base_s, cap_s, deadline_s=abs_deadline, seed=seed, clock=clock, sleep=sleep
    )
    while True:
        try:
            return fn()
        except retry_on:
            if b.attempts + 1 >= attempts or not b.sleep():
                raise
