"""Metrics registry + Prometheus text exposition — the metrics-server analog.

The reference exposes controller-runtime's Prometheus registry on a
configurable bind address (`operator/internal/controller/manager.go:94-96`,
chart `operator/charts/templates/service.yaml`). Here: a dependency-free
registry (counters, gauges, histograms with labels) rendered in Prometheus
text format, served by the manager's HTTP endpoints at /metrics.

Thread-safety: metric mutation happens on the reconcile thread while the
probe-server thread renders scrapes, so every metric guards its state with a
lock. Values render via repr() (full float precision) — %g-style shortening
corrupts counters past ~1e6.
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass, field
from typing import Optional


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    """Full-precision float, integer-valued floats without the trailing .0."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


@dataclass
class Counter:
    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        return lines


@dataclass
class Gauge:
    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(tuple(sorted(labels.items())), 0.0)

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {_fmt_value(v)}")
        return lines


@dataclass
class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics)."""

    name: str
    help: str
    buckets: tuple = (0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0)
    _counts: dict[tuple, list] = field(default_factory=dict)
    _sums: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.buckets) + 1))
            idx = bisect.bisect_left(self.buckets, value)
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def render(self) -> list[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            snapshot = [
                (key, list(counts), self._sums[key])
                for key, counts in sorted(self._counts.items())
            ]
        for key, counts, total in snapshot:
            labels = dict(key)
            cum = 0
            for ub, c in zip(self.buckets, counts):
                cum += c
                lines.append(
                    f"{self.name}_bucket{_fmt_labels({**labels, 'le': f'{ub:g}'})} {cum}"
                )
            cum += counts[-1]
            lines.append(
                f"{self.name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {cum}"
            )
            lines.append(f"{self.name}_sum{_fmt_labels(labels)} {_fmt_value(total)}")
            lines.append(f"{self.name}_count{_fmt_labels(labels)} {cum}")
        return lines


class Registry:
    """Thread-safe named-metric registry with text exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help), Counter)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help), Gauge)

    def histogram(
        self, name: str, help: str = "", buckets: Optional[tuple] = None
    ) -> Histogram:
        factory = lambda: Histogram(name, help, buckets or Histogram.buckets)  # noqa: E731
        return self._get_or_create(name, factory, Histogram)

    def _get_or_create(self, name, factory, kind):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise ValueError(f"metric {name!r} already registered as {type(m).__name__}")
            return m

    def render_text(self) -> str:
        with self._lock:
            metrics = [self._metrics[name] for name in sorted(self._metrics)]
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())  # type: ignore[attr-defined]
        return "\n".join(lines) + "\n"


# Default process-wide registry (controller-runtime's global registry analog).
DEFAULT_REGISTRY = Registry()
