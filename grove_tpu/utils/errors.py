"""Typed error model driving reconcile flow control.

Mirror of the reference's `operator/internal/errors/{errors,sentinel}.go`:
every controller error carries a stable machine code + the operation that
failed, errors wrap causes, and two sentinel codes are flow-control signals
(requeue-after / continue-and-requeue) rather than failures. The reconcile
flow (grove_tpu/runtime/flow.py) and the error recorder (LastErrors persisted
to status) consume these.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# Stable error codes (internal/errors/errors.go analog).
ERR_GET_RESOURCE = "ERR_GET_RESOURCE"
ERR_SYNC_RESOURCE = "ERR_SYNC_RESOURCE"
ERR_DELETE_RESOURCE = "ERR_DELETE_RESOURCE"
ERR_EXPAND_WORKLOAD = "ERR_EXPAND_WORKLOAD"
ERR_SOLVE = "ERR_SOLVE"
ERR_VALIDATION = "ERR_VALIDATION"
ERR_CONFIG = "ERR_CONFIG"
ERR_BACKEND = "ERR_BACKEND"
ERR_PERSISTENCE = "ERR_PERSISTENCE"

# Sentinel codes: flow-control, not failures (internal/errors/sentinel.go).
ERR_CODE_REQUEUE_AFTER = "ERR_REQUEUE_AFTER"
ERR_CODE_CONTINUE_RECONCILE_AND_REQUEUE = "ERR_CONTINUE_RECONCILE_AND_REQUEUE"

_SENTINELS = {ERR_CODE_REQUEUE_AFTER, ERR_CODE_CONTINUE_RECONCILE_AND_REQUEUE}


@dataclass
class GroveError(Exception):
    """Typed error: {code, operation, message}, optionally wrapping a cause."""

    code: str
    operation: str
    message: str
    cause: Optional[BaseException] = field(default=None, repr=False)

    def __str__(self) -> str:  # [code] operation: message (cause)
        base = f"[{self.code}] {self.operation}: {self.message}"
        return f"{base} (cause: {self.cause})" if self.cause else base

    @property
    def is_sentinel(self) -> bool:
        return self.code in _SENTINELS


def wrap(code: str, operation: str, err: BaseException) -> GroveError:
    """Wrap any exception into a GroveError, preserving an existing code."""
    if isinstance(err, GroveError):
        return err
    return GroveError(code=code, operation=operation, message=str(err), cause=err)


def requeue_after(operation: str, seconds: float) -> GroveError:
    """Sentinel: stop this reconcile, run again after `seconds`."""
    e = GroveError(
        code=ERR_CODE_REQUEUE_AFTER,
        operation=operation,
        message=f"requeue after {seconds:g}s",
    )
    e.requeue_seconds = seconds  # type: ignore[attr-defined]
    return e


def continue_and_requeue(operation: str, seconds: float) -> GroveError:
    """Sentinel: keep reconciling subsequent steps, but also requeue."""
    e = GroveError(
        code=ERR_CODE_CONTINUE_RECONCILE_AND_REQUEUE,
        operation=operation,
        message=f"continue, requeue after {seconds:g}s",
    )
    e.requeue_seconds = seconds  # type: ignore[attr-defined]
    return e
