"""Filesystem primitives shared by lease and persistence paths."""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any


def atomic_write_json(path: str, doc: Any) -> None:
    """Write JSON to `path` via temp-file + rename (atomic on POSIX).

    Readers never observe a torn file; on any failure the target is left
    untouched and the temp file removed.
    """
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".atomic-")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
