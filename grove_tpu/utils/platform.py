"""JAX platform hardening for tunnel-attached TPU environments.

The TPU chip in this environment is reached through a relay plugin that
registers itself at interpreter start (via PYTHONPATH sitecustomize) and
rewrites the jax ``jax_platforms`` config to ``"axon,cpu"`` — overriding the
``JAX_PLATFORMS`` environment variable. When the relay is healthy this is
transparent; when it is wedged, *any* first backend use (even a CPU-only
program) blocks inside native PJRT plugin init, uninterruptible from Python.

Consequences that shape this module:

1. A hung backend init cannot be timed out in-process — the only safe way to
   test "is the default backend usable?" is a *subprocess* probe with a kill
   timeout.
2. Once the probe fails, the in-process escape hatch is
   ``jax.config.update("jax_platforms", "cpu")`` *before* first device use —
   the config (not the env var) is what backend selection actually reads.
3. Code that must run multi-device on virtual CPU devices (sharding dryruns)
   should re-exec in a subprocess with the relay's PYTHONPATH entry scrubbed,
   so the plugin never registers at all.

Every driver-facing entry point (bench.py, __graft_entry__.py) and the test
suite route through these helpers so that a wedged relay degrades to CPU
evidence instead of a hang/crash (round-1 failure mode: BENCH_r01 rc=1,
MULTICHIP_r01 rc=124).
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time
from typing import Optional

_REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent

_PROBE_SRC = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"

# Persisted probe-verdict cache: a WEDGE verdict (relay failed to answer
# within the budget) is written here so the next process in the window falls
# back to CPU immediately instead of re-paying the full multi-minute probe
# loop (BENCH_r05's `error` field shows the 5 x 72s re-probe being paid on
# every bench run against the same wedged relay). Success verdicts are
# recorded for observability but never short-circuit the probe — a healthy
# probe is seconds, and trusting a stale success could hang the process at
# first device use if the relay wedged since. TTL 0 disables the cache.
_PROBE_CACHE_PATH_ENV = "GROVE_PLATFORM_PROBE_CACHE_PATH"
_PROBE_CACHE_TTL_ENV = "GROVE_PLATFORM_PROBE_TTL_S"
_PROBE_TIMEOUT_ENV = "GROVE_PLATFORM_PROBE_TIMEOUT_S"
_PROBE_MAX_ATTEMPTS_ENV = "GROVE_PLATFORM_PROBE_MAX_ATTEMPTS"
_DEFAULT_PROBE_CACHE = "/tmp/grove-tpu-state/platform-probe.json"
_DEFAULT_PROBE_TTL_S = 900.0


def _probe_cache_path() -> str:
    return os.environ.get(_PROBE_CACHE_PATH_ENV, _DEFAULT_PROBE_CACHE)


def _probe_cache_ttl() -> float:
    try:
        return float(os.environ.get(_PROBE_CACHE_TTL_ENV, _DEFAULT_PROBE_TTL_S))
    except ValueError:
        return _DEFAULT_PROBE_TTL_S


def read_probe_verdict() -> Optional[dict]:
    """The persisted probe verdict if present AND inside its TTL window,
    else None. Verdict doc: {"platform": str|None, "wedged": bool,
    "ts": epoch-seconds, "attempts": int}."""
    ttl = _probe_cache_ttl()
    if ttl <= 0:
        return None
    try:
        with open(_probe_cache_path()) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    try:
        age = time.time() - float(doc.get("ts", 0.0))
    except (TypeError, ValueError):
        return None
    if age < 0 or age >= ttl:
        return None
    return doc


def write_probe_verdict(platform: Optional[str], wedged: bool, attempts: int) -> None:
    """Persist the probe outcome (best-effort; the cache is an optimization,
    never fatal)."""
    if _probe_cache_ttl() <= 0:
        return
    try:
        from grove_tpu.utils.fsio import atomic_write_json

        atomic_write_json(
            _probe_cache_path(),
            {
                "platform": platform,
                "wedged": bool(wedged),
                "ts": time.time(),
                "attempts": int(attempts),
            },
        )
    except OSError:
        pass


def probe_default_platform(timeout_s: float = 90.0) -> Optional[str]:
    """Initialize the default JAX backend in a throwaway subprocess.

    Returns the platform name (e.g. "axon", "tpu", "cpu") if init succeeds
    within the timeout, else None. Must be a subprocess: a wedged relay hangs
    in native code and cannot be interrupted in-process.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=str(_REPO_ROOT),
        )
    except (subprocess.TimeoutExpired, OSError):
        return None
    if proc.returncode != 0:
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return None


def enable_compilation_cache(cache_dir: str) -> bool:
    """Turn on JAX's persistent compilation cache (jax-idiomatic: serialized
    XLA executables keyed by HLO+config, reused across PROCESSES). The
    solver's warm-up pays ~20-40s of TPU compilation per boot; with the
    cache, every boot after the first loads the executables from disk in
    well under a second. Safe to call before or after first device use for
    subsequently-compiled functions; errors are non-fatal (cache off =
    slower, never wrong)."""
    import os

    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_enable_compilation_cache", True)
        # The default 1s threshold would skip small solver kernels whose
        # compiles still add up across wave-shape buckets.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        return True
    except Exception:  # noqa: BLE001 — cache is an optimization, never fatal
        return False


def force_cpu() -> None:
    """Point this process's JAX at the CPU backend, bypassing the relay.

    Works even after the relay plugin rewrote jax_platforms at interpreter
    start, as long as no backend has been initialized yet. Safe to call
    multiple times.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_usable_backend(
    probe_timeout_s: float = 90.0,
    retries: int = 2,
    retry_wait_s: float = 5.0,
) -> tuple[str, Optional[str]]:
    """Guarantee the process can run JAX computations without hanging.

    Thin wrapper over wait_for_accelerator with the attempt-count interface
    the runtime/graft callers use: a budget of `retries` probes (plus the
    sleeps between them), then the CPU fallback. Returns (platform, error)
    where error is None on the happy path and a diagnostic string when the
    CPU fallback was taken.
    """
    retries = max(1, retries)
    budget = retries * probe_timeout_s + (retries - 1) * retry_wait_s
    return wait_for_accelerator(
        wait_budget_s=budget,
        probe_timeout_s=probe_timeout_s,
        retry_sleep_s=retry_wait_s,
    )


def wait_for_accelerator(
    wait_budget_s: float,
    probe_timeout_s: float = 60.0,
    retry_sleep_s: float = 15.0,
) -> tuple[str, Optional[str]]:
    """Deadline-based relay wait: keep probing the default backend until it
    answers with an accelerator or the budget runs out, then fall back to CPU.

    The round-3 postmortem: the 90s x2 probe gave up while the relay was
    mid-wedge, and the headline bench fell back to CPU even though the chip
    recovered later in the window. This variant spends the CALLER'S time
    budget (e.g. bench budget minus a reserve for the CPU run) probing —
    wedges are sometimes transient, and one extra probe cycle is the
    difference between on-chip evidence and another cpu-platform artifact.

    Returns (platform, error) like ensure_usable_backend. A probe that finds
    a CPU-only default backend returns immediately (nothing to wait for).

    Wedge verdicts persist across processes (GROVE_PLATFORM_PROBE_TTL_S,
    default 900; 0 disables): when a previous process already burned its
    budget proving the relay wedged, this one falls back to CPU immediately
    instead of re-paying the probe loop. Probe timeout and attempt count are
    env-tunable (GROVE_PLATFORM_PROBE_TIMEOUT_S overrides `probe_timeout_s`,
    GROVE_PLATFORM_PROBE_MAX_ATTEMPTS caps the loop).
    """
    if os.environ.get("GROVE_FORCE_CPU") == "1":
        force_cpu()
        return "cpu", None
    env_timeout = os.environ.get(_PROBE_TIMEOUT_ENV)
    if env_timeout:
        try:
            probe_timeout_s = float(env_timeout)
        except ValueError:
            pass
    max_attempts = 0  # 0 = unbounded within the budget
    env_attempts = os.environ.get(_PROBE_MAX_ATTEMPTS_ENV)
    if env_attempts:
        try:
            max_attempts = max(0, int(env_attempts))
        except ValueError:
            pass
    verdict = read_probe_verdict()
    if verdict is not None and verdict.get("wedged"):
        force_cpu()
        return (
            "cpu",
            "TPU relay marked wedged by a probe "
            f"{time.time() - float(verdict.get('ts', 0.0)):.0f}s ago "
            f"(cached verdict, ttl {_probe_cache_ttl():.0f}s); "
            "forced jax_platforms=cpu",
        )
    deadline = time.monotonic() + max(0.0, wait_budget_s)
    attempts = 0
    while True:
        remaining = deadline - time.monotonic()
        if attempts > 0 and remaining <= 5.0:
            break
        timeout = min(probe_timeout_s, max(10.0, remaining))
        platform = probe_default_platform(timeout)
        attempts += 1
        if platform is not None:
            write_probe_verdict(platform, wedged=False, attempts=attempts)
            return platform, None
        if max_attempts and attempts >= max_attempts:
            break
        if deadline - time.monotonic() > retry_sleep_s:
            time.sleep(retry_sleep_s)
    write_probe_verdict(None, wedged=True, attempts=attempts)
    force_cpu()
    return (
        "cpu",
        "default JAX backend failed to initialize within "
        f"{wait_budget_s:.0f}s across {attempts} probes (TPU relay wedged?); "
        "forced jax_platforms=cpu",
    )


def _set_virtual_device_flags(env: dict, n_virtual_devices: int) -> None:
    """Rewrite env's XLA_FLAGS to request exactly n virtual CPU devices."""
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    if n_virtual_devices > 0:
        flags.append(f"--xla_force_host_platform_device_count={n_virtual_devices}")
    env["XLA_FLAGS"] = " ".join(flags)


def force_virtual_cpu_devices(n_virtual_devices: int) -> None:
    """In-process: CPU backend with n virtual devices.

    Must run before first backend use — XLA reads XLA_FLAGS at CPU-client
    creation. Used by the test suite (8-device virtual mesh standing in for a
    TPU slice) and by the dryrun inner process.
    """
    _set_virtual_device_flags(os.environ, n_virtual_devices)
    force_cpu()


def scrubbed_cpu_env(
    n_virtual_devices: int = 0, extra_env: Optional[dict] = None
) -> dict:
    """Environment for a subprocess that must never touch the relay.

    Drops the relay's sitecustomize from PYTHONPATH (so the plugin never
    registers), pins JAX_PLATFORMS=cpu, and optionally requests N virtual CPU
    devices via XLA_FLAGS. The repo root is prepended to PYTHONPATH so the
    child can import grove_tpu / __graft_entry__ without the scrubbed entry.
    """
    env = dict(os.environ)
    parts = [
        p
        for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and ".axon_site" not in p
    ]
    parts.insert(0, str(_REPO_ROOT))
    env["PYTHONPATH"] = os.pathsep.join(parts)
    env["JAX_PLATFORMS"] = "cpu"
    _set_virtual_device_flags(env, n_virtual_devices)
    if extra_env:
        env.update(extra_env)
    return env
