"""grove_tpu — a TPU-native gang-scheduling orchestration framework.

A from-scratch rebuild of the capabilities of NVIDIA Grove (ai-dynamo/grove):
declarative workload API (PodCliqueSet / PodClique / PodCliqueScalingGroup /
ClusterTopology), hierarchical gang scheduling via a PodGang scheduler IR,
topology-aware placement, multi-level autoscaling, startup ordering, gang
termination and rolling updates — with the placement engine rebuilt as a JAX
batched bin-packing solver that runs on TPU.

Layout (mirrors SURVEY.md §7):
  api/          workload model + scheduler IR + naming/defaulting/validation
  orchestrator/ reconcile cascade: expansion, gating, termination, updates
  state/        dense cluster snapshot tensors (nodes × resources × domains)
  solver/       the TPU part: masks, scoring, all-or-nothing gang commit
  backend/      scheduler-backend boundary (gRPC sidecar, GREP-375 contract)
  sim/          synthetic cluster generator + event-driven simulator
"""

from grove_tpu.version import VERSION as __version__  # noqa: E402
