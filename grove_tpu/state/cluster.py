"""Dense cluster snapshot: nodes × resources × topology-domain tensors.

The analog of the reference's informer caches (node/pod listers) flattened into
the tensors the TPU solver consumes. The reference reads cluster state through
kube-apiserver watch streams (SURVEY.md §5.8); here a snapshot is built from any
source (simulator, KWOK replay, live lister) and handed to the solver whole.

Encoding:
  capacity / allocated : float32 [N, R]  (base units; R = len(resource_names))
  node_domain_id       : int32  [L, N]   (domain ordinal per topology level;
                                          -1 = node not labeled at that level)
  schedulable          : bool   [N]      (False = cordoned/unready)

Topology levels are the sorted (broad→narrow) levels of the ClusterTopology
(clustertopology.go:92-136). Domain ordinals are dense per level so per-domain
aggregates are jax.ops.segment_sum calls.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from grove_tpu.api.pod import Pod
from grove_tpu.api.types import ClusterTopology, TopologyDomain

DEFAULT_RESOURCES = ("cpu", "memory", "google.com/tpu", "nvidia.com/gpu")


@dataclass
class Node:
    """One schedulable node."""

    name: str
    capacity: dict[str, float] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    schedulable: bool = True
    # k8s taints ({"key", "value", "effect"}); NoSchedule/NoExecute block
    # placement unless the pod tolerates them (we ARE the scheduler).
    taints: list[dict] = field(default_factory=list)
    # Spot/preemptible capacity: a revocable node can receive a revocation
    # notice (revocation_deadline = sim/wall time the capacity disappears).
    # A pending notice makes the node unschedulable for NEW placement —
    # build_snapshot masks it — while existing bindings keep running until
    # the controller migrates/evicts them or the deadline kills the node.
    revocable: bool = False
    revocation_deadline: float | None = None


@dataclass
class ClusterSnapshot:
    """Immutable dense view of the cluster at one instant."""

    resource_names: tuple[str, ...]
    node_names: list[str]
    capacity: np.ndarray  # f32 [N, R]
    allocated: np.ndarray  # f32 [N, R]
    schedulable: np.ndarray  # bool [N]
    # Topology:
    topology: ClusterTopology
    level_domains: list[TopologyDomain]  # broad→narrow, length L
    node_domain_id: np.ndarray  # i32 [L, N]
    domain_names: list[list[str]]  # per level: ordinal -> domain value
    num_domains: np.ndarray  # i32 [L] (actual domain count per level)
    node_index_map: dict[str, int] = field(default_factory=dict)
    # Raw node labels (shared references, not copies), padded rows empty —
    # nodeSelector matching happens against these at encode time.
    node_labels: list[dict] = field(default_factory=list)
    # Raw node taints, same layout; empty for untainted/padded rows.
    node_taints: list[list] = field(default_factory=list)
    # Memo for tainted_node_indices, keyed by the effects tuple (a single
    # unkeyed slot would silently serve one caller's effects set to
    # another). The snapshot is immutable for its lifetime, so one O(N)
    # taint scan per effects set serves every encode against it — at bench
    # scale the per-wave rescan was the dominant node-linear term in host
    # encode (round-5 profile: 1.2s of a 4.8s 8x encode).
    _tainted_idx: Optional[dict] = None
    # Memo for encode_epoch (same immutability argument).
    _encode_epoch: Optional[tuple] = None
    # Memo for node_names_arr (same immutability argument).
    _node_names_arr: Optional[np.ndarray] = None
    # Memo for cap_scale (capacity is immutable for the snapshot's life).
    _cap_scale: Optional[np.ndarray] = None

    @property
    def n_nodes(self) -> int:
        return len(self.node_names)

    def cap_scale(self) -> np.ndarray:
        """Per-resource capacity maxima (score normalization, encode group
        ordering), memoized — an O(N) column max otherwise re-paid by every
        encode against this snapshot."""
        if self._cap_scale is None:
            self._cap_scale = np.maximum(self.capacity.max(axis=0), 1e-9)
        return self._cap_scale

    def node_names_arr(self) -> np.ndarray:
        """node_names as an object array, memoized — the batch decode
        (solver/core.decode_bindings) gathers admitted pods' node names
        through it, so the O(N) list->array conversion is paid once per
        snapshot instead of once per wave."""
        if self._node_names_arr is None:
            self._node_names_arr = np.asarray(self.node_names, dtype=object)
        return self._node_names_arr

    def tainted_node_indices(self, blocking_effects) -> list[int]:
        """Indices of nodes carrying scheduling-blocking taints; memoized
        per effects set (empty on the common untainted cluster)."""
        key = tuple(sorted(blocking_effects))
        if self._tainted_idx is None:
            self._tainted_idx = {}
        if key not in self._tainted_idx:
            self._tainted_idx[key] = [
                i
                for i, taints in enumerate(self.node_taints)
                if any(t.get("effect") in blocking_effects for t in taints)
            ]
        return self._tainted_idx[key]

    def encode_epoch(self) -> tuple:
        """Hashable digest of every snapshot input the dense ENCODE reads:
        resource axis, capacity (cap_scale for group ordering), the domain
        map (pack-set pins), node labels (selector rows), and node taints
        (toleration rows). The per-gang encode-row cache (solver/warm.py)
        keys on this so rows can never be reused against a snapshot they
        were not built for. Memoized — the snapshot is immutable."""
        if self._encode_epoch is None:
            import hashlib

            h = hashlib.blake2b(digest_size=16)
            h.update(repr(self.resource_names).encode())
            h.update(np.ascontiguousarray(self.capacity).tobytes())
            h.update(np.ascontiguousarray(self.node_domain_id).tobytes())
            for labels in self.node_labels:
                h.update(repr(sorted(labels.items())).encode())
            for taints in self.node_taints:
                if taints:
                    h.update(repr(taints).encode())
            self._encode_epoch = (self.capacity.shape, h.hexdigest())
        return self._encode_epoch

    @property
    def free(self) -> np.ndarray:
        return self.capacity - self.allocated

    def node_index(self, name: str) -> int:
        return self.node_index_map[name]

    def level_index(self, domain: TopologyDomain) -> Optional[int]:
        try:
            return self.level_domains.index(domain)
        except ValueError:
            return None

    def domain_of_node(self, node: int | str, level: TopologyDomain) -> Optional[str]:
        if isinstance(node, str):
            node = self.node_index(node)
        li = self.level_index(level)
        if li is None:
            return None
        did = int(self.node_domain_id[li, node])
        if did < 0:
            return None
        return self.domain_names[li][did]


def build_snapshot(
    nodes: list[Node],
    topology: ClusterTopology,
    resource_names: tuple[str, ...] = DEFAULT_RESOURCES,
    bound_pods: list[Pod] | None = None,
    pad_nodes_to: int | None = None,
) -> ClusterSnapshot:
    """Flatten node objects + topology labels into the dense snapshot.

    `pad_nodes_to` pads the node axis with unschedulable zero-capacity phantom
    nodes so snapshots of similar size share one compiled solver (bucketing
    discipline, SURVEY.md §7 "ragged shapes").
    """
    topology = topology.with_host_level()
    levels = topology.sorted_levels()
    n_real = len(nodes)
    n = pad_nodes_to if pad_nodes_to is not None else n_real
    if n < n_real:
        raise ValueError(f"pad_nodes_to={n} < node count {n_real}")
    r = len(resource_names)

    capacity = np.zeros((n, r), dtype=np.float32)
    schedulable = np.zeros((n,), dtype=bool)
    for i, node in enumerate(nodes):
        # A revocation-pending node is masked like a cordoned one: every
        # placement path (serving solves, defrag, rescue) reads this tensor,
        # so no new pod can land on capacity that is about to vanish.
        schedulable[i] = node.schedulable and node.revocation_deadline is None
        for j, res in enumerate(resource_names):
            capacity[i, j] = node.capacity.get(res, 0.0)

    node_domain_id = np.full((len(levels), n), -1, dtype=np.int32)
    domain_names: list[list[str]] = []
    num_domains = np.zeros((len(levels),), dtype=np.int32)
    # Invariant the solver's host-level identity fast path relies on
    # (solver/core.py agg_by_domain): host-level domain ordinal == node index.
    # Holds by construction: every node gets a host value (label or node name,
    # unique), and ordinals are assigned in node-enumeration order.
    # Domain identity is the PATH of label values down the hierarchy, not the
    # raw value: rack "rack-1" in zone "z0" is a different physical rack than
    # "rack-1" in zone "z1" (labels are commonly only unique within a parent).
    node_paths: list[tuple[str, ...]] = [() for _ in range(n_real)]
    for li, level in enumerate(levels):
        ordinals: dict[tuple[str, ...], int] = {}
        for i, node in enumerate(nodes):
            value = node.labels.get(level.node_label_key)
            if value is None and level.domain == TopologyDomain.HOST:
                value = node.name  # hostname label implied by node identity
            if value is None:
                continue
            path = node_paths[i] + (value,)
            node_paths[i] = path
            if path not in ordinals:
                ordinals[path] = len(ordinals)
            node_domain_id[li, i] = ordinals[path]
        domain_names.append(
            ["/".join(p) for p, _ in sorted(ordinals.items(), key=lambda kv: kv[1])]
        )
        num_domains[li] = len(ordinals)
        if level.domain == TopologyDomain.HOST and len(ordinals) != n_real:
            # Enforce, not just assume: a duplicate host label value would
            # merge two nodes into one host domain on the segment-sum path
            # while the TPU identity path keeps them separate — silent
            # backend-dependent admission divergence.
            raise ValueError(
                f"duplicate host-level domain values: {len(ordinals)} host "
                f"domains for {n_real} nodes (host labels must be unique)"
            )

    allocated = np.zeros_like(capacity)
    snap = ClusterSnapshot(
        resource_names=tuple(resource_names),
        node_names=[x.name for x in nodes],
        capacity=capacity,
        allocated=allocated,
        schedulable=schedulable,
        topology=topology,
        level_domains=[lv.domain for lv in levels],
        node_domain_id=node_domain_id,
        domain_names=domain_names,
        num_domains=num_domains,
        node_index_map={x.name: i for i, x in enumerate(nodes)},
        node_labels=[x.labels for x in nodes] + [{} for _ in range(n - n_real)],
        node_taints=[x.taints for x in nodes] + [[] for _ in range(n - n_real)],
    )
    for pod in bound_pods or []:
        # Skip stale bindings to nodes that no longer exist (routine race
        # between node deletion and pod cleanup) — the binding holds no
        # capacity on any node in this snapshot.
        if pod.node_name is not None and pod.node_name in snap.node_index_map:
            apply_binding(snap, pod)
    return snap


# Request-vector memo: keyed by (id(pod), id(spec), resource axis) with a
# weakref guard (a dead pod's recycled id can never serve a stale vector; a
# replaced spec object misses by key). The cached array is READ-ONLY so an
# accidental in-place mutation raises instead of corrupting every consumer.
# Same object-stability convention as the encode-row digest (solver/warm.py
# _pod_sig): live specs are replaced wholesale, never mutated in place.
_REQ_VEC_MEMO: dict[tuple, tuple] = {}
_REQ_VEC_MAX = 131072


def pod_request_vector(pod: Pod, resource_names: tuple[str, ...]) -> np.ndarray:
    import weakref

    key = (id(pod), id(pod.spec), resource_names)
    hit = _REQ_VEC_MEMO.get(key)
    if hit is not None and hit[0]() is pod:
        return hit[1]
    total = pod.spec.total_requests()
    vec = np.array([total.get(res, 0.0) for res in resource_names], dtype=np.float32)
    vec.setflags(write=False)
    try:
        if len(_REQ_VEC_MEMO) >= _REQ_VEC_MAX:
            _REQ_VEC_MEMO.clear()
        _REQ_VEC_MEMO[key] = (weakref.ref(pod), vec)
    except TypeError:
        pass  # un-weakref-able pod stand-ins: just recompute per call
    return vec


def apply_binding(snap: ClusterSnapshot, pod: Pod) -> None:
    """Account a bound pod's requests against its node."""
    idx = snap.node_index(pod.node_name)
    snap.allocated[idx] += pod_request_vector(pod, snap.resource_names)


def release_binding(snap: ClusterSnapshot, pod: Pod) -> None:
    idx = snap.node_index(pod.node_name)
    snap.allocated[idx] -= pod_request_vector(pod, snap.resource_names)
    np.maximum(snap.allocated[idx], 0.0, out=snap.allocated[idx])
