"""Dense cluster snapshot tensors."""

from grove_tpu.state.cluster import (  # noqa: F401
    DEFAULT_RESOURCES,
    ClusterSnapshot,
    Node,
    apply_binding,
    build_snapshot,
    pod_request_vector,
    release_binding,
)
