"""Event-driven cluster simulator — the KWOK/k3d analog (SURVEY.md §4).

Plays the kubelet/runtime role against the in-memory store: bound pods start
after a configurable delay, become Ready after another, honoring the startup
ordering gate (the grove-initc analog, orchestrator/startup.py). Fault
injection mirrors the e2e suite's techniques: fail pods, cordon nodes, kill
nodes (e2e/setup/k8s_clusters.go:130-244 restarts node containers;
gang_scheduling_test.go manipulates capacity by cordoning).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from grove_tpu.api.pod import PodPhase
from grove_tpu.orchestrator.controller import GroveController
from grove_tpu.orchestrator.startup import may_start
from grove_tpu.orchestrator.store import Cluster


@dataclass
class SimConfig:
    start_delay: float = 2.0  # bound -> containers running (image pull etc.)
    ready_delay: float = 3.0  # running -> Ready probes pass
    # Startup gate evaluation: "agent" drives the grove-initc code path (the
    # injected init container's own --podcliques args through
    # initc/agent.requirements_met, exactly what the binary runs); "predicate"
    # keeps the legacy pure-predicate gate (orchestrator/startup.may_start).
    startup_gate: str = "agent"


@dataclass(frozen=True)
class ScriptedFault:
    """One schedulable chaos action: at sim-time `at`, apply `action` to
    `target`. Actions are the simulator's own fault methods (kill_node,
    cordon, uncordon, fail_pod, crash_pod, revoke_node), so a script entry journals and
    behaves exactly like a hand-driven fault — but the schedule is DATA,
    shippable with a chaos scenario and replayable run after run."""

    at: float
    action: str
    target: str


@dataclass
class Simulator:
    cluster: Cluster
    controller: GroveController
    config: SimConfig = field(default_factory=SimConfig)
    now: float = 0.0
    # Deterministic chaos script: ScriptedFault entries (or (at, action,
    # target) tuples) executed when sim time reaches them — BEFORE the
    # reconcile pass, so a node killed at t lands between the previous
    # pass's bind and this pass's solve (the mid-wave death window the
    # stale-plan revalidation exists for). Order within one step follows
    # the schedule order.
    fault_script: list = field(default_factory=list)
    # Grace window granted with a revocation notice (revoke_node and the
    # sim.node_revocation injector site): revocation_deadline = now + grace.
    revocation_grace_s: float = 30.0
    _bound_at: dict[str, float] = field(default_factory=dict)
    _running_at: dict[str, float] = field(default_factory=dict)

    _SCRIPT_ACTIONS = (
        "kill_node",
        "cordon",
        "uncordon",
        "fail_pod",
        "crash_pod",
        "revoke_node",
    )

    def schedule_fault(self, at: float, action: str, target: str) -> None:
        """Append one scripted fault (validated; keeps the script sorted)."""
        if action not in self._SCRIPT_ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; one of "
                + "|".join(self._SCRIPT_ACTIONS)
            )
        self.fault_script.append(ScriptedFault(float(at), action, target))
        self.fault_script.sort(key=lambda f: f.at)

    def _run_script(self) -> None:
        """Execute (and consume) scripted faults due at or before `now`;
        entries scheduled in the past fire on the next step."""
        while self.fault_script:
            entry = self.fault_script[0]
            if not isinstance(entry, ScriptedFault):
                entry = ScriptedFault(*entry)
            if entry.at > self.now:
                break
            self.fault_script.pop(0)
            getattr(self, entry.action)(entry.target)
        # Injector-driven node death (site sim.node_death): kills the first
        # schedulable node in name order — deterministic under the seeded
        # schedule, no script needed.
        from grove_tpu import faults as faults_mod

        inj = faults_mod.active()
        if inj.enabled and inj.should_fire("sim.node_death") is not None:
            victim = next(
                (
                    name
                    for name in sorted(self.cluster.nodes)
                    if self.cluster.nodes[name].schedulable
                ),
                None,
            )
            if victim is not None:
                self.kill_node(victim)
        # Injector-driven revocation notice (site sim.node_revocation): the
        # first revocable, schedulable node without a pending notice, in name
        # order. Candidates are checked BEFORE the dice roll so a fleet with
        # nothing left to revoke doesn't consume (and journal) no-op fires.
        if inj.enabled and "sim.node_revocation" in inj.specs:
            victim = next(
                (
                    name
                    for name in sorted(self.cluster.nodes)
                    if (n := self.cluster.nodes[name]).revocable
                    and n.schedulable
                    and n.revocation_deadline is None
                ),
                None,
            )
            if victim is not None and inj.should_fire("sim.node_revocation") is not None:
                self.revoke_node(victim)
        # Expired notices: the capacity actually disappears — node-death
        # semantics for whatever the controller did not rescue in time.
        for name in sorted(self.cluster.nodes):
            node = self.cluster.nodes[name]
            if (
                node.revocation_deadline is not None
                and node.revocation_deadline <= self.now
                and node.schedulable
            ):
                self._journal_chaos("chaos.revocation_expired", name)
                self.kill_node(name)

    def step(self, dt: float = 1.0) -> None:
        """Advance time, run scripted chaos, pod lifecycle, then one
        reconcile pass."""
        self.now += dt
        self._run_script()
        self._lifecycle()
        self.controller.reconcile(self.now)
        self._lifecycle()  # let fresh bindings from this pass register

    def run(self, seconds: float, dt: float = 1.0) -> None:
        steps = int(seconds / dt)
        for _ in range(steps):
            self.step(dt)

    def run_until(self, predicate, timeout: float = 300.0, dt: float = 1.0) -> bool:
        deadline = self.now + timeout
        while self.now < deadline:
            self.step(dt)
            if predicate():
                return True
        return False

    # --- pod lifecycle -----------------------------------------------------------

    def _lifecycle(self) -> None:
        for pod in list(self.cluster.pods.values()):
            if not pod.is_active:
                continue
            if pod.is_scheduled and pod.name not in self._bound_at:
                self._bound_at[pod.name] = self.now
            if (
                pod.is_scheduled
                and pod.phase == PodPhase.PENDING
                and self.now - self._bound_at.get(pod.name, self.now) >= self.config.start_delay
                and self._startup_gate_open(pod)  # initc gate (wait.go:240-275)
            ):
                pod.phase = PodPhase.RUNNING
                pod.started_at = self.now
                self._running_at[pod.name] = self.now
            if (
                pod.phase == PodPhase.RUNNING
                and not pod.ready
                and not pod.crashlooping
                and self.now - self._running_at.get(pod.name, self.now) >= self.config.ready_delay
            ):
                pod.ready = True

    def _startup_gate_open(self, pod) -> bool:
        """Agent path: run the injected grove-initc container's own args
        through the agent's wait logic (one poll) against the store — sim pods
        start through the agent, not a parallel predicate. Pods without the
        container have no gate, exactly like the reference (initcontainer.go
        only injects for cliques with parents)."""
        if self.config.startup_gate != "agent":
            return may_start(self.cluster, pod)
        from grove_tpu.initc.agent import (
            parse_podcliques_arg,
            requirements_met,
            store_fetch,
        )
        from grove_tpu.orchestrator.expansion import INITC_CONTAINER_NAME

        initc = next(
            (c for c in pod.spec.init_containers if c.name == INITC_CONTAINER_NAME),
            None,
        )
        if initc is None:
            return True
        arg = next(
            (a for a in initc.args if a.startswith("--podcliques=")), "--podcliques="
        )
        reqs = parse_podcliques_arg(arg[len("--podcliques="):])
        return requirements_met(store_fetch(self.cluster), reqs)

    # --- fault injection ----------------------------------------------------------

    def _journal_chaos(self, action: str, obj: str, **fields) -> None:
        """Chaos events land in the flight-recorder journal (when the
        controller carries one) so an incident trace shows the fault that
        displaced a gang right next to the re-admission solve that healed
        it."""
        rec = getattr(self.controller, "recorder", None)
        if rec is None:
            return
        try:
            rec.capture_action(self.now, action, obj, **fields)
        except Exception:  # noqa: BLE001 — tracing must never break the sim
            pass

    def fail_pod(self, pod_name: str) -> None:
        """Hard failure (eviction/OOM-kill of the pod): phase Failed, inactive,
        replaced by the clique controller."""
        pod = self.cluster.pods.get(pod_name)
        if pod is None:
            return
        pod.phase = PodPhase.FAILED
        pod.ready = False
        self.cluster.record_event(self.now, pod.pclq_fqn, f"pod {pod_name} failed")
        self._journal_chaos("chaos.fail_pod", pod_name, clique=pod.pclq_fqn)

    def crash_pod(self, pod_name: str) -> None:
        """Crash loop: container exits non-zero and restarts forever. The pod
        stays bound and active but never Ready — the state that drives
        MinAvailableBreached and eventually gang termination."""
        pod = self.cluster.pods.get(pod_name)
        if pod is None:
            return
        pod.crashlooping = True
        pod.ready = False
        self.cluster.record_event(self.now, pod.pclq_fqn, f"pod {pod_name} crash-looping")
        self._journal_chaos("chaos.crash_pod", pod_name, clique=pod.pclq_fqn)

    def cordon(self, node_name: str) -> None:
        self.cluster.nodes[node_name].schedulable = False
        self._journal_chaos("chaos.cordon", node_name)

    def uncordon(self, node_name: str) -> None:
        self.cluster.nodes[node_name].schedulable = True
        self._journal_chaos("chaos.uncordon", node_name)

    def kill_node(self, node_name: str) -> None:
        """Node dies: cordon + every pod on it fails."""
        self._journal_chaos("chaos.kill_node", node_name)
        self.cordon(node_name)
        for pod in self.cluster.pods.values():
            if pod.node_name == node_name and pod.is_active:
                self.fail_pod(pod.name)

    def revoke_node(self, node_name: str) -> None:
        """Revocation notice: the node's capacity disappears at
        now + revocation_grace_s. The node is marked revocable (a scripted
        notice on a permanent node models a spot conversion), the deadline is
        stamped, and the controller gets the grace window to migrate or evict
        residents; whatever remains dies with the node when the deadline
        expires (see _run_script)."""
        node = self.cluster.nodes.get(node_name)
        if node is None or node.revocation_deadline is not None:
            return
        node.revocable = True
        node.revocation_deadline = self.now + self.revocation_grace_s
        self.cluster.record_event(
            self.now, node_name, f"node {node_name} revocation notice"
        )
        self._journal_chaos(
            "chaos.revoke_node", node_name, deadline=node.revocation_deadline
        )
