"""Synthetic cluster generation + event-driven simulation."""

from grove_tpu.sim.simulator import SimConfig, Simulator  # noqa: F401
