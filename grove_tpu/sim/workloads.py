"""Synthetic clusters and workload backlogs for benchmarks and scale tests.

Shapes mirror the reference's sample workloads and e2e fixtures
(operator/samples/user-guide/01_core-concepts/*.yaml: single-node
disaggregated, multi-node aggregated leader/worker, multi-node disaggregated;
scale rig: KWOK fake nodes, operator/hack/kind-up.sh:252-265; topology label
shape: operator/hack/e2e-cluster/create-e2e-cluster.py:133-135).

The TPU analog of the GPU fleet: hosts carry `google.com/tpu` chips, racks are
the ICI-domain analog (pack constraints target them), zones/blocks the DCN
level.
"""

from __future__ import annotations

from typing import Any

from grove_tpu.api import PodCliqueSet, default_podcliqueset
from grove_tpu.api.types import ClusterTopology, TopologyDomain, TopologyLevel
from grove_tpu.state.cluster import Node

ZONE_KEY = "topology.kubernetes.io/zone"
BLOCK_KEY = "topology.kubernetes.io/block"
RACK_KEY = "topology.kubernetes.io/rack"


def bench_topology() -> ClusterTopology:
    return ClusterTopology(
        name="bench",
        levels=[
            TopologyLevel(TopologyDomain.ZONE, ZONE_KEY),
            TopologyLevel(TopologyDomain.BLOCK, BLOCK_KEY),
            TopologyLevel(TopologyDomain.RACK, RACK_KEY),
        ],
    )


def synthetic_cluster(
    zones: int = 4,
    blocks_per_zone: int = 4,
    racks_per_block: int = 16,
    hosts_per_rack: int = 20,
    cpu: float = 32.0,
    memory: float = 128 * 2**30,
    tpu: float = 8.0,
) -> list[Node]:
    """Defaults: 4*4*16*20 = 5120 hosts — the 5k-node north-star scale."""
    nodes: list[Node] = []
    for z in range(zones):
        for b in range(blocks_per_zone):
            for r in range(racks_per_block):
                for h in range(hosts_per_rack):
                    nodes.append(
                        Node(
                            name=f"z{z}b{b}r{r}h{h}",
                            capacity={
                                "cpu": cpu,
                                "memory": memory,
                                "google.com/tpu": tpu,
                            },
                            labels={
                                ZONE_KEY: f"z{z}",
                                BLOCK_KEY: f"b{b}",
                                RACK_KEY: f"r{r}",
                            },
                        )
                    )
    return nodes


def _clique(name: str, replicas: int, cpu: str, tpu: int = 0,
            min_available: int | None = None) -> dict[str, Any]:
    requests: dict[str, Any] = {"cpu": cpu, "memory": "1Gi"}
    if tpu:
        requests["google.com/tpu"] = str(tpu)
    spec: dict[str, Any] = {
        "roleName": name,
        "replicas": replicas,
        "podSpec": {
            "containers": [
                {"name": name, "image": f"registry.local/{name}:latest",
                 "resources": {"requests": requests}}
            ]
        },
    }
    if min_available is not None:
        spec["minAvailable"] = min_available
    return {"name": name, "spec": spec}


def _pcs(name: str, cliques: list[dict], scaling_groups: list[dict] | None = None,
         constraint_domain: str | None = None, replicas: int = 1) -> PodCliqueSet:
    template: dict[str, Any] = {
        "cliques": cliques,
        "startupType": "CliqueStartupTypeAnyOrder",
    }
    if scaling_groups:
        template["podCliqueScalingGroups"] = scaling_groups
    if constraint_domain:
        template["topologyConstraint"] = {"packDomain": constraint_domain}
    doc = {
        "apiVersion": "grove.io/v1alpha1",
        "kind": "PodCliqueSet",
        "metadata": {"name": name},
        "spec": {"replicas": replicas, "template": template},
    }
    return default_podcliqueset(PodCliqueSet.from_dict(doc))


def disagg_pcs(name: str) -> PodCliqueSet:
    """Single-node-disaggregated shape: prefill+decode scaled together behind a
    router, PCSG rack-packed (single-node-disaggregated.yaml pattern)."""
    return _pcs(
        name,
        cliques=[
            _clique("router", 2, "500m"),
            _clique("prefill", 4, "1", tpu=1),
            _clique("decode", 4, "1", tpu=1),
        ],
        scaling_groups=[
            {
                "name": "workers",
                "cliqueNames": ["prefill", "decode"],
                "replicas": 2,
                "minAvailable": 1,
                "topologyConstraint": {"packDomain": "rack"},
            }
        ],
    )


def aggregated_pcs(name: str) -> PodCliqueSet:
    """Multi-node-aggregated shape: leader + workers gang, rack-required
    (multi-node-aggregated.yaml pattern)."""
    return _pcs(
        name,
        cliques=[
            _clique("frontend", 2, "500m"),
            _clique("leader", 1, "1", tpu=2),
            _clique("worker", 7, "1", tpu=2),
        ],
        scaling_groups=[
            {
                "name": "model",
                "cliqueNames": ["leader", "worker"],
                "replicas": 1,
                "minAvailable": 1,
                "topologyConstraint": {"packDomain": "rack"},
            }
        ],
        constraint_domain="block",
    )


def frontend_pcs(name: str) -> PodCliqueSet:
    """Small standalone-clique workload (simple1 frontend analog)."""
    return _pcs(name, cliques=[_clique("frontend", 4, "250m")])


def synthetic_backlog(
    n_disagg: int = 350, n_agg: int = 250, n_frontend: int = 300
) -> list[PodCliqueSet]:
    """~10k pods with defaults: 350*18 + 250*10 + 300*4 = 10000."""
    out: list[PodCliqueSet] = []
    for i in range(n_disagg):
        out.append(disagg_pcs(f"disagg-{i}"))
    for i in range(n_agg):
        out.append(aggregated_pcs(f"agg-{i}"))
    for i in range(n_frontend):
        out.append(frontend_pcs(f"fe-{i}"))
    return out
