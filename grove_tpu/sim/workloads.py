"""Synthetic clusters and workload backlogs for benchmarks and scale tests.

Shapes mirror the reference's sample workloads and e2e fixtures
(operator/samples/user-guide/01_core-concepts/*.yaml: single-node
disaggregated, multi-node aggregated leader/worker, multi-node disaggregated;
scale rig: KWOK fake nodes, operator/hack/kind-up.sh:252-265; topology label
shape: operator/hack/e2e-cluster/create-e2e-cluster.py:133-135).

The TPU analog of the GPU fleet: hosts carry `google.com/tpu` chips, racks are
the ICI-domain analog (pack constraints target them), zones/blocks the DCN
level.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

from grove_tpu.api import PodCliqueSet, default_podcliqueset
from grove_tpu.api.types import ClusterTopology, TopologyDomain, TopologyLevel
from grove_tpu.state.cluster import Node

ZONE_KEY = "topology.kubernetes.io/zone"
BLOCK_KEY = "topology.kubernetes.io/block"
RACK_KEY = "topology.kubernetes.io/rack"


def bench_topology() -> ClusterTopology:
    return ClusterTopology(
        name="bench",
        levels=[
            TopologyLevel(TopologyDomain.ZONE, ZONE_KEY),
            TopologyLevel(TopologyDomain.BLOCK, BLOCK_KEY),
            TopologyLevel(TopologyDomain.RACK, RACK_KEY),
        ],
    )


def synthetic_cluster(
    zones: int = 4,
    blocks_per_zone: int = 4,
    racks_per_block: int = 16,
    hosts_per_rack: int = 20,
    cpu: float = 32.0,
    memory: float = 128 * 2**30,
    tpu: float = 8.0,
) -> list[Node]:
    """Defaults: 4*4*16*20 = 5120 hosts — the 5k-node north-star scale."""
    nodes: list[Node] = []
    for z in range(zones):
        for b in range(blocks_per_zone):
            for r in range(racks_per_block):
                for h in range(hosts_per_rack):
                    nodes.append(
                        Node(
                            name=f"z{z}b{b}r{r}h{h}",
                            capacity={
                                "cpu": cpu,
                                "memory": memory,
                                "google.com/tpu": tpu,
                            },
                            labels={
                                ZONE_KEY: f"z{z}",
                                BLOCK_KEY: f"b{b}",
                                RACK_KEY: f"r{r}",
                            },
                        )
                    )
    return nodes


def _clique(name: str, replicas: int, cpu: str, tpu: int = 0,
            min_available: int | None = None) -> dict[str, Any]:
    requests: dict[str, Any] = {"cpu": cpu, "memory": "1Gi"}
    if tpu:
        requests["google.com/tpu"] = str(tpu)
    spec: dict[str, Any] = {
        "roleName": name,
        "replicas": replicas,
        "podSpec": {
            "containers": [
                {"name": name, "image": f"registry.local/{name}:latest",
                 "resources": {"requests": requests}}
            ]
        },
    }
    if min_available is not None:
        spec["minAvailable"] = min_available
    return {"name": name, "spec": spec}


def _pcs(name: str, cliques: list[dict], scaling_groups: list[dict] | None = None,
         constraint_domain: str | None = None, replicas: int = 1) -> PodCliqueSet:
    template: dict[str, Any] = {
        "cliques": cliques,
        "startupType": "CliqueStartupTypeAnyOrder",
    }
    if scaling_groups:
        template["podCliqueScalingGroups"] = scaling_groups
    if constraint_domain:
        template["topologyConstraint"] = {"packDomain": constraint_domain}
    doc = {
        "apiVersion": "grove.io/v1alpha1",
        "kind": "PodCliqueSet",
        "metadata": {"name": name},
        "spec": {"replicas": replicas, "template": template},
    }
    return default_podcliqueset(PodCliqueSet.from_dict(doc))


def disagg_pcs(name: str) -> PodCliqueSet:
    """Single-node-disaggregated shape: prefill+decode scaled together behind a
    router, PCSG rack-packed (single-node-disaggregated.yaml pattern)."""
    return _pcs(
        name,
        cliques=[
            _clique("router", 2, "500m"),
            _clique("prefill", 4, "1", tpu=1),
            _clique("decode", 4, "1", tpu=1),
        ],
        scaling_groups=[
            {
                "name": "workers",
                "cliqueNames": ["prefill", "decode"],
                "replicas": 2,
                "minAvailable": 1,
                "topologyConstraint": {"packDomain": "rack"},
            }
        ],
    )


def aggregated_pcs(name: str) -> PodCliqueSet:
    """Multi-node-aggregated shape: leader + workers gang, rack-required
    (multi-node-aggregated.yaml pattern)."""
    return _pcs(
        name,
        cliques=[
            _clique("frontend", 2, "500m"),
            _clique("leader", 1, "1", tpu=2),
            _clique("worker", 7, "1", tpu=2),
        ],
        scaling_groups=[
            {
                "name": "model",
                "cliqueNames": ["leader", "worker"],
                "replicas": 1,
                "minAvailable": 1,
                "topologyConstraint": {"packDomain": "rack"},
            }
        ],
        constraint_domain="block",
    )


def frontend_pcs(name: str) -> PodCliqueSet:
    """Small standalone-clique workload (simple1 frontend analog)."""
    return _pcs(name, cliques=[_clique("frontend", 4, "250m")])


def synthetic_backlog(
    n_disagg: int = 350, n_agg: int = 250, n_frontend: int = 300
) -> list[PodCliqueSet]:
    """~10k pods with defaults: 350*18 + 250*10 + 300*4 = 10000."""
    out: list[PodCliqueSet] = []
    for i in range(n_disagg):
        out.append(disagg_pcs(f"disagg-{i}"))
    for i in range(n_agg):
        out.append(aggregated_pcs(f"agg-{i}"))
    for i in range(n_frontend):
        out.append(frontend_pcs(f"fe-{i}"))
    return out


# --- contended quality scenario (round-2 weak #5) ---------------------------------
#
# The uncontended bench admits 100% both ways, proving nothing. This scenario
# makes the batched solver and the per-pod greedy cycle diverge on a property
# the reference path genuinely lacks: HIERARCHICAL feasibility. "Trap" blocks
# are the best-fit choice by aggregate capacity/slots, but their free hosts
# are spread one-per-rack, so a rack-packed group can never fit inside them;
# "good" blocks look worse (more free) but hold whole racks of empty hosts.
# A scheduler that commits the block before checking rack nesting (greedy,
# KAI-style Filter/Score/Permit) strands every gang on a trap; the solver's
# nested-feasibility guard (solver/core.py) skips traps outright.


def contended_cluster(
    trap_blocks: int = 8,
    good_blocks: int = 8,
    racks_per_block: int = 4,
    hosts_per_rack: int = 4,
    cpu: float = 8.0,
    memory: float = 32 * 2**30,
) -> tuple[list[Node], list]:
    """Returns (nodes, squatter_pods). Squatters pre-occupy capacity:

    - trap blocks: every rack keeps ONE empty host (block slots ample,
      rack slots insufficient for a 2-pod rack-packed gang)
    - good blocks: every rack keeps TWO empty hosts (gang fits), total free
      2x the trap's — so best-fit aggregate ordering prefers traps
    """
    from grove_tpu.api.pod import Pod
    from grove_tpu.api.types import Container as C, PodSpec

    nodes: list[Node] = []
    squatters: list = []

    def host(b: int, r: int, h: int) -> Node:
        return Node(
            name=f"cb{b}r{r}h{h}",
            capacity={"cpu": cpu, "memory": memory},
            labels={ZONE_KEY: "z0", BLOCK_KEY: f"b{b}", RACK_KEY: f"r{r}"},
        )

    def squat(node: Node, frac: float, idx: int) -> None:
        squatters.append(
            Pod(
                name=f"squat-{idx}",
                spec=PodSpec(
                    containers=[
                        C(name="s", requests={"cpu": cpu * frac, "memory": memory * frac})
                    ]
                ),
                node_name=node.name,
                pclq_fqn="squatters",
            )
        )

    si = 0
    for b in range(trap_blocks + good_blocks):
        empty_per_rack = 1 if b < trap_blocks else 2
        for r in range(racks_per_block):
            for h in range(hosts_per_rack):
                node = host(b, r, h)
                nodes.append(node)
                if h >= empty_per_rack:  # fully squat the non-empty hosts
                    squat(node, 1.0, si)
                    si += 1
    return nodes, squatters


def contended_backlog(n_gangs: int = 24) -> list[PodCliqueSet]:
    """Rack-packed 2-pod full-host gangs under a block-level gang constraint."""
    out = []
    for i in range(n_gangs):
        doc = {
            "apiVersion": "grove.io/v1alpha1",
            "kind": "PodCliqueSet",
            "metadata": {"name": f"packed-{i}"},
            "spec": {
                "replicas": 1,
                "template": {
                    "startupType": "CliqueStartupTypeAnyOrder",
                    "topologyConstraint": {"packDomain": "block"},
                    "cliques": [
                        {
                            "name": "w",
                            "topologyConstraint": {"packDomain": "rack"},
                            "spec": {
                                "roleName": "w",
                                "replicas": 2,
                                "podSpec": {
                                    "containers": [
                                        {
                                            "name": "w",
                                            "image": "registry.local/w:latest",
                                            "resources": {
                                                "requests": {"cpu": "8", "memory": "32Gi"}
                                            },
                                        }
                                    ]
                                },
                            },
                        }
                    ],
                },
            },
        }
        out.append(default_podcliqueset(PodCliqueSet.from_dict(doc)))
    return out


def binpack_trap_cluster(n_nodes: int = 6, node_cpu: float = 7.0) -> list[Node]:
    """Identical nodes sized so only one packing admits the whole trap
    backlog (see binpack_trap_backlog)."""
    return [
        Node(
            name=f"bp-{i}",
            capacity={"cpu": node_cpu, "memory": 64.0 * 2**30},
            labels={
                "topology.kubernetes.io/zone": "z0",
                "topology.kubernetes.io/block": "b0",
                "topology.kubernetes.io/rack": f"r{i}",
            },
        )
        for i in range(n_nodes)
    ]


def binpack_trap_backlog(n_pairs: int = 6) -> list[PodCliqueSet]:
    """The packing-polarity trap (portfolio quality scenario).

    n_pairs small gangs (3 cpu) arrive BEFORE n_pairs big gangs (4 cpu) on
    n_pairs 7-cpu nodes — demand exactly equals capacity, so only the
    4+3-per-node pairing admits everything. Best-fit doubles the smalls up
    (3+3 on one node leaves 1 cpu: dead) and strands bigs; worst-fit
    (spread-first, negative w_tight) spreads the smalls one-per-node and
    every big fits. No single score polarity wins both this and the tight-
    consolidation workloads — which is exactly the regime the solver
    portfolio (parallel/portfolio.py params_population) exists for.
    """

    def one(name: str, cpu: str) -> PodCliqueSet:
        doc = {
            "apiVersion": "grove.io/v1alpha1",
            "kind": "PodCliqueSet",
            "metadata": {"name": name},
            "spec": {
                "replicas": 1,
                "template": {
                    "cliques": [
                        {
                            "name": "w",
                            "spec": {
                                "roleName": "w",
                                "replicas": 1,
                                "podSpec": {
                                    "containers": [
                                        {
                                            "name": "w",
                                            "image": "registry.local/w:latest",
                                            "resources": {"requests": {"cpu": cpu}},
                                        }
                                    ]
                                },
                            },
                        }
                    ],
                },
            },
        }
        return default_podcliqueset(PodCliqueSet.from_dict(doc))

    smalls = [one(f"bp-small-{i}", "3") for i in range(n_pairs)]
    bigs = [one(f"bp-big-{i}", "4") for i in range(n_pairs)]
    return smalls + bigs


# --- placement-quality scenario: mixed Required / Preferred pack-sets ------------
#
# The synthetic bench backlog carries only REQUIRED pack-sets, so every
# admitted gang scores exactly 1.0 and solver-vs-greedy score comparisons
# are vacuous (round-5 verdict: saturated quality metrics). These workloads
# make `placement_score < 1.0` reachable: Preferred gangs are sized so the
# backlog exactly fills the fleet — once Required gangs carve 2-host chunks
# out of racks, the remnants cannot hold a whole Preferred gang, and every
# policy must split SOME of them across racks (score < 1.0). How MUCH each
# policy splits is the discriminating signal.


def quality_cluster(
    blocks: int = 2,
    racks_per_block: int = 4,
    hosts_per_rack: int = 4,
    cpu: float = 8.0,
    memory: float = 32 * 2**30,
) -> list[Node]:
    """Small empty fleet for the mixed-quality scenario (one zone; rack is
    the contended preferred level)."""
    return synthetic_cluster(
        zones=1,
        blocks_per_zone=blocks,
        racks_per_block=racks_per_block,
        hosts_per_rack=hosts_per_rack,
        cpu=cpu,
        memory=memory,
    )


def required_pcs(name: str, pods: int = 2, cpu: str = "8") -> PodCliqueSet:
    """Full-host gang with a REQUIRED rack pack (all-or-nothing in one rack)."""
    return _pcs(
        name,
        cliques=[_clique("w", pods, cpu, min_available=pods)],
        constraint_domain="rack",
    )


def preferred_pcs(name: str, pods: int = 3, cpu: str = "8") -> PodCliqueSet:
    """Full-host gang with a PREFERRED rack pack: admission never depends on
    the rack, but the PlacementScore does — the NetworkPackGroupConfigs
    soft-pack semantics (podgang.go:101-117 Preferred)."""
    doc = {
        "apiVersion": "grove.io/v1alpha1",
        "kind": "PodCliqueSet",
        "metadata": {"name": name},
        "spec": {
            "replicas": 1,
            "template": {
                "startupType": "CliqueStartupTypeAnyOrder",
                "topologyConstraint": {"preferredDomain": "rack"},
                "cliques": [
                    _clique("w", pods, cpu, min_available=pods)
                ],
            },
        },
    }
    return default_podcliqueset(PodCliqueSet.from_dict(doc))


def mixed_backlog(
    n_required: int = 4,
    n_preferred: int = 8,
    required_pods: int = 2,
    preferred_pods: int = 3,
    cpu: str = "8",
) -> list[PodCliqueSet]:
    """Required gangs first (they carve the racks), then Preferred gangs.

    Defaults fill `quality_cluster()` exactly: 4*2 + 8*3 = 32 full-host pods
    on 2 blocks x 4 racks x 4 hosts = 32 hosts — every gang is admissible,
    but the 3-pod Preferred gangs cannot all find whole racks once the
    2-host Required chunks land, so mean placement score < 1.0 for ANY
    policy and the solver-vs-greedy delta is real signal.
    """
    out: list[PodCliqueSet] = []
    for i in range(n_required):
        out.append(required_pcs(f"mix-req-{i}", pods=required_pods, cpu=cpu))
    for i in range(n_preferred):
        out.append(preferred_pcs(f"mix-pref-{i}", pods=preferred_pods, cpu=cpu))
    return out


# --- streaming arrival process (BandPilot-shaped live traffic) --------------------
#
# The drain scenarios above hand the solver a backlog that exists all at
# once. The streaming drain (solver/stream.py) needs the opposite: traffic
# that ARRIVES — bursty, diurnally modulated, heavy-tailed, multi-tenant —
# so steady-state gangs/sec and time-to-bind are measured against a live
# queue instead of a pre-staged list. The generator is deterministic in its
# seed (same seed => identical trace: timestamps, tenants, kinds, sizes,
# names), which is what lets the serial and pipelined disciplines be
# parity-checked on IDENTICAL offered work and lets tests pin traces.


@dataclass(frozen=True)
class ArrivalEvent:
    """One gang-workload arrival in a generated trace."""

    t: float  # seconds offset from stream start
    name: str  # PCS name (unique within the trace)
    tenant: str
    kind: str  # frontend | disagg | train
    size: int  # worker replicas (train; heavy-tailed), else the fixed shape
    slo_class: str = "standard"  # api.constants.SLO_CLASSES member


def _slo_pick(seed: int, tenant: str, seq: int, slo_mix: tuple) -> str:
    """Stable per-(tenant, seq) SLO-class draw for arrival_process.

    Keyed on a hash rather than the trace RNG on purpose: adding slo_mix to
    an existing trace must not perturb the main generator's draw sequence,
    so a (seed, slo_mix=None) trace is bitwise-identical to what the
    generator produced before the field existed, and turning slo_mix on
    changes ONLY the slo_class column. Each tenant sees its own
    deterministic class sequence (seq counts that tenant's arrivals), so
    the per-tenant mix converges to the requested weights independent of
    how tenants interleave."""
    import hashlib

    digest = hashlib.blake2b(
        f"{seed}:{tenant}:{seq}".encode(), digest_size=8
    ).digest()
    u = int.from_bytes(digest, "big") / 2.0**64
    total = sum(w for _, w in slo_mix)
    acc = 0.0
    for cls, w in slo_mix:
        acc += w / total
        if u < acc:
            return cls
    return slo_mix[-1][0]


def arrival_process(
    seed: int,
    duration_s: float = 30.0,
    base_rate: float = 4.0,  # gangs/sec, mean of the diurnal cycle
    diurnal_amplitude: float = 0.5,  # 0 = flat rate
    diurnal_period_s: float = 20.0,  # one "day" of the modulation
    burst_rate: float = 0.1,  # burst episodes/sec (0 = pure Poisson)
    burst_size_mean: float = 6.0,  # mean extra arrivals per episode
    burst_span_s: float = 0.5,  # episode arrivals land inside this span
    pareto_alpha: float = 1.6,  # train-gang size tail (smaller = heavier)
    max_workers: int = 16,  # train-gang size cap (keeps gangs admissible)
    tenants: int = 6,
    active_tenants: int = 3,  # concurrently-active tenant subset size
    tenant_churn_s: float = 10.0,  # active-set rotation period
    mix: tuple = (("frontend", 0.45), ("disagg", 0.35), ("train", 0.20)),
    slo_mix: tuple | None = None,  # ((slo_class, weight), ...) per-tenant mix
) -> list[ArrivalEvent]:
    """Deterministic arrival trace: inhomogeneous Poisson (diurnal rate
    modulation via thinning) + compound burst episodes, heavy-tailed train
    gang sizes (truncated Pareto), and multi-tenant churn (a rotating
    active-tenant window — tenants come and go on `tenant_churn_s`).

    Events are returned sorted by offset; names embed (kind, tenant, seq) so
    two traces are comparable field-by-field.

    `slo_mix`: optional ((slo_class, weight), ...) tuple. When given, every
    event's slo_class is drawn from the mix via a stable hash of
    (seed, tenant, that tenant's arrival sequence number) — see _slo_pick —
    so the draw is deterministic in the seed, per-tenant, and does NOT
    consume main-RNG entropy: the rest of the trace (times, tenants, kinds,
    sizes, names) is bitwise-identical with slo_mix on or off.
    """
    import numpy as np

    rng = np.random.default_rng(seed)
    two_pi = 2.0 * math.pi

    def rate(t: float) -> float:
        if diurnal_amplitude <= 0.0:
            return base_rate
        return base_rate * (
            1.0 + diurnal_amplitude * math.sin(two_pi * t / diurnal_period_s)
        )

    # Base process: thinning against the diurnal peak rate.
    lam_max = base_rate * (1.0 + max(0.0, diurnal_amplitude))
    times: list[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / lam_max)) if lam_max > 0 else duration_s
        if t >= duration_s:
            break
        if float(rng.uniform()) * lam_max <= rate(t):
            times.append(t)
    # Burst episodes: a compound Poisson overlay — each episode drops a
    # geometric-sized clump of arrivals inside `burst_span_s`.
    if burst_rate > 0:
        bt = 0.0
        while True:
            bt += float(rng.exponential(1.0 / burst_rate))
            if bt >= duration_s:
                break
            clump = int(rng.geometric(1.0 / max(1.0, burst_size_mean)))
            offs = rng.uniform(0.0, burst_span_s, size=clump)
            times.extend(
                min(duration_s, bt + float(o)) for o in np.sort(offs)
            )
    times.sort()

    tenant_names = [f"tenant{i}" for i in range(max(1, tenants))]
    window_size = max(1, min(active_tenants, len(tenant_names)))
    kinds = [k for k, _ in mix]
    weights = np.asarray([w for _, w in mix], dtype=np.float64)
    weights = weights / weights.sum()

    events: list[ArrivalEvent] = []
    tenant_seq: dict[str, int] = {}
    for i, at in enumerate(times):
        # Tenant churn: the active window slides one tenant per churn period,
        # so over the trace every tenant enters and leaves the mix.
        window = int(at // tenant_churn_s) if tenant_churn_s > 0 else 0
        active = [
            tenant_names[(window + j) % len(tenant_names)]
            for j in range(window_size)
        ]
        tenant = active[int(rng.integers(0, len(active)))]
        kind = kinds[int(rng.choice(len(kinds), p=weights))]
        if kind == "train":
            # Heavy-tailed worker counts: truncated Pareto — most gangs are
            # small, the tail asks for a whole rack's worth.
            size = min(max_workers, 1 + int(rng.pareto(pareto_alpha) * 2.0))
        elif kind == "disagg":
            size = 18  # disagg_pcs pod count (fixed shape)
        else:
            size = 4  # frontend_pcs pod count (fixed shape)
        seq = tenant_seq.get(tenant, 0)
        tenant_seq[tenant] = seq + 1
        slo = (
            _slo_pick(seed, tenant, seq, slo_mix)
            if slo_mix
            else "standard"
        )
        events.append(
            ArrivalEvent(
                t=round(float(at), 6),
                name=f"{kind[0]}-{tenant}-{i:05d}",
                tenant=tenant,
                kind=kind,
                size=size,
                slo_class=slo,
            )
        )
    return events


def arrival_pcs(ev: ArrivalEvent) -> PodCliqueSet:
    """Build the PodCliqueSet for one arrival event (pure in the event)."""
    if ev.kind == "frontend":
        pcs = frontend_pcs(ev.name)
    elif ev.kind == "disagg":
        pcs = disagg_pcs(ev.name)
    else:
        # train: rack-packed all-or-nothing gang, heavy-tailed worker count.
        pcs = _pcs(
            ev.name,
            cliques=[_clique("w", ev.size, "1", tpu=1, min_available=ev.size)],
            constraint_domain="rack",
        )
    if ev.slo_class:
        # Stamp the event's SLO class onto the template so expansion carries
        # it into every PodGang of the set (orchestrator/expansion.py).
        pcs.spec.template.slo_class = ev.slo_class
    return pcs


def expand_arrivals(
    events: list[ArrivalEvent], topology: ClusterTopology | None = None
) -> tuple[list, dict]:
    """ArrivalEvents -> ([(t_offset, PodGang)], {pod name: Pod}) for the
    streaming drain. Gangs of one event share its offset in expansion order,
    which places a base gang before every gang scaled from it — the ordering
    invariant drain_stream relies on (scaled verdicts resolve through the
    ok_global device chain when the base landed in an earlier wave)."""
    from grove_tpu.orchestrator import expand_podcliqueset

    topo = topology or bench_topology()
    arrivals: list = []
    pods: dict = {}
    for ev in events:
        ds = expand_podcliqueset(arrival_pcs(ev), topo)
        for g in ds.podgangs:
            arrivals.append((ev.t, g))
        pods.update({p.name: p for p in ds.pods})
    return arrivals, pods


def fragmented_backlog(
    racks: int,
    hosts_per_rack: int = 8,
    squat_pods_per_rack: int = 2,
    tpu_per_host: int = 8,
) -> tuple[list[PodCliqueSet], PodCliqueSet]:
    """Defrag-scenario workloads: (squatters, large rack-packed gang).

    One squatter PCS per rack — `squat_pods_per_rack` full-host pods each.
    With every squatter bound in a DIFFERENT rack (the bench scatters them;
    churn does it organically in the sim), every rack keeps
    `hosts_per_rack - squat_pods_per_rack` free hosts, so the large gang
    (`hosts_per_rack` full-host pods, REQUIRED rack pack) fails admission
    even though total free capacity is several racks' worth — until the
    defrag planner consolidates the squatters.
    """
    squatters = [
        _pcs(
            f"frag-squat-{i}",
            [
                _clique(
                    "sq",
                    squat_pods_per_rack,
                    cpu="4",
                    tpu=tpu_per_host,
                    min_available=squat_pods_per_rack,
                )
            ],
        )
        for i in range(racks)
    ]
    big = _pcs(
        "frag-big",
        [
            _clique(
                "big",
                hosts_per_rack,
                cpu="4",
                tpu=tpu_per_host,
                min_available=hosts_per_rack,
            )
        ],
        constraint_domain="rack",
    )
    return squatters, big
