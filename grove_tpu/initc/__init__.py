from grove_tpu.initc.agent import (  # noqa: F401
    Requirement,
    http_fetch,
    parse_podcliques_arg,
    store_fetch,
    wait_until_ready,
)
