"""grove-initc: the startup-ordering agent.

Executable analog of the reference's `grove-initc` binary
(`operator/initc/cmd/main.go`, `operator/initc/internal/wait.go:111-275`):
injected as an init container into pods of cliques with startup parents, it
blocks the user containers until every parent PodClique has at least
minAvailable Ready pods, then exits 0.

Arg format matches the reference injection
(`podclique/components/pod/initcontainer.go:142-158`):

    python -m grove_tpu.initc --podcliques=<fqn>:<minAvailable>[,<fqn>:<min>...] \
        --server http://127.0.0.1:2751 [--poll-interval 1.0] [--timeout 900]

Where the reference informer-watches gang pods through the apiserver with the
pod's projected ServiceAccount token, this agent polls the manager's HTTP API
(`/api/v1/podcliques/<fqn>`) — the apiserver analog in this stack. The wait
loop itself is a pure function over a `fetch` callable so the simulator
drives the exact same code against the in-process store.
"""

from __future__ import annotations

import json
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Iterable, Optional


@dataclass(frozen=True)
class Requirement:
    """One parent gate: clique FQN must have >= min_available Ready pods."""

    fqn: str
    min_available: int


def parse_podcliques_arg(value: str) -> list[Requirement]:
    """`a-0-prefill:2,a-0-router:1` -> [Requirement(...), ...] (options.go)."""
    reqs: list[Requirement] = []
    for part in value.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"--podcliques entry {part!r}: want <fqn>:<minAvailable>")
        fqn, _, min_s = part.rpartition(":")
        try:
            min_avail = int(min_s)
        except ValueError:
            raise ValueError(f"--podcliques entry {part!r}: minAvailable not an int")
        if not fqn or min_avail < 0:
            raise ValueError(f"--podcliques entry {part!r}: invalid")
        reqs.append(Requirement(fqn=fqn, min_available=min_avail))
    return reqs


# fetch: fqn -> (ready_count, exists). Missing cliques gate (wait.go treats a
# not-yet-created parent as not ready).
FetchFn = Callable[[str], tuple[int, bool]]


def requirements_met(fetch: FetchFn, reqs: Iterable[Requirement]) -> bool:
    for req in reqs:
        ready, exists = fetch(req.fqn)
        if not exists or ready < req.min_available:
            return False
    return True


def wait_until_ready(
    fetch: FetchFn,
    reqs: list[Requirement],
    *,
    timeout_s: float = 900.0,
    poll_interval_s: float = 1.0,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    on_poll: Optional[Callable[[int], None]] = None,
) -> bool:
    """Block until all requirements are met; False on timeout (exit 1)."""
    deadline = clock() + timeout_s
    polls = 0
    while True:
        if requirements_met(fetch, reqs):
            return True
        polls += 1
        if on_poll is not None:
            on_poll(polls)
        if clock() >= deadline:
            return False
        sleep(poll_interval_s)


def http_fetch(
    server: str,
    timeout_s: float = 5.0,
    token: str | None = None,
    cafile: str | None = None,
) -> FetchFn:
    """Poll the manager's HTTP(S) API (the apiserver analog). `token` is the
    per-PCS SA token (api/resources.TokenSecret) sent as a bearer credential
    — required when the manager runs with the authorizer enabled. `cafile`
    pins the manager's serving cert for https servers (tls auto mode's
    self-signed cert doubles as the CA bundle)."""
    ssl_ctx = None
    if cafile is not None:
        from grove_tpu.runtime.certs import pinned_client_context

        ssl_ctx = pinned_client_context(cafile)

    def fetch(fqn: str) -> tuple[int, bool]:
        url = f"{server.rstrip('/')}/api/v1/podcliques/{fqn}"
        req = urllib.request.Request(url)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s, context=ssl_ctx) as resp:
                doc = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code in (401, 403):
                # A rejected credential never fixes itself by polling — fail
                # fast with a diagnosis instead of gating until timeout.
                raise PermissionError(
                    f"manager rejected the SA token ({e.code}) for {fqn}"
                ) from e
            # 404 = clique not created yet; 5xx = manager restarting. Either
            # way: keep gating, keep retrying — never crash the init phase.
            return 0, False
        except (OSError, TimeoutError, ValueError):
            # URLError/ConnectionReset/RemoteDisconnected/short-read JSON —
            # the manager being briefly unreachable means: keep gating, keep
            # retrying. An init container must never crash on a blip.
            return 0, False
        return int(doc.get("ready", 0)), True

    return fetch


def kube_fetch(
    server: str,
    namespace: str,
    token: str | None = None,
    cafile: str | None = None,
    timeout_s: float = 5.0,
    rbac_grace_s: float = 60.0,
) -> FetchFn:
    """Count Ready gang pods straight from the kube-apiserver — the
    reference agent's own path (`initc/internal/wait.go:111-164` informer;
    polled LIST here): pods selected by the `grove.io/podclique` label,
    ready = condition Ready=True and not terminating. Unlike http_fetch this
    needs no operator URL at all — the only dependency is the apiserver the
    pod already lives on, authenticated by the mounted per-PCS SA token
    (satokensecret component)."""
    import urllib.parse

    ssl_ctx = None
    if cafile is not None:
        import ssl

        # The cluster CA verifies the apiserver's own DNS SANs — full
        # hostname verification, unlike the operator-cert pin.
        ssl_ctx = ssl.create_default_context(cafile=cafile)
    # 401/403 right after pod start is EXPECTED here: the operator mirrors
    # the RoleBinding in the same push that creates the pod, and the
    # apiserver's RBAC cache can lag by seconds. Unlike the operator-API
    # path (where a rejected credential never heals), keep gating through a
    # grace window and only fail fast when the rejection persists.
    denied_since: list[float] = []

    def fetch(fqn: str) -> tuple[int, bool]:
        selector = urllib.parse.quote(f"grove.io/podclique={fqn}")
        url = (
            f"{server.rstrip('/')}/api/v1/namespaces/"
            f"{urllib.parse.quote(namespace)}/pods?labelSelector={selector}"
        )
        req = urllib.request.Request(url)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            with urllib.request.urlopen(req, timeout=timeout_s, context=ssl_ctx) as resp:
                doc = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            if e.code in (401, 403):
                now = time.monotonic()
                if not denied_since:
                    denied_since.append(now)
                if now - denied_since[0] >= rbac_grace_s:
                    raise PermissionError(
                        f"apiserver rejected the SA token ({e.code}) listing "
                        f"pods of {fqn} for {rbac_grace_s:.0f}s (RBAC grace "
                        "exhausted)"
                    ) from e
            return 0, False
        except (OSError, TimeoutError, ValueError):
            return 0, False
        denied_since.clear()
        ready = 0
        for pod in doc.get("items", []) or []:
            if (pod.get("metadata", {}) or {}).get("deletionTimestamp"):
                continue
            conds = (pod.get("status", {}) or {}).get("conditions", []) or []
            if any(c.get("type") == "Ready" and c.get("status") == "True" for c in conds):
                ready += 1
        # A clique with no pods yet lists as empty — that still gates
        # (ready=0), matching the informer counting zero Ready pods.
        return ready, True

    return fetch


# In-cluster defaults (the downward/projected mounts every pod carries).
IN_CLUSTER_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_server() -> str | None:
    """https URL of the apiserver from the standard in-cluster env."""
    import os

    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        return None
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # bare IPv6 literal must be bracketed in a URL
    return f"https://{host}:{port}"


def store_fetch(cluster) -> FetchFn:
    """In-process fetch over the store — the simulator's agent path uses the
    same wait/requirements code as the binary."""

    def fetch(fqn: str) -> tuple[int, bool]:
        clique = cluster.podcliques.get(fqn)
        if clique is None:
            return 0, False
        ready = sum(1 for p in cluster.pods_of_clique(fqn) if p.ready and p.is_active)
        return ready, True

    return fetch
