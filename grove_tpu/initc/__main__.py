"""`python -m grove_tpu.initc` — the init-container entry point.

Exit codes mirror the reference binary (initc/cmd/main.go): 0 = all parent
cliques ready, 1 = timeout waiting, 2 = bad arguments.
"""

from __future__ import annotations

import argparse
import sys

from grove_tpu.initc.agent import http_fetch, parse_podcliques_arg, wait_until_ready


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="grove-initc")
    parser.add_argument(
        "--podcliques",
        required=True,
        help="comma-separated <cliqueFQN>:<minAvailable> gates",
    )
    parser.add_argument(
        "--server",
        default="http://127.0.0.1:2751",
        help="manager HTTP API base (apiserver analog)",
    )
    parser.add_argument("--poll-interval", type=float, default=1.0)
    parser.add_argument("--timeout", type=float, default=900.0)
    parser.add_argument(
        "--token", default="", help="SA bearer token (authorizer-enabled managers)"
    )
    parser.add_argument(
        "--token-file", default="", help="file holding the SA token (mount analog)"
    )
    parser.add_argument(
        "--cafile", default="", help="CA bundle pinning an https manager's cert"
    )
    parser.add_argument(
        "--kube",
        action="store_true",
        help="gate on the kube-apiserver directly (the reference agent's "
        "path, wait.go:111-164) instead of the operator HTTP API; --server "
        "defaults to the in-cluster apiserver, --cafile to the mounted "
        "cluster CA",
    )
    parser.add_argument(
        "--namespace",
        default="",
        help="pod namespace for --kube (default: the in-cluster namespace "
        "file, else 'default')",
    )
    args = parser.parse_args(argv)
    token = args.token
    if args.token_file:
        try:
            with open(args.token_file) as f:
                token = f.read().strip()
        except OSError as e:
            # Mount missing (authorizer likely off): proceed tokenless — the
            # 401 fail-fast path catches a genuinely required credential.
            print(f"grove-initc: no token file ({e}); proceeding without", file=sys.stderr)

    try:
        reqs = parse_podcliques_arg(args.podcliques)
    except ValueError as e:
        print(f"grove-initc: {e}", file=sys.stderr)
        return 2
    if not reqs:
        return 0

    if args.kube:
        import os

        from grove_tpu.initc.agent import (
            IN_CLUSTER_SA_DIR,
            in_cluster_server,
            kube_fetch,
        )

        # --server set explicitly wins (tests point it at a fixture);
        # otherwise the standard in-cluster env names the apiserver.
        server = args.server if args.server != parser.get_default("server") else None
        server = server or in_cluster_server()
        if server is None:
            print(
                "grove-initc: --kube but no --server and no in-cluster env "
                "(KUBERNETES_SERVICE_HOST)",
                file=sys.stderr,
            )
            return 2
        namespace = args.namespace
        if not namespace:
            try:
                with open(f"{IN_CLUSTER_SA_DIR}/namespace") as f:
                    namespace = f.read().strip()
            except OSError:
                namespace = "default"
        cafile = args.cafile or None
        if cafile is None and os.path.isfile(f"{IN_CLUSTER_SA_DIR}/ca.crt"):
            cafile = f"{IN_CLUSTER_SA_DIR}/ca.crt"
        fetch = kube_fetch(server, namespace, token=token or None, cafile=cafile)
    else:
        fetch = http_fetch(
            args.server, token=token or None, cafile=args.cafile or None
        )

    def log_poll(n: int) -> None:
        if n == 1 or n % 30 == 0:
            print(f"grove-initc: waiting on {len(reqs)} parent clique(s)", flush=True)

    try:
        ok = wait_until_ready(
            fetch,
            reqs,
            timeout_s=args.timeout,
            poll_interval_s=args.poll_interval,
            on_poll=log_poll,
        )
    except PermissionError as e:
        print(f"grove-initc: {e}", file=sys.stderr)
        return 2
    if not ok:
        print("grove-initc: timed out waiting for parent cliques", file=sys.stderr)
        return 1
    print("grove-initc: all parent cliques ready", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
