"""Deployment manifest rendering — the Helm-chart analog (L9).

The reference ships a Helm chart (`operator/charts/templates/*.yaml`:
deployment, services, RBAC, priorityclass, operator ConfigMap). This module
renders the equivalent Kubernetes manifests for the TPU stack straight from
a validated OperatorConfiguration, so one config file is both the runtime
input and the deployment source of truth:

    python -m grove_tpu.deploy --config examples/operator-config.yaml [--out dir]

Rendered objects: Namespace, ConfigMap (the operator config, mounted at
/etc/grove/config.yaml), operator ServiceAccount + minimal RBAC, Deployment
(manager container; the scheduler-backend sidecar runs in-process when
backend.enabled — GREP-375's sidecar model), and Services for the
health/metrics/backend ports.
"""

from __future__ import annotations

import argparse
import hashlib
import pathlib
import sys

import yaml

from grove_tpu.runtime.config import OperatorConfiguration, load_operator_config

APP = "grove-tpu-operator"
IMAGE = "grove-tpu/operator:latest"


def render_crd() -> dict:
    """The PodCliqueSet CustomResourceDefinition (reference: generated CRDs
    in `operator/api/core/v1alpha1/crds/`, shipped by the chart).

    Deliberately a STRUCTURAL schema with preserve-unknown-fields rather
    than a generated 10k-line OpenAPI dump: validation authority lives in
    the operator's admission chain (api/validation.py), which the CR watch
    runs for every object — the apiserver schema only needs to admit the
    shape. Status and scale subresources mirror the reference
    (`podcliqueset.go:27`): scale points at spec.replicas/status.replicas
    with status.selector for HPA compatibility."""
    preserve = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "podcliquesets.grove.io", "labels": _labels()},
        "spec": {
            "group": "grove.io",
            "names": {
                "kind": "PodCliqueSet",
                "listKind": "PodCliqueSetList",
                "plural": "podcliquesets",
                "singular": "podcliqueset",
                "shortNames": ["pcs"],
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": "v1alpha1",
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "spec": preserve,
                                "status": preserve,
                            },
                        }
                    },
                    "subresources": {
                        "status": {},
                        "scale": {
                            "specReplicasPath": ".spec.replicas",
                            "statusReplicasPath": ".status.replicas",
                            "labelSelectorPath": ".status.selector",
                        },
                    },
                    "additionalPrinterColumns": [
                        {
                            "name": "Available",
                            "type": "integer",
                            "jsonPath": ".status.availableReplicas",
                        },
                        {
                            "name": "Age",
                            "type": "date",
                            "jsonPath": ".metadata.creationTimestamp",
                        },
                    ],
                }
            ],
        },
    }


def render_child_crds() -> list[dict]:
    """PodClique + PodCliqueScalingGroup CRDs: the operator-owned child
    objects are projected to the apiserver as CRs with live status
    (`kubectl get pclq,pcsg` — the reference materializes the same kinds).
    Status is operator-owned, but spec.replicas via the SCALE subresource is
    a public surface (reference: HPA ScaleTargetRef targets PCLQ/PCSG scale,
    components/hpa/hpa.go:249-259): the operator watches these CRs and turns
    external replica writes into the same scale path its own HPA step and
    the CLI scale verb use — so `kubectl scale pclq/pcsg` and cluster HPAs
    work."""
    preserve = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    out = []
    for kind, plural, singular, short in (
        ("PodClique", "podcliques", "podclique", "pclq"),
        (
            "PodCliqueScalingGroup",
            "podcliquescalinggroups",
            "podcliquescalinggroup",
            "pcsg",
        ),
    ):
        out.append(
            {
                "apiVersion": "apiextensions.k8s.io/v1",
                "kind": "CustomResourceDefinition",
                "metadata": {"name": f"{plural}.grove.io", "labels": _labels()},
                "spec": {
                    "group": "grove.io",
                    "names": {
                        "kind": kind,
                        "listKind": f"{kind}List",
                        "plural": plural,
                        "singular": singular,
                        "shortNames": [short],
                    },
                    "scope": "Namespaced",
                    "versions": [
                        {
                            "name": "v1alpha1",
                            "served": True,
                            "storage": True,
                            "schema": {
                                "openAPIV3Schema": {
                                    "type": "object",
                                    "properties": {
                                        "spec": preserve,
                                        "status": preserve,
                                    },
                                }
                            },
                            "subresources": {
                                "status": {},
                                "scale": {
                                    "specReplicasPath": ".spec.replicas",
                                    "statusReplicasPath": ".status.replicas",
                                    "labelSelectorPath": ".status.selector",
                                },
                            },
                        }
                    ],
                },
            }
        )
    return out


def render_topology_crd() -> dict:
    """The cluster-scoped ClusterTopology CRD (`grove.io_clustertopologies`
    upstream; name `grove-topology`, short name `ct`) — the operator writes
    it at startup from the config's TAS levels (cluster/kubernetes.py
    sync_cluster_topology)."""
    preserve = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "clustertopologies.grove.io", "labels": _labels()},
        "spec": {
            "group": "grove.io",
            "names": {
                "kind": "ClusterTopology",
                "listKind": "ClusterTopologyList",
                "plural": "clustertopologies",
                "singular": "clustertopology",
                "shortNames": ["ct"],
            },
            "scope": "Cluster",
            "versions": [
                {
                    "name": "v1alpha1",
                    "served": True,
                    "storage": True,
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {"spec": preserve},
                        }
                    },
                }
            ],
        },
    }


def _labels() -> dict:
    return {"app.kubernetes.io/name": APP, "app.kubernetes.io/managed-by": "grove-tpu"}


def render_manifests(
    cfg: OperatorConfiguration,
    config_yaml: str,
    *,
    namespace: str = "grove-system",
    image: str = IMAGE,
    replicas: int | None = None,
) -> list[dict]:
    """OperatorConfiguration -> list of Kubernetes manifest documents."""
    # HA honesty (round-3 finding): leader election protects multi-replica
    # Deployments ONLY when the lease lives somewhere every replica can see.
    # The file lease coordinates one filesystem; in a Deployment each pod has
    # its own, so two replicas would both lead. Only the apiserver-backed
    # lease (cluster.source: kubernetes -> KubeLease) makes replicas>1 safe.
    ha_capable = (
        cfg.leader_election.enabled and cfg.cluster.source == "kubernetes"
    )
    webhook_enabled = cfg.servers.webhook_port >= 0
    if replicas is None:
        replicas = 2 if ha_capable and not webhook_enabled else 1
    elif replicas > 1 and not ha_capable:
        raise ValueError(
            "replicas > 1 requires leaderElection.enabled AND cluster.source: "
            "kubernetes (apiserver-backed lease); the file lease cannot "
            "coordinate pods on separate filesystems"
        )
    if webhook_enabled and replicas > 1:
        # Each replica self-signs its own webhook cert into its container
        # filesystem, but caBundle can only hold one trust root and the
        # webhook Service load-balances across pods — the apiserver would
        # fail TLS on whichever pod lost the boot-time patch race. Until
        # certs are Secret-shared, webhooks mean one replica.
        raise ValueError(
            "servers.webhookPort with replicas > 1 would intermittently fail "
            "apiserver TLS verification (per-pod self-signed webhook certs, "
            "one caBundle); run a single replica or disable the webhook"
        )

    if cfg.servers.bind_address.startswith("127.") or cfg.servers.bind_address in (
        "localhost", "::1",
    ):
        # Probes and Services reach the POD IP; a loopback bind would render
        # manifests whose probes can never connect.
        raise ValueError(
            "servers.bindAddress is loopback; set 0.0.0.0 (or a pod-routable "
            "address) before rendering deployment manifests"
        )
    ports = []
    for name, port, enabled in (
        ("health", cfg.servers.health_port, cfg.servers.health_port >= 0),
        ("metrics", cfg.servers.metrics_port, cfg.servers.metrics_port >= 0),
        ("webhook", cfg.servers.webhook_port, cfg.servers.webhook_port >= 0),
        ("backend", cfg.backend.port, cfg.backend.enabled),
    ):
        if not enabled:
            continue
        if port == 0:
            # 0 = auto-assign, fine for local runs but unroutable in a
            # manifest: probes and Services would point at a port the
            # manager never binds. Fail loudly instead of rendering lies.
            raise ValueError(
                f"{name} port is 0 (auto-assign); set an explicit port in the "
                "config before rendering deployment manifests"
            )
        ports.append({"name": name, "containerPort": port})

    # TLS-enabled managers serve HTTPS on every port; probes must say so or
    # the kubelet handshakes plaintext and the pod never goes Ready.
    probe_scheme = {"scheme": "HTTPS"} if cfg.servers.tls_mode != "disabled" else {}
    # Manual TLS: the cert/key must arrive via a Secret volume; require paths
    # under the mount so the rendered pod can actually read them.
    TLS_MOUNT = "/etc/grove/tls"
    TLS_SECRET = f"{APP}-tls"
    extra_volumes: list[dict] = []
    extra_mounts: list[dict] = []
    if cfg.servers.tls_mode == "manual":
        for label, path in (
            ("tlsCertFile", cfg.servers.tls_cert_file),
            ("tlsKeyFile", cfg.servers.tls_key_file),
        ):
            if not path.startswith(TLS_MOUNT + "/"):
                raise ValueError(
                    f"servers.{label} must live under {TLS_MOUNT} (delivered by "
                    f"Secret {TLS_SECRET!r}) for deployment rendering; got {path!r}"
                )
        extra_volumes.append(
            {"name": "tls", "secret": {"secretName": TLS_SECRET}}
        )
        extra_mounts.append({"name": "tls", "mountPath": TLS_MOUNT, "readOnly": True})

    # Content-addressed ConfigMap: a config change renames the ConfigMap,
    # which changes the pod template, which rolls the Deployment — the
    # checksum-annotation pattern charts use, compatible with immutability.
    config_hash = hashlib.sha256(config_yaml.encode()).hexdigest()[:8]
    configmap_name = f"{APP}-config-{config_hash}"

    if cfg.cluster.source == "kubernetes" and cfg.cluster.initc_mode == "operator":
        # Remote pods run the injected initc against --server: the URL must
        # exist (else pods poll localhost in their own netns), the serving
        # port must actually be enabled, and the scheme must be one the
        # agent can speak (no CA distribution to workload pods yet, so the
        # advertised surface must be plaintext; terminate TLS in front if
        # needed). Each failure here would otherwise be silent gang pods
        # gating until init timeout. initcMode kubernetes escapes ALL of
        # this: the agent talks to the apiserver with the mirrored SA token
        # and the operator URL never enters the pod.
        if cfg.servers.health_port < 0:
            raise ValueError(
                "servers.healthPort must be enabled for cluster.source: "
                "kubernetes deployments — the workload API the injected "
                "grove-initc polls is served there"
            )
        if not cfg.servers.advertise_url:
            raise ValueError(
                "servers.advertiseUrl is required for cluster.source: "
                "kubernetes deployments (the injected grove-initc polls it); "
                f"set e.g. http://{APP}.{namespace}.svc:{cfg.servers.health_port}"
            )
        if cfg.servers.tls_mode != "disabled":
            raise ValueError(
                "cluster.source: kubernetes deployments require servers."
                "tlsMode: disabled for now — the injected grove-initc has no "
                "CA distribution, so an HTTPS workload API would fail cert "
                "verification in every pod; terminate TLS in front of the "
                "operator instead"
            )
        if not cfg.servers.advertise_url.startswith("http://"):
            raise ValueError(
                "servers.advertiseUrl must be a plaintext http:// URL (the "
                "injected grove-initc has no CA material for https)"
            )

    webhook_svc_dns = f"{APP}-webhook.{namespace}.svc"
    if webhook_enabled:
        if cfg.cluster.source != "kubernetes":
            raise ValueError(
                "servers.webhookPort requires cluster.source: kubernetes — "
                "the running operator must patch the rendered webhook "
                "configs' caBundle via the apiserver"
            )
        # NB: rendered webhook certs are always auto-generated — webhooks
        # require cluster.source kubernetes (above), which in turn requires
        # tlsMode disabled (below), so the manual-cert path cannot reach
        # this renderer and webhookSans always governs the real cert.
        if webhook_svc_dns not in cfg.servers.webhook_sans:
            # The apiserver verifies the webhook serving cert against the
            # Service DNS name; a cert without it fails every admission call
            # (failurePolicy Fail => cluster-wide PCS write outage).
            raise ValueError(
                f"servers.webhookSans must include {webhook_svc_dns!r} so the "
                "auto-generated webhook cert verifies against the rendered "
                "Service"
            )

    docs: list[dict] = []
    # PriorityClasses from scheduling.priorityClasses (the chart's
    # priorityclass.yaml analog): cluster-scoped, consumed by pod specs'
    # priorityClassName and the solver's preemption ordering.
    for pc_name, value in sorted(cfg.scheduling.priority_classes.items()):
        docs.append(
            {
                "apiVersion": "scheduling.k8s.io/v1",
                "kind": "PriorityClass",
                "metadata": {"name": pc_name, "labels": _labels()},
                "value": int(value),
                "globalDefault": False,
                "description": "grove-tpu workload priority "
                "(scheduling.priorityClasses)",
            }
        )
    if cfg.cluster.source == "kubernetes":
        # The topology CR is written at startup regardless of the workload
        # watch; its CRD ships with every kubernetes-source deployment.
        docs.append(render_topology_crd())
    if cfg.cluster.source == "kubernetes":
        # Child CR projections (kubectl get pclq,pcsg) ship their CRDs too.
        docs.extend(render_child_crds())
    if cfg.cluster.source == "kubernetes" and cfg.cluster.watch_workloads:
        # The CR watch needs the grove.io CRD installed; ship it with the
        # operator exactly as the reference chart ships its generated CRDs.
        docs.append(render_crd())
    docs += [
        {
            "apiVersion": "v1",
            "kind": "Namespace",
            "metadata": {"name": namespace, "labels": _labels()},
        },
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {
                "name": configmap_name,
                "namespace": namespace,
                "labels": _labels(),
            },
            "immutable": True,  # safe: a config change renames the ConfigMap
            "data": {"config.yaml": config_yaml},
        },
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": APP, "namespace": namespace, "labels": _labels()},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "Role",
            "metadata": {"name": APP, "namespace": namespace, "labels": _labels()},
            "rules": [
                {
                    "apiGroups": [""],
                    # pods/binding: the solver's placements land through the
                    # scheduler binding subresource (cluster/kubernetes.py).
                    "resources": ["pods", "pods/binding", "services", "secrets"],
                    "verbs": ["get", "list", "watch", "create", "update", "delete"],
                },
                {
                    "apiGroups": [""],
                    # Control-plane events mirror to corev1 Events
                    # (kubectl get events; publish_events).
                    "resources": ["events"],
                    "verbs": ["create"],
                },
            ]
            + (
                [
                    {
                        # initcMode kubernetes: the operator mirrors per-PCS
                        # SA/Role/RoleBinding so the service-account-token
                        # Secret resolves to a real apiserver credential
                        # (sync_rbac). Escalation-safe: everything granted
                        # is a subset of the operator's own permissions.
                        "apiGroups": [""],
                        "resources": ["serviceaccounts"],
                        "verbs": ["get", "list", "create", "update", "delete"],
                    },
                    {
                        "apiGroups": ["rbac.authorization.k8s.io"],
                        "resources": ["roles", "rolebindings"],
                        "verbs": ["get", "list", "create", "update", "delete"],
                    },
                ]
                if cfg.cluster.initc_mode == "kubernetes"
                else []
            )
            + [
                {
                    "apiGroups": ["grove.io"],
                    # The CR watch + status write-back (status subresource);
                    # delete: an operator-API delete must remove the CR too
                    # or the next relist resurrects the workload. Child CR
                    # projections (podcliques/pcsgs) are created and GC'd by
                    # the operator outright.
                    "resources": [
                        "podcliquesets",
                        "podcliquesets/status",
                        "podcliques",
                        "podcliques/status",
                        "podcliquescalinggroups",
                        "podcliquescalinggroups/status",
                    ],
                    "verbs": [
                        "get", "list", "watch", "create", "update", "patch",
                        "delete",
                    ],
                },
                {
                    "apiGroups": ["coordination.k8s.io"],
                    "resources": ["leases"],
                    # delete: KubeLease.release() removes the lease on
                    # graceful stop so handover is immediate, not a full
                    # leaseDurationSeconds of leaderless downtime.
                    "verbs": ["get", "create", "update", "delete"],
                },
            ],
        },
        {
            # Nodes are cluster-scoped: a namespaced Role cannot grant them
            # (listing them there is silently dead RBAC) — the node watch
            # needs a ClusterRole.
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            # Namespace-qualified: cluster-scoped names collide across
            # installs — a second install must not rewrite the first's
            # binding subjects and revoke its node access.
            "metadata": {"name": f"{APP}-{namespace}-nodes", "labels": _labels()},
            "rules": [
                {
                    "apiGroups": [""],
                    "resources": ["nodes"],
                    "verbs": ["get", "list", "watch"],
                },
                {
                    "apiGroups": ["grove.io"],
                    # Startup topology sync writes this cluster-scoped CR.
                    "resources": ["clustertopologies"],
                    "verbs": ["get", "create", "update"],
                },
            ]
            + (
                [
                    {
                        # Boot-time caBundle patch (sync_webhook_ca): the
                        # configs are cluster-scoped; scope the grant to
                        # exactly our two objects.
                        "apiGroups": ["admissionregistration.k8s.io"],
                        "resources": [
                            "mutatingwebhookconfigurations",
                            "validatingwebhookconfigurations",
                        ],
                        "resourceNames": [APP],
                        "verbs": ["get", "update"],
                    }
                ]
                if webhook_enabled
                else []
            ),
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": f"{APP}-{namespace}-nodes", "labels": _labels()},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": f"{APP}-{namespace}-nodes",
            },
            "subjects": [
                {
                    "kind": "ServiceAccount",
                    "name": APP,
                    "namespace": namespace,
                }
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            "metadata": {"name": APP, "namespace": namespace, "labels": _labels()},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "Role",
                "name": APP,
            },
            "subjects": [
                {"kind": "ServiceAccount", "name": APP, "namespace": namespace}
            ],
        },
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {"name": APP, "namespace": namespace, "labels": _labels()},
            "spec": {
                "replicas": replicas,
                "selector": {"matchLabels": {"app.kubernetes.io/name": APP}},
                "template": {
                    "metadata": {"labels": _labels()},
                    "spec": {
                        "serviceAccountName": APP,
                        "containers": [
                            {
                                "name": "manager",
                                "image": image,
                                "command": [
                                    "python",
                                    "-m",
                                    "grove_tpu.runtime",
                                    "--config",
                                    "/etc/grove/config.yaml",
                                ],
                                "ports": ports,
                                "volumeMounts": [
                                    {"name": "config", "mountPath": "/etc/grove"}
                                ] + extra_mounts,
                                **(
                                    {
                                        "readinessProbe": {
                                            "httpGet": {
                                                "path": "/readyz",
                                                "port": "health",
                                                **probe_scheme,
                                            }
                                        },
                                        "livenessProbe": {
                                            "httpGet": {
                                                "path": "/healthz",
                                                "port": "health",
                                                **probe_scheme,
                                            }
                                        },
                                    }
                                    if cfg.servers.health_port >= 0
                                    else {}
                                ),
                            }
                        ],
                        "volumes": [
                            {
                                "name": "config",
                                "configMap": {"name": configmap_name},
                            }
                        ] + extra_volumes,
                    },
                },
            },
        },
    ]
    if ports:
        docs.append(
            {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": APP, "namespace": namespace, "labels": _labels()},
                "spec": {
                    "selector": {"app.kubernetes.io/name": APP},
                    "ports": [
                        {
                            "name": p["name"],
                            "port": p["containerPort"],
                            "targetPort": p["name"],
                        }
                        for p in ports
                    ],
                },
            }
        )
    if webhook_enabled:
        docs.extend(
            _render_webhook_objects(namespace, authorizer=cfg.authorizer.enabled)
        )
    return docs


def _render_webhook_objects(namespace: str, authorizer: bool = False) -> list[dict]:
    """The inbound admission surface (webhook/register.go:34-62 analog): a
    dedicated webhook Service on 443 plus Mutating/Validating
    WebhookConfigurations for PodCliqueSet writes. caBundle is left empty;
    the running operator completes it at boot (sync_webhook_ca — the
    cert-controller rotator pattern, cert.go:66-93)."""

    def _client_config(path: str) -> dict:
        return {
            "service": {
                "name": f"{APP}-webhook",
                "namespace": namespace,
                "path": path,
                "port": 443,
            }
        }

    rules = [
        {
            "apiGroups": ["grove.io"],
            "apiVersions": ["v1alpha1"],
            "operations": ["CREATE", "UPDATE"],
            "resources": ["podcliquesets"],
            "scope": "Namespaced",
        }
    ]
    common = {
        "rules": rules,
        "failurePolicy": "Fail",
        "sideEffects": "None",
        "admissionReviewVersions": ["v1"],
        "matchPolicy": "Equivalent",
        "timeoutSeconds": 10,
    }
    return [
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": f"{APP}-webhook",
                "namespace": namespace,
                "labels": _labels(),
            },
            "spec": {
                "selector": {"app.kubernetes.io/name": APP},
                "ports": [{"name": "webhook", "port": 443, "targetPort": "webhook"}],
            },
        },
        {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": APP, "labels": _labels()},
            "webhooks": [
                {
                    "name": "defaulting.pcs.grove.io",
                    "clientConfig": _client_config("/webhook/v1/default"),
                    **common,
                }
            ],
        },
        {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": APP, "labels": _labels()},
            "webhooks": [
                {
                    "name": "validation.pcs.grove.io",
                    "clientConfig": _client_config("/webhook/v1/validate"),
                    **common,
                }
            ]
            + (
                [
                    {
                        # Authorizer webhook (authorization/handler.go:60-135):
                        # only the operator (and exempt actors) may mutate
                        # managed resources. objectSelector scopes the
                        # apiserver's calls to grove-managed objects so an
                        # operator outage cannot block unrelated writes.
                        # Pod DELETE is deliberately NOT registered: the
                        # kubelet's completion deletes and the GC's
                        # owner-reference cascade are system identities no
                        # exempt list could enumerate (the handler also
                        # allows them as defense in depth, handler.go:
                        # 121-124).
                        **common,
                        "name": "authorization.pcs.grove.io",
                        "clientConfig": _client_config("/webhook/v1/authorize"),
                        "rules": [
                            {
                                "apiGroups": ["grove.io"],
                                "apiVersions": ["v1alpha1"],
                                "operations": ["CREATE", "UPDATE", "DELETE"],
                                # Status subresources listed explicitly:
                                # webhooks do not fire for unlisted
                                # subresources, and the operator-owned
                                # status projections are a write surface.
                                "resources": [
                                    "podcliques",
                                    "podcliques/status",
                                    "podcliquescalinggroups",
                                    "podcliquescalinggroups/status",
                                ],
                                "scope": "Namespaced",
                            },
                            {
                                "apiGroups": [""],
                                "apiVersions": ["v1"],
                                "operations": ["UPDATE"],
                                "resources": ["pods"],
                                "scope": "Namespaced",
                            },
                        ],
                        "objectSelector": {
                            "matchLabels": {
                                "app.kubernetes.io/managed-by": APP,
                            }
                        },
                    }
                ]
                if authorizer
                else []
            ),
        },
    ]


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="grove-tpu-deploy")
    parser.add_argument("--config", required=True, help="operator config YAML")
    parser.add_argument("--namespace", default="grove-system")
    parser.add_argument("--image", default=IMAGE)
    parser.add_argument("--out", default="", help="directory for per-doc files; default stdout")
    args = parser.parse_args(argv)

    try:
        cfg = load_operator_config(args.config)
        config_yaml = pathlib.Path(args.config).read_text()
        docs = render_manifests(
            cfg, config_yaml, namespace=args.namespace, image=args.image
        )
    except ValueError as e:
        print(f"grove-tpu-deploy: {e}", file=sys.stderr)
        return 2
    if args.out:
        out = pathlib.Path(args.out)
        out.mkdir(parents=True, exist_ok=True)
        for doc in docs:
            name = f"{doc['kind'].lower()}-{doc['metadata']['name']}.yaml"
            (out / name).write_text(yaml.safe_dump(doc, sort_keys=False))
        print(f"wrote {len(docs)} manifests to {out}")
    else:
        print(yaml.safe_dump_all(docs, sort_keys=False))
    return 0


if __name__ == "__main__":
    sys.exit(main())
