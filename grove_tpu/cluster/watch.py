"""The watch driver: external cluster events → store, bindings → cluster.

The reference's controllers see the world exclusively through kube-apiserver
watch streams (informers, SURVEY.md §5.8); the in-pod agent watches too
(`operator/initc/internal/wait.go:111-164`). This module is that integration
path for the TPU stack: a WatchSource — KwokCluster (cluster/kwok.py) or the
live-apiserver KubernetesWatchSource (cluster/kubernetes.py) — produces
`WatchEvent`s, the WatchDriver applies them to the Manager's store, and
control-plane decisions (bindings, deletions) flow back out.

Apply discipline (the ExpectationsStore lesson,
`operator/internal/expect/expectations.go:33-71`): watch events are DELAYED
VIEWS, not commands. A pod event for an object the controller has deleted or
replaced must not resurrect it — pod events only ever update fields of a pod
that still exists in the store under the same binding. The store itself stays
strongly consistent (single writer: the manager loop), so unlike the
reference we need no create/delete expectation counters — the lag lives
entirely on the inbound event side.

Optionally forwards node state to a scheduler-backend sidecar via
UpdateCluster, so an out-of-process solver sees the same fleet
(backend/service.py; GREP-375).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass, field
from typing import Optional, Protocol

from grove_tpu.api.pod import PodPhase
from grove_tpu.state.cluster import Node


@dataclass
class WatchRetryPolicy:
    """Resubscribe pacing + resync accounting for an informer loop.

    The reference informer contract under churn: a dropped watch stream
    RESUBSCRIBES from the last-seen resourceVersion after a capped backoff
    (decorrelated jitter — a flapping apiserver must not see every informer
    reconnect in lockstep), and a 410 Gone (resourceVersion expired while
    we were away) forces a FULL RESYNC (relist + synthesized DELETEDs for
    ghosts). Both transitions are counted — a cluster whose watches flap is
    a cluster whose operator should know (grove_watch_* metrics).

    One policy instance per resource watch; `note_healthy()` after a
    successful list resets the backoff so the next episode starts fast."""

    base_s: float = 0.5
    cap_s: float = 30.0
    seed: int | None = None
    # Monotonic counters (read by the source's stats and the manager).
    reconnects: int = 0
    resyncs: int = 0
    _backoff: object = None

    def _ensure(self):
        if self._backoff is None:
            from grove_tpu.utils.backoff import Backoff

            self._backoff = Backoff(self.base_s, self.cap_s, seed=self.seed)
        return self._backoff

    def next_delay(self) -> float:
        """Backoff before the next resubscribe attempt (counts a reconnect)."""
        self.reconnects += 1
        return self._ensure().next_delay() or self.cap_s

    def note_resync(self) -> None:
        """A 410 Gone forced a full relist."""
        self.resyncs += 1

    def note_healthy(self) -> None:
        """List/watch re-established: next failure episode backs off from
        the fast first retry again."""
        self._ensure().reset()


class EventType(str, enum.Enum):
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


@dataclass
class WatchEvent:
    type: EventType
    kind: str  # "Node" | "Pod"
    name: str
    obj: dict


class WatchSource(Protocol):
    def poll(self, now: float) -> list[WatchEvent]: ...

    # observe_* return None/True when the push landed durably; an explicit
    # False means "failed, retry me next tick" (a live apiserver can 500).
    def observe_binding(self, pod_name: str, node_name: str, now: float): ...

    def observe_deletion(self, pod_name: str, now: float): ...


@dataclass
class WatchDriver:
    """Pumps a WatchSource into a Cluster store and pushes decisions back."""

    cluster: "object"  # orchestrator.store.Cluster (duck-typed to avoid cycle)
    source: WatchSource
    backend: Optional["object"] = None  # backend.client.BackendClient
    # Workload CR events (PodCliqueSet, kubernetes source): handed to the
    # manager's admission-gated apply/delete path, NOT written raw into the
    # store — watch events never bypass the webhook-analog chain.
    workload_sink: Optional[object] = None  # callable(WatchEvent)
    child_scale_sink: Optional[object] = None  # callable(WatchEvent, now)
    # pods we've told the source about (bind pushed), and known-deleted pods
    _pushed_bindings: set[str] = field(default_factory=set)
    # pods whose bind FAILED after the source may have already materialized
    # the object (create-succeeded/bind-500): if the store drops such a pod
    # before a retry lands, it still needs an outbound deletion or the real
    # cluster keeps an unschedulable Pending pod forever.
    _attempted_bindings: set[str] = field(default_factory=set)
    _nodes_dirty: bool = field(default=True)
    # last-pushed CR status (JSON-canonical) per PCS: change detection for
    # the status write-back
    _pushed_status: dict = field(default_factory=dict)
    # control-plane events already mirrored as corev1 Events (index into
    # cluster.events)
    _pushed_events: int = 0

    # ---- inbound: events -> store --------------------------------------------------

    def pump(self, now: float) -> int:
        """Apply all due events; returns how many were applied."""
        events = self.source.poll(now)
        for ev in events:
            if ev.kind == "Node":
                self._apply_node(ev, now)
            elif ev.kind == "Pod":
                self._apply_pod(ev, now)
            elif ev.kind == "PodCliqueSet" and self.workload_sink is not None:
                if ev.type == EventType.ADDED:
                    # A CR (re)appeared at the apiserver: any cached "no CR
                    # there" status-push verdict is stale — push again even
                    # if the status itself hasn't changed since.
                    self._pushed_status.pop(ev.name, None)
                self.workload_sink(ev, now)
            elif (
                ev.kind in ("PodClique", "PodCliqueScalingGroup")
                and self.child_scale_sink is not None
            ):
                # External writes to the child CRs' scale subresource
                # (kubectl scale pclq / a cluster HPA); echoes of our own
                # projection PUTs no-op inside the sink.
                self.child_scale_sink(ev, now)
        # Dirty-flag, not event-count, gates forwarding: a failed UpdateCluster
        # (sidecar briefly down) must retry on the NEXT pump even if no new
        # node events arrive in between.
        if self.backend is not None and self._nodes_dirty:
            self._forward_nodes()
        return len(events)

    def _apply_node(self, ev: WatchEvent, now: float) -> None:
        c = self.cluster
        if ev.type == EventType.DELETED:
            c.nodes.pop(ev.name, None)
            # Pods on a vanished node are failed-with-the-machine; status
            # rollup + gang termination handle recovery from there.
            for pod in c.pods.values():
                if pod.node_name == ev.name and pod.is_active:
                    pod.phase = PodPhase.FAILED
                    pod.ready = False
        else:
            c.nodes[ev.name] = Node(
                name=ev.name,
                capacity=dict(ev.obj.get("capacity", {})),
                labels=dict(ev.obj.get("labels", {})),
                schedulable=bool(ev.obj.get("schedulable", True)),
                taints=[dict(t) for t in ev.obj.get("taints", [])],
            )
        self._nodes_dirty = True

    def _apply_pod(self, ev: WatchEvent, now: float) -> None:
        """Stale-view discipline: only mutate a pod that still exists AND is
        still bound where the event says — a lagged event for a deleted or
        re-placed pod is dropped, never resurrected."""
        pod = self.cluster.pods.get(ev.name)
        if pod is None:
            return  # controller already deleted it; lagged event is stale
        if ev.type == EventType.DELETED:
            # Controller-initiated deletions leave the store first, so a
            # DELETED for a pod still in the store is an OUT-OF-BAND removal
            # (kubectl delete, eviction): the pod died with the external
            # world — fail it so status rollup + gang termination recover,
            # and drop the binding record so a recreated namesake re-pushes.
            if pod.is_scheduled:
                pod.phase = PodPhase.FAILED
                pod.ready = False
                self._pushed_bindings.discard(ev.name)
            return
        node = ev.obj.get("node")
        if node is not None and pod.node_name != node:
            return  # stale: the pod has been re-placed since this event
        phase = ev.obj.get("phase")
        if phase is not None:
            try:
                pod.phase = PodPhase(phase)
            except ValueError:
                return  # unknown phase string from a foreign source: drop
        if "ready" in ev.obj:
            pod.ready = bool(ev.obj["ready"])
            if pod.ready and pod.started_at is None:
                pod.started_at = now

    # ---- outbound: store decisions -> source/backend -------------------------------

    def push(self, now: float) -> int:
        """Tell the source about new bindings and deletions; returns pushes.

        A push is recorded as done only when the source does NOT report
        failure (an explicit False return): a transient apiserver error on
        bind/delete must leave the pod in the retry set, or the store
        believes a placement the cluster never saw (orphaned forever)."""
        c = self.cluster
        pushed = 0
        live = set()
        for pod in c.pods.values():
            live.add(pod.name)
            if pod.is_scheduled and pod.name not in self._pushed_bindings:
                ok = self.source.observe_binding(pod.name, pod.node_name, now)
                if ok is not False:
                    self._pushed_bindings.add(pod.name)
                    self._attempted_bindings.discard(pod.name)
                    pushed += 1
                else:
                    self._attempted_bindings.add(pod.name)
        for name in list(self._pushed_bindings | self._attempted_bindings):
            if name not in live:
                ok = self.source.observe_deletion(name, now)
                if ok is not False:
                    self._pushed_bindings.discard(name)
                    self._attempted_bindings.discard(name)
                    pushed += 1
        pushed += self._push_workload_statuses()
        sync_services = getattr(self.source, "sync_services", None)
        if sync_services is not None:
            # Managed headless Services mirror to the real cluster (pod DNS
            # needs them); the source change-detects, so this is cheap.
            sync_services(list(self.cluster.services.values()))
        sync_rbac = getattr(self.source, "sync_rbac", None)
        if sync_rbac is not None:
            # SA/Role/RoleBinding BEFORE the token Secret that binds to the
            # SA (initcMode kubernetes; no-op in operator mode).
            sync_rbac(
                list(self.cluster.service_accounts.values()),
                list(self.cluster.roles.values()),
                list(self.cluster.role_bindings.values()),
            )
        sync_secrets = getattr(self.source, "sync_secrets", None)
        if sync_secrets is not None:
            # SA-token Secrets BEFORE pods need their mounts.
            sync_secrets(list(self.cluster.secrets.values()))
        sync_children = getattr(self.source, "sync_workload_children", None)
        if sync_children is not None:
            # kubectl-visible PodClique/PCSG projections (status included).
            sync_children(
                list(self.cluster.podcliques.values()),
                list(self.cluster.scaling_groups.values()),
            )
        publish_events = getattr(self.source, "publish_events", None)
        if publish_events is not None:
            # Control-plane events -> corev1 Events (kubectl get events).
            # High-water mark in the store's MONOTONIC event index
            # (events_total), not a deque position — the bounded ring drops
            # its oldest entries, so positions shift; events that fell off
            # before mirroring count as pushed (they are gone either way).
            evs = self.cluster.recent_events()
            skip = len(evs) - (self.cluster.events_total - self._pushed_events)
            if skip < 0:
                self._pushed_events = self.cluster.events_total - len(evs)
                skip = 0
            new = evs[skip : skip + 100]
            if new:
                self._pushed_events += publish_events(new)
        return pushed

    def _push_workload_statuses(self) -> int:
        """Reconciled PCS status -> the CR's status subresource (sources
        without publish_workload_status — KWOK — skip). Change-detected so
        a quiet control plane writes nothing."""
        publish = getattr(self.source, "publish_workload_status", None)
        if publish is None:
            return 0
        from grove_tpu.utils.serde import to_k8s

        pushed = 0
        for name, pcs in list(self.cluster.podcliquesets.items()):
            doc = to_k8s(pcs.status)
            key = json.dumps(doc, sort_keys=True)
            if self._pushed_status.get(name) == key:
                continue
            ok = publish(name, doc)
            # None = no CR at the apiserver (store-only workload): record
            # the key so the doomed GET doesn't repeat every tick; False =
            # transient, retry next tick.
            if ok is not False:
                self._pushed_status[name] = key
                if ok is True:
                    pushed += 1
        for name in [n for n in self._pushed_status if n not in self.cluster.podcliquesets]:
            del self._pushed_status[name]
        return pushed

    def step(self, now: float) -> None:
        """One full exchange: inbound events, then outbound decisions."""
        self.pump(now)
        self.push(now)

    # ---- backend forwarding ---------------------------------------------------------

    def _forward_nodes(self) -> None:
        """Mirror the store's node fleet into the sidecar (UpdateCluster)."""
        self.backend.update_cluster(list(self.cluster.nodes.values()), full_replace=True)
        self._nodes_dirty = False
