"""KWOK-shaped fake cluster: fabricated nodes + staged pod lifecycles.

The reference scales its control plane against KWOK v0.7.0 fake nodes
(`operator/hack/kind-up.sh:31,245-265`): nodes exist as API objects, and
stage configs advance bound pods through Pending → Running → Ready on timers
without any kubelet. This module is that mechanism for the TPU stack — an
external "cluster" the control plane only sees through watch events:

  control plane --> observe_binding(pod, node)    (the bind call)
  cluster       --> WatchEvent stream             (node + pod state changes)

`event_lag_s` models informer latency: an event becomes visible to pollers
only lag seconds after it happened. This is the stale-read window that
motivates the reference's ExpectationsStore
(`operator/internal/expect/expectations.go:33-71`); the WatchDriver's apply
discipline is tested against it.

Clock discipline matches grove_tpu/sim: explicit `now` everywhere, no
wall-clock reads, so tests are deterministic.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from grove_tpu.cluster.watch import EventType, WatchEvent
from grove_tpu.state.cluster import Node


@dataclass
class _KwokPod:
    name: str
    node: str
    bound_at: float
    running_at: float | None = None
    ready_at: float | None = None
    deleted: bool = False


@dataclass
class KwokCluster:
    """Fake node fleet with staged pod lifecycles and lagged watch delivery."""

    nodes: dict[str, Node] = field(default_factory=dict)
    # Stage latencies (kind-up.sh:264-265 stage configs): bind -> Running,
    # Running -> Ready.
    running_delay_s: float = 0.5
    ready_delay_s: float = 0.5
    event_lag_s: float = 0.0

    _pods: dict[str, _KwokPod] = field(default_factory=dict)
    _queue: list[tuple[float, WatchEvent]] = field(default_factory=list)  # (visible_at, ev)

    # ---- cluster-side mutations (the "real world") -------------------------------

    def add_node(self, node: Node, now: float) -> None:
        self.nodes[node.name] = node
        self._emit(now, EventType.ADDED, "Node", node.name, self._node_payload(node))

    def remove_node(self, name: str, now: float) -> None:
        """Node disappears; its pods fail (terminated with the machine)."""
        self.nodes.pop(name, None)
        self._emit(now, EventType.DELETED, "Node", name, {})
        for pod in self._pods.values():
            if pod.node == name and not pod.deleted:
                pod.deleted = True
                self._emit(
                    now, EventType.MODIFIED, "Pod", pod.name,
                    {"phase": "Failed", "ready": False, "node": name},
                )

    def set_schedulable(self, name: str, schedulable: bool, now: float) -> None:
        node = self.nodes[name]
        node.schedulable = schedulable
        self._emit(now, EventType.MODIFIED, "Node", name, self._node_payload(node))

    def fail_pod(self, name: str, now: float) -> None:
        pod = self._pods.get(name)
        if pod is None or pod.deleted:
            return
        pod.deleted = True
        self._emit(
            now, EventType.MODIFIED, "Pod", name,
            {"phase": "Failed", "ready": False, "node": pod.node},
        )

    # ---- control-plane side ------------------------------------------------------

    def observe_binding(self, pod_name: str, node_name: str, now: float) -> None:
        """The bind call: control plane placed pod on node; stages start."""
        if pod_name in self._pods:
            return
        self._pods[pod_name] = _KwokPod(name=pod_name, node=node_name, bound_at=now)

    def observe_deletion(self, pod_name: str, now: float) -> None:
        """Control plane deleted the pod object; stop its lifecycle."""
        pod = self._pods.pop(pod_name, None)
        if pod is not None and not pod.deleted:
            self._emit(now, EventType.DELETED, "Pod", pod_name, {"node": pod.node})

    # ---- time + watch ------------------------------------------------------------

    def tick(self, now: float) -> None:
        """Advance pod stages up to `now` (KWOK stage controller analog)."""
        for pod in self._pods.values():
            if pod.deleted:
                continue
            if pod.running_at is None and now >= pod.bound_at + self.running_delay_s:
                pod.running_at = pod.bound_at + self.running_delay_s
                self._emit(
                    pod.running_at, EventType.MODIFIED, "Pod", pod.name,
                    {"phase": "Running", "ready": False, "node": pod.node},
                )
            if (
                pod.running_at is not None
                and pod.ready_at is None
                and now >= pod.running_at + self.ready_delay_s
            ):
                pod.ready_at = pod.running_at + self.ready_delay_s
                self._emit(
                    pod.ready_at, EventType.MODIFIED, "Pod", pod.name,
                    {"phase": "Running", "ready": True, "node": pod.node},
                )

    def poll(self, now: float) -> list[WatchEvent]:
        """Deliver events whose lag window has passed, in emission order."""
        self.tick(now)
        due = [(t, e) for t, e in self._queue if t <= now]
        self._queue = [(t, e) for t, e in self._queue if t > now]
        return [e for _, e in due]

    # ---- internals ---------------------------------------------------------------

    def _node_payload(self, node: Node) -> dict:
        return {
            "capacity": dict(node.capacity),
            "labels": dict(node.labels),
            "schedulable": node.schedulable,
            "taints": [dict(t) for t in node.taints],
        }

    def _emit(self, at: float, etype: EventType, kind: str, name: str, obj: dict) -> None:
        self._queue.append((at + self.event_lag_s, WatchEvent(etype, kind, name, obj)))


def kwok_fleet(nodes: list[Node], now: float = 0.0, **kwargs) -> KwokCluster:
    """Boot a KwokCluster pre-populated with `nodes` (events included)."""
    cluster = KwokCluster(**kwargs)
    for node in nodes:
        cluster.add_node(node, now)
    return cluster


def kwok_fleet_from_config(cluster_cfg, topology, now: float = 0.0) -> KwokCluster:
    """Fabricate the fleet declared by `cluster.source: kwok` in the operator
    config — the in-binary `make kind-up FAKE_NODES=N` analog
    (operator/hack/kind-up.sh:31,252-265).

    Every non-host topology level gets a node label so TAS pack constraints
    resolve against this fleet: hosts group into racks of `kwokHostsPerRack`,
    racks into blocks of `kwokRacksPerBlock`, and each broader level groups
    by the matching `kwokLevelGroupFactors` entry (narrowest first). The
    default zone-over-block shape keeps an implicit factor of 4 (the e2e
    rig's shape, operator/hack/e2e-cluster/create-e2e-cluster.py:133-135);
    config validation demands explicit factors only for hierarchies deeper
    than zone.
    """
    from grove_tpu.api.types import TopologyDomain

    levels = [
        lvl
        for lvl in topology.sorted_levels()
        if lvl.domain != TopologyDomain.HOST
    ]
    # Group sizes, narrowest level first.
    factors = list(getattr(cluster_cfg, "kwok_level_group_factors", []) or [])
    sizes: list[int] = []
    for i in range(len(levels)):
        if i == 0:
            sizes.append(max(1, cluster_cfg.kwok_hosts_per_rack))
        elif i == 1:
            sizes.append(sizes[-1] * max(1, cluster_cfg.kwok_racks_per_block))
        elif i - 2 < len(factors):
            sizes.append(sizes[-1] * max(1, factors[i - 2]))
        else:
            # Implicit zone factor for the default <=3-level shape; configs
            # deeper than zone never get here (validation requires explicit
            # factors for them).
            sizes.append(sizes[-1] * 4)
    nodes = []
    # Revocable (spot) slice: the LAST `cluster.revocableNodes` nodes carry
    # the revocable attribute — the fleet segment a revocation notice
    # (sim.node_revocation site / Simulator.revoke_node) may take back.
    revocable_from = cluster_cfg.kwok_nodes - max(
        0, int(getattr(cluster_cfg, "revocable_nodes", 0) or 0)
    )
    for n in range(cluster_cfg.kwok_nodes):
        labels: dict[str, str] = {}
        for lvl, size in zip(reversed(levels), sizes):
            labels[lvl.node_label_key] = f"{lvl.domain.value}-{n // size}"
        nodes.append(
            Node(
                name=f"kwok-{n}",
                capacity={
                    "cpu": cluster_cfg.kwok_cpu_per_node,
                    "memory": cluster_cfg.kwok_memory_per_node,
                    "google.com/tpu": cluster_cfg.kwok_tpu_per_node,
                },
                labels=labels,
                revocable=n >= revocable_from,
            )
        )
    return kwok_fleet(
        nodes,
        now=now,
        running_delay_s=cluster_cfg.running_delay_seconds,
        ready_delay_s=cluster_cfg.ready_delay_seconds,
        event_lag_s=cluster_cfg.event_lag_seconds,
    )
