"""Real-cluster WatchSource: the kube-apiserver list/watch protocol.

The reference's controllers see the world exclusively through apiserver
watch streams (informers — `operator/internal/controller/manager.go:53-121`;
the in-pod agent watches the same way, `operator/initc/internal/wait.go:
111-164`). This module is that integration path for the TPU stack, speaking
the wire protocol directly with no client dependency:

  list:   GET  {server}/api/v1/nodes                      -> NodeList + resourceVersion
  watch:  GET  {server}/api/v1/nodes?watch=1&resourceVersion=RV
          newline-delimited JSON {"type": ADDED|MODIFIED|DELETED|BOOKMARK,
          "object": {...}} until the server closes the stream; a 410 Gone
          (resourceVersion too old) forces a relist.
  bind:   POST {server}/api/v1/namespaces/{ns}/pods/{name}/binding
          — the kube-scheduler bind subresource; this is how solver
          assignments become real placements.
  create: POST {server}/api/v1/namespaces/{ns}/pods (pod materialization;
          the reference's pod component creates these objects the same way,
          `podclique/components/pod/pod.go:68`).

Reader threads pump each resource's list+watch loop into one queue;
``poll(now)`` (the WatchSource contract, cluster/watch.py) drains it on the
manager's reconcile cadence, so the driver's stale-view discipline applies
to real clusters exactly as it does to the KWOK fake.

Auth: kubeconfig (token / client cert / CA, base64 ``*-data`` variants
included) or the in-cluster service-account mount. No client library —
stdlib http.client + ssl for the wire (yaml only for kubeconfig parsing),
same dependency policy as the rest of the runtime.
"""

from __future__ import annotations

import base64
import http.client
import json
import os
import queue
import ssl
import tempfile
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, Optional

from grove_tpu.api import constants as api_constants
from grove_tpu.api.quantity import parse_quantity
from grove_tpu.cluster.watch import EventType, WatchEvent, WatchRetryPolicy
from grove_tpu import faults as faults_mod
from grove_tpu.utils.backoff import Backoff

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# The watch must select exactly the pods expansion stamps (expansion.py uses
# these constants) — a literal here would silently diverge from the label.
DEFAULT_POD_LABEL_SELECTOR = (
    f"{api_constants.LABEL_MANAGED_BY}={api_constants.LABEL_MANAGED_BY_VALUE}"
)


class KubeApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"apiserver returned {status}: {message}")
        self.status = status


@dataclass
class KubeContext:
    """Connection material for one cluster, resolved from kubeconfig or the
    in-cluster service-account mount."""

    server: str  # e.g. https://10.0.0.1:6443
    token: Optional[str] = None
    ca_pem: Optional[str] = None  # PEM bundle (verify server)
    client_cert_file: Optional[str] = None
    client_key_file: Optional[str] = None
    insecure_skip_verify: bool = False
    namespace: str = "default"

    def ssl_context(self) -> Optional[ssl.SSLContext]:
        if not self.server.startswith("https"):
            return None
        if self.insecure_skip_verify:
            ctx = ssl._create_unverified_context()  # explicit kubeconfig opt-in
        else:
            ctx = ssl.create_default_context()
            if self.ca_pem:
                ctx.load_verify_locations(cadata=self.ca_pem)
        if self.client_cert_file:
            ctx.load_cert_chain(self.client_cert_file, self.client_key_file)
        return ctx


def _b64_to_tempfile(data_b64: str, suffix: str) -> str:
    """Client cert/key *-data entries must become files for load_cert_chain;
    0600 tempfiles owned by this process, unlinked at interpreter exit so
    key material never outlives the run."""
    import atexit

    f = tempfile.NamedTemporaryFile(
        mode="wb", suffix=suffix, delete=False, prefix="grove-kubeconfig-"
    )
    os.chmod(f.name, 0o600)
    f.write(base64.b64decode(data_b64))
    f.close()

    def _cleanup(path=f.name):
        try:
            os.unlink(path)
        except OSError:
            pass

    atexit.register(_cleanup)
    return f.name


def load_kube_context(
    kubeconfig_path: Optional[str] = None,
    context_name: Optional[str] = None,
    namespace: Optional[str] = None,
) -> KubeContext:
    """Resolve connection material: explicit kubeconfig path, else
    $KUBECONFIG (colon-separated list: the first file DEFINING the
    requested/current context wins — per-file resolution, not kubectl's
    full cross-file merge), else ~/.kube/config, else the in-cluster
    mount."""
    candidates: list[str]
    if kubeconfig_path:
        candidates = [kubeconfig_path]
    elif os.environ.get("KUBECONFIG"):
        candidates = [
            p for p in os.environ["KUBECONFIG"].split(os.pathsep) if p
        ]
    else:
        candidates = [os.path.expanduser("~/.kube/config")]
    errors: list[str] = []
    for path in candidates:
        if not os.path.exists(path):
            continue
        try:
            return _context_from_kubeconfig(path, context_name, namespace)
        except ValueError as e:
            # Context not in THIS file — a later $KUBECONFIG entry may
            # define it (kubectl finds it via merging; we find it by file).
            errors.append(str(e))
    if os.path.exists(os.path.join(_SA_DIR, "token")):
        return _in_cluster_context(namespace)
    if errors:  # files existed but none defined the context
        raise ValueError("; ".join(errors))
    raise FileNotFoundError(
        f"no kubeconfig at {':'.join(candidates)} and no in-cluster "
        "service account mount"
    )


def _context_from_kubeconfig(
    path: str, context_name: Optional[str], namespace: Optional[str]
) -> KubeContext:
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    by_name = lambda items: {i["name"]: i for i in items or []}  # noqa: E731
    contexts = by_name(doc.get("contexts"))
    clusters = by_name(doc.get("clusters"))
    users = by_name(doc.get("users"))
    name = context_name or doc.get("current-context")
    if not name or name not in contexts:
        raise ValueError(f"{path}: context {name!r} not found")
    ctx = contexts[name]["context"]
    cluster = clusters[ctx["cluster"]]["cluster"]
    user = users.get(ctx.get("user", ""), {}).get("user", {})

    ca_pem = None
    if cluster.get("certificate-authority-data"):
        ca_pem = base64.b64decode(cluster["certificate-authority-data"]).decode()
    elif cluster.get("certificate-authority"):
        with open(cluster["certificate-authority"]) as f:
            ca_pem = f.read()

    cert_file = user.get("client-certificate")
    key_file = user.get("client-key")
    if user.get("client-certificate-data"):
        cert_file = _b64_to_tempfile(user["client-certificate-data"], ".crt")
    if user.get("client-key-data"):
        key_file = _b64_to_tempfile(user["client-key-data"], ".key")

    return KubeContext(
        server=cluster["server"].rstrip("/"),
        token=user.get("token"),
        ca_pem=ca_pem,
        client_cert_file=cert_file,
        client_key_file=key_file,
        insecure_skip_verify=bool(cluster.get("insecure-skip-tls-verify", False)),
        namespace=namespace or ctx.get("namespace", "default"),
    )


def _in_cluster_context(namespace: Optional[str]) -> KubeContext:
    with open(os.path.join(_SA_DIR, "token")) as f:
        token = f.read().strip()
    ca_path = os.path.join(_SA_DIR, "ca.crt")
    ca_pem = None
    if os.path.exists(ca_path):
        with open(ca_path) as f:
            ca_pem = f.read()
    ns = namespace
    ns_path = os.path.join(_SA_DIR, "namespace")
    if ns is None and os.path.exists(ns_path):
        with open(ns_path) as f:
            ns = f.read().strip()
    host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    return KubeContext(
        server=f"https://{host}:{port}",
        token=token,
        ca_pem=ca_pem,
        namespace=ns or "default",
    )


# ---------------------------------------------------------------------------------
# Client-side rate limiting (ClientConnectionConfiguration{QPS, Burst} analog)
# ---------------------------------------------------------------------------------


class TokenBucket:
    """QPS/Burst token bucket — the client-go flowcontrol rate limiter the
    reference's ClientConnectionConfiguration{QPS, Burst} configures.

    `burst` tokens of headroom refill at `qps` tokens/s; `acquire()` takes
    one token, sleeping out any deficit first (callers go at most `burst`
    over the sustained rate before throttling kicks in). qps <= 0 disables
    the limiter entirely. Thread-safe: the watch source's reader threads and
    the reconcile thread's binding calls share one bucket, which is the
    point — TOTAL apiserver pressure is what the server-side priority &
    fairness layer penalizes.
    """

    def __init__(
        self,
        qps: float,
        burst: int,
        time_fn=time.monotonic,
        sleep_fn=time.sleep,
    ):
        self.qps = float(qps)
        self.capacity = max(1, int(burst))
        self._tokens = float(self.capacity)
        self._time = time_fn
        self._sleep = sleep_fn
        self._last = time_fn()
        self._lock = threading.Lock()
        # Observability (the throttle counter metric's source of truth).
        self.throttled = 0  # acquisitions that had to wait
        self.wait_seconds = 0.0  # cumulative time spent waiting

    def acquire(self) -> float:
        """Take one token; returns the seconds waited (0.0 = no throttle)."""
        if self.qps <= 0:
            return 0.0
        with self._lock:
            now = self._time()
            self._tokens = min(
                self.capacity, self._tokens + (now - self._last) * self.qps
            )
            self._last = now
            self._tokens -= 1.0
            # Deficit tokens model queued requests: each waiter sleeps until
            # its token would have refilled, so concurrent callers space out
            # at the sustained rate instead of thundering on each refill.
            wait = max(0.0, -self._tokens / self.qps)
            if wait > 0:
                self.throttled += 1
                self.wait_seconds += wait
        if wait > 0:
            self._sleep(wait)
        return wait


# ---------------------------------------------------------------------------------
# Shared transport helpers
# ---------------------------------------------------------------------------------


def _open_connection(ctx: KubeContext, timeout: float) -> http.client.HTTPConnection:
    u = urllib.parse.urlsplit(ctx.server)
    if u.scheme == "https":
        return http.client.HTTPSConnection(
            u.hostname, u.port or 443, timeout=timeout, context=ctx.ssl_context()
        )
    return http.client.HTTPConnection(u.hostname, u.port or 80, timeout=timeout)


def _auth_headers(ctx: KubeContext) -> dict:
    h = {"Accept": "application/json"}
    if ctx.token:
        h["Authorization"] = f"Bearer {ctx.token}"
    return h


# ---------------------------------------------------------------------------------
# Object translation: k8s wire objects -> WatchEvent payloads
# ---------------------------------------------------------------------------------


def node_payload(obj: dict) -> dict:
    """corev1.Node -> the driver's node dict. Allocatable over capacity (what
    the scheduler may actually use); quantity strings -> base-unit floats."""
    status = obj.get("status", {}) or {}
    spec = obj.get("spec", {}) or {}
    raw = status.get("allocatable") or status.get("capacity") or {}
    return {
        "capacity": {k: parse_quantity(v) for k, v in raw.items()},
        "labels": dict((obj.get("metadata", {}) or {}).get("labels", {}) or {}),
        "schedulable": not spec.get("unschedulable", False),
        "taints": [dict(t) for t in spec.get("taints", []) or []],
    }


def pod_payload(obj: dict) -> dict:
    """corev1.Pod -> the driver's pod dict: phase, readiness (the Ready
    condition — same definition the initc agent counts,
    `initc/internal/wait.go:240-275`), and the bound node."""
    status = obj.get("status", {}) or {}
    ready = any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in status.get("conditions", []) or []
    )
    out: dict = {"ready": ready}
    if status.get("phase"):
        out["phase"] = status["phase"]
    node = (obj.get("spec", {}) or {}).get("nodeName")
    if node:
        out["node"] = node
    return out


# ---------------------------------------------------------------------------------
# The watch source
# ---------------------------------------------------------------------------------


@dataclass
class _ResourceWatch:
    kind: str  # "Node" | "Pod" | "PodCliqueSet"
    list_path: str  # e.g. /api/v1/nodes
    selector: str = ""  # labelSelector value, if any
    # 404 on the LIST means the resource type itself is absent (the grove.io
    # CRD not installed): back off this long instead of hot-looping, and log
    # the condition once, not per retry.
    missing_backoff_s: float = 1.0
    _missing_logged: bool = False
    # Disconnect handling: capped decorrelated-jitter resubscribe pacing +
    # resync accounting (cluster/watch.py WatchRetryPolicy). Replaces the
    # old fixed 1s sleep — a flapping apiserver sees spread-out reconnects,
    # and every reconnect/forced-resync is COUNTED (grove_watch_* metrics).
    retry: WatchRetryPolicy = field(default_factory=WatchRetryPolicy)


class KubernetesWatchSource:
    """WatchSource (cluster/watch.py protocol) backed by a live apiserver.

    Inbound: reader threads run list+watch per resource, translating wire
    objects into WatchEvents on a shared queue; `poll` drains it. Outbound:
    `observe_binding` materializes the pod object (if needed) and POSTs the
    binding subresource; `observe_deletion` deletes the pod.
    """

    def __init__(
        self,
        ctx: KubeContext,
        pod_label_selector: Optional[str] = None,  # None = the managed-by label
        pod_manifest_for: Optional[Callable[[str], Optional[dict]]] = None,
        request_timeout_s: float = 10.0,
        watch_read_timeout_s: float = 30.0,
        watch_workloads: bool = True,
        initc_kube_tokens: bool = False,
        qps: float = 50.0,  # ClientConnectionConfiguration.QPS (0 = unlimited)
        burst: int = 100,  # ClientConnectionConfiguration.Burst
        bind_retry_attempts: int = 1,  # in-call bind retries (resilience.*)
        transport_retries: int = 1,  # per-request reconnect attempts
        backoff_base_s: float = 0.05,  # shared decorrelated-jitter pacing
        backoff_cap_s: float = 2.0,
    ):
        if pod_label_selector is None:
            pod_label_selector = DEFAULT_POD_LABEL_SELECTOR
        self.ctx = ctx
        # One bucket for every request this source issues (unary calls AND
        # watch-stream initiations): total apiserver pressure is the thing
        # being limited.
        self.limiter = TokenBucket(qps, burst)
        self.pod_manifest_for = pod_manifest_for
        self._local = threading.local()  # per-thread persistent connection
        self._queue: "queue.Queue[WatchEvent]" = queue.Queue()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._request_timeout_s = request_timeout_s
        self._watch_read_timeout_s = watch_read_timeout_s
        ns = urllib.parse.quote(ctx.namespace)
        self._pods_path = f"/api/v1/namespaces/{ns}/pods"
        # The user workload API over the SAME apiserver: PodCliqueSet CRs
        # arrive by watch exactly as the reference's controllers see them
        # (kubectl apply -> etcd -> watch, SURVEY §3.2-3.3); reconciled
        # status is written back to the CR's status subresource.
        self._pcs_path = (
            f"/apis/grove.io/v1alpha1/namespaces/{ns}/podcliquesets"
        )
        self._watches = [
            _ResourceWatch("Node", "/api/v1/nodes"),
            _ResourceWatch("Pod", self._pods_path, selector=pod_label_selector),
        ]
        if watch_workloads:
            self._watches.append(
                _ResourceWatch(
                    "PodCliqueSet", self._pcs_path, missing_backoff_s=30.0
                )
            )
            # Child CR projections are operator-owned, but their SCALE
            # subresource is a public surface (reference: HPA ScaleTargetRef
            # -> PCLQ/PCSG scale, components/hpa/hpa.go:249-259; kubectl
            # scale pclq). Watching them turns external spec.replicas writes
            # into scale events; echoes of our own projection PUTs compare
            # equal at the driver and cost nothing.
            for kind, plural in (
                ("PodClique", "podcliques"),
                ("PodCliqueScalingGroup", "podcliquescalinggroups"),
            ):
                self._watches.append(
                    _ResourceWatch(
                        kind,
                        f"/apis/grove.io/v1alpha1/namespaces/{ns}/{plural}",
                        missing_backoff_s=30.0,
                    )
                )
        # Bind retry (resilience.bindMaxAttempts): attempts per observe_
        # binding call, decorrelated-jitter paced; 1 = one shot, the
        # WatchDriver's cross-tick retry set is the outer loop either way.
        self.bind_retry_attempts = max(1, int(bind_retry_attempts))
        self.transport_retries = max(0, int(transport_retries))
        self._backoff_base_s = float(backoff_base_s)
        self._backoff_cap_s = float(backoff_cap_s)
        # Monotonic fault-recovery counters (manager -> grove_bind_retries_
        # total; watch reconnect/resync counters live per _ResourceWatch).
        self.bind_retries = 0
        # Wire-visible error log (last few), surfaced via statusz/tests.
        self.errors: list[str] = []
        # Managed Services mirrored to the cluster: name -> last manifest.
        self._synced_services: dict[str, dict] = {}
        # Child CR projections (podcliques/pcsgs): plural -> name -> manifest.
        self._synced_children: dict[str, dict] = {}
        # SA-token Secrets mirrored (pods mount them): name -> manifest.
        self._synced_secrets: dict[str, dict] = {}
        # cluster.initcMode kubernetes: token Secrets become REAL
        # service-account-token Secrets (the control plane mints the token)
        # and the per-PCS SA/Role/RoleBinding are mirrored too.
        self.initc_kube_tokens = initc_kube_tokens
        self._synced_rbac: dict[str, dict[str, dict]] = {
            "serviceaccounts": {},
            "roles": {},
            "rolebindings": {},
        }
        # Collections whose cluster-side members have been LISTed into the
        # cache (crash-orphan GC; _sync_collection).
        self._seeded_bases: set[str] = set()
        # Per-collection sync counter driving the periodic resync relist.
        self._resync_counts: dict[str, int] = {}

    # ---- lifecycle ----------------------------------------------------------------

    def start(self) -> None:
        if self._threads:
            return
        for rw in self._watches:
            t = threading.Thread(
                target=self._run_watch, args=(rw,), daemon=True,
                name=f"kube-watch-{rw.kind.lower()}",
            )
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()

    # ---- WatchSource protocol -------------------------------------------------------

    def poll(self, now: float) -> list[WatchEvent]:
        events: list[WatchEvent] = []
        while True:
            try:
                events.append(self._queue.get_nowait())
            except queue.Empty:
                return events

    def observe_binding(self, pod_name: str, node_name: str, now: float) -> bool:
        """Materialize + bind: ensure the Pod object exists (409 = already
        there), then POST the binding subresource — the scheduler-side bind
        call that turns a solver assignment into a kubelet start.

        Retry discipline (resilience.bindMaxAttempts): the whole
        create+bind sequence retries in-call with decorrelated-jitter
        pacing — both halves are idempotent (409 on create = already there,
        409 on bind = already bound), so a retry after an ambiguous
        transport failure converges instead of double-binding. Exhaustion
        returns False so the WatchDriver keeps the pod in its cross-tick
        retry set (a transient 500 must not orphan the placement)."""
        backoff = Backoff(self._backoff_base_s, self._backoff_cap_s)
        attempt = 0
        while True:
            ok = self._observe_binding_once(pod_name, node_name)
            if ok:
                return True
            attempt += 1
            if attempt >= self.bind_retry_attempts:
                return False
            self.bind_retries += 1
            backoff.sleep()

    def _observe_binding_once(self, pod_name: str, node_name: str) -> bool:
        manifest = (
            self.pod_manifest_for(pod_name) if self.pod_manifest_for else None
        )
        if manifest is not None:
            # Single-namespace operation (the store is single-namespace too,
            # orchestrator/store.py): the create must target the namespace
            # the watch covers or its events would never flow back.
            manifest.setdefault("metadata", {})["namespace"] = self.ctx.namespace
            try:
                self._request("POST", self._pods_path, manifest)
            except (KubeApiError, OSError, ValueError) as e:
                if not (isinstance(e, KubeApiError) and e.status == 409):
                    self._record_error(f"create pod {pod_name}: {e}")
                    return False  # AlreadyExists is the steady state; rest retry
        binding = {
            "apiVersion": "v1",
            "kind": "Binding",
            "metadata": {"name": pod_name, "namespace": self.ctx.namespace},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node_name},
        }
        try:
            self._request("POST", f"{self._pods_path}/{pod_name}/binding", binding)
        except (KubeApiError, OSError, ValueError) as e:
            if isinstance(e, KubeApiError) and e.status == 409:
                return True  # already bound = this push already landed
            self._record_error(f"bind pod {pod_name} -> {node_name}: {e}")
            return False
        return True

    def watch_stats(self) -> dict:
        """Fault-recovery view of the informer loops (manager /statusz
        resilience.watch + grove_watch_* metrics)."""
        return {
            "reconnects": sum(rw.retry.reconnects for rw in self._watches),
            "resyncs": sum(rw.retry.resyncs for rw in self._watches),
            "bindRetries": self.bind_retries,
        }

    def sync_services(self, services: list) -> bool:
        """Mirror the store's HeadlessService objects into real cluster
        Services (service.go:137-155): pod DNS (`<hostname>.<subdomain>`)
        only resolves when the headless Service actually exists at the
        apiserver. Create-or-update for desired, delete for stale managed
        ones; returns False when any write failed (retried next push)."""
        ns = urllib.parse.quote(self.ctx.namespace)
        path = f"/api/v1/namespaces/{ns}/services"
        desired = {}
        for svc in services:
            desired[svc.name] = {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {
                    "name": svc.name,
                    "namespace": self.ctx.namespace,
                    "labels": {
                        api_constants.LABEL_MANAGED_BY: api_constants.LABEL_MANAGED_BY_VALUE,
                        api_constants.LABEL_PART_OF: svc.pcs_name,
                    },
                },
                "spec": {
                    "clusterIP": "None",
                    "selector": dict(svc.selector),
                    "publishNotReadyAddresses": bool(
                        svc.publish_not_ready_addresses
                    ),
                },
            }
        return self._sync_collection(path, desired, self._synced_services)

    def sync_secrets(self, secrets: list) -> bool:
        """Mirror the store's SA-token Secrets to the cluster — the rendered
        pods MOUNT them (initc token volume, satokensecret component
        analog); without this mirror every gated pod wedges in
        ContainerCreating on FailedMount."""
        ns = urllib.parse.quote(self.ctx.namespace)
        path = f"/api/v1/namespaces/{ns}/secrets"
        desired = {}
        for sec in secrets:
            meta = {
                "name": sec.name,
                "namespace": self.ctx.namespace,
                "labels": {
                    api_constants.LABEL_MANAGED_BY: api_constants.LABEL_MANAGED_BY_VALUE,
                    api_constants.LABEL_PART_OF: getattr(sec, "pcs_name", ""),
                },
            }
            if self.initc_kube_tokens:
                # initcMode kubernetes: the mounted token must be one the
                # APISERVER honors — a legacy service-account-token Secret,
                # whose `token` key the k8s control plane populates for the
                # bound SA (the reference's satokensecret component does
                # exactly this, components/satokensecret/).
                meta["annotations"] = {
                    "kubernetes.io/service-account.name": getattr(
                        sec, "service_account_name", ""
                    )
                }
                desired[sec.name] = {
                    "apiVersion": "v1",
                    "kind": "Secret",
                    "metadata": meta,
                    "type": "kubernetes.io/service-account-token",
                }
            else:
                desired[sec.name] = {
                    "apiVersion": "v1",
                    "kind": "Secret",
                    "metadata": meta,
                    "type": "Opaque",
                    "stringData": {"token": sec.token},
                }
        return self._sync_collection(
            path, desired, self._synced_secrets, recreate_on_invalid=True
        )

    def sync_rbac(self, service_accounts: list, roles: list, bindings: list) -> bool:
        """Mirror the per-PCS ServiceAccount/Role/RoleBinding so the
        service-account-token Secret resolves to a credential the apiserver
        accepts for listing gang pods (initcMode kubernetes; the reference's
        serviceaccount/role/rolebinding components). No-op unless
        initc_kube_tokens — operator-mode tokens never reach the apiserver."""
        if not self.initc_kube_tokens:
            return True
        ns_raw = self.ctx.namespace
        ns = urllib.parse.quote(ns_raw)

        def _meta(obj) -> dict:
            return {
                "name": obj.name,
                "namespace": ns_raw,
                "labels": {
                    api_constants.LABEL_MANAGED_BY: api_constants.LABEL_MANAGED_BY_VALUE,
                    api_constants.LABEL_PART_OF: getattr(obj, "pcs_name", ""),
                },
            }

        ok = self._sync_collection(
            f"/api/v1/namespaces/{ns}/serviceaccounts",
            {
                sa.name: {
                    "apiVersion": "v1",
                    "kind": "ServiceAccount",
                    "metadata": _meta(sa),
                }
                for sa in service_accounts
            },
            self._synced_rbac["serviceaccounts"],
        )
        rbac_base = f"/apis/rbac.authorization.k8s.io/v1/namespaces/{ns}"

        def _k8s_rules(role) -> list:
            # Store-level rules carry their apiGroup explicitly
            # (api/resources.Role) — no name-based guessing here.
            return [
                {
                    "apiGroups": [rule.get("apiGroup", "")],
                    "resources": list(rule.get("resources", [])),
                    "verbs": sorted(set(rule.get("verbs", [])) | {"watch"}),
                }
                for rule in role.rules
            ]

        ok = self._sync_collection(
            f"{rbac_base}/roles",
            {
                role.name: {
                    "apiVersion": "rbac.authorization.k8s.io/v1",
                    "kind": "Role",
                    "metadata": _meta(role),
                    "rules": _k8s_rules(role),
                }
                for role in roles
            },
            self._synced_rbac["roles"],
        ) and ok
        ok = self._sync_collection(
            f"{rbac_base}/rolebindings",
            {
                rb.name: {
                    "apiVersion": "rbac.authorization.k8s.io/v1",
                    "kind": "RoleBinding",
                    "metadata": _meta(rb),
                    "roleRef": {
                        "apiGroup": "rbac.authorization.k8s.io",
                        "kind": "Role",
                        "name": rb.role_name,
                    },
                    "subjects": [
                        {
                            "kind": "ServiceAccount",
                            "name": rb.service_account_name,
                            "namespace": ns_raw,
                        }
                    ],
                }
                for rb in bindings
            },
            self._synced_rbac["rolebindings"],
        ) and ok
        return ok

    # ---- managed-object sync plumbing ----------------------------------------------

    _PREEXISTING = {"_preexisting": True}  # cache sentinel from seeding
    # Managed-collection syncs between resync relists (the informer-resync
    # analog healing out-of-band deletes of unchanged objects).
    RESYNC_SYNCS = 30

    def _seed_cache(self, base: str, cache: dict) -> bool:
        """First sync after (re)start: LIST the cluster's managed objects so
        ones surviving a crash participate in GC — an in-memory cache alone
        would orphan them forever (live DNS records, stale CRs)."""
        names = self._list_names(base, op="seed")
        if names is None:
            return False
        for name in names:
            cache.setdefault(name, dict(self._PREEXISTING))
        return True

    def _list_names(self, base: str, op: str = "resync") -> set | None:
        """Names of the collection's live managed objects, None on failure
        (a failed seed retries; a failed resync LIST must not evict)."""
        try:
            doc = self._request(
                "GET", base, query={"labelSelector": DEFAULT_POD_LABEL_SELECTOR}
            )
        except (KubeApiError, OSError, ValueError) as e:
            self._record_error(f"{op} {base}: {e}")
            return None
        return {
            item["metadata"]["name"] for item in doc.get("items", []) or []
        }

    def _upsert_object(
        self, base: str, name: str, manifest: dict, known: bool,
        status_subresource: bool = False,
        recreate_on_invalid: bool = False,
    ) -> bool:
        """Create-or-update with real apiserver semantics: updates are
        GET-then-PUT (resourceVersion threaded through), and when the CRD
        declares a status subresource the .status field — which the main
        PUT/POST STRIPS — is written with a second PUT to /status.

        `recreate_on_invalid`: a 422 on the update PUT means an immutable
        field changed (e.g. a Secret's `type` when cluster.initcMode flips)
        — delete + re-create instead of wedging on the same rejected PUT
        forever."""

        def _put_main() -> None:
            try:
                cur = self._request("GET", f"{base}/{name}")
            except KubeApiError as e:
                if e.status != 404:
                    raise
                # Known-to-us but gone from the cluster (out-of-band
                # kubectl delete): heal by re-creating instead of failing
                # the GET-then-PUT forever.
                self._request("POST", base, manifest)
                return
            body = dict(manifest)
            rv = (cur.get("metadata", {}) or {}).get("resourceVersion")
            if rv:
                body["metadata"] = {**manifest["metadata"], "resourceVersion": rv}
            try:
                self._request("PUT", f"{base}/{name}", body)
            except KubeApiError as e:
                if not (recreate_on_invalid and e.status == 422):
                    raise
                self._request("DELETE", f"{base}/{name}")
                self._request("POST", base, manifest)

        try:
            if known:
                _put_main()
            else:
                try:
                    self._request("POST", base, manifest)
                except KubeApiError as e:
                    if e.status != 409:
                        raise
                    _put_main()
            if status_subresource and "status" in manifest:
                cur = self._request("GET", f"{base}/{name}")
                cur["status"] = manifest["status"]
                self._request("PUT", f"{base}/{name}/status", cur)
        except (KubeApiError, OSError, ValueError) as e:
            self._record_error(f"sync {base}/{name}: {e}")
            return False
        return True

    def _sync_collection(
        self, base: str, desired: dict, cache: dict,
        status_subresource: bool = False,
        recreate_on_invalid: bool = False,
    ) -> bool:
        """Reconcile one managed collection: seed once, upsert changed,
        delete stale. `cache` maps name -> last-pushed manifest (or the
        seeding sentinel, which never equals a desired manifest)."""
        ok = True
        if base not in self._seeded_bases:
            if self._seed_cache(base, cache):
                self._seeded_bases.add(base)
            else:
                ok = False  # retry the seed next push; GC waits for it
        else:
            # Informer-resync analog: every RESYNC_SYNCS passes, re-LIST and
            # evict cache entries whose live object vanished (out-of-band
            # kubectl delete of an UNCHANGED object would otherwise be
            # skipped-as-synced forever; the upsert loop below re-creates
            # evicted names). Counted per collection, cheap: one LIST.
            self._resync_counts[base] = self._resync_counts.get(base, 0) + 1
            if self._resync_counts[base] >= self.RESYNC_SYNCS:
                live = self._list_names(base)
                if live is not None:
                    # Reset only on success: a failed relist retries next
                    # pass instead of waiting out another full interval.
                    self._resync_counts[base] = 0
                    for name in [n for n in cache if n not in live]:
                        del cache[name]
        for name, manifest in desired.items():
            if cache.get(name) == manifest:
                continue
            known = name in cache
            if self._upsert_object(
                base, name, manifest, known, status_subresource,
                recreate_on_invalid,
            ):
                cache[name] = manifest
            else:
                ok = False
        for name in [n for n in cache if n not in desired]:
            try:
                self._request("DELETE", f"{base}/{name}")
            except (KubeApiError, OSError, ValueError) as e:
                if not (isinstance(e, KubeApiError) and e.status == 404):
                    self._record_error(f"delete {base}/{name}: {e}")
                    ok = False
                    continue
            del cache[name]
        return ok

    def invalidate_child_projection(self, name: str) -> None:
        """Drop the sync cache entry for one child CR so the next push
        re-PUTs it even though the DESIRED manifest hasn't changed — the
        heal for an external write the operator rejected (the wire changed
        behind the cache's back; without this the CR would show the
        rejected value forever)."""
        for plural in ("podcliques", "podcliquescalinggroups"):
            self._synced_children.get(plural, {}).pop(name, None)

    def last_projected_replicas(self, name: str) -> Optional[int]:
        """spec.replicas of the child-CR manifest THIS process last pushed
        (None = never pushed / pre-existing from before a restart). The
        child-scale sink uses it to tell external writes from echoes and
        relist replays of our own projections — store state can't do that:
        a pending override makes the store disagree with what's actually on
        the wire."""
        for plural in ("podcliques", "podcliquescalinggroups"):
            manifest = self._synced_children.get(plural, {}).get(name)
            if isinstance(manifest, dict) and "spec" in manifest:
                reps = (manifest.get("spec") or {}).get("replicas")
                return reps if isinstance(reps, int) else None
        return None

    def sync_workload_children(self, podcliques: list, scaling_groups: list) -> bool:
        """Mirror the operator-owned PodClique / PodCliqueScalingGroup
        objects to the apiserver as CRs (the reference materializes these
        as CRs with status; here the store is authoritative and the CRs are
        a one-way kubectl-visible projection: `kubectl get pclq,pcsg`).
        Spec carries the scale-relevant fields; status is the full rollup."""
        from grove_tpu.utils.serde import to_k8s

        ns = urllib.parse.quote(self.ctx.namespace)
        ok = True
        for plural, kind, objs, spec_of in (
            (
                "podcliques",
                "PodClique",
                podcliques,
                lambda o: {
                    "roleName": o.spec.role_name,
                    "replicas": o.spec.replicas,
                    "minAvailable": o.min_available,
                },
            ),
            (
                "podcliquescalinggroups",
                "PodCliqueScalingGroup",
                scaling_groups,
                lambda o: {
                    "replicas": o.spec.replicas,
                    "minAvailable": o.spec.min_available,
                    "cliqueNames": list(o.spec.clique_names),
                },
            ),
        ):
            base = f"/apis/grove.io/v1alpha1/namespaces/{ns}/{plural}"
            desired = {}
            for obj in objs:
                name = obj.metadata.name
                desired[name] = {
                    "apiVersion": "grove.io/v1alpha1",
                    "kind": kind,
                    "metadata": {
                        "name": name,
                        "namespace": self.ctx.namespace,
                        "labels": {
                            api_constants.LABEL_MANAGED_BY: api_constants.LABEL_MANAGED_BY_VALUE,
                            api_constants.LABEL_PART_OF: obj.pcs_name,
                        },
                    },
                    "spec": spec_of(obj),
                    "status": to_k8s(obj.status),
                }
            cache = self._synced_children.setdefault(plural, {})
            # status_subresource: the child CRDs declare one, so a real
            # apiserver STRIPS .status from the main POST/PUT — the rollup
            # must land through PUT .../status or kubectl shows none.
            ok = (
                self._sync_collection(
                    base, desired, cache, status_subresource=True
                )
                and ok
            )
        return ok

    def publish_events(self, events: list) -> int:
        """Mirror control-plane events ((ts, object, message) tuples) as
        corev1 Events — the reference records a k8s Event on every component
        action (`podgang/syncflow.go:451-458,547-554`); this is that
        visibility for `kubectl get events`. Returns how many landed (the
        caller advances its high-water mark by the return value, so a
        mid-batch failure retries only the tail)."""
        ns = urllib.parse.quote(self.ctx.namespace)
        path = f"/api/v1/namespaces/{ns}/events"
        landed = 0
        for ts, obj, msg in events:
            stamp = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts if ts > 1e6 else time.time())
            )
            name = f"grove-{abs(hash((round(ts, 3), obj, msg))):x}"
            body = {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"name": name, "namespace": self.ctx.namespace},
                "involvedObject": {
                    "namespace": self.ctx.namespace,
                    "name": obj,
                },
                "reason": "GroveReconcile",
                "message": msg,
                "type": "Normal",
                "firstTimestamp": stamp,
                "lastTimestamp": stamp,
                "count": 1,
                "source": {"component": "grove-tpu-operator"},
            }
            try:
                self._request("POST", path, body)
            except (KubeApiError, OSError, ValueError) as e:
                if isinstance(e, KubeApiError) and e.status == 409:
                    pass  # already mirrored (retry overlap): landed
                elif isinstance(e, KubeApiError) and 400 <= e.status < 500:
                    # Permanent rejection (e.g. stricter Event validation):
                    # SKIP it — a poison event must not head-of-line block
                    # every later event forever.
                    self._record_error(f"event publish (skipped): {e}")
                else:
                    self._record_error(f"event publish: {e}")
                    break  # transient: retry from here next push
            landed += 1
        return landed

    def sync_cluster_topology(self, topology) -> bool:
        """Create/update the cluster-scoped ClusterTopology CR from the
        operator config (the reference's startup sync,
        `internal/clustertopology/clustertopology.go:39-51`; CR name
        `grove-topology` per DefaultClusterTopologyName). Best-effort: a
        cluster without the CRD returns False and the operator runs on its
        in-memory topology."""
        path = "/apis/grove.io/v1alpha1/clustertopologies/grove-topology"
        levels = topology.levels_doc()
        body = {
            "apiVersion": "grove.io/v1alpha1",
            "kind": "ClusterTopology",
            "metadata": {"name": "grove-topology"},
            "spec": {"levels": levels},
        }
        try:
            try:
                cur = self._request("GET", path)
            except KubeApiError as e:
                if e.status != 404:
                    raise
                self._request(
                    "POST", "/apis/grove.io/v1alpha1/clustertopologies", body
                )
                return True
            cur["spec"] = body["spec"]
            self._request("PUT", path, cur)
            return True
        except (KubeApiError, OSError, ValueError) as e:
            self._record_error(f"ClusterTopology sync: {e}")
            return False

    def sync_webhook_ca(self, ca_pem: bytes, app: str = "grove-tpu-operator") -> bool:
        """Write the webhook serving cert into the Mutating/Validating
        WebhookConfigurations' clientConfig.caBundle — the cert-controller
        rotator's job in the reference (cert.go:66-93): deploy renders the
        configs with an empty bundle, the running operator completes them so
        the apiserver can verify the TLS it is told to call. Best-effort: a
        cluster without the configs (webhook disabled at deploy) returns
        False."""
        bundle = base64.b64encode(ca_pem).decode()
        ok = True
        for kind in ("mutatingwebhookconfigurations", "validatingwebhookconfigurations"):
            path = f"/apis/admissionregistration.k8s.io/v1/{kind}/{app}"
            try:
                cur = self._request("GET", path)
                changed = False
                for wh in cur.get("webhooks", []) or []:
                    cc = wh.setdefault("clientConfig", {})
                    if cc.get("caBundle") != bundle:
                        cc["caBundle"] = bundle
                        changed = True
                if changed:
                    self._request("PUT", path, cur)
            except (KubeApiError, OSError, ValueError) as e:
                self._record_error(f"webhook caBundle sync ({kind}): {e}")
                ok = False
        return ok

    def delete_workload(self, name: str) -> bool:
        """Delete the PodCliqueSet CR (an operator-API delete must also
        remove the CR, or the next relist re-emits ADDED and resurrects the
        workload). 404 = already gone = success."""
        try:
            self._request("DELETE", f"{self._pcs_path}/{name}")
        except (KubeApiError, OSError, ValueError) as e:
            if isinstance(e, KubeApiError) and e.status == 404:
                return True
            self._record_error(f"delete workload CR {name}: {e}")
            return False
        return True

    def publish_workload_status(self, name: str, status: dict):
        """Write reconciled status back to the PodCliqueSet CR's status
        subresource (the reference persists status the same way,
        reconcilestatus.go). GET-then-PUT with the live resourceVersion.

        Returns True on success, None when no such CR exists at the
        apiserver (a store-only workload applied via the operator's own
        HTTP API — nothing to write to; the caller must NOT retry until
        the status changes, or every tick pays a doomed GET), and False on
        transient failures (conflict/wire) that should retry next tick."""
        try:
            cur = self._request("GET", f"{self._pcs_path}/{name}")
            cur["status"] = status
            self._request("PUT", f"{self._pcs_path}/{name}/status", cur)
        except (KubeApiError, OSError, ValueError) as e:
            if isinstance(e, KubeApiError) and e.status == 404:
                return None
            if not (isinstance(e, KubeApiError) and e.status == 409):
                self._record_error(f"status write {name}: {e}")
            return False
        return True

    def list_node_capacities(self) -> Optional[list]:
        """One-shot node LIST for boot-time preflights (the accelerator
        preflight checks the slice resource is visible SOMEWHERE before the
        manager commits to auto-slice injection). Returns each node's
        capacity dict, or None when the apiserver is unreachable — a
        transient outage must not fail a boot the watch loop would heal."""
        try:
            doc = self._request("GET", "/api/v1/nodes")
        except (KubeApiError, OSError, ValueError) as e:
            self._record_error(f"node preflight list: {e}")
            return None
        return [
            node_payload(item).get("capacity", {})
            for item in (doc or {}).get("items", []) or []
        ]

    def observe_deletion(self, pod_name: str, now: float) -> bool:
        try:
            self._request("DELETE", f"{self._pods_path}/{pod_name}")
        except (KubeApiError, OSError, ValueError) as e:
            if isinstance(e, KubeApiError) and e.status == 404:
                return True  # already gone is success
            self._record_error(f"delete pod {pod_name}: {e}")
            return False  # retry next tick or the cluster pod runs forever
        return True

    # ---- list+watch loop ------------------------------------------------------------

    def _run_watch(self, rw: _ResourceWatch) -> None:
        """One resource's informer loop: list (seeding ADDED events), then
        stream the watch from the list's resourceVersion. A clean stream end
        (server timeout/close) RESUMES the watch from the last-seen
        resourceVersion — no relist, no error; only a wire error or a 410
        Gone forces the relist (the real informer contract)."""
        known: set[str] = set()
        while not self._stop.is_set():
            try:
                rv, names = self._list(rw, known)
                rw._missing_logged = False
                rw.retry.note_healthy()
                known = names
                while not self._stop.is_set():
                    rv = self._stream_watch(rw, rv, known)
            except (OSError, KubeApiError, json.JSONDecodeError) as e:
                if isinstance(e, KubeApiError) and e.status == 404:
                    # Resource type absent (CRD not installed): long
                    # backoff, one log line — not a hot loop that drowns
                    # real Node/Pod errors out of the 20-entry buffer.
                    if not rw._missing_logged:
                        rw._missing_logged = True
                        self._record_error(
                            f"{rw.kind} watch: resource absent at the "
                            f"apiserver (404); retrying every "
                            f"{rw.missing_backoff_s:.0f}s"
                        )
                    if self._stop.wait(rw.missing_backoff_s):
                        return
                    continue
                if isinstance(e, KubeApiError) and e.status == 410:
                    # resourceVersion expired while we were away: the
                    # relist above IS the full resync (ghost DELETEDs
                    # synthesized); count it — silent resyncs hide a
                    # chronically-lagging informer.
                    rw.retry.note_resync()
                self._record_error(f"{rw.kind} watch: {e}")
                # Capped decorrelated-jitter resubscribe (counted): fast
                # after one blip, spread out under a flapping apiserver.
                if self._stop.wait(rw.retry.next_delay()):
                    return

    def _list(self, rw: _ResourceWatch, known: set[str]) -> tuple[str, set[str]]:
        qs = {"labelSelector": rw.selector} if rw.selector else {}
        doc = self._request("GET", rw.list_path, query=qs)
        rv = (doc.get("metadata", {}) or {}).get("resourceVersion", "")
        seen: set[str] = set()
        for obj in doc.get("items", []) or []:
            name = obj["metadata"]["name"]
            seen.add(name)
            self._emit(EventType.ADDED, rw.kind, name, obj)
        # Objects that vanished between watch interruptions would otherwise
        # be ghosts forever: synthesize their DELETED on relist.
        for name in known - seen:
            self._emit(EventType.DELETED, rw.kind, name, {})
        return rv, seen

    def _stream_watch(self, rw: _ResourceWatch, rv: str, known: set[str]) -> str:
        """Stream one watch request; returns the last-seen resourceVersion
        so the caller can RESUME without relisting. The server is asked to
        close the stream (timeoutSeconds) just before our socket timeout
        would fire, so an idle-but-healthy cluster cycles cleanly instead of
        raising and relisting every read-timeout."""
        qs = {
            "watch": "1",
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(max(1, int(self._watch_read_timeout_s))),
        }
        if rv:
            qs["resourceVersion"] = rv
        if rw.selector:
            qs["labelSelector"] = rw.selector
        # Fault site: a dropped watch stream (network partition, apiserver
        # restart) surfaces as OSError here; the informer loop resubscribes
        # with capped backoff and resyncs on 410 — the path this site tests.
        faults_mod.active().maybe_raise(
            "watch.disconnect",
            resource=rw.kind,
            exc_factory=lambda s: KubeApiError(s, "injected watch fault"),
        )
        # Stream initiation counts against the bucket (long-lived reads do
        # not — the server's timeoutSeconds already paces re-establishment).
        self.limiter.acquire()
        conn = self._connect(timeout=self._watch_read_timeout_s + 5.0)
        try:
            conn.request(
                "GET",
                f"{rw.list_path}?{urllib.parse.urlencode(qs)}",
                headers=self._headers(),
            )
            resp = conn.getresponse()
            if resp.status == 410:
                raise KubeApiError(410, "resourceVersion too old; relisting")
            if resp.status != 200:
                raise KubeApiError(resp.status, resp.read(2048).decode("utf-8", "replace"))
            while not self._stop.is_set():
                try:
                    line = resp.readline()
                except TimeoutError:
                    return rv  # idle stream; resume from the same rv
                if not line:
                    return rv  # server closed cleanly; resume
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                etype, obj = ev.get("type"), ev.get("object", {}) or {}
                if isinstance(obj, dict):
                    new_rv = (obj.get("metadata", {}) or {}).get("resourceVersion")
                    if new_rv:
                        rv = new_rv
                if etype == "BOOKMARK":
                    continue
                if etype == "ERROR":
                    code = (obj.get("code") or 0) if isinstance(obj, dict) else 0
                    raise KubeApiError(int(code) or 500, "watch ERROR event")
                if etype not in ("ADDED", "MODIFIED", "DELETED"):
                    continue
                name = obj["metadata"]["name"]
                if etype == "DELETED":
                    known.discard(name)
                else:
                    known.add(name)
                self._emit(EventType(etype), rw.kind, name, obj)
            return rv
        finally:
            conn.close()

    def _emit(self, etype: EventType, kind: str, name: str, obj: dict) -> None:
        payload: dict = {}
        if etype != EventType.DELETED:
            if kind == "Node":
                payload = node_payload(obj)
            elif kind == "Pod":
                payload = pod_payload(obj)
            else:  # PodCliqueSet: the raw CR — the admission chain parses it
                payload = obj
        self._queue.put(WatchEvent(etype, kind, name, payload))

    # ---- HTTP plumbing --------------------------------------------------------------

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        return _open_connection(self.ctx, timeout)

    def _headers(self) -> dict:
        return _auth_headers(self.ctx)

    def _request(
        self, method: str, path: str, body: Optional[dict] = None,
        query: Optional[dict] = None,
    ):
        """One apiserver call over a thread-confined persistent connection
        (binding an N-pod gang is 2N calls per tick — a fresh TLS handshake
        each would tax both sides). Transport failures retry up to
        `transport_retries` times paced by decorrelated-jitter backoff
        (utils/backoff — the shared policy; the first retry is immediate-ish
        for the common stale-keep-alive case); real API errors propagate as
        KubeApiError — write idempotency is the CALLER's contract (binding
        treats 409 as success, deletes treat 404 as success), so blind
        status-code retries here would be unsafe. Every attempt pays the
        QPS/Burst token bucket first. The `kube.request` fault site injects
        409/5xx/transport errors at the top — the whole retry/rollback
        machinery above this call is exercised by it."""
        faults_mod.active().maybe_raise(
            "kube.request",
            method=method,
            path=path.split("?")[0],
            exc_factory=lambda s: KubeApiError(s, "injected apiserver fault"),
        )
        if query:
            path = f"{path}?{urllib.parse.urlencode(query)}"
        headers = self._headers()
        data = None
        if body is not None:
            data = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        backoff = Backoff(self._backoff_base_s, self._backoff_cap_s)
        attempt = 0
        while True:
            self.limiter.acquire()
            conn = getattr(self._local, "conn", None)
            if conn is None:
                conn = self._connect(timeout=self._request_timeout_s)
                self._local.conn = conn
            try:
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except (OSError, http.client.HTTPException):
                conn.close()
                self._local.conn = None
                if attempt >= self.transport_retries:
                    raise
                attempt += 1
                if attempt > 1:
                    # First retry immediate (stale keep-alive is the common
                    # case and a fresh connection fixes it); later ones pace.
                    backoff.sleep()
                continue
            if resp.status >= 300:
                raise KubeApiError(resp.status, raw[:2048].decode("utf-8", "replace"))
            return json.loads(raw) if raw else None

    def _record_error(self, msg: str) -> None:
        self.errors.append(msg)
        del self.errors[:-20]


# ---------------------------------------------------------------------------------
# Apiserver-backed leader election (coordination.k8s.io/v1 Lease)
# ---------------------------------------------------------------------------------


class KubeLease:
    """Leader election over a k8s Lease object — the reference's actual
    mechanism (`operator/api/config/v1alpha1/types.go:73-104` rides
    controller-runtime's Lease-based election). Same try_acquire/release
    interface as runtime.lease.FileLease, so the Manager swaps them by
    cluster source: with a live apiserver the lease lives where every
    replica can see it, making multi-replica Deployments honest (a file
    lease only coordinates processes sharing a filesystem).

    Concurrency control is the apiserver's optimistic resourceVersion: the
    renewing PUT carries the GET's resourceVersion; a 409 means another
    replica won the race and this one stands down.
    """

    def __init__(
        self,
        ctx: KubeContext,
        name: str = "grove-tpu-operator-leader",
        lease_duration_seconds: float = 15.0,
        renew_deadline_seconds: Optional[float] = None,
        identity: Optional[str] = None,
        request_timeout_s: float = 5.0,
    ):
        import uuid

        self.ctx = ctx
        self.name = name
        self.lease_duration_seconds = lease_duration_seconds
        self.renew_deadline_seconds = renew_deadline_seconds
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self._request_timeout_s = request_timeout_s
        self._last_renew: Optional[float] = None
        ns = urllib.parse.quote(ctx.namespace)
        self._path = f"/apis/coordination.k8s.io/v1/namespaces/{ns}/leases"

    # -- wire helpers ---------------------------------------------------------------

    def _req(self, method: str, path: str, body: Optional[dict] = None):
        # Fresh connection per call: one call per reconcile tick, so
        # handshake cost is irrelevant here (unlike the binding path).
        conn = _open_connection(self.ctx, timeout=self._request_timeout_s)
        try:
            data = json.dumps(body).encode() if body is not None else None
            headers = _auth_headers(self.ctx)
            if data is not None:
                headers["Content-Type"] = "application/json"
            conn.request(method, path, body=data, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status >= 300:
                raise KubeApiError(resp.status, raw[:1024].decode("utf-8", "replace"))
            return json.loads(raw) if raw else None
        finally:
            conn.close()

    @staticmethod
    def _micro_time(now: float) -> str:
        import datetime

        dt = datetime.datetime.fromtimestamp(now, tz=datetime.timezone.utc)
        return dt.strftime("%Y-%m-%dT%H:%M:%S.%f") + "Z"

    @staticmethod
    def _parse_micro_time(s: str) -> float:
        import datetime

        return (
            datetime.datetime.strptime(s, "%Y-%m-%dT%H:%M:%S.%fZ")
            .replace(tzinfo=datetime.timezone.utc)
            .timestamp()
        )

    # -- FileLease-compatible surface -----------------------------------------------

    def try_acquire(self, now: Optional[float] = None) -> bool:
        now = time.time() if now is None else now
        # Renew-deadline stand-down first (types.go semantics): an overslept
        # holder must stop leading BEFORE the lease could be stolen.
        if (
            self.renew_deadline_seconds is not None
            and self._last_renew is not None
            and now - self._last_renew > self.renew_deadline_seconds
        ):
            self._last_renew = None
            self.release()
            return False
        try:
            return self._acquire_or_renew(now)
        except (KubeApiError, OSError, ValueError):
            # Apiserver unreachable: WITHOUT a renewed lease we cannot lead.
            self._last_renew = None
            return False

    def _acquire_or_renew(self, now: float) -> bool:
        try:
            cur = self._req("GET", f"{self._path}/{self.name}")
        except KubeApiError as e:
            if e.status != 404:
                raise
            body = {
                "apiVersion": "coordination.k8s.io/v1",
                "kind": "Lease",
                "metadata": {"name": self.name, "namespace": self.ctx.namespace},
                "spec": {
                    "holderIdentity": self.identity,
                    "leaseDurationSeconds": int(self.lease_duration_seconds),
                    "acquireTime": self._micro_time(now),
                    "renewTime": self._micro_time(now),
                    "leaseTransitions": 0,
                },
            }
            try:
                self._req("POST", self._path, body)
            except KubeApiError as e2:
                if e2.status == 409:  # another replica created it first
                    return False
                raise
            self._last_renew = now
            return True
        spec = cur.get("spec", {}) or {}
        holder = spec.get("holderIdentity")
        renew_raw = spec.get("renewTime")
        expired = True
        if renew_raw:
            try:
                renewed = self._parse_micro_time(renew_raw)
                expired = now - renewed >= self.lease_duration_seconds
            except ValueError:
                expired = True
        if holder != self.identity and not expired:
            self._last_renew = None
            return False
        transitions = int(spec.get("leaseTransitions", 0) or 0)
        if holder != self.identity:
            transitions += 1
            spec["acquireTime"] = self._micro_time(now)
        spec.update(
            holderIdentity=self.identity,
            leaseDurationSeconds=int(self.lease_duration_seconds),
            renewTime=self._micro_time(now),
            leaseTransitions=transitions,
        )
        cur["spec"] = spec
        try:
            self._req("PUT", f"{self._path}/{self.name}", cur)
        except KubeApiError as e:
            if e.status == 409:  # lost the optimistic-concurrency race
                self._last_renew = None
                return False
            raise
        self._last_renew = now
        return True

    def release(self) -> None:
        try:
            cur = self._req("GET", f"{self._path}/{self.name}")
            if (cur.get("spec", {}) or {}).get("holderIdentity") == self.identity:
                # Preconditioned delete: between the GET and the DELETE a
                # successor may have stolen an expired lease — deleting
                # unconditionally would evict THEIR active lease and open a
                # two-leader window. The resourceVersion precondition makes
                # the apiserver reject (409) the stale delete.
                rv = (cur.get("metadata", {}) or {}).get("resourceVersion")
                self._req(
                    "DELETE",
                    f"{self._path}/{self.name}",
                    {"preconditions": {"resourceVersion": rv}} if rv else None,
                )
        except (KubeApiError, OSError, ValueError):
            pass  # releasing best-effort; expiry reclaims it anyway


# ---------------------------------------------------------------------------------
# Pod manifest rendering (store Pod -> corev1.Pod the apiserver accepts)
# ---------------------------------------------------------------------------------


def render_pod_manifest(pod) -> dict:
    """Our store Pod -> a minimal corev1.Pod manifest. The reference's pod
    component builds the same object in Go (`podclique/components/pod/
    pod.go:135-172,232-269`): labels, GROVE_* env, stable hostname +
    subdomain, resource requests. Scheduling is OURS: the pod is created
    with spec.schedulerName=grove-tpu so kube-scheduler leaves it alone,
    and placement arrives via the binding subresource."""
    from grove_tpu.api.quantity import format_quantity

    def _container_doc(c) -> dict:
        env = [{"name": k, "value": v} for k, v in {**c.env, **pod.env}.items()]
        env += [
            {"name": k, "valueFrom": v} for k, v in c.env_value_from.items()
        ]
        cdoc: dict = {"name": c.name, "image": c.image}
        if c.command:
            cdoc["command"] = list(c.command)
        if c.args:
            cdoc["args"] = list(c.args)
        if env:
            cdoc["env"] = env
        res: dict = {}
        if c.requests:
            res["requests"] = {
                k: format_quantity(v) for k, v in c.requests.items()
            }
        if c.limits:
            res["limits"] = {k: format_quantity(v) for k, v in c.limits.items()}
        if res:
            cdoc["resources"] = res
        if c.ports:
            cdoc["ports"] = [{"containerPort": p} for p in c.ports]
        if c.volume_mounts:
            # e.g. the injected initc's SA-token mount — dropping it would
            # leave the agent credential-less on a real cluster.
            cdoc["volumeMounts"] = [dict(vm) for vm in c.volume_mounts]
        return cdoc

    spec: dict = {
        "containers": [_container_doc(c) for c in pod.spec.containers],
        "schedulerName": "grove-tpu",
        "restartPolicy": pod.spec.restart_policy,
    }
    if pod.spec.init_containers:
        # Startup ordering rides on the injected initc container
        # (expansion.py; the reference injects the same way,
        # initcontainer.go:98-126) — dropping it would silently void the
        # startsAfter guarantee on real clusters.
        spec["initContainers"] = [
            _container_doc(c) for c in pod.spec.init_containers
        ]
    if pod.spec.hostname or pod.hostname:
        spec["hostname"] = pod.spec.hostname or pod.hostname
    if pod.spec.subdomain:
        spec["subdomain"] = pod.spec.subdomain
    if pod.spec.node_selector:
        spec["nodeSelector"] = dict(pod.spec.node_selector)
    if pod.spec.tolerations:
        spec["tolerations"] = list(pod.spec.tolerations)
    if pod.spec.priority_class_name:
        spec["priorityClassName"] = pod.spec.priority_class_name
    if pod.spec.volumes:
        # Declared volumes (the initc token secret volume among them).
        spec["volumes"] = [dict(v) for v in pod.spec.volumes]
    annotations = dict(pod.annotations)
    for rc in pod.spec.resource_claims:
        # The store-level ICI-slice claim shape is OUR analog, not valid
        # corev1 PodResourceClaim (which requires resourceClaimName/
        # ...TemplateName backed by DRA objects) — rendering it verbatim
        # would 422 every MNNVL-annotated pod create. Carry the intent as
        # annotations until real DRA wiring exists; the node runtime /
        # device plugin reads them.
        src = rc.get("source", {}) or {}
        if src.get("iciDomain"):
            annotations[api_constants.ANNOTATION_ICI_DOMAIN] = src["iciDomain"]
    spec["terminationGracePeriodSeconds"] = (
        pod.spec.termination_grace_period_seconds
    )
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod.name,
            "namespace": pod.namespace,
            "labels": dict(pod.labels),
            "annotations": annotations,
        },
        "spec": spec,
    }
