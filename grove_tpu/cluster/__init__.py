from grove_tpu.cluster.kubernetes import (  # noqa: F401
    KubeContext,
    KubernetesWatchSource,
    load_kube_context,
)
from grove_tpu.cluster.kwok import KwokCluster  # noqa: F401
from grove_tpu.cluster.watch import EventType, WatchDriver, WatchEvent  # noqa: F401
