"""The thin cross-cell layer: routing, borrowed capacity, reclaim.

Cells are deliberately ignorant of each other — a cell admits only gangs
pinned to its own queues (Cell.serve refuses the rest), so EVERY cross-cell
decision concentrates here:

  route    queue-pinned gangs go to their subtree's cell (the partition
           plan); unpinned gangs (no queue, or a queue the tree doesn't
           know) spread deterministically by gang family in first-appearance
           order. Families never split: a base and its scaled siblings
           always land on one cell (the engine requires it, and a gang
           spanning cells would otherwise double-admit).
  borrow   a gang its home cell rejected (slice full) may ride another
           cell's spare capacity. Contending borrowers are ordered by the
           SAME slo/priority order as tenancy admission (latency never
           borrows — tenancy/slo.py); target cells are tried in headroom
           order (most free first, name tie-break). Every borrow routes
           through Cell.admit_borrowed — the coordinator-only entry — and is
           registered for reclaim.
  reclaim  a home cell that needs its capacity back names its borrowed
           gangs in eviction order (batch-preemptible first, then lowest
           priority — tenancy.revocation_victim_key) and the coordinator
           releases them on the host cells.

The `cell.partition` fault site gates every cross-cell touch: a partitioned
cell is unreachable this pass — borrows and reclaims against it defer
(counted, journal-visible), never half-apply.
"""

from __future__ import annotations

from dataclasses import dataclass

from grove_tpu import faults as faults_mod
from grove_tpu.cells.cell import Cell, CellCrash
from grove_tpu.cells.partition import CellPlan
from grove_tpu.tenancy.slo import (
    revocation_victim_key,
    slo_borrow_eligible,
    slo_rank,
)


@dataclass
class CoordinatorStats:
    routed: int = 0  # gangs routed to their pinned/assigned cell
    unpinned: int = 0  # gangs spread by family (no queue pin)
    borrows: int = 0  # gangs admitted onto another cell's capacity
    borrow_denied: int = 0  # borrow candidates no cell could host
    partition_deferred: int = 0  # cross-cell touches deferred by cell.partition
    reclaims: int = 0  # borrowed gangs released back to their home cell

    def to_doc(self) -> dict:
        return {
            "routed": self.routed,
            "unpinned": self.unpinned,
            "borrows": self.borrows,
            "borrowDenied": self.borrow_denied,
            "partitionDeferred": self.partition_deferred,
            "reclaims": self.reclaims,
        }


class CellCoordinator:
    """Deterministic cross-cell routing over a partition plan."""

    def __init__(
        self,
        plan: CellPlan,
        cells: dict[str, Cell],
        *,
        faults=None,  # faults.FaultInjector; None = the process-installed one
    ) -> None:
        self.plan = plan
        self.cells = dict(cells)
        self.faults = faults
        self.stats = CoordinatorStats()
        # family key -> assigned cell, in first-appearance order (the
        # deterministic spread for unpinned traffic)
        self._family_cell: dict[str, str] = {}
        # borrowed gang -> (home cell, host cell), for reclaim
        self._borrowed: dict[str, tuple[str, str]] = {}

    # ---- routing -----------------------------------------------------------------

    def route(self, gang) -> str:
        """The cell this gang belongs on. Pure given the plan and the
        arrival order seen so far (the family spread counter is the only
        state, and it advances deterministically)."""
        family = gang.base_podgang_name or gang.name
        assigned = self._family_cell.get(family)
        if assigned is not None:
            return assigned
        pinned = self.plan.cell_of_queue(getattr(gang, "queue", ""))
        if pinned is not None:
            cell = pinned
            self.stats.routed += 1
        else:
            # Unpinned: round-robin by family in first-appearance order.
            cell = self.plan.cells[
                len(self._family_cell) % len(self.plan.cells)
            ]
            self.stats.unpinned += 1
        self._family_cell[family] = cell
        return cell

    def assign(self, arrivals: list) -> dict[str, list]:
        """Partition an arrival trace by cell (family-whole, order
        preserved within each cell's slice)."""
        out: dict[str, list] = {c: [] for c in self.plan.cells}
        for t, g in arrivals:
            out[self.route(g)].append((t, g))
        return out

    # ---- reachability (cell.partition) -------------------------------------------

    def reachable(self, cell: str) -> bool:
        """One cross-cell touch: False (and counted) when the partition
        fault fires for this cell this evaluation."""
        inj = self.faults if self.faults is not None else faults_mod.active()
        try:
            inj.maybe_raise("cell.partition", cell=cell)
        except faults_mod.InjectedFault:
            self.stats.partition_deferred += 1
            return False
        return True

    # ---- borrowed capacity -------------------------------------------------------

    def _headroom_order(self, exclude: str) -> list[str]:
        """Candidate host cells, most spare capacity first (deterministic:
        free sum descending, then name)."""
        scored = []
        for name, cell in self.cells.items():
            if name == exclude or not cell.alive:
                continue
            scored.append((-float(cell.snapshot.free.sum()), name))
        return [name for _, name in sorted(scored)]

    def borrow(self, arrivals: list, pods_by_name: dict, home: str) -> dict:
        """Try to place gangs their home cell rejected onto other cells'
        spare capacity; returns the bindings that landed ({gang: {pod:
        node}}). Families move whole; contenders go in tenancy admission
        order (slo tier, then original position); latency-class gangs never
        borrow (tenancy/slo.py — which is what keeps them unreclaimable)."""
        families: dict[str, list] = {}
        order: list[str] = []
        for pos, (t, g) in enumerate(arrivals):
            key = g.base_podgang_name or g.name
            if key not in families:
                families[key] = []
                order.append(key)
            families[key].append((t, g))
        ranked = sorted(
            order,
            key=lambda k: (
                min(slo_rank(getattr(g, "slo_class", "")) for _, g in families[k]),
                order.index(k),
            ),
        )
        bound: dict[str, dict[str, str]] = {}
        for key in ranked:
            fam = families[key]
            if not all(
                slo_borrow_eligible(getattr(g, "slo_class", "")) for _, g in fam
            ):
                self.stats.borrow_denied += len(fam)
                continue
            landed = False
            for target in self._headroom_order(exclude=home):
                if not self.reachable(target):
                    continue
                try:
                    got = self.cells[target].admit_borrowed(fam, pods_by_name)
                except CellCrash as e:
                    if not e.partial:
                        continue  # nothing landed on the dead cell: the
                        # next target is safe to try
                    # The cell died BETWEEN family chunks: the chunks it
                    # committed are journaled there and rebind on recovery,
                    # so retrying the family elsewhere would double-admit
                    # them. Register what landed (reclaim can undo it) and
                    # stop; the unlanded remainder re-offers once the cell
                    # recovers.
                    for gang in e.partial:
                        self._borrowed[gang] = (home, target)
                    self.stats.borrows += len(e.partial)
                    self.stats.borrow_denied += sum(
                        1 for _, g in fam if g.name not in e.partial
                    )
                    bound.update(e.partial)
                    landed = True
                    break
                if got:
                    for gang in got:
                        self._borrowed[gang] = (home, target)
                    self.stats.borrows += len(got)
                    bound.update(got)
                    landed = True
                    break
            if not landed:
                self.stats.borrow_denied += len(fam)
        return bound

    # ---- reclaim -----------------------------------------------------------------

    def borrowed_from(self, home: str) -> list[tuple[str, str]]:
        """(gang, host cell) pairs currently riding borrowed capacity on
        behalf of `home`, name-ordered (the registry only knows names;
        reclaim() re-sorts with tenancy.revocation_victim_key when the
        caller supplies gang objects)."""
        return sorted(
            (gang, host)
            for gang, (h, host) in self._borrowed.items()
            if h == home
        )

    def reclaim(
        self, home: str, pods_by_name: dict, gangs_by_name: dict | None = None
    ) -> list[str]:
        """Release `home`'s borrowed gangs on their host cells (the home
        cell needs its capacity back). With `gangs_by_name` the eviction
        order is the tenancy one (revocation_victim_key); without it,
        name order (still deterministic). Unreachable hosts defer — their
        gangs stay borrowed and a later pass retries."""
        rows = self.borrowed_from(home)
        if gangs_by_name:
            rows.sort(
                key=lambda row: revocation_victim_key(
                    getattr(gangs_by_name.get(row[0]), "slo_class", ""),
                    int(getattr(gangs_by_name.get(row[0]), "priority", 0) or 0),
                    row[0],
                )
            )
        released: list[str] = []
        for gang, host in rows:
            if not self.reachable(host):
                continue
            if self.cells[host].release_gang(gang, pods_by_name):
                del self._borrowed[gang]
                self.stats.reclaims += 1
                released.append(gang)
        return released

    def status(self) -> dict:
        return {
            "plan": self.plan.to_doc(),
            "borrowedInFlight": len(self._borrowed),
            **self.stats.to_doc(),
        }
