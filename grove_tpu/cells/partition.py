"""Deterministic control-plane partitioning: subtree seams -> cells.

The QueueTree already draws the boundaries: a ROOT queue can never borrow
(orchestrator/queues.py — no parent to borrow from), so each root's subtree
is a self-contained admission/borrow domain. A cell plan assigns whole root
subtrees to cells; every queue inherits its root's cell, so a gang pinned to
any queue resolves to exactly one cell and in-subtree borrowing never
crosses a cell boundary. Cross-subtree traffic (spanning gangs, borrowed
capacity, reclaim) is the coordinator's job by construction.

The fleet shards the same way along a topology level: domains (zones by
default) round-robin onto cells, so a cell's node slice is topologically
contiguous and its drain engine sees a coherent sub-snapshot.

Everything here is a PURE function of its inputs — sorted names,
round-robin in sorted order, no clocks, no randomness — so two processes
computing a plan from the same tree/fleet agree byte-for-byte
(tests/test_cells.py pins determinism and the exactly-one-cell invariant).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from grove_tpu.orchestrator.queues import QueueTree


def cell_names(count: int) -> tuple[str, ...]:
    """Canonical cell names: cell-0 .. cell-(n-1)."""
    return tuple(f"cell-{i}" for i in range(max(1, int(count))))


@dataclass(frozen=True)
class CellPlan:
    """The partition: which cell owns which queues and topology domains."""

    cells: tuple[str, ...]
    # every queue in the tree -> owning cell (root's assignment inherited)
    queue_cell: dict[str, str] = field(default_factory=dict)
    # root queue -> cell (the seam-level assignment queue_cell derives from)
    root_cell: dict[str, str] = field(default_factory=dict)
    # topology domain value (e.g. "z0") -> cell; empty when fleet sharding
    # was not requested
    domain_cell: dict[str, str] = field(default_factory=dict)

    def cell_of_queue(self, queue: str) -> str | None:
        """The owning cell, or None for an unknown/empty queue — those are
        unpinned and the coordinator places them."""
        return self.queue_cell.get(queue) if queue else None

    def queues_of(self, cell: str) -> list[str]:
        return sorted(q for q, c in self.queue_cell.items() if c == cell)

    def domains_of(self, cell: str) -> list[str]:
        return sorted(d for d, c in self.domain_cell.items() if c == cell)

    def to_doc(self) -> dict:
        return {
            "cells": list(self.cells),
            "rootCell": dict(sorted(self.root_cell.items())),
            "queueCell": dict(sorted(self.queue_cell.items())),
            "domainCell": dict(sorted(self.domain_cell.items())),
        }


def partition_tree(tree: QueueTree | None, count: int) -> CellPlan:
    """Assign each root subtree to a cell: roots sorted, round-robin over
    the cell list. Pure in (tree shape, count) — spec-dict insertion order,
    clocks, and process identity cannot change the answer. A None/empty
    tree yields a plan with cells but no queue pins (every gang is unpinned
    and the coordinator spreads families deterministically)."""
    cells = cell_names(count)
    if tree is None:
        return CellPlan(cells=cells)
    root_cell = {
        root: cells[i % len(cells)] for i, root in enumerate(tree.roots())
    }
    queue_cell = {
        name: root_cell[tree.root_of(name)] for name in sorted(tree.specs)
    }
    return CellPlan(cells=cells, queue_cell=queue_cell, root_cell=root_cell)


def partition_domains(domains, cells: tuple[str, ...]) -> dict[str, str]:
    """Topology domain values -> cells, sorted round-robin (pure)."""
    cells = tuple(cells) or ("cell-0",)
    return {d: cells[i % len(cells)] for i, d in enumerate(sorted(set(domains)))}


def with_fleet(plan: CellPlan, nodes, label_key: str) -> CellPlan:
    """Extend a plan with a fleet shard along `label_key` (e.g. the zone
    label): each domain's nodes land wholly in one cell. Nodes missing the
    label shard with the "" domain."""
    domain_cell = partition_domains(
        (n.labels.get(label_key, "") for n in nodes), plan.cells
    )
    return CellPlan(
        cells=plan.cells,
        queue_cell=dict(plan.queue_cell),
        root_cell=dict(plan.root_cell),
        domain_cell=domain_cell,
    )


def fleet_slices(plan: CellPlan, nodes, label_key: str) -> dict[str, list]:
    """The per-cell node slices a plan's domain map implies, preserving the
    fleet's node order within each slice (order is identity for snapshot
    indices). Every node lands in exactly one slice."""
    out: dict[str, list] = {c: [] for c in plan.cells}
    for n in nodes:
        cell = plan.domain_cell.get(n.labels.get(label_key, ""))
        if cell is None:
            cell = plan.cells[0]
        out[cell].append(n)
    return out
