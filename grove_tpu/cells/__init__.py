"""Cellular control plane: sharded reconcile cells with journal-replay
crash recovery (docs/design.md "Cellular control plane").

The control plane partitions into cells along the seams the QueueTree
already draws — each root subtree is a self-contained borrow domain, so a
whole subtree (and a topology slice of the fleet) lands in exactly one cell
(partition.py). Each cell owns its slice outright: its own sub-snapshot,
its own drain/stream engine (solver/drain.py + solver/stream.py, reused
unchanged), its own warm-path cache handle, its own flight-recorder journal
and named lease (cell.py). A thin coordinator owns everything cross-cell:
routing, borrowed capacity, reclaim (coordinator.py).

Crash recovery is journal replay: every wave record carries its full encode
closure, so a restarting cell bitwise-replays its journal tail
(trace/replay.py), rebuilds allocated/decided/bindings from the recorded
verdicts, and resumes past its last engine epoch — zero lost gangs, zero
double-bound gangs, proven by `make bench-cells` and the tier-1 smoke in
tests/test_cells.py.
"""

from grove_tpu.cells.cell import (
    Cell,
    CellCrash,
    CellStats,
    RecoveryReport,
    audit_journal,
    recover,
)
from grove_tpu.cells.coordinator import CellCoordinator, CoordinatorStats
from grove_tpu.cells.partition import (
    CellPlan,
    cell_names,
    fleet_slices,
    partition_domains,
    partition_tree,
    with_fleet,
)

__all__ = [
    "Cell",
    "CellCrash",
    "CellStats",
    "RecoveryReport",
    "audit_journal",
    "recover",
    "CellCoordinator",
    "CoordinatorStats",
    "CellPlan",
    "cell_names",
    "fleet_slices",
    "partition_domains",
    "partition_tree",
    "with_fleet",
]
