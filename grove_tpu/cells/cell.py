"""One reconcile cell: a slice of the fleet with its own engine + journal.

A Cell owns a node slice (its sub-snapshot), a set of pinned queues (the
roots `partition.partition_tree` assigned to it), and runs the SAME
drain/stream engine as the monolithic control plane (`solver/stream.py`,
unchanged) over its slice — with its own warm-path cache handle, its own
flight-recorder journal directory, and (optionally) its own named lease
from `runtime/lease.LeaseSet`. Host participation is therefore O(own
slice): adding cells adds engines, it never widens any one engine's fleet.

Crash recovery is the flight-recorder contract cashed in: every journaled
wave carries its full encode closure and is bitwise-pinned by
`trace/replay.py`, so `recover()` rebuilds a dead cell's allocated/free
state and bindings purely from its journal tail — verified by replaying it
bitwise first — then warm-starts (persistent XLA cache + shape history make
the warm path cheap; the replay itself re-populates the executable cache).
Gangs whose waves never reached the journal are simply NOT in the rebuilt
state; the coordinator re-offers them, so a crash loses nothing and
double-binds nothing — `bindings` (admitted gangs holding capacity) gates
re-admission, while `decided` (every journaled verdict) is the zero-lost
ledger. Journaled `cell.reclaim` actions are mirrored during the rebuild,
so a gang released before the crash stays released, and a journal whose
oldest segments were rotation-pruned recovers flagged `truncated` (never
`verified`) because the pruned admissions are unrecoverable.

The `cell.crash` fault site fires BETWEEN engine runs (the engine itself is
reused unchanged — its own sites keep covering the in-wave failure modes):
a serve() call streams its arrivals in bounded chunks and evaluates the
site before each chunk after the first, so a deterministic fault spec kills
the cell mid-stream with journaled waves behind it and undecided arrivals
ahead of it — exactly the recovery problem production restarts pose.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field

import numpy as np

from grove_tpu import faults as faults_mod
from grove_tpu.solver.stream import StreamConfig, drain_stream
from grove_tpu.state.cluster import build_snapshot, pod_request_vector
from grove_tpu.trace.recorder import (
    TraceRecorder,
    read_journal,
    read_manifest,
)
from grove_tpu.utils import serde

_EPOCH_RE = re.compile(r"^c(\d+)-")


class CellCrash(RuntimeError):
    """The cell died mid-stream (injected via the `cell.crash` site). The
    instance is unusable; recover() builds its replacement from the
    journal. `partial` carries the bindings the interrupted call committed
    (journaled) BEFORE the crash — a caller that was admitting a family
    must treat those gangs as landed on this cell (they rebind on
    recovery), never re-admit them elsewhere."""

    def __init__(self, cell: str, partial: dict | None = None):
        super().__init__(f"cell {cell} crashed mid-stream")
        self.cell = cell
        self.partial: dict[str, dict[str, str]] = dict(partial or {})


class _CellRecorder(TraceRecorder):
    """Cell-scoped journal: every engine life numbers its waves from zero
    (`stream-000000`...), so the cell prefixes wave ids with a monotonic
    engine epoch (`c0002-stream-000003`) — ids stay unique across crashes
    and restarts and the manifest's lastWave names a real resume point."""

    def __init__(self, path: str, *, epoch: int = 0, **kw) -> None:
        super().__init__(path, **kw)
        self.epoch = int(epoch)

    def capture_wave(self, *, wave: str, **kw) -> bool:
        return super().capture_wave(wave=f"c{self.epoch:04d}-{wave}", **kw)


@dataclass
class CellStats:
    """Aggregate of every engine run this cell instance performed."""

    offered: int = 0
    admitted: int = 0
    pods_bound: int = 0
    waves: int = 0
    dispatches: int = 0
    device_roundtrips: int = 0
    host_total_s: float = 0.0  # engine host-stage ledger sum (hostTotalS)
    host_blocked_s: float = 0.0  # host time blocked on verdict fetches
    wall_s: float = 0.0
    engine_runs: int = 0
    crashes: int = 0
    recoveries: int = 0
    borrowed_in: int = 0  # gangs admitted on behalf of another cell's queue
    released: int = 0  # gangs released by cross-cell reclaim

    def to_doc(self) -> dict:
        return {
            "offered": self.offered,
            "admitted": self.admitted,
            "podsBound": self.pods_bound,
            "waves": self.waves,
            "dispatches": self.dispatches,
            "deviceRoundtrips": self.device_roundtrips,
            "hostTotalS": round(self.host_total_s, 4),
            "hostBlockedS": round(self.host_blocked_s, 4),
            "wallS": round(self.wall_s, 4),
            "engineRuns": self.engine_runs,
            "crashes": self.crashes,
            "recoveries": self.recoveries,
            "borrowedIn": self.borrowed_in,
            "released": self.released,
        }


@dataclass
class RecoveryReport:
    """What journal-tail recovery rebuilt, and the bitwise handoff proof."""

    cell: str
    waves_replayed: int = 0
    divergences: int = 0
    gangs_rebound: int = 0  # admitted gangs whose bindings were rebuilt
    gangs_reclaimed: int = 0  # cell.reclaim records mirrored (releases)
    gangs_decided: int = 0  # gangs with ANY journaled verdict (zero-lost)
    resume_point: str | None = None  # manifest lastWave (None: no manifest)
    manifest_segments: int = 0
    # Rotation pruning dropped the journal's oldest waves: the rebuilt
    # allocated/bindings state is missing their admissions, so recovery is
    # NOT sound — verified stays False even when the surviving tail
    # replays bitwise.
    truncated: bool = False
    verified: bool = False  # replay diverged nowhere AND the tail is complete

    def to_doc(self) -> dict:
        return {
            "cell": self.cell,
            "wavesReplayed": self.waves_replayed,
            "divergences": self.divergences,
            "gangsRebound": self.gangs_rebound,
            "gangsReclaimed": self.gangs_reclaimed,
            "gangsDecided": self.gangs_decided,
            "resumePoint": self.resume_point,
            "manifestSegments": self.manifest_segments,
            "truncated": self.truncated,
            "verified": self.verified,
        }


class Cell:
    """A reconcile cell: fleet slice + pinned queues + its own engine."""

    def __init__(
        self,
        name: str,
        nodes: list,
        topology,
        *,
        journal_path: str,
        owned_queues=(),
        stream_config: StreamConfig | None = None,
        params=None,
        warm_path=None,
        lease=None,  # runtime.lease.FileLease (from a LeaseSet), optional
        faults=None,  # faults.FaultInjector; None = the installed one
        crash_check_every: int = 128,  # arrivals between cell.crash checks
        scan=None,  # forwarded to drain_stream (fused/resident dispatch)
        pipeline: bool = True,
        max_records_per_file: int = 256,
        max_files: int = 512,
        epoch: int = 0,
    ) -> None:
        from grove_tpu.solver.warm import WarmPath

        self.name = name
        self.nodes = list(nodes)
        self.topology = topology
        self.owned_queues = frozenset(owned_queues)
        self.journal_path = journal_path
        self.snapshot = build_snapshot(self.nodes, topology)
        self.config = stream_config or StreamConfig()
        self.params = params
        self.warm_path = warm_path if warm_path is not None else WarmPath()
        self.lease = lease
        self.faults = faults
        self.crash_check_every = max(1, int(crash_check_every))
        self.scan = scan
        self.pipeline = pipeline
        self.recorder = _CellRecorder(
            journal_path,
            epoch=epoch,
            max_records_per_file=max_records_per_file,
            max_files=max_files,
        )
        # bindings = admitted gangs still holding capacity — the re-admit
        # gate (zero double-bound); decided = every journaled verdict,
        # admitted or rejected — the zero-lost ledger. Rejected gangs are
        # in decided but not bindings, so they stay re-offerable.
        self.bindings: dict[str, dict[str, str]] = {}
        self.decided: set[str] = set()
        self.stats = CellStats()
        self.alive = False

    # ---- lifecycle ---------------------------------------------------------------

    def start(self, now: float | None = None) -> bool:
        """Start the journal writer and (when leased) acquire the cell's
        lease. Returns lease holdership (True when no lease is configured —
        an unleased cell is always 'leader' of itself)."""
        self.recorder.start()
        self.alive = True
        if self.lease is None:
            return True
        return self.lease.try_acquire(now)

    def close(self) -> None:
        """Graceful shutdown: flush + stop the writer, release the lease."""
        self.alive = False
        self.recorder.stop()
        if self.lease is not None:
            self.lease.release()

    def crash(self) -> None:
        """Simulated process death. The journal (what the writer thread has
        persisted/accepted) is the only survivor: the snapshot, bindings,
        and decided set die with the instance, and the lease is NOT
        released — it expires, exactly as a killed process's would."""
        self.stats.crashes += 1
        self.alive = False
        self.recorder.stop()

    # ---- admission ---------------------------------------------------------------

    def owns(self, gang) -> bool:
        """Is this gang pinned to this cell? Unquoted gangs (no queue) are
        unpinned — any cell may host them, the coordinator picks. A gang on
        a queue some OTHER cell owns must route through the coordinator."""
        queue = getattr(gang, "queue", "")
        return not queue or not self.owned_queues or queue in self.owned_queues

    def serve(self, arrivals: list, pods_by_name: dict) -> dict:
        """Stream this cell's pinned arrivals through its own engine;
        returns the new bindings ({gang: {pod: node}}). Refuses foreign
        gangs outright — cross-cell traffic is the coordinator's
        (admit_borrowed), never a cell's own call to make."""
        for _, g in arrivals:
            if not self.owns(g):
                raise ValueError(
                    f"cell {self.name}: gang {g.name} (queue {g.queue!r}) is "
                    "pinned to another cell — route it via the coordinator"
                )
        return self._stream(arrivals, pods_by_name)

    def admit_borrowed(self, arrivals: list, pods_by_name: dict) -> dict:
        """Coordinator-only entry: admit gangs pinned elsewhere onto this
        cell's spare capacity (borrowed across the subtree seam). Same
        engine, same journal; only the ownership gate is waived. The
        borrowed_in count updates even when the call dies in a CellCrash —
        the chunks committed before the crash DID land here."""
        before = self.stats.admitted
        try:
            out = self._stream(arrivals, pods_by_name)
        finally:
            self.stats.borrowed_in += self.stats.admitted - before
        return out

    def _stream(self, arrivals: list, pods_by_name: dict) -> dict:
        if not self.alive:
            raise CellCrash(self.name)
        inj = self.faults if self.faults is not None else faults_mod.active()
        fresh = [
            (t, g) for t, g in arrivals if g.name not in self.bindings
        ]  # BOUND gangs (admitted, capacity held) never re-admit — the
        # zero-double-bound gate is enforced at the cell boundary. Gangs
        # merely REJECTED stay re-offerable: once capacity frees (release,
        # reclaim) a later offer re-solves them instead of no-opping.
        new_bindings: dict[str, dict[str, str]] = {}
        for i, chunk in enumerate(
            _family_chunks(fresh, self.crash_check_every)
        ):
            if i:
                # Between-chunk crash point: deterministic, mid-stream,
                # with journaled waves behind and undecided arrivals ahead.
                try:
                    inj.maybe_raise("cell.crash", cell=self.name)
                except faults_mod.InjectedFault as e:
                    self.crash()
                    # new_bindings = the chunks this call committed (and
                    # journaled) before dying: the caller must count them
                    # as landed here, they rebind on recovery.
                    raise CellCrash(self.name, partial=new_bindings) from e
            self.recorder.epoch += 1
            bindings, stats = drain_stream(
                [(t, g) for t, g in chunk],
                pods_by_name,
                self.snapshot,
                config=self.config,
                params=self.params,
                warm_path=self.warm_path,
                recorder=self.recorder,
                pipeline=self.pipeline,
                scan=self.scan,
                faults=self.faults,
            )
            # The engine journals its waves asynchronously; a verdict only
            # counts as decided once it is on disk (crash() persists what
            # the writer accepted, so post-flush == journaled).
            self.recorder.flush()
            self._commit(bindings, chunk, pods_by_name, stats)
            new_bindings.update(bindings)
        return new_bindings

    def _commit(self, bindings, chunk, pods_by_name, stats) -> None:
        """Fold one engine run into the cell state: allocated rows advance
        by the bound pods' requests (the next run's snapshot carries them),
        every verdict latches into the `decided` ledger, admissions into
        the `bindings` gate."""
        for gang, per in bindings.items():
            self.bindings[gang] = dict(per)
            for pod_name, node_name in per.items():
                idx = self.snapshot.node_index(node_name)
                self.snapshot.allocated[idx] += pod_request_vector(
                    pods_by_name[pod_name], self.snapshot.resource_names
                )
        for _, g in chunk:
            self.decided.add(g.name)
        st = self.stats
        st.offered += stats.offered
        st.admitted += stats.admitted
        st.pods_bound += stats.pods_bound
        st.waves += stats.waves
        st.dispatches += stats.drain.dispatches
        st.device_roundtrips += stats.drain.device_roundtrips
        st.host_total_s += stats.drain.host_stages()["hostTotalS"]
        st.host_blocked_s += stats.drain.harvest_s
        st.wall_s += stats.wall_s
        st.engine_runs += 1

    def release_gang(self, gang: str, pods_by_name: dict) -> bool:
        """Cross-cell reclaim: give a borrowed gang's capacity back (the
        coordinator calls this on the HOST cell). Journaled as an action
        record so recovery (and the trace) sees the reclaim beside the
        admissions — recover() mirrors these records, or a released gang
        would resurrect with its capacity. The verdict stays in `decided`
        (it WAS decided here); only the `bindings` gate opens, so the gang
        may legitimately re-admit later."""
        per = self.bindings.pop(gang, None)
        if per is None:
            return False
        for pod_name, node_name in per.items():
            idx = self.snapshot.node_index(node_name)
            row = self.snapshot.allocated[idx]
            row -= pod_request_vector(
                pods_by_name[pod_name], self.snapshot.resource_names
            )
            np.maximum(row, 0.0, out=row)
        self.stats.released += 1
        self.recorder.capture_action(
            time.time(), "cell.reclaim", gang, cell=self.name
        )
        return True

    def status(self) -> dict:
        return {
            "name": self.name,
            "alive": self.alive,
            "nodes": len(self.nodes),
            "queues": sorted(self.owned_queues),
            "journal": self.journal_path,
            "leaseHeld": (None if self.lease is None else self.lease.held()),
            "epoch": self.recorder.epoch,
            **self.stats.to_doc(),
        }


def _family_chunks(arrivals: list, size: int) -> list[list]:
    """Split arrivals into engine-run chunks of WHOLE gang families.

    A scaled gang must share an engine run with its base (or a run where
    the base is already `scheduled`): the encoder gates a scaled gang whose
    base it cannot see, and engine instances don't share their
    scheduled-admitted sets. So chunk boundaries fall only between
    families: members group at the family's first appearance (arrival
    order within a family is preserved, so base-before-scaled holds), and
    a chunk closes once it has at least `size` arrivals. Pure in (arrival
    order, size) — chunking is as replayable as the waves it feeds."""
    order: list[str] = []
    members: dict[str, list] = {}
    for t, g in arrivals:
        key = g.base_podgang_name or g.name
        fam = members.get(key)
        if fam is None:
            fam = members[key] = []
            order.append(key)
        fam.append((t, g))
    chunks: list[list] = []
    cur: list = []
    for key in order:
        cur.extend(members[key])
        if len(cur) >= max(1, size):
            chunks.append(cur)
            cur = []
    if cur:
        chunks.append(cur)
    return chunks


# ---- journal-tail recovery ---------------------------------------------------------


def _next_epoch(records: list[dict]) -> int:
    """Highest engine epoch in the journal (wave ids carry the cell epoch
    prefix); the replacement cell starts past it — `_stream` pre-increments
    before each engine run, so passing the max yields max+1 first."""
    top = 0
    for rec in records:
        if rec.get("kind") != "wave":
            continue
        m = _EPOCH_RE.match(rec.get("wave", ""))
        if m:
            top = max(top, int(m.group(1)))
    return top


def recover(
    name: str,
    nodes: list,
    topology,
    *,
    journal_path: str,
    verify: bool = True,
    warm_path=None,
    **cell_kwargs,
) -> tuple[Cell, RecoveryReport]:
    """Build a crashed cell's replacement from its journal tail.

    1. The manifest names the resume point (last journaled wave id) without
       scanning segments; the tail itself loads via `read_journal`.
    2. With `verify` (the default), the tail REPLAYS bitwise first
       (`trace/replay.replay_journal`) — every wave re-solved through the
       warm path must reproduce its recorded plan exactly; replaying also
       re-populates the executable cache, so verification IS the warm
       start.
    3. Allocated/free state and bindings rebuild by walking the records in
       commit order: wave records add admitted gangs' bindings + capacity,
       `cell.reclaim` action records (journaled by release_gang) undo them
       — skipping those would resurrect a released gang's binding and
       capacity, and double-bind it if it re-admitted elsewhere after the
       reclaim. Every journaled verdict lands in `decided` (the zero-lost
       ledger); `bindings` gates re-admission, so re-offered traffic can
       neither double-bind a recovered gang nor lose an undecided one.

    A journal whose oldest segments were rotation-pruned away is flagged
    `truncated` (and never `verified`): the pruned waves' admissions are
    unrecoverable, so the rebuilt state under-counts allocation — the
    caller must treat the recovery as best-effort, not sound.

    An empty journal (the cell died before its first segment) recovers to
    a fresh cell with an empty report — nothing was decided, everything
    re-offers.
    """
    from grove_tpu.trace.recorder import journal_truncated
    from grove_tpu.trace.replay import replay_journal

    report = RecoveryReport(cell=name)
    manifest = read_manifest(journal_path)
    if manifest is not None:
        report.resume_point = manifest.get("lastWave")
        report.manifest_segments = len(manifest.get("segments", []))
    try:
        records = read_journal(journal_path)
    except FileNotFoundError:
        records = []
    report.truncated = journal_truncated(journal_path)
    if verify and records:
        rep = replay_journal(records, warm_path=warm_path)
        report.waves_replayed = len(rep.waves)
        report.divergences = rep.divergence_count
        report.verified = rep.divergence_count == 0 and not report.truncated
    cell = Cell(
        name,
        nodes,
        topology,
        journal_path=journal_path,
        warm_path=warm_path,
        epoch=_next_epoch(records),
        **cell_kwargs,
    )
    # Per-gang allocation contributions applied so far, so a later
    # cell.reclaim record can subtract exactly what its wave added.
    contrib: dict[str, list[tuple[int, np.ndarray]]] = {}
    for rec in records:
        kind = rec.get("kind")
        if kind == "action" and rec.get("action") == "cell.reclaim":
            gang = rec.get("object")
            report.gangs_reclaimed += 1
            if cell.bindings.pop(gang, None) is None:
                continue  # admit wave pruned away; nothing was re-applied
            for idx, vec in contrib.pop(gang, ()):
                row = cell.snapshot.allocated[idx]
                row -= vec
                np.maximum(row, 0.0, out=row)
            continue
        if kind != "wave":
            continue
        pods_enc = rec.get("pods", {})
        for gang, ok in rec.get("ok", {}).items():
            cell.decided.add(gang)
            if not ok:
                continue
            per = rec.get("plan", {}).get(gang, {})
            cell.bindings[gang] = dict(per)
            report.gangs_rebound += 1
            rows = contrib[gang] = []
            for pod_name, node_name in per.items():
                enc = pods_enc.get(pod_name)
                if enc is None or node_name not in cell.snapshot.node_index_map:
                    continue
                pod = serde.decode(enc)
                idx = cell.snapshot.node_index(node_name)
                vec = pod_request_vector(pod, cell.snapshot.resource_names)
                cell.snapshot.allocated[idx] += vec
                rows.append((idx, vec))
    report.gangs_decided = len(cell.decided)
    cell.stats.recoveries = 1
    return cell, report


def audit_journal(records: list[dict], rel_eps: float = 1e-5) -> dict:
    """Whole-trace oversubscription audit from the journal alone: at every
    wave, entering allocated + the admitted plan's pod requests must fit
    capacity on every touched node. One (wave, node) pair is a node-tick;
    the bench gates `oversubscribed == 0` across the whole trace."""
    fleets: dict[str, dict] = {}
    ticks = 0
    oversubscribed = 0
    for rec in records:
        if rec.get("kind") == "fleet":
            fleets[rec["digest"]] = {
                nd["name"]: nd.get("capacity", {}) for nd in rec["nodes"]
            }
            continue
        if rec.get("kind") != "wave":
            continue
        caps = fleets.get(rec.get("fleet"), {})
        resources = list(rec.get("resources", []))
        load: dict[str, np.ndarray] = {
            node: np.asarray(row, dtype=np.float64)
            for node, row in rec.get("allocated", {}).items()
        }
        pods_enc = rec.get("pods", {})
        req_memo: dict[str, np.ndarray] = {}
        for gang, per in rec.get("plan", {}).items():
            if not rec.get("ok", {}).get(gang):
                continue
            for pod_name, node_name in per.items():
                req = req_memo.get(pod_name)
                if req is None:
                    enc = pods_enc.get(pod_name)
                    if enc is None:
                        continue
                    total = serde.decode(enc).spec.total_requests()
                    req = np.asarray(
                        [total.get(r, 0.0) for r in resources], dtype=np.float64
                    )
                    req_memo[pod_name] = req
                row = load.get(node_name)
                if row is None:
                    row = load[node_name] = np.zeros(len(resources))
                load[node_name] = row + req
        for node, row in load.items():
            ticks += 1
            cap = np.asarray(
                [caps.get(node, {}).get(r, 0.0) for r in resources],
                dtype=np.float64,
            )
            if np.any(row > cap * (1.0 + rel_eps) + 1e-9):
                oversubscribed += 1
    return {"nodeTicks": ticks, "oversubscribed": oversubscribed}
