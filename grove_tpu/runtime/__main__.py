"""Operator binary entry point: `python -m grove_tpu.runtime --config <yaml>`.

Mirror of `operator/cmd/main.go:46-128` + `cmd/cli/cli.go`: parse flags, load
and validate the OperatorConfiguration (exit non-zero listing every problem),
boot the manager, run until SIGINT/SIGTERM.
"""

from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="grove-tpu-operator")
    parser.add_argument("--config", required=True, help="OperatorConfiguration YAML")
    parser.add_argument(
        "--run-for", type=float, default=None, help="exit after N seconds (testing)"
    )
    from grove_tpu.version import version_string

    parser.add_argument(
        "--version", action="version", version=version_string("grove-tpu")
    )
    args = parser.parse_args(argv)

    from grove_tpu.runtime.config import load_operator_config
    from grove_tpu.runtime.manager import Manager

    try:
        config = load_operator_config(args.config)
    except (OSError, ValueError) as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2

    # Same relay hardening as bench.py: the solver's first device use must
    # not hang the control plane when the TPU tunnel is wedged — probe in a
    # subprocess, fall back to CPU (grove_tpu/utils/platform.py).
    from grove_tpu.utils.platform import ensure_usable_backend

    _, plat_err = ensure_usable_backend()
    if plat_err:
        print(f"platform fallback: {plat_err}", file=sys.stderr)

    manager = Manager(config)

    def _stop(signum, frame):
        manager.stop()

    signal.signal(signal.SIGINT, _stop)
    signal.signal(signal.SIGTERM, _stop)
    try:
        manager.run(stop_after_seconds=args.run_for)
    finally:
        manager.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
