"""Cert management for the manager's HTTP surface.

The reference manages webhook TLS with the cert-controller rotator (auto
mode: generate + rotate a self-signed CA and serving cert) or externally
provided certs (manual mode), and blocks readiness until certs are ready
(`internal/controller/cert/cert.go:46-98`,
`api/config/v1alpha1/types.go:154-169`). This stack's inbound surface is the
manager HTTP API (probes + object API + initc endpoint) instead of an
admission webhook; the same two modes apply:

  auto    — generate a self-signed serving cert into `cert_dir` at boot
            (reused while >10% of its lifetime remains), openssl-backed
  manual  — operator-provided cert/key paths, validated at boot

The generated cert doubles as the CA bundle clients pin (self-signed), the
in-cluster analog of the rotator writing the CA into the webhook config.
"""

from __future__ import annotations

import os
import pathlib
import subprocess


class CertError(Exception):
    pass


def ensure_serving_certs(
    mode: str,
    cert_dir: str,
    *,
    cert_file: str = "",
    key_file: str = "",
    common_name: str = "grove-tpu-manager",
    days: int = 365,
    san_dns: tuple[str, ...] = (),
) -> tuple[str, str]:
    """Return (cert_path, key_path) ready to serve, per the configured mode.

    Raises CertError when manual files are missing or generation fails —
    the boot contract mirrors the reference: no serving without certs.
    """
    if mode == "manual":
        for label, path in (("certFile", cert_file), ("keyFile", key_file)):
            if not path or not pathlib.Path(path).is_file():
                raise CertError(f"tls mode manual: {label} {path!r} not found")
        return cert_file, key_file
    if mode != "auto":
        raise CertError(f"unknown tls mode {mode!r} (want auto|manual)")

    out = pathlib.Path(cert_dir)
    out.mkdir(parents=True, exist_ok=True, mode=0o700)
    # The dir may pre-exist (shared /tmp is a predictable path): refuse one
    # we don't own — an attacker-planted key there would MITM the
    # bearer-token API — and close group/world access on ours.
    st = out.stat()
    if st.st_uid != os.getuid():
        raise CertError(f"cert dir {out} is owned by uid {st.st_uid}, not us")
    os.chmod(out, 0o700)
    cert = out / "tls.crt"
    key = out / "tls.key"
    # SANs are baked into the cert: if the requested set changed (e.g. a
    # webhook Service DNS name was added to the config), the cached cert is
    # stale even while time-valid — track the set in a sidecar marker.
    san = "subjectAltName=" + ",".join(
        ["DNS:localhost", "IP:127.0.0.1"] + [f"DNS:{d}" for d in san_dns]
    )
    san_marker = out / "san.txt"
    if san_marker.is_file():
        san_current = san_marker.read_text()
    else:
        # Pre-marker certs were all generated with the bare default SAN set:
        # treat a missing marker as that set (and stamp it on reuse below) so
        # upgrading does not churn a still-valid cert that pinned clients
        # (initc agents, GroveClients) already trust.
        san_current = "subjectAltName=DNS:localhost,IP:127.0.0.1"
    if (
        cert.is_file()
        and key.is_file()
        and _still_valid(cert, days)
        and san_current == san
    ):
        if not san_marker.is_file():
            san_marker.write_text(san)
        os.chmod(key, 0o600)
        return str(cert), str(key)
    try:
        proc = subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
                "-keyout", str(key), "-out", str(cert),
                "-days", str(days),
                "-subj", f"/CN={common_name}",
                "-addext", san,
            ],
            capture_output=True,
            text=True,
        )
    except OSError as e:  # openssl missing: keep the CertError boot contract
        raise CertError(f"cannot run openssl: {e}") from e
    if proc.returncode != 0:
        raise CertError(f"self-signed cert generation failed: {proc.stderr.strip()}")
    san_marker.write_text(san)
    os.chmod(key, 0o600)
    return str(cert), str(key)


def pinned_client_context(cafile: str):
    """ssl context trusting exactly the pinned serving cert (auto mode's
    self-signed cert doubles as the CA bundle). Hostname checking is off —
    the pin itself is the trust anchor. The ONE place the client-side TLS
    policy lives (GroveClient and the initc agent both use it)."""
    import ssl

    ctx = ssl.create_default_context(cafile=cafile)
    ctx.check_hostname = False
    return ctx


def _still_valid(cert: pathlib.Path, days: int) -> bool:
    """True while >10% of the requested lifetime remains (rotation point)."""
    margin_s = int(days * 24 * 3600 * 0.1)
    proc = subprocess.run(
        ["openssl", "x509", "-checkend", str(margin_s), "-noout", "-in", str(cert)],
        capture_output=True,
    )
    return proc.returncode == 0
