"""Reconcile-flow plumbing: typed step results and the step runner.

Mirror of `operator/internal/controller/common/flow.go:34-116`: every
reconcile phase is a step function returning a ReconcileStepResult —
continue, requeue-after, continue-but-requeue, or short-circuit (with or
without errors). The runner executes steps in order, honors the result
semantics, and aggregates the requeue horizon; the error recorder persists
LastErrors to the object's status (reconcileerrorrecorder.go analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

from grove_tpu.utils.errors import GroveError


@dataclass
class ReconcileStepResult:
    """Outcome of one reconcile step (flow.go:34-57)."""

    continue_reconcile: bool = True
    requeue_after_seconds: Optional[float] = None
    errors: list[GroveError] = field(default_factory=list)
    description: str = ""

    @property
    def has_errors(self) -> bool:
        return bool(self.errors)


def continue_reconcile() -> ReconcileStepResult:
    """Proceed to the next step (flow.go ContinueReconcile)."""
    return ReconcileStepResult()


def reconcile_after(seconds: float, description: str = "") -> ReconcileStepResult:
    """Stop the flow; run the whole reconcile again after `seconds`
    (flow.go ReconcileAfter)."""
    return ReconcileStepResult(
        continue_reconcile=False,
        requeue_after_seconds=seconds,
        description=description,
    )


def continue_and_requeue_after(
    seconds: float, description: str = ""
) -> ReconcileStepResult:
    """Keep running later steps, but also requeue (sentinel
    ErrCodeContinueReconcileAndRequeue semantics)."""
    return ReconcileStepResult(
        continue_reconcile=True,
        requeue_after_seconds=seconds,
        description=description,
    )


def reconcile_with_errors(
    description: str, *errors: GroveError, requeue_after_seconds: float = 5.0
) -> ReconcileStepResult:
    """Stop the flow with errors; errors imply a retry requeue
    (flow.go ReconcileWithErrors)."""
    return ReconcileStepResult(
        continue_reconcile=False,
        requeue_after_seconds=requeue_after_seconds,
        errors=list(errors),
        description=description,
    )


def short_circuit(description: str = "") -> ReconcileStepResult:
    """Stop the flow successfully — nothing more to do this pass
    (flow.go ShortCircuitReconcileFlow)."""
    return ReconcileStepResult(continue_reconcile=False, description=description)


@dataclass
class FlowOutcome:
    """Aggregate of a full flow run."""

    requeue_after_seconds: Optional[float] = None
    errors: list[GroveError] = field(default_factory=list)
    steps_run: list[str] = field(default_factory=list)

    @property
    def has_errors(self) -> bool:
        return bool(self.errors)


def run_reconcile_flow(
    steps: list[tuple[str, Callable[[], ReconcileStepResult]]],
    error_recorder: Optional[Callable[[list[GroveError]], None]] = None,
) -> FlowOutcome:
    """Execute named steps in order with flow.go semantics.

    - a step that raises GroveError is treated as reconcile_with_errors
    - any other exception is wrapped (operation = step name)
    - the outcome's requeue horizon is the MINIMUM of all requested requeues
      (the soonest need wins, matching workqueue semantics)
    - error_recorder receives the accumulated errors (possibly empty — an
      empty record CLEARS LastErrors, as the reference recorder does)
    """
    outcome = FlowOutcome()
    for name, step in steps:
        outcome.steps_run.append(name)
        try:
            result = step()
        except GroveError as e:
            seconds = getattr(e, "requeue_seconds", 5.0)
            if e.is_sentinel:
                result = ReconcileStepResult(
                    continue_reconcile="CONTINUE" in e.code,
                    requeue_after_seconds=seconds,
                    description=str(e),
                )
            else:
                result = reconcile_with_errors(name, e)
        except Exception as e:  # noqa: BLE001 — reconcile must not crash the loop
            result = reconcile_with_errors(
                name,
                GroveError(code="ERR_SYNC_RESOURCE", operation=name, message=str(e), cause=e),
            )
        outcome.errors.extend(result.errors)
        if result.requeue_after_seconds is not None:
            outcome.requeue_after_seconds = (
                result.requeue_after_seconds
                if outcome.requeue_after_seconds is None
                else min(outcome.requeue_after_seconds, result.requeue_after_seconds)
            )
        if not result.continue_reconcile:
            break
    if error_recorder is not None:
        error_recorder(outcome.errors)
    return outcome
