"""File-lease leader election — the k8s Lease-object analog.

Mirror of the reference's leader-election contract
(`operator/api/config/v1alpha1/types.go:73-104`): one holder at a time,
lease must be renewed within renewDeadline, a stale lease (past
leaseDuration) can be stolen. Implemented over an atomic
write-to-temp + rename on a shared filesystem path, which gives HA restarts
on a single host or a shared volume — the deployment surfaces this stack
actually targets (there is no kube-apiserver to host a Lease CR).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field

from grove_tpu.utils.fsio import atomic_write_json


@dataclass
class FileLease:
    path: str
    lease_duration_seconds: float = 15.0
    # Leader stands down if it failed to renew within this window (types.go:
    # renewDeadline): a stalled reconcile loop must stop acting as leader
    # BEFORE the lease can be stolen at lease_duration, so two leaders never
    # overlap. None = no deadline enforcement.
    renew_deadline_seconds: float | None = None
    identity: str = field(default_factory=lambda: f"{os.getpid()}-{uuid.uuid4().hex[:8]}")
    _last_renew: float | None = field(default=None, repr=False)

    def _read(self) -> dict | None:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _write(self, doc: dict) -> None:
        atomic_write_json(self.path, doc)

    def try_acquire(self, now: float | None = None) -> bool:
        """Acquire or renew; returns True when this process holds the lease.

        A different holder's lease is honored until it expires
        (leaseDurationSeconds past its last renewal), then stolen.
        """
        now = time.time() if now is None else now
        doc = self._read()
        if doc is not None:
            holder = doc.get("holder")
            renewed = float(doc.get("renewed", 0.0))
            if holder != self.identity and now - renewed < self.lease_duration_seconds:
                self._last_renew = None
                return False
        # Renew-deadline enforcement: if we held the lease but overslept the
        # renewal window (e.g. a reconcile pass stalled), stand down for this
        # tick instead of silently extending — the reference leader cancels
        # itself rather than risk overlapping a successor (types.go:73-104).
        if (
            self.renew_deadline_seconds is not None
            and self._last_renew is not None
            and now - self._last_renew > self.renew_deadline_seconds
        ):
            self._last_renew = None
            self.release()
            return False
        self._write({"holder": self.identity, "renewed": now})
        # Re-read to confirm we won any racing rename (last writer wins; the
        # loser observes the winner's identity here and stands down).
        doc = self._read()
        won = bool(doc and doc.get("holder") == self.identity)
        self._last_renew = now if won else None
        return won

    def held(self, now: float | None = None) -> bool:
        """True while this process holds the lease: the last try_acquire
        won, no stand-down happened since, and leaseDurationSeconds has not
        elapsed without renewal — an expired lease is stealable by anyone,
        so it no longer counts as held even if nobody has stolen it yet."""
        if self._last_renew is None:
            return False
        now = time.time() if now is None else now
        return now - self._last_renew < self.lease_duration_seconds

    def release(self) -> None:
        doc = self._read()
        if doc and doc.get("holder") == self.identity:
            try:
                os.unlink(self.path)
            except OSError:
                pass


_LEASE_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)


class LeaseSet:
    """Multiple named leases held by ONE process — one per reconcile cell.

    Each name maps to its own `FileLease` at `<directory>/<name>.lease` with
    its OWN renewal clock (`_last_renew` is per-FileLease state), so a cell
    whose reconcile stalls past its renew deadline stands down for THAT
    lease only: losing one cell's lease never releases another's
    (tests/test_cells.py pins this with a fake clock). All leases share one
    process identity so a restarted process steals its own stale leases
    uniformly.
    """

    def __init__(
        self,
        directory: str,
        *,
        lease_duration_seconds: float = 15.0,
        renew_deadline_seconds: float | None = None,
        identity: str | None = None,
    ) -> None:
        self.directory = directory
        self.lease_duration_seconds = float(lease_duration_seconds)
        self.renew_deadline_seconds = renew_deadline_seconds
        self.identity = (
            identity
            if identity is not None
            else f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        self._leases: dict[str, FileLease] = {}

    def lease(self, name: str) -> FileLease:
        """The named lease (created lazily). Names are path components, so
        only [A-Za-z0-9._-] is accepted — a separator in a cell name must
        not escape the lease directory."""
        got = self._leases.get(name)
        if got is not None:
            return got
        if not name or not set(name) <= _LEASE_NAME_OK or name.startswith("."):
            raise ValueError(f"lease name {name!r}: use [A-Za-z0-9_-][A-Za-z0-9._-]*")
        got = FileLease(
            path=os.path.join(self.directory, f"{name}.lease"),
            lease_duration_seconds=self.lease_duration_seconds,
            renew_deadline_seconds=self.renew_deadline_seconds,
            identity=self.identity,
        )
        self._leases[name] = got
        return got

    def try_acquire(self, name: str, now: float | None = None) -> bool:
        """Acquire/renew one named lease; the other names' clocks are
        untouched (independent renewal — the whole point of the set)."""
        return self.lease(name).try_acquire(now)

    def held(self, now: float | None = None) -> dict[str, bool]:
        """Holdership per name (True = the most recent try_acquire won, no
        stand-down since, and the lease has not expired unrenewed). Pass
        `now` when driving the leases on a fake clock."""
        return {
            name: lease.held(now)
            for name, lease in sorted(self._leases.items())
        }

    def release(self, name: str) -> None:
        got = self._leases.get(name)
        if got is not None:
            got.release()

    def release_all(self) -> None:
        for lease in self._leases.values():
            lease.release()
