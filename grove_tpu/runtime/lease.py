"""File-lease leader election — the k8s Lease-object analog.

Mirror of the reference's leader-election contract
(`operator/api/config/v1alpha1/types.go:73-104`): one holder at a time,
lease must be renewed within renewDeadline, a stale lease (past
leaseDuration) can be stolen. Implemented over an atomic
write-to-temp + rename on a shared filesystem path, which gives HA restarts
on a single host or a shared volume — the deployment surfaces this stack
actually targets (there is no kube-apiserver to host a Lease CR).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from dataclasses import dataclass, field

from grove_tpu.utils.fsio import atomic_write_json


@dataclass
class FileLease:
    path: str
    lease_duration_seconds: float = 15.0
    # Leader stands down if it failed to renew within this window (types.go:
    # renewDeadline): a stalled reconcile loop must stop acting as leader
    # BEFORE the lease can be stolen at lease_duration, so two leaders never
    # overlap. None = no deadline enforcement.
    renew_deadline_seconds: float | None = None
    identity: str = field(default_factory=lambda: f"{os.getpid()}-{uuid.uuid4().hex[:8]}")
    _last_renew: float | None = field(default=None, repr=False)

    def _read(self) -> dict | None:
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def _write(self, doc: dict) -> None:
        atomic_write_json(self.path, doc)

    def try_acquire(self, now: float | None = None) -> bool:
        """Acquire or renew; returns True when this process holds the lease.

        A different holder's lease is honored until it expires
        (leaseDurationSeconds past its last renewal), then stolen.
        """
        now = time.time() if now is None else now
        doc = self._read()
        if doc is not None:
            holder = doc.get("holder")
            renewed = float(doc.get("renewed", 0.0))
            if holder != self.identity and now - renewed < self.lease_duration_seconds:
                self._last_renew = None
                return False
        # Renew-deadline enforcement: if we held the lease but overslept the
        # renewal window (e.g. a reconcile pass stalled), stand down for this
        # tick instead of silently extending — the reference leader cancels
        # itself rather than risk overlapping a successor (types.go:73-104).
        if (
            self.renew_deadline_seconds is not None
            and self._last_renew is not None
            and now - self._last_renew > self.renew_deadline_seconds
        ):
            self._last_renew = None
            self.release()
            return False
        self._write({"holder": self.identity, "renewed": now})
        # Re-read to confirm we won any racing rename (last writer wins; the
        # loser observes the winner's identity here and stands down).
        doc = self._read()
        won = bool(doc and doc.get("holder") == self.identity)
        self._last_renew = now if won else None
        return won

    def release(self) -> None:
        doc = self._read()
        if doc and doc.get("holder") == self.identity:
            try:
                os.unlink(self.path)
            except OSError:
                pass
