"""OperatorConfiguration: the single YAML that boots the whole stack.

Mirror of `operator/api/config/v1alpha1/types.go:57-70` (+ defaults.go and
api/config/validation/): leader election (types.go:73-104), server binds
(types.go:120-151), per-controller concurrent syncs (types.go:180-208),
log config, authorizer (types.go:211-220), topology-aware scheduling
(types.go:223-230), network acceleration (types.go:233-240) — re-keyed for
the TPU-native stack: the scheduler backend sidecar and the JAX solver get
first-class sections, and network acceleration configures the TPU-slice (ICI
domain) resource injection instead of MNNVL.

Everything has a default; `validate_operator_config` returns a list of
field-path errors (empty = valid), matching the reference's
LoadAndValidateOperatorConfig boot contract (cmd/cli/cli.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import yaml

from grove_tpu.api import constants as api_constants
from grove_tpu.api.types import ClusterTopology, DEFAULT_CLUSTER_TOPOLOGY

# Runtime state dir: on-disk caches that survive operator restarts (the
# persistent XLA compilation cache, the solver shape-bucket history the
# prewarm thread compiles from). Distinct from persistence.path, which is
# control-plane STATE — losing this dir only costs warm-up time.
RUNTIME_STATE_DIR = "/tmp/grove-tpu-state"


@dataclass
class LeaderElectionConfig:
    """types.go:73-104; lease-file analog of the k8s Lease object."""

    enabled: bool = False
    lease_file: str = "/tmp/grove-tpu-leader.lease"
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    retry_period_seconds: float = 2.0


@dataclass
class ServerConfig:
    """Bind addresses (types.go:120-151). Port 0 = auto-assign, -1 = disabled."""

    bind_address: str = "127.0.0.1"  # 0.0.0.0 for in-cluster deployments
    health_port: int = 2751
    metrics_port: int = 2752
    # URL WORKLOAD PODS reach the operator's HTTP API at (the injected
    # grove-initc agent's --server). "" = the agent's localhost default —
    # fine for single-host runs, wrong for real clusters, where this must
    # be the operator Service, e.g. http://grove-tpu-operator.grove-system.svc:2751
    advertise_url: str = ""
    profiling_enabled: bool = False  # pprof analog (manager.go:42-44)
    # TLS for the HTTP surface (cert mode auto/manual, types.go:154-169):
    # disabled | auto (self-signed into tlsCertDir) | manual (provided files).
    tls_mode: str = "disabled"
    tls_cert_dir: str = "/tmp/grove-tpu-certs"
    tls_cert_file: str = ""
    tls_key_file: str = ""
    # Manual mode only: the ISSUING CA bundle for tlsCertFile. Required for
    # the webhook caBundle patch when the manual cert is CA-issued — a leaf
    # installed as a trust root verifies nothing. Unset = the cert file
    # itself is the root (self-signed manual certs).
    tls_ca_file: str = ""
    # Inbound AdmissionReview webhook server (the controller-runtime webhook
    # server analog, manager.go:90-121 / register.go:34-62). -1 = disabled,
    # 0 = auto-assign (tests). ALWAYS HTTPS — the apiserver refuses plaintext
    # webhooks — with certs independent of tlsMode (auto self-signed into
    # tlsCertDir/webhook unless tlsMode is manual, which reuses its files).
    webhook_port: int = -1
    # Extra DNS SANs baked into the auto-generated webhook serving cert —
    # must include the webhook Service DNS name for in-cluster use (the
    # apiserver verifies the cert against clientConfig.service).
    webhook_sans: list[str] = field(default_factory=list)


@dataclass
class ControllerConfig:
    """Reconcile loop knobs (types.go:180-208)."""

    concurrent_syncs: int = 1
    reconcile_interval_seconds: float = 1.0
    # Control-plane event ring capacity (Cluster.events deque maxlen). The
    # ring was unbounded through PR 3 and leaked on long soaks; overflow now
    # drops the oldest event and counts it (grove_events_dropped_total).
    events_buffer: int = 4096
    # Heal-event dedupe window: repeated "rejected/unparseable CR" heal
    # events for one (object, reason) pair emit at most once per window —
    # an external writer flapping between bad values must not flood the
    # event ring every relist. 0 disables the window (every heal events).
    heal_event_dedupe_seconds: float = 60.0


@dataclass
class LogConfig:
    level: str = "info"  # debug|info|error
    format: str = "text"  # json|text


@dataclass
class AuthorizerConfig:
    """types.go:211-220: block mutation of managed resources by non-operators."""

    enabled: bool = False
    exempt_actors: list[str] = field(default_factory=list)


@dataclass
class TopologyAwareSchedulingConfig:
    """types.go:223-230: enable TAS + the level list (ClusterTopology source)."""

    enabled: bool = True
    # Each: {"domain": "rack", "nodeLabelKey": "topology.kubernetes.io/rack"}
    levels: list[dict] = field(default_factory=list)


@dataclass
class NetworkAccelerationConfig:
    """types.go:233-240 MNNVL analog: auto TPU-slice/ICI resource injection."""

    auto_slice_enabled: bool = False
    slice_resource_name: str = api_constants.DEFAULT_SLICE_RESOURCE


@dataclass
class SchedulingConfig:
    """Priority classes (the chart's priorityclass.yaml analog): name ->
    numeric priority consumed by the preemption pass and pending-sort.

    `queues` is the KAI Queue analog (the reference deploys KAI queues,
    e2e/setup/kai_scheduler.go:90): name -> {resource: quota}, quantity
    strings or -1 for unlimited. A PodCliqueSet opts in with the
    `grove.io/queue` annotation; its gangs' floors are admitted only while
    the queue's cumulative usage fits the quota (hard quota — KAI's
    over-quota fair-share borrowing is out of scope)."""

    priority_classes: dict[str, int] = field(default_factory=dict)
    queues: dict = field(default_factory=dict)


@dataclass
class SolverConfig:
    """The placement engine (no reference analog — the KAI replacement)."""

    # Portfolio width: >1 solves every batch under P score-weight variants
    # and keeps the winner (parallel/portfolio.py) — the multi-chip quality
    # knob; the variants shard across the device mesh when one is available.
    # (A `speculative` knob existed through round 3; the path was deleted
    # after losing to the sequential scan in every measured regime.)
    portfolio: int = 1
    # Rejection escalation: when a solve at `portfolio` width leaves valid
    # gangs rejected and this value is LARGER, re-solve that batch once at
    # this width and keep the winner (bounded, once per solve; the seeded
    # population is prefix-stable, so the wider winner can only admit
    # more). <= portfolio disables. Defaults ON so the default serving
    # path fixes packing-artifact rejections without paying the portfolio
    # cost on uncontended solves; the serving paths damp it to base cost
    # in an unchanged saturated steady state.
    portfolio_escalation: int = 4
    # Persistent XLA compilation cache dir ("" = off): solver warm-up
    # compiles (~20-40s on TPU) are reused across operator restarts.
    # Defaults ON under the runtime state dir — the cold-start compile tax
    # (BENCH_r05: compile_s=4.32 vs solve 0.85s) is paid once per
    # (code, shape, platform), not once per boot. Tests/processes can
    # override with the JAX_COMPILATION_CACHE_DIR env var (JAX reads it
    # natively) without touching config.
    compilation_cache_dir: str = RUNTIME_STATE_DIR + "/xla-cache"
    # Startup prewarm: a background thread AOT-compiles the top-K
    # historically hottest solver shape buckets (recorded per solve to
    # shapeHistoryPath) so the first drain/solve_pending after a restart
    # never blocks on XLA. 0 = off.
    prewarm_top_k: int = 4
    shape_history_path: str = RUNTIME_STATE_DIR + "/solve-shapes.json"
    max_groups: Optional[int] = None
    max_sets: Optional[int] = None
    max_pods: Optional[int] = None
    pad_gangs_to: Optional[int] = None
    # Score-weight overrides (SolverParams fields, camelCase: wTight, wPref,
    # wReuse, wReserve, wSpread). Unset fields keep their defaults.
    weights: dict = field(default_factory=dict)
    # Candidate-node pruning (solver/pruning.py): a cheap host pre-filter
    # gathers the nodes that could possibly serve any gang in the wave onto
    # a compact pow2 candidate axis, the unchanged batched solver runs on
    # the sub-fleet, and the AOT executable cache keys on the CANDIDATE pad
    # instead of the fleet pad (executables stop growing with fleet size).
    # Lossy rejections escalate to a dense re-solve — admitted sets match
    # the dense solver, escalations counted, never silent. Keys:
    #   enabled        bool, default false
    #   maxCandidates  int >= 1, candidate budget (default 8191 — pairs with
    #                  the 8192 bucket + the cap-anchor pad row)
    #   padLadder      list of increasing ints; [] = every pow2 from minPad
    #   minPad         int >= 2, smallest candidate bucket (default 64)
    #   minFleet       int >= 0, fleets below this never prune (default 256)
    pruning: dict = field(default_factory=dict)
    # Streaming drain (solver/stream.py): the double-buffered pipelined
    # admission loop under live arrival traffic — encode wave N+1 and
    # decode/bind wave N-1 on the host while wave N solves on device. Keys:
    #   depth     int >= 1, waves in flight before the host blocks on the
    #             oldest (default 2 — classic double buffering)
    #   waveSize  int >= 1, max gangs per formed arrival window (default 64;
    #             smaller binds sooner, larger amortizes dispatch better)
    #   maxWaitS  number >= 0, paced mode: how long the oldest queued gang
    #             waits for companions before a partial wave dispatches
    #             (default 0.05)
    #   pollS     number > 0, paced mode: idle poll granularity (default
    #             0.005)
    streaming: dict = field(default_factory=dict)
    # On-device fused drain (solver/drain.py harvest="scan"): an entire
    # shape-class of planned waves dispatches as ONE lax.scan program — the
    # free/ok_global carry threads between waves on device, verdict planes
    # accumulate as scan outputs, and the host pays O(shape classes +
    # escalations) round-trips instead of O(waves). Bitwise-equal admitted
    # sets vs the per-wave disciplines (test-pinned), so enabling it is a
    # pure host-overhead choice; the resilience ladder's first rung steps
    # scan -> pipelined on failure. Keys:
    #   enabled           bool, default true (the block gates callers that
    #                     request the scan discipline; serving paths still
    #                     choose harvest explicitly)
    #   maxScanLen        int >= 1, max waves fused into one scan chunk
    #                     (default 32; chunk lengths bucket to pow2)
    #   minWavesPerClass  int >= 1, runs shorter than this dispatch
    #                     per-wave — fusion overhead isn't worth one wave
    #                     (default 2)
    #   affinityLookahead int >= 0, stream saturated mode: planned waves
    #                     from up to this many windows ahead reorder by
    #                     (rank, shape class) before dispatch so same-
    #                     class runs form and fuse; 0 = strict window-at-
    #                     a-time dispatch order (default 4). Window
    #                     composition and admitted sets are unchanged.
    #   deviceResident    bool, default false: saturated stream drains
    #                     retire nothing until the trace is exhausted —
    #                     ONE batched harvest at the end, device round-
    #                     trips O(1 + escalations). First ladder rung
    #                     ("resident"), stepping down to scanned.
    scan: dict = field(default_factory=dict)
    # Mesh-sharded solve (parallel/mesh.py): distribute the single-variant
    # batched solve across the TPU mesh — node-axis tensors split over the
    # devices (GSPMD inserts the segment-reduction collectives), the free
    # carry chains node-sharded between waves, the AOT cache keys on the
    # mesh shape, and journaled waves record the mesh fingerprint for
    # replay. Bitwise-equal to the unsharded solve, so enabling it is a
    # pure throughput choice; negotiation fallbacks (no divisible layout)
    # solve unsharded and are counted (/statusz warmPath shardFallbacks).
    # Keys:
    #   enabled     bool, default false
    #   minNodes    int >= 0, fleets whose padded node axis is below this
    #               stay unsharded (default 512 — collectives would cost
    #               more than the split saves)
    #   maxDevices  int >= 0, devices the solve may occupy (default 0 =
    #               every visible device)
    mesh: dict = field(default_factory=dict)

    def solver_params(self):
        """SolverConfig.weights -> SolverParams (validated at config load)."""
        from grove_tpu.solver.core import SolverParams

        snake = {_CAMEL_FIELDS.get(k, k): float(v) for k, v in self.weights.items()}
        return SolverParams(**snake)

    def pruning_config(self):
        """SolverConfig.pruning -> solver.pruning.PruningConfig, or None
        when pruning is disabled (validated at config load)."""
        p = self.pruning or {}
        if not p.get("enabled", False):
            return None
        from grove_tpu.solver.pruning import PruningConfig

        kwargs = {}
        if "maxCandidates" in p:
            kwargs["max_candidates"] = int(p["maxCandidates"])
        if "padLadder" in p:
            kwargs["pad_ladder"] = tuple(int(x) for x in p["padLadder"])
        if "minPad" in p:
            kwargs["min_pad"] = int(p["minPad"])
        if "minFleet" in p:
            kwargs["min_fleet"] = int(p["minFleet"])
        return PruningConfig(enabled=True, **kwargs)

    def mesh_config(self):
        """SolverConfig.mesh -> parallel.mesh.MeshConfig (validated at
        config load; always returns a config — the enabled bit rides it)."""
        m = self.mesh or {}
        from grove_tpu.parallel.mesh import MeshConfig

        kwargs = {}
        if "minNodes" in m:
            kwargs["min_nodes"] = int(m["minNodes"])
        if "maxDevices" in m:
            kwargs["max_devices"] = int(m["maxDevices"])
        return MeshConfig(enabled=bool(m.get("enabled", False)), **kwargs)

    def streaming_config(self):
        """SolverConfig.streaming -> solver.stream.StreamConfig (validated
        at config load; always returns a config — streaming has no enabled
        bit, the block only parameterizes callers of drain_stream)."""
        s = self.streaming or {}
        from grove_tpu.solver.stream import StreamConfig

        kwargs = {}
        if "depth" in s:
            kwargs["depth"] = int(s["depth"])
        if "waveSize" in s:
            kwargs["wave_size"] = int(s["waveSize"])
        if "maxWaitS" in s:
            kwargs["max_wait_s"] = float(s["maxWaitS"])
        if "pollS" in s:
            kwargs["poll_s"] = float(s["pollS"])
        return StreamConfig(**kwargs)

    def scan_config(self):
        """SolverConfig.scan -> solver.drain.ScanConfig (validated at config
        load; always returns a config — the enabled bit rides it, default
        ON: a disabled block makes harvest="scan" requests fall back to
        pipelined)."""
        s = self.scan or {}
        from grove_tpu.solver.drain import ScanConfig

        kwargs = {}
        if "maxScanLen" in s:
            kwargs["max_scan_len"] = int(s["maxScanLen"])
        if "minWavesPerClass" in s:
            kwargs["min_waves_per_class"] = int(s["minWavesPerClass"])
        if "affinityLookahead" in s:
            kwargs["affinity_lookahead"] = int(s["affinityLookahead"])
        if "deviceResident" in s:
            kwargs["device_resident"] = bool(s["deviceResident"])
        return ScanConfig(enabled=bool(s.get("enabled", True)), **kwargs)


@dataclass
class DefragConfig:
    """Defragmentation & rebalance loop (solver/defrag.py + the controller's
    defrag_tick): periodic fragmentation scoring over the cluster snapshot;
    when the score crosses `threshold`, the batched migration planner
    re-places movable gangs (through the same warm-path AOT executable
    cache as serving solves) and the controller executes the winning plan
    under a disruption budget with make-before-break ordering."""

    enabled: bool = False
    # Fragmentation score in [0, 1] at which planning kicks in (1 - best
    # domain free / ideal consolidated free, worst over levels+resources).
    threshold: float = 0.5
    # Evaluation cadence of the background loop.
    interval_seconds: float = 30.0
    # Disruption budget: max gangs migrating (rebound, not yet Ready again)
    # at any instant. Plan moves beyond it defer to later cycles.
    max_concurrent_migrations: int = 1
    # A migrated gang is immune to re-migration for this long.
    gang_cooldown_seconds: float = 300.0
    # Cap on gangs re-placed per plan (candidate prefix ladder top).
    max_moves_per_plan: int = 8
    # Minimum (capacity recovered / pods migrated) for a plan to execute;
    # units of the binding resource. 0 = any strict improvement runs.
    min_efficiency: float = 0.0


@dataclass
class RolloutConfig:
    """Make-before-break rolling updates (orchestrator/rollout.py;
    docs/design.md "Fleet lifecycle"): when enabled — globally here or
    per-PCS via the grove.io/rollout-strategy annotation — the current
    replica's new generation is planned onto capacity that is free WHILE the
    old placement still holds (plan_rescue with usage held), cut over
    atomically under the shared disruption budget, and deferred whole (with
    surge/next-replica what-if pricing journaled) when it does not fit.
    Off = the seed delete-then-recreate behavior exactly."""

    enabled: bool = False
    # "+surge racks" what-if size priced for parked replicas (0 disables
    # the surge scenario; the next-replica what-if always runs).
    surge_racks: int = 1
    # Decorrelated-jitter retry pacing for deferred replicas
    # (utils/backoff.py): first retry after base, capped growth after.
    backoff_base_seconds: float = 0.5
    backoff_cap_seconds: float = 30.0
    # Per-replica make-before-break deadline: once spent, the replica falls
    # back to the seed delete-then-recreate path (always makes progress).
    deadline_seconds: float = 600.0


@dataclass
class TraceConfig:
    """Decision flight recorder (grove_tpu/trace): journals every solve wave
    (snapshot digest, compact node/gang encodings, solver config fingerprint,
    resulting plan with per-gang rejection reasons, timings) plus preemption/
    defrag/rolling-update actions, off the hot path via a bounded queue and a
    writer thread with atomic segment rotation. Journals feed deterministic
    replay (`grove-tpu trace replay` — bitwise plan equivalence, divergence =
    solver-nondeterminism regression) and what-if counterfactuals
    (`grove-tpu trace whatif` — +N racks / different solver config scored
    with the placement-quality report)."""

    enabled: bool = False
    # Journal directory (segment files rotate inside it).
    path: str = RUNTIME_STATE_DIR + "/trace"
    # Segment rotation: records per segment file, and how many segment files
    # to keep (oldest pruned; every segment is self-contained for replay).
    max_records_per_file: int = 256
    max_files: int = 16
    # Bounded hand-off queue between the reconcile thread and the writer; a
    # full queue DROPS records (counted) rather than blocking a solve.
    queue_size: int = 2048
    # Writer flush cadence; the manager's trace flow step also requests a
    # flush each reconcile, so journal staleness is bounded by min(this,
    # reconcile interval).
    flush_interval_seconds: float = 1.0


@dataclass
class TuningConfig:
    """Offline config-sweep tuning (grove_tpu/tuning): `grove-tpu tune
    sweep` replays a recorded journal once while K candidate solver configs
    ride the solver's variant axis (one AOT executable per (wave shape
    bucket, K)), prunes losers by successive halving between trace chunks,
    and emits a recommended config validated two ways — bitwise agreement
    with a plain single-config replay, and admitted-ratio parity against
    the exact B&B reference on the seeded audit instances. This block only
    parameterizes the sweep driver; nothing in the serving path reads it."""

    # Config-grid size: the incumbent (recorded) config + gridK-1 candidates.
    grid_k: int = 16
    # Successive-halving rungs over the trace (1 = score the whole grid on
    # the whole trace, no halving).
    halving_rungs: int = 3
    # Log-normal weight-perturbation spread for the generated grid.
    spread: float = 0.5
    # Grid generation seed (the sweep is deterministic given the journal).
    seed: int = 0
    # Exact-audit instance seeds for winner validation; [] = the default
    # tier-1 audit set (quality/audit.AUDIT_SEEDS).
    audit_seeds: list = field(default_factory=list)


@dataclass
class FaultsConfig:
    """Deterministic fault injection (grove_tpu/faults): named sites
    threaded through the stack — solver dispatch/harvest, bind commit, the
    kube wire client, the watch stream, the recorder's segment writes, sim
    node death — fire on a seed-driven schedule so chaos runs replay
    bit-for-bit. Off by default and off in production; the `GROVE_FAULTS`
    env override ("site=kind:rate[:count[:after]];...") wins over this
    block outright. Every injected fault is journaled as a flight-recorder
    action record and counted (/statusz resilience.faults)."""

    enabled: bool = False
    # Site-schedule derivation seed (per-site streams are independent).
    seed: int = 0
    # site -> {kind, rate, count, after}; see faults.SITES / faults.KINDS.
    sites: dict = field(default_factory=dict)


@dataclass
class CellsConfig:
    """Cellular control plane (grove_tpu/cells; docs/design.md "Cellular
    control plane"). When enabled the manager partitions the control plane
    into `count` reconcile cells along QueueTree root-subtree seams (each
    root subtree is a self-contained borrow domain) and shards the fleet
    along `topologyLevel`; each cell runs its own drain/stream engine with
    its own journal (under journalRoot/<cell>) and its own named lease
    (runtime/lease.LeaseSet — losing one cell's lease never touches
    another's). A restarting cell recovers by replaying its journal tail
    bitwise (trace/replay) before admitting new work. Cross-cell traffic
    (spanning gangs, borrowed capacity, reclaim) routes through the
    coordinator only."""

    enabled: bool = False
    # How many cells to shard into (cell-0 .. cell-(count-1)).
    count: int = 2
    # Partition axis: "queue" pins gangs by QueueTree root subtree;
    # "topology" leaves queues unpinned (pure fleet sharding).
    shard_by: str = "queue"
    # TAS domain the fleet shards along (a domain's nodes land wholly in
    # one cell, so each engine sees a topologically coherent sub-snapshot).
    topology_level: str = "zone"
    # Per-cell journal directories: journalRoot/<cell-name>/.
    journal_root: str = RUNTIME_STATE_DIR + "/cells"
    # Per-cell named lease files: leaseDir/<cell-name>.lease.
    lease_dir: str = RUNTIME_STATE_DIR + "/cell-leases"
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    # Gangs per engine run between crash-fault checkpoints (cell.crash
    # fires only at chunk boundaries; families never split across chunks).
    crash_check_every: int = 128


@dataclass
class ResilienceConfig:
    """Graceful-degradation ladder + failure-domain hardening
    (solver/resilience.py). When enabled: a watchdog cancels and
    re-dispatches in-flight solve waves that hang; per-subsystem circuit
    breakers step the solve loop down mesh-sharded->unsharded,
    pruned->dense, pipelined->serial, portfolio->single (each rung
    admitted-set-preserving by the PR 5-7 equivalence pins) and step back
    up after probation; kube binds retry with decorrelated-jitter backoff;
    gang binds commit all-or-nothing with rollback; and stale plans
    (target node died between solve and bind) requeue instead of binding.
    Every step-down/step-up is counted (grove_degradation_*), journaled,
    and surfaced on /statusz resilience + `grove-tpu get resilience` —
    never silent."""

    enabled: bool = False
    # In-flight wave watchdog: hung-solve deadline and re-dispatch budget.
    watchdog_seconds: float = 30.0
    max_wave_retries: int = 2
    # Circuit breakers: failures within the window that open a rung, and
    # how long it stays open before a half-open (trial) probe.
    breaker_threshold: int = 3
    breaker_window_seconds: float = 60.0
    probation_seconds: float = 30.0
    # Kube bind push: in-call retry attempts with decorrelated jitter
    # (utils/backoff.py; 1 = single shot, cross-tick retry set still applies).
    bind_max_attempts: int = 3
    backoff_base_seconds: float = 0.05
    backoff_cap_seconds: float = 2.0
    # Retire-time stale-plan revalidation (bind into live nodes only).
    stale_plan_revalidation: bool = True

    def resilience_config(self):
        """-> solver.resilience.ResilienceConfig (the solver-side value
        object; always returns one — the enabled bit rides it)."""
        from grove_tpu.solver.resilience import (
            ResilienceConfig as SolverResilienceConfig,
        )

        return SolverResilienceConfig(
            enabled=bool(self.enabled),
            watchdog_seconds=float(self.watchdog_seconds),
            max_wave_retries=int(self.max_wave_retries),
            breaker_threshold=int(self.breaker_threshold),
            breaker_window_seconds=float(self.breaker_window_seconds),
            probation_seconds=float(self.probation_seconds),
            bind_max_attempts=int(self.bind_max_attempts),
            backoff_base_seconds=float(self.backoff_base_seconds),
            backoff_cap_seconds=float(self.backoff_cap_seconds),
            stale_plan_revalidation=bool(self.stale_plan_revalidation),
        )


@dataclass
class TenancyConfig:
    """Multi-tenant SLO tiers (grove_tpu/tenancy; docs/design.md
    "Multi-tenant SLO tiers"). When enabled: the workload sloClass
    (latency | standard | batch-preemptible) leads the admission order,
    `latency` gangs never ride borrowed queue capacity, starved pending
    gangs climb effective priority on a deterministic half-life-doubling
    aging ladder, quota-reclaim evictions draw from the defrag disruption
    budget (deferred — never partially applied — when over it), and a
    per-tenant fairness ledger feeds /statusz tenancy, the
    grove_tenancy_* metrics, and `grove-tpu get tenancy`. Disabled = the
    pre-tenancy scheduling behavior exactly."""

    enabled: bool = False
    # Aging ladder: boost step k unlocks after half_life*(2^k - 1) seconds
    # pending (tenancy/aging.py), capped at aging_max_boost.
    aging_half_life_seconds: float = 300.0
    aging_max_boost: int = 4


@dataclass
class BackendConfig:
    """Scheduler-backend sidecar (GREP-375 boundary)."""

    enabled: bool = False
    port: int = 0  # 0 = auto-assign
    max_workers: int = 8


@dataclass
class PersistenceConfig:
    """Control-plane state snapshot/restore (CR-status persistence analog)."""

    enabled: bool = False
    path: str = "/tmp/grove-tpu-state.json"
    snapshot_interval_seconds: float = 10.0


@dataclass
class ClusterConfig:
    """Node source for the manager. `none` (default): the store is fed
    externally (attach_watch / backend RPCs / simulator). `kwok`: the manager
    fabricates a KWOK-shaped fake fleet at boot and drives it through the
    watch path — the in-binary analog of the reference's scale rig
    (`make kind-up FAKE_NODES=N`, operator/hack/kind-up.sh:31,252-265), which
    makes `python -m grove_tpu.runtime` a self-contained e2e environment.
    `kubernetes`: a live apiserver via the list/watch wire protocol
    (cluster/kubernetes.py; the informer pattern of manager.go:53-121) —
    node/pod events stream in, solver placements POST back as pod creates +
    binding subresource calls."""

    source: str = "none"  # none | kwok | kubernetes
    # kubernetes source: kubeconfig path ("" = $KUBECONFIG, ~/.kube/config,
    # then the in-cluster service-account mount), context ("" = current),
    # namespace ("" = the context's), and the pod watch label selector.
    kubeconfig: str = ""
    kube_context: str = ""
    kube_namespace: str = ""
    # "" = the managed-by selector derived from api/constants
    # (cluster/kubernetes.py DEFAULT_POD_LABEL_SELECTOR).
    pod_label_selector: str = ""
    # Client-side rate limit on the kubernetes wire client — the reference's
    # ClientConnectionConfiguration{QPS, Burst} (types.go client-connection
    # section; client-go flowcontrol defaults). Token bucket over every
    # apiserver request the watch source issues (binding an N-pod gang is
    # 2N calls per tick): sustained `kubeQps` requests/s with `kubeBurst`
    # tokens of headroom. kubeQps 0 disables throttling entirely.
    kube_qps: float = 50.0
    kube_burst: int = 100
    # Watch PodCliqueSet CRs at the apiserver (kubectl-apply -> admission ->
    # reconcile -> status write-back). Off = fleet mirroring only (workloads
    # arrive via the operator's own HTTP API).
    watch_workloads: bool = True
    # How injected grove-initc agents read parent-clique readiness:
    #   operator   — poll the operator HTTP API (servers.advertiseUrl)
    #   kubernetes — list gang pods at the kube-apiserver directly with the
    #                mirrored per-PCS SA token (the reference agent's path,
    #                wait.go:111-164); no operator URL in the pod at all.
    initc_mode: str = "operator"
    kwok_nodes: int = 8
    kwok_cpu_per_node: float = 32.0
    kwok_memory_per_node: float = 128 * 2**30
    kwok_tpu_per_node: float = 8.0
    kwok_hosts_per_rack: int = 4
    kwok_racks_per_block: int = 4
    # Group factors for topology levels BEYOND rack/block, narrowest first
    # (e.g. [2, 3] = 2 blocks per zone, 3 zones per super-zone). Required
    # when the TAS config declares more than rack/block/host — a deeper
    # hierarchy must not silently get a fleet shape nobody asked for.
    kwok_level_group_factors: list = field(default_factory=list)
    # KWOK stage latencies (kind-up.sh:264-265): bind -> Running -> Ready.
    running_delay_seconds: float = 0.2
    ready_delay_seconds: float = 0.2
    # Informer-latency model: events become pollable only this much later.
    event_lag_seconds: float = 0.0
    # Revocable (spot) capacity: mark the LAST N kwok nodes revocable — the
    # fleet slice the provider may take back on a revocation notice
    # (Node.revocable; sim.node_revocation fault site). 0 = all on-demand.
    revocable_nodes: int = 0
    # Grace window granted with a notice: seconds between the notice and the
    # capacity disappearing (Simulator.revocation_grace_s analog).
    revocable_grace_seconds: float = 30.0
    # Controller reaction ladder: with at least this much grace left it
    # migrates residents make-before-break; inside the lead it evicts in
    # SLO-rank order (batch-preemptible first) so the node drains in time.
    revocable_eviction_lead_seconds: float = 10.0


@dataclass
class OperatorConfiguration:
    leader_election: LeaderElectionConfig = field(default_factory=LeaderElectionConfig)
    servers: ServerConfig = field(default_factory=ServerConfig)
    controllers: ControllerConfig = field(default_factory=ControllerConfig)
    log: LogConfig = field(default_factory=LogConfig)
    authorizer: AuthorizerConfig = field(default_factory=AuthorizerConfig)
    topology_aware_scheduling: TopologyAwareSchedulingConfig = field(
        default_factory=TopologyAwareSchedulingConfig
    )
    network_acceleration: NetworkAccelerationConfig = field(
        default_factory=NetworkAccelerationConfig
    )
    scheduling: SchedulingConfig = field(default_factory=SchedulingConfig)
    solver: SolverConfig = field(default_factory=SolverConfig)
    defrag: DefragConfig = field(default_factory=DefragConfig)
    rollout: RolloutConfig = field(default_factory=RolloutConfig)
    trace: TraceConfig = field(default_factory=TraceConfig)
    tuning: TuningConfig = field(default_factory=TuningConfig)
    faults: FaultsConfig = field(default_factory=FaultsConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    cells: CellsConfig = field(default_factory=CellsConfig)
    tenancy: TenancyConfig = field(default_factory=TenancyConfig)
    backend: BackendConfig = field(default_factory=BackendConfig)
    persistence: PersistenceConfig = field(default_factory=PersistenceConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)

    def cluster_topology(self) -> ClusterTopology:
        """TAS levels -> ClusterTopology (clustertopology sync analog)."""
        tas = self.topology_aware_scheduling
        if not tas.levels:
            return DEFAULT_CLUSTER_TOPOLOGY
        topo = ClusterTopology.from_dict(
            {"name": "operator-config", "levels": tas.levels}
        )
        # Auto-append the host level, as the operator's topology sync does
        # (internal/clustertopology/clustertopology.go:102-107).
        return topo.with_host_level()


# Valid score-weight field names, kept jax-free (config validation must not
# import the solver). tests/test_config_wiring.py pins this against
# SolverParams._fields so the two cannot drift.
_WEIGHT_FIELDS = frozenset(
    {"w_tight", "w_pref", "w_reuse", "w_reserve", "w_spread"}
)

_SECTION_TYPES = {
    "leaderElection": ("leader_election", LeaderElectionConfig),
    "servers": ("servers", ServerConfig),
    "controllers": ("controllers", ControllerConfig),
    "log": ("log", LogConfig),
    "authorizer": ("authorizer", AuthorizerConfig),
    "topologyAwareScheduling": ("topology_aware_scheduling", TopologyAwareSchedulingConfig),
    "networkAcceleration": ("network_acceleration", NetworkAccelerationConfig),
    "scheduling": ("scheduling", SchedulingConfig),
    "solver": ("solver", SolverConfig),
    "defrag": ("defrag", DefragConfig),
    "rollout": ("rollout", RolloutConfig),
    "trace": ("trace", TraceConfig),
    "tuning": ("tuning", TuningConfig),
    "faults": ("faults", FaultsConfig),
    "resilience": ("resilience", ResilienceConfig),
    "cells": ("cells", CellsConfig),
    "tenancy": ("tenancy", TenancyConfig),
    "backend": ("backend", BackendConfig),
    "persistence": ("persistence", PersistenceConfig),
    "cluster": ("cluster", ClusterConfig),
}

_CAMEL_FIELDS = {
    # camelCase YAML key -> snake_case dataclass field, per section type
    "leaseFile": "lease_file",
    "leaseDurationSeconds": "lease_duration_seconds",
    "renewDeadlineSeconds": "renew_deadline_seconds",
    "retryPeriodSeconds": "retry_period_seconds",
    "bindAddress": "bind_address",
    "healthPort": "health_port",
    "advertiseUrl": "advertise_url",
    "metricsPort": "metrics_port",
    "profilingEnabled": "profiling_enabled",
    "tlsMode": "tls_mode",
    "tlsCertDir": "tls_cert_dir",
    "tlsCertFile": "tls_cert_file",
    "tlsKeyFile": "tls_key_file",
    "tlsCaFile": "tls_ca_file",
    "webhookPort": "webhook_port",
    "webhookSans": "webhook_sans",
    "concurrentSyncs": "concurrent_syncs",
    "reconcileIntervalSeconds": "reconcile_interval_seconds",
    "eventsBuffer": "events_buffer",
    "healEventDedupeSeconds": "heal_event_dedupe_seconds",
    "maxRecordsPerFile": "max_records_per_file",
    "maxFiles": "max_files",
    "gridK": "grid_k",
    "halvingRungs": "halving_rungs",
    "watchdogSeconds": "watchdog_seconds",
    "maxWaveRetries": "max_wave_retries",
    "breakerThreshold": "breaker_threshold",
    "breakerWindowSeconds": "breaker_window_seconds",
    "probationSeconds": "probation_seconds",
    "bindMaxAttempts": "bind_max_attempts",
    "backoffBaseSeconds": "backoff_base_seconds",
    "backoffCapSeconds": "backoff_cap_seconds",
    "stalePlanRevalidation": "stale_plan_revalidation",
    "agingHalfLifeSeconds": "aging_half_life_seconds",
    "agingMaxBoost": "aging_max_boost",
    "sites": "sites",
    "auditSeeds": "audit_seeds",
    "queueSize": "queue_size",
    "flushIntervalSeconds": "flush_interval_seconds",
    "exemptActors": "exempt_actors",
    "autoSliceEnabled": "auto_slice_enabled",
    "sliceResourceName": "slice_resource_name",
    "priorityClasses": "priority_classes",
    "queues": "queues",
    "maxGroups": "max_groups",
    "maxSets": "max_sets",
    "maxPods": "max_pods",
    "padGangsTo": "pad_gangs_to",
    "compilationCacheDir": "compilation_cache_dir",
    "prewarmTopK": "prewarm_top_k",
    "shapeHistoryPath": "shape_history_path",
    "portfolioEscalation": "portfolio_escalation",
    "intervalSeconds": "interval_seconds",
    "maxConcurrentMigrations": "max_concurrent_migrations",
    "gangCooldownSeconds": "gang_cooldown_seconds",
    "maxMovesPerPlan": "max_moves_per_plan",
    "minEfficiency": "min_efficiency",
    "maxWorkers": "max_workers",
    "snapshotIntervalSeconds": "snapshot_interval_seconds",
    "wTight": "w_tight",
    "wPref": "w_pref",
    "wReuse": "w_reuse",
    "wReserve": "w_reserve",
    "wSpread": "w_spread",
    "kubeconfig": "kubeconfig",
    "kubeContext": "kube_context",
    "kubeNamespace": "kube_namespace",
    "podLabelSelector": "pod_label_selector",
    "kubeQps": "kube_qps",
    "kubeBurst": "kube_burst",
    "watchWorkloads": "watch_workloads",
    "initcMode": "initc_mode",
    "kwokNodes": "kwok_nodes",
    "kwokCpuPerNode": "kwok_cpu_per_node",
    "kwokMemoryPerNode": "kwok_memory_per_node",
    "kwokTpuPerNode": "kwok_tpu_per_node",
    "kwokHostsPerRack": "kwok_hosts_per_rack",
    "kwokRacksPerBlock": "kwok_racks_per_block",
    "kwokLevelGroupFactors": "kwok_level_group_factors",
    "runningDelaySeconds": "running_delay_seconds",
    "readyDelaySeconds": "ready_delay_seconds",
    "eventLagSeconds": "event_lag_seconds",
    "surgeRacks": "surge_racks",
    "deadlineSeconds": "deadline_seconds",
    "shardBy": "shard_by",
    "topologyLevel": "topology_level",
    "journalRoot": "journal_root",
    "leaseDir": "lease_dir",
    "crashCheckEvery": "crash_check_every",
    "revocableNodes": "revocable_nodes",
    "revocableGraceSeconds": "revocable_grace_seconds",
    "revocableEvictionLeadSeconds": "revocable_eviction_lead_seconds",
}


def _build_section(cls, doc: dict, path: str, errors: list[str]):
    if doc is not None and not isinstance(doc, dict):
        errors.append(f"{path}: must be a mapping, got {type(doc).__name__}")
        return cls()
    kwargs = {}
    valid_fields = set(cls.__dataclass_fields__)
    for key, value in (doc or {}).items():
        fname = _CAMEL_FIELDS.get(key, key)
        if fname not in valid_fields:
            errors.append(f"{path}.{key}: unknown field")
            continue
        kwargs[fname] = value
    try:
        return cls(**kwargs)
    except TypeError as e:
        errors.append(f"{path}: {e}")
        return cls()


def parse_operator_config(doc: dict) -> tuple[OperatorConfiguration, list[str]]:
    """Dict -> config + field errors (unknown sections/fields are errors —
    a typo'd knob silently ignored is the worst failure mode of config)."""
    errors: list[str] = []
    cfg = OperatorConfiguration()
    for key, value in (doc or {}).items():
        entry = _SECTION_TYPES.get(key)
        if entry is None:
            errors.append(f"{key}: unknown section")
            continue
        attr, cls = entry
        setattr(cfg, attr, _build_section(cls, value, key, errors))
    errors.extend(validate_operator_config(cfg))
    return cfg, errors


def validate_operator_config(cfg: OperatorConfiguration) -> list[str]:
    """Semantic validation (api/config/validation analog)."""
    errors: list[str] = []
    if cfg.log.level not in ("debug", "info", "error"):
        errors.append(f"log.level: {cfg.log.level!r} not in debug|info|error")
    if cfg.log.format not in ("json", "text"):
        errors.append(f"log.format: {cfg.log.format!r} not in json|text")
    if cfg.controllers.concurrent_syncs < 1:
        errors.append("controllers.concurrentSyncs: must be >= 1")
    if cfg.controllers.reconcile_interval_seconds <= 0:
        errors.append("controllers.reconcileIntervalSeconds: must be > 0")
    le = cfg.leader_election
    if le.enabled:
        if le.renew_deadline_seconds >= le.lease_duration_seconds:
            errors.append(
                "leaderElection.renewDeadlineSeconds: must be < leaseDurationSeconds"
            )
        if le.retry_period_seconds <= 0:
            errors.append("leaderElection.retryPeriodSeconds: must be > 0")
        # The leader renews once per run-loop iteration, so the renewal gap is
        # at least the reconcile interval; a deadline below it would make
        # leadership flap every cycle (stand down -> re-acquire, forever).
        if cfg.controllers.reconcile_interval_seconds >= le.renew_deadline_seconds:
            errors.append(
                "leaderElection.renewDeadlineSeconds: must be > "
                "controllers.reconcileIntervalSeconds (renewal happens once "
                "per reconcile cycle)"
            )
    if not isinstance(cfg.scheduling.priority_classes, dict):
        errors.append(
            "scheduling.priorityClasses: must be a mapping of name -> integer"
        )
    else:
        for pc_name, pc_value in cfg.scheduling.priority_classes.items():
            if not isinstance(pc_value, int) or isinstance(pc_value, bool):
                errors.append(
                    f"scheduling.priorityClasses.{pc_name}: {pc_value!r} is not an integer"
                )
    if cfg.servers.tls_mode not in ("disabled", "auto", "manual"):
        errors.append(
            f"servers.tlsMode: {cfg.servers.tls_mode!r} not in disabled|auto|manual"
        )
    if cfg.servers.tls_mode == "manual" and not (
        cfg.servers.tls_cert_file and cfg.servers.tls_key_file
    ):
        errors.append("servers.tlsCertFile/tlsKeyFile: required for tlsMode manual")
    if cfg.servers.tls_ca_file:
        import os as _os

        if cfg.servers.tls_mode != "manual":
            errors.append("servers.tlsCaFile: only meaningful with tlsMode manual")
        elif not _os.path.isfile(cfg.servers.tls_ca_file):
            errors.append(
                f"servers.tlsCaFile: {cfg.servers.tls_ca_file!r} does not exist"
            )
    for port_name, port in (
        ("servers.healthPort", cfg.servers.health_port),
        ("servers.metricsPort", cfg.servers.metrics_port),
        ("servers.webhookPort", cfg.servers.webhook_port),
        ("backend.port", cfg.backend.port),
    ):
        if port < -1 or port > 65535:
            errors.append(f"{port_name}: {port} out of range")
    if not isinstance(cfg.servers.webhook_sans, list):
        # A bare YAML string would iterate char-by-char below AND turn the
        # deploy renderer's `dns in sans` membership test into a substring
        # match — two silent passes ending in cluster-wide TLS failure.
        errors.append("servers.webhookSans: must be a list of DNS names")
    else:
        for i, san in enumerate(cfg.servers.webhook_sans):
            if not isinstance(san, str) or not san:
                errors.append(f"servers.webhookSans[{i}]: must be a non-empty DNS name")
    tas = cfg.topology_aware_scheduling
    seen_domains: set[str] = set()
    for i, lvl in enumerate(tas.levels):
        if not isinstance(lvl, dict) or "domain" not in lvl or "nodeLabelKey" not in lvl:
            errors.append(
                f"topologyAwareScheduling.levels[{i}]: want {{domain, nodeLabelKey}}"
            )
            continue
        if lvl["domain"] in seen_domains:
            errors.append(
                f"topologyAwareScheduling.levels[{i}]: duplicate domain {lvl['domain']!r}"
            )
        seen_domains.add(lvl["domain"])
    if tas.levels:
        try:
            cfg.cluster_topology()
        except Exception as e:
            errors.append(f"topologyAwareScheduling.levels: {e}")
    if cfg.persistence.enabled and not cfg.persistence.path:
        errors.append("persistence.path: required when persistence is enabled")
    ce = cfg.cells
    if not isinstance(ce.count, int) or isinstance(ce.count, bool) or ce.count < 1:
        errors.append("cells.count: must be an int >= 1")
    if ce.shard_by not in ("queue", "topology"):
        errors.append(f"cells.shardBy: {ce.shard_by!r} not in queue|topology")
    if (
        not isinstance(ce.crash_check_every, int)
        or isinstance(ce.crash_check_every, bool)
        or ce.crash_check_every < 1
    ):
        errors.append("cells.crashCheckEvery: must be an int >= 1")
    if ce.enabled:
        if not ce.journal_root:
            errors.append("cells.journalRoot: required when cells are enabled")
        if not ce.lease_dir:
            errors.append("cells.leaseDir: required when cells are enabled")
        if ce.renew_deadline_seconds >= ce.lease_duration_seconds:
            errors.append(
                "cells.renewDeadlineSeconds: must be < leaseDurationSeconds"
            )
    import re as _re

    pcs_map = cfg.scheduling.priority_classes
    for pc_name in (pcs_map if isinstance(pcs_map, dict) else ()):
        # Rendered as cluster-scoped PriorityClass manifests (deploy.py):
        # the name must be a DNS-1123 subdomain or kubectl apply rejects
        # it (and a "/" would even break the --out file write).
        if not _re.fullmatch(r"[a-z0-9]([-a-z0-9.]*[a-z0-9])?", str(pc_name)):
            errors.append(
                f"scheduling.priorityClasses.{pc_name}: name must be a "
                "lowercase DNS-1123 subdomain"
            )
    if not isinstance(cfg.scheduling.queues, dict):
        errors.append("scheduling.queues: must be a mapping of name -> quotas")
    else:
        # Both queue shapes (legacy flat quotas and hierarchical
        # parentQueue/resources trees) validate through the one parser the
        # manager boots from — shape, quantities, weights, parent
        # existence, and cycles (orchestrator/queues.py).
        from grove_tpu.orchestrator.queues import parse_queue_config

        parse_queue_config(cfg.scheduling.queues, errors)
    pf = cfg.solver.portfolio
    if not isinstance(pf, int) or isinstance(pf, bool) or pf < 1:
        errors.append("solver.portfolio: must be an int >= 1")
    pe = cfg.solver.portfolio_escalation
    if not isinstance(pe, int) or isinstance(pe, bool) or pe < 1:
        errors.append("solver.portfolioEscalation: must be an int >= 1 (1 = off)")
    pw = cfg.solver.prewarm_top_k
    if not isinstance(pw, int) or isinstance(pw, bool) or pw < 0:
        errors.append("solver.prewarmTopK: must be an int >= 0 (0 = off)")
    if pw > 0 and not cfg.solver.shape_history_path:
        errors.append(
            "solver.shapeHistoryPath: required when prewarmTopK > 0 "
            "(the prewarm thread compiles from the recorded shape history)"
        )
    if not isinstance(cfg.solver.weights, dict):
        errors.append("solver.weights: must be a mapping of weight -> number")
    elif cfg.solver.weights:
        import math as _math

        seen_weights: dict[str, str] = {}
        for wk, wv in cfg.solver.weights.items():
            field_name = _CAMEL_FIELDS.get(wk, wk)
            if field_name not in _WEIGHT_FIELDS:
                errors.append(f"solver.weights.{wk}: unknown weight")
                continue
            if field_name in seen_weights:
                errors.append(
                    f"solver.weights.{wk}: duplicate of "
                    f"{seen_weights[field_name]!r} after case normalization"
                )
                continue
            seen_weights[field_name] = wk
            if not isinstance(wv, (int, float)) or isinstance(wv, bool) or not _math.isfinite(float(wv)):
                errors.append(f"solver.weights.{wk}: {wv!r} is not a finite number")
    pr = cfg.solver.pruning
    if not isinstance(pr, dict):
        errors.append("solver.pruning: must be a mapping")
    elif pr:
        _PRUNING_KEYS = {
            "enabled", "maxCandidates", "padLadder", "minPad", "minFleet",
        }
        for pk in pr:
            if pk not in _PRUNING_KEYS:
                errors.append(f"solver.pruning.{pk}: unknown field")
        if "enabled" in pr and not isinstance(pr["enabled"], bool):
            errors.append("solver.pruning.enabled: must be a boolean")
        for pk, lo in (("maxCandidates", 1), ("minPad", 2), ("minFleet", 0)):
            if pk in pr and (
                not isinstance(pr[pk], int)
                or isinstance(pr[pk], bool)
                or pr[pk] < lo
            ):
                errors.append(f"solver.pruning.{pk}: must be an int >= {lo}")
        ladder = pr.get("padLadder")
        if ladder is not None:
            if not isinstance(ladder, list) or any(
                not isinstance(v, int) or isinstance(v, bool) or v < 2
                for v in ladder
            ):
                errors.append(
                    "solver.pruning.padLadder: must be a list of ints >= 2"
                )
            elif any(b <= a for a, b in zip(ladder, ladder[1:])):
                errors.append(
                    "solver.pruning.padLadder: must be strictly increasing"
                )
    sm = cfg.solver.streaming
    if not isinstance(sm, dict):
        errors.append("solver.streaming: must be a mapping")
    elif sm:
        _STREAM_KEYS = {"depth", "waveSize", "maxWaitS", "pollS"}
        for sk in sm:
            if sk not in _STREAM_KEYS:
                errors.append(f"solver.streaming.{sk}: unknown field")
        for sk in ("depth", "waveSize"):
            if sk in sm and (
                not isinstance(sm[sk], int)
                or isinstance(sm[sk], bool)
                or sm[sk] < 1
            ):
                errors.append(f"solver.streaming.{sk}: must be an int >= 1")
        if "maxWaitS" in sm and (
            not isinstance(sm["maxWaitS"], (int, float))
            or isinstance(sm["maxWaitS"], bool)
            or sm["maxWaitS"] < 0
        ):
            errors.append("solver.streaming.maxWaitS: must be >= 0")
        if "pollS" in sm and (
            not isinstance(sm["pollS"], (int, float))
            or isinstance(sm["pollS"], bool)
            or sm["pollS"] <= 0
        ):
            errors.append("solver.streaming.pollS: must be > 0")
    sc = cfg.solver.scan
    if not isinstance(sc, dict):
        errors.append("solver.scan: must be a mapping")
    elif sc:
        _SCAN_KEYS = {
            "enabled",
            "maxScanLen",
            "minWavesPerClass",
            "affinityLookahead",
            "deviceResident",
        }
        for ck in sc:
            if ck not in _SCAN_KEYS:
                errors.append(f"solver.scan.{ck}: unknown field")
        for ck in ("enabled", "deviceResident"):
            if ck in sc and not isinstance(sc[ck], bool):
                errors.append(f"solver.scan.{ck}: must be a boolean")
        for ck in ("maxScanLen", "minWavesPerClass"):
            if ck in sc and (
                not isinstance(sc[ck], int)
                or isinstance(sc[ck], bool)
                or sc[ck] < 1
            ):
                errors.append(f"solver.scan.{ck}: must be an int >= 1")
        if "affinityLookahead" in sc and (
            not isinstance(sc["affinityLookahead"], int)
            or isinstance(sc["affinityLookahead"], bool)
            or sc["affinityLookahead"] < 0
        ):
            errors.append("solver.scan.affinityLookahead: must be an int >= 0")
    mh = cfg.solver.mesh
    if not isinstance(mh, dict):
        errors.append("solver.mesh: must be a mapping")
    elif mh:
        _MESH_KEYS = {"enabled", "minNodes", "maxDevices"}
        for mk in mh:
            if mk not in _MESH_KEYS:
                errors.append(f"solver.mesh.{mk}: unknown field")
        if "enabled" in mh and not isinstance(mh["enabled"], bool):
            errors.append("solver.mesh.enabled: must be a boolean")
        for mk in ("minNodes", "maxDevices"):
            if mk in mh and (
                not isinstance(mh[mk], int)
                or isinstance(mh[mk], bool)
                or mh[mk] < 0
            ):
                errors.append(f"solver.mesh.{mk}: must be an int >= 0")
    df = cfg.defrag
    if not isinstance(df.threshold, (int, float)) or isinstance(
        df.threshold, bool
    ) or not 0.0 <= float(df.threshold) <= 1.0:
        errors.append("defrag.threshold: must be a number in [0, 1]")
    if not isinstance(df.interval_seconds, (int, float)) or isinstance(
        df.interval_seconds, bool
    ) or df.interval_seconds <= 0:
        errors.append("defrag.intervalSeconds: must be > 0")
    mc = df.max_concurrent_migrations
    if not isinstance(mc, int) or isinstance(mc, bool) or mc < 1:
        errors.append("defrag.maxConcurrentMigrations: must be an int >= 1")
    if not isinstance(df.gang_cooldown_seconds, (int, float)) or isinstance(
        df.gang_cooldown_seconds, bool
    ) or df.gang_cooldown_seconds < 0:
        errors.append("defrag.gangCooldownSeconds: must be >= 0")
    mm = df.max_moves_per_plan
    if not isinstance(mm, int) or isinstance(mm, bool) or mm < 1:
        errors.append("defrag.maxMovesPerPlan: must be an int >= 1")
    if not isinstance(df.min_efficiency, (int, float)) or isinstance(
        df.min_efficiency, bool
    ) or df.min_efficiency < 0:
        errors.append("defrag.minEfficiency: must be >= 0")
    ro = cfg.rollout
    if not isinstance(ro.surge_racks, int) or isinstance(
        ro.surge_racks, bool
    ) or ro.surge_racks < 0:
        errors.append("rollout.surgeRacks: must be an int >= 0")
    for ro_name, ro_val in (
        ("rollout.backoffBaseSeconds", ro.backoff_base_seconds),
        ("rollout.backoffCapSeconds", ro.backoff_cap_seconds),
        ("rollout.deadlineSeconds", ro.deadline_seconds),
    ):
        if not isinstance(ro_val, (int, float)) or isinstance(
            ro_val, bool
        ) or ro_val <= 0:
            errors.append(f"{ro_name}: must be a number > 0")
    if (
        isinstance(ro.backoff_base_seconds, (int, float))
        and isinstance(ro.backoff_cap_seconds, (int, float))
        and not isinstance(ro.backoff_base_seconds, bool)
        and not isinstance(ro.backoff_cap_seconds, bool)
        and ro.backoff_cap_seconds < ro.backoff_base_seconds
    ):
        errors.append(
            "rollout.backoffCapSeconds: must be >= rollout.backoffBaseSeconds"
        )
    tn = cfg.tenancy
    if not isinstance(tn.aging_half_life_seconds, (int, float)) or isinstance(
        tn.aging_half_life_seconds, bool
    ) or tn.aging_half_life_seconds <= 0:
        errors.append("tenancy.agingHalfLifeSeconds: must be > 0")
    if not isinstance(tn.aging_max_boost, int) or isinstance(
        tn.aging_max_boost, bool
    ) or tn.aging_max_boost < 0:
        errors.append("tenancy.agingMaxBoost: must be an int >= 0")
    tr = cfg.trace
    if tr.enabled and not tr.path:
        errors.append("trace.path: required when trace is enabled")
    for tname, tval in (
        ("trace.maxRecordsPerFile", tr.max_records_per_file),
        ("trace.maxFiles", tr.max_files),
        ("trace.queueSize", tr.queue_size),
    ):
        if not isinstance(tval, int) or isinstance(tval, bool) or tval < 1:
            errors.append(f"{tname}: must be an int >= 1")
    if not isinstance(tr.flush_interval_seconds, (int, float)) or isinstance(
        tr.flush_interval_seconds, bool
    ) or tr.flush_interval_seconds <= 0:
        errors.append("trace.flushIntervalSeconds: must be > 0")
    tu = cfg.tuning
    for tu_name, tu_val in (
        ("tuning.gridK", tu.grid_k),
        ("tuning.halvingRungs", tu.halving_rungs),
    ):
        if not isinstance(tu_val, int) or isinstance(tu_val, bool) or tu_val < 1:
            errors.append(f"{tu_name}: must be an int >= 1")
    import math as _tmath

    if not isinstance(tu.spread, (int, float)) or isinstance(
        tu.spread, bool
    ) or not _tmath.isfinite(float(tu.spread)) or tu.spread <= 0:
        errors.append("tuning.spread: must be a finite number > 0")
    if not isinstance(tu.seed, int) or isinstance(tu.seed, bool) or tu.seed < 0:
        errors.append("tuning.seed: must be an int >= 0")
    if not isinstance(tu.audit_seeds, list) or any(
        not isinstance(s, int) or isinstance(s, bool) for s in tu.audit_seeds
    ):
        errors.append("tuning.auditSeeds: must be a list of ints")
    fa = cfg.faults
    if not isinstance(fa.seed, int) or isinstance(fa.seed, bool) or fa.seed < 0:
        errors.append("faults.seed: must be an int >= 0")
    if not isinstance(fa.sites, dict):
        errors.append("faults.sites: must be a mapping of site -> schedule")
    else:
        # Site names and schedule shapes validate through the injector's
        # own parser — the chaos rig and the config cannot drift.
        from grove_tpu.faults import SITES, parse_spec_entry

        for site, doc in fa.sites.items():
            if site not in SITES:
                errors.append(
                    f"faults.sites.{site}: unknown site; one of "
                    + "|".join(SITES)
                )
                continue
            try:
                parse_spec_entry(site, doc)
            except ValueError as e:
                errors.append(f"faults.sites.{e}")
    rs = cfg.resilience
    for rname, rval, lo in (
        ("resilience.watchdogSeconds", rs.watchdog_seconds, 0.0),
        ("resilience.breakerWindowSeconds", rs.breaker_window_seconds, 0.0),
        ("resilience.probationSeconds", rs.probation_seconds, 0.0),
        ("resilience.backoffBaseSeconds", rs.backoff_base_seconds, 0.0),
    ):
        if not isinstance(rval, (int, float)) or isinstance(rval, bool) or rval <= lo:
            errors.append(f"{rname}: must be a number > {lo:g}")
    for rname, rval, lo in (
        ("resilience.maxWaveRetries", rs.max_wave_retries, 0),
        ("resilience.breakerThreshold", rs.breaker_threshold, 1),
        ("resilience.bindMaxAttempts", rs.bind_max_attempts, 1),
    ):
        if not isinstance(rval, int) or isinstance(rval, bool) or rval < lo:
            errors.append(f"{rname}: must be an int >= {lo}")
    if not isinstance(rs.backoff_cap_seconds, (int, float)) or isinstance(
        rs.backoff_cap_seconds, bool
    ) or (
        isinstance(rs.backoff_base_seconds, (int, float))
        and not isinstance(rs.backoff_base_seconds, bool)
        and rs.backoff_cap_seconds < rs.backoff_base_seconds
    ):
        errors.append(
            "resilience.backoffCapSeconds: must be a number >= "
            "backoffBaseSeconds"
        )
    eb = cfg.controllers.events_buffer
    if not isinstance(eb, int) or isinstance(eb, bool) or eb < 1:
        errors.append("controllers.eventsBuffer: must be an int >= 1")
    hd = cfg.controllers.heal_event_dedupe_seconds
    if not isinstance(hd, (int, float)) or isinstance(hd, bool) or hd < 0:
        errors.append(
            "controllers.healEventDedupeSeconds: must be >= 0 (0 = off)"
        )
    cl = cfg.cluster
    if cl.initc_mode not in ("operator", "kubernetes"):
        errors.append(
            f"cluster.initcMode: {cl.initc_mode!r} not in operator|kubernetes"
        )
    if cl.initc_mode == "kubernetes" and cl.source != "kubernetes":
        errors.append(
            "cluster.initcMode: kubernetes requires cluster.source: kubernetes "
            "(the agent lists gang pods at the apiserver)"
        )
    if cl.source not in ("none", "kwok", "kubernetes"):
        errors.append(
            f"cluster.source: {cl.source!r} not in none|kwok|kubernetes"
        )
    if not isinstance(cl.kube_qps, (int, float)) or isinstance(
        cl.kube_qps, bool
    ) or cl.kube_qps < 0:
        errors.append("cluster.kubeQps: must be a number >= 0 (0 = unlimited)")
    if not isinstance(cl.kube_burst, int) or isinstance(
        cl.kube_burst, bool
    ) or cl.kube_burst < 0:
        errors.append("cluster.kubeBurst: must be an int >= 0")
    elif (
        isinstance(cl.kube_qps, (int, float))
        and not isinstance(cl.kube_qps, bool)
        and cl.kube_qps > 0
        and cl.kube_burst < 1
    ):
        errors.append(
            "cluster.kubeBurst: must be >= 1 when kubeQps > 0 (a zero-token "
            "bucket would block every request forever)"
        )
    if cl.source == "kubernetes" and cl.kubeconfig:
        import os as _os

        if not _os.path.exists(cl.kubeconfig):
            errors.append(f"cluster.kubeconfig: {cl.kubeconfig!r} does not exist")
    if cl.source == "kwok":
        if cl.kwok_nodes < 1:
            errors.append("cluster.kwokNodes: must be >= 1")
        if cl.kwok_hosts_per_rack < 1 or cl.kwok_racks_per_block < 1:
            errors.append(
                "cluster.kwokHostsPerRack/kwokRacksPerBlock: must be >= 1"
            )
        if cl.running_delay_seconds < 0 or cl.ready_delay_seconds < 0:
            errors.append(
                "cluster.runningDelaySeconds/readyDelaySeconds: must be >= 0"
            )
        if cl.event_lag_seconds < 0:
            errors.append("cluster.eventLagSeconds: must be >= 0")
        if not isinstance(cl.revocable_nodes, int) or isinstance(
            cl.revocable_nodes, bool
        ) or cl.revocable_nodes < 0 or (
            isinstance(cl.kwok_nodes, int) and cl.revocable_nodes > cl.kwok_nodes
        ):
            errors.append(
                "cluster.revocableNodes: must be an int in [0, kwokNodes]"
            )
        if not isinstance(cl.revocable_grace_seconds, (int, float)) or isinstance(
            cl.revocable_grace_seconds, bool
        ) or cl.revocable_grace_seconds <= 0:
            errors.append("cluster.revocableGraceSeconds: must be > 0")
        if not isinstance(
            cl.revocable_eviction_lead_seconds, (int, float)
        ) or isinstance(
            cl.revocable_eviction_lead_seconds, bool
        ) or cl.revocable_eviction_lead_seconds < 0:
            errors.append("cluster.revocableEvictionLeadSeconds: must be >= 0")
        if (
            cl.kwok_cpu_per_node < 0
            or cl.kwok_memory_per_node < 0
            or cl.kwok_tpu_per_node < 0
        ):
            errors.append(
                "cluster.kwokCpuPerNode/kwokMemoryPerNode/kwokTpuPerNode: "
                "must be >= 0"
            )
        factors = cl.kwok_level_group_factors
        if not isinstance(factors, list) or any(
            not isinstance(fct, int) or isinstance(fct, bool) or fct < 1
            for fct in factors
        ):
            errors.append(
                "cluster.kwokLevelGroupFactors: must be a list of ints >= 1"
            )
        else:
            from grove_tpu.api.types import TopologyDomain

            try:
                non_host = [
                    lvl
                    for lvl in cfg.cluster_topology().sorted_levels()
                    if lvl.domain != TopologyDomain.HOST
                ]
            except Exception:
                non_host = []  # reported above via topologyAwareScheduling
            # The default rack/block/zone shape keeps its implicit factors
            # (zone groups 4 blocks, the e2e rig's shape); anything DEEPER
            # must spell out every factor beyond block — a 5-level hierarchy
            # silently shaped by a hardcoded 4 is a fleet nobody asked for.
            extra = len(non_host) - 2
            if len(non_host) > 3 and extra > len(factors):
                errors.append(
                    f"cluster.kwokLevelGroupFactors: topology declares {extra} "
                    "level(s) beyond rack/block; list a group factor for each "
                    "(narrowest first) — hierarchies deeper than zone get no "
                    "implicit shape"
                )
    return errors


def load_operator_config(path: str) -> OperatorConfiguration:
    """YAML file -> validated config; raises ValueError listing every problem
    (LoadAndValidateOperatorConfig boot contract, cmd/cli/cli.go)."""
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: config root must be a mapping")
    cfg, errors = parse_operator_config(doc)
    if errors:
        raise ValueError(f"{path}: invalid operator config:\n  " + "\n  ".join(errors))
    return cfg
