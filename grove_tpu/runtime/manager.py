"""The Manager: one config file boots the whole control plane.

Mirror of `operator/internal/controller/manager.go:53-121` +
`operator/cmd/main.go:46-128`: from a validated OperatorConfiguration it
wires the logger, the metrics registry + HTTP exposition, health/readiness
probes, leader election, the store + reconcile loop (with flow.go requeue
semantics), optional control-plane persistence, and optionally hosts the
scheduler-backend gRPC sidecar in-process.

The store is fed by the simulator, the watch driver
(grove_tpu/cluster/watch.py — KWOK fake or a live kube-apiserver), or
backend RPCs. Admission runs in-process at every apply path AND as inbound
AdmissionReview webhooks on a dedicated HTTPS port (servers.webhookPort;
api/webhook.py) whose caBundle the manager patches into the rendered
webhook configurations at boot — the cert-controller rotator analog.
"""

from __future__ import annotations

import math
import http.server
import json
import threading
import time
from typing import Optional

from grove_tpu.api import constants
from grove_tpu.api.admission import AdmissionChain, Authorizer
from grove_tpu.api.types import PodCliqueSet
from grove_tpu.orchestrator.controller import GroveController
from grove_tpu.orchestrator.queues import parse_queue_config
from grove_tpu.orchestrator.store import Cluster
from grove_tpu.runtime.config import OperatorConfiguration
from grove_tpu.runtime.flow import (
    FlowOutcome,
    ReconcileStepResult,
    continue_reconcile,
    run_reconcile_flow,
)
from grove_tpu.utils.errors import GroveError
from grove_tpu.runtime.lease import FileLease
from grove_tpu.utils.logging import Logger, new_logger
from grove_tpu.utils.metrics import Registry


class _ProbeHandler(http.server.BaseHTTPRequestHandler):
    manager: "Manager"  # set per server instance

    def do_GET(self):  # noqa: N802 (stdlib API)
        if self.path == "/healthz":
            self._respond(200, "ok")
        elif self.path == "/readyz":
            ready = self.manager.ready
            self._respond(200 if ready else 503, "ok" if ready else "not ready")
        elif self.path == "/metrics":
            self._respond(200, self.manager.metrics.render_text())
        elif self.path == "/statusz":
            self._respond(200, json.dumps(self.manager.statusz()), "application/json")
        elif self.path.startswith("/api/v1/podcliques/"):
            # apiserver analog the grove-initc agent polls (initc/agent.py).
            # Readiness definition is store_fetch — the simulator's agent gate
            # and this endpoint must never diverge. This handler runs on an
            # HTTP thread while the reconcile thread mutates the pod dict;
            # retry the (GIL-atomic-per-step, but not per-iteration) scan on
            # the rare mid-iteration resize.
            from grove_tpu.initc.agent import store_fetch

            fqn = self.path[len("/api/v1/podcliques/"):]
            clique = self.manager.cluster.podcliques.get(fqn)
            # Auth first: an unauthenticated caller must not learn which
            # clique FQNs exist (404 only after a valid credential).
            if not self._authorized(clique):
                self._respond(401, "unauthorized")
            elif clique is None:
                self._respond(404, "not found")
            else:
                fetch = store_fetch(self.manager.cluster)
                for _ in range(8):
                    try:
                        ready, _exists = fetch(fqn)
                        break
                    except RuntimeError:  # dict changed size during iteration
                        continue
                else:
                    self._respond(503, "store busy")
                    return
                self._respond(
                    200,
                    json.dumps(
                        {
                            "name": fqn,
                            "minAvailable": clique.min_available,
                            "ready": ready,
                        }
                    ),
                    "application/json",
                )
        elif self.path == "/profilez":
            # pprof analog (manager.go:42-44,114-119): reconcile-step timing
            # breakdown; only served when servers.profilingEnabled.
            if self.manager.config.servers.profiling_enabled:
                self._respond(200, json.dumps(self.manager.profilez()), "application/json")
            else:
                self._respond(404, "profiling disabled")
        elif self.path.startswith("/api/v1/"):
            # Same credential gate as the initc endpoint: with the authorizer
            # on, the WHOLE object API requires a valid workload token (the
            # apiserver-authn analog) — otherwise pod names would leak the
            # clique FQNs the 401-before-404 design protects.
            if not self._authorized(None):
                self._respond(401, "unauthorized")
            else:
                self._api_get(self.path[len("/api/v1/"):])
        else:
            self._respond(404, "not found")

    # ---- object API (typed-client surface; generated-clientset analog) ----------

    _COLLECTIONS = {
        "podcliquesets": "podcliquesets",
        # Cliques/PCSGs are LIST-only here (by-name GET on
        # /api/v1/podcliques/<fqn> is the initc readiness endpoint, matched
        # earlier in do_GET; by-name PCSG is blocked for symmetry). With the
        # authorizer on, these listings are scoped to the presented token's
        # OWNING PCS — the per-PCS RBAC discipline of the readiness
        # endpoint. (Pod listings stay namespace-wide for any valid token:
        # the reference's workload SA Role can list all pods too,
        # initc/internal/wait.go informers.)
        "podcliques": "podcliques",
        "podcliquescalinggroups": "scaling_groups",
        "podgangs": "podgangs",
        "pods": "pods",
        "nodes": "nodes",
        "services": "services",
        "hpas": "hpas",
        "events": None,  # special-cased
    }

    def _api_get(self, rest: str) -> None:
        from grove_tpu.utils import serde

        rest, _, query = rest.partition("?")
        parts = [p for p in rest.split("/") if p]
        if not parts or parts[0] not in self._COLLECTIONS:
            self._respond(404, "not found")
            return
        kind = parts[0]
        c = self.manager.cluster
        if kind == "events":
            self._respond(
                200,
                json.dumps([list(e) for e in c.recent_events(constants.EVENTS_BUFFER)]),
                "application/json",
            )
            return
        coll = getattr(c, self._COLLECTIONS[kind])
        scoped = kind in ("podcliques", "podcliquescalinggroups")
        if scoped and len(parts) > 1:
            self._respond(404, "not found")  # LIST-only collections
            return
        if scoped and self.manager.config.authorizer.enabled:
            owner = self._token_pcs()
            coll = {
                name: obj
                for name, obj in coll.items()
                if getattr(obj, "pcs_name", None) == owner
            }
        if len(parts) == 1:
            if query == "full=1":
                # Bulk listing: one response with every object, so table
                # clients (the CLI) don't do N+1 round trips at scale. Same
                # mid-iteration-resize retry as the initc endpoint above —
                # this thread races the reconcile thread's dict mutations.
                for _ in range(8):
                    try:
                        doc = {
                            name: serde.encode(obj)
                            for name, obj in sorted(coll.items())
                        }
                        break
                    except RuntimeError:
                        continue
                else:
                    self._respond(503, "store busy")
                    return
                self._respond(200, json.dumps(doc), "application/json")
                return
            self._respond(200, json.dumps(sorted(coll)), "application/json")
            return
        obj = coll.get("/".join(parts[1:]))
        if obj is None:
            self._respond(404, "not found")
            return
        self._respond(200, json.dumps(serde.encode(obj)), "application/json")

    def do_POST(self):  # noqa: N802 (stdlib API)
        """Apply a PodCliqueSet through the admission chain (kubectl-apply
        analog). Body: YAML or JSON PCS document. Also accepts HPA metrics
        pushes on /api/v1/metrics (the metrics-server feed)."""
        if self.path == "/api/v1/metrics":
            if not self._authorized(None):
                self._respond(401, "unauthorized")
                return
            length = int(self.headers.get("Content-Length", "0"))
            try:
                doc = json.loads(self.rfile.read(length).decode())
                if not isinstance(doc, dict):
                    raise ValueError("metrics body must be a JSON object")
                update = {str(k): float(v) for k, v in doc.items()}
                # json.loads admits the NaN/Infinity literals; a non-finite
                # ratio would make autoscale's ceil() raise on every tick.
                bad = [k for k, v in update.items() if not math.isfinite(v)]
                if bad:
                    raise ValueError(f"non-finite utilization for {bad}")
            except (ValueError, TypeError) as e:
                self._respond(400, json.dumps({"errors": [str(e)]}), "application/json")
                return
            self.manager.hpa_metrics.update(update)
            self._respond(200, json.dumps({"targets": len(update)}), "application/json")
            return
        if self.path == "/api/v1/scale":
            # kubectl-scale analog: {"target": <pclq|pcsg FQN>, "replicas": N}
            # writes the scale subresource (same path the HPA drives).
            if not self._authorized(None):
                self._respond(401, "unauthorized")
                return
            length = int(self.headers.get("Content-Length", "0"))
            actor = self.headers.get("X-Grove-Actor", "user")
            try:
                doc = json.loads(self.rfile.read(length).decode())
                if not isinstance(doc, dict) or "target" not in doc or "replicas" not in doc:
                    raise ValueError('body must be {"target": ..., "replicas": N}')
                target = str(doc["target"])
                replicas = doc["replicas"]
                if not isinstance(replicas, int) or isinstance(replicas, bool):
                    raise ValueError("replicas must be an integer")
                previous = self.manager.scale_target(target, replicas, actor=actor)
            except KeyError as e:
                self._respond(
                    404, json.dumps({"errors": [f"unknown scale target {e}"]}),
                    "application/json",
                )
                return
            except (ValueError, TypeError) as e:
                self._respond(400, json.dumps({"errors": [str(e)]}), "application/json")
                return
            self._respond(
                200,
                json.dumps({"target": target, "replicas": replicas, "previous": previous}),
                "application/json",
            )
            return
        if self.path != "/api/v1/podcliquesets":
            self._respond(404, "not found")
            return
        if not self._authorized(None):
            self._respond(401, "unauthorized")
            return
        import yaml as _yaml

        from grove_tpu.api.admission import AdmissionError
        from grove_tpu.api.types import PodCliqueSet

        length = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(length).decode()
        actor = self.headers.get("X-Grove-Actor", "user")
        try:
            doc = _yaml.safe_load(body)
            pcs = self.manager.apply_podcliqueset(
                PodCliqueSet.from_dict(doc), actor=actor
            )
        except AdmissionError as e:
            self._respond(
                422,
                json.dumps({"errors": [str(x) for x in e.errors]}),
                "application/json",
            )
            return
        except Exception as e:  # malformed body is a client error, not a crash
            self._respond(400, json.dumps({"errors": [str(e)]}), "application/json")
            return
        self._respond(200, json.dumps({"name": pcs.metadata.name}), "application/json")

    def do_DELETE(self):  # noqa: N802 (stdlib API)
        prefix = "/api/v1/podcliquesets/"
        if not self.path.startswith(prefix):
            self._respond(404, "not found")
            return
        if not self._authorized(None):
            self._respond(401, "unauthorized")
            return
        name = self.path[len(prefix):]
        actor = self.headers.get("X-Grove-Actor", "user")
        if name not in self.manager.cluster.podcliquesets:
            self._respond(404, "not found")
            return
        self.manager.delete_podcliqueset(name, actor=actor)
        self._respond(200, json.dumps({"deleted": name}), "application/json")

    def _token_pcs(self):
        """The PCS whose initc token secret matches the presented bearer
        credential, or None — the per-PCS scope for clique/PCSG listings."""
        import hmac

        from grove_tpu.api import naming

        auth = self.headers.get("Authorization", "")
        for pcs_name in list(self.manager.cluster.podcliquesets):
            secret = self.manager.cluster.secrets.get(
                naming.initc_sa_token_secret_name(pcs_name)
            )
            if secret is not None and hmac.compare_digest(
                auth, f"Bearer {secret.token}"
            ):
                return pcs_name
        return None

    def _authorized(self, clique) -> bool:
        """SA-token check (satokensecret component made real): when the
        authorizer is on, the initc credential for the OWNING PCS must be
        presented as a bearer token — the RBAC scope is per-PCS, so one
        workload's token cannot read another's cliques. Unknown cliques
        require SOME valid token (any PCS's) so existence isn't probeable
        without a credential."""
        if not self.manager.config.authorizer.enabled:
            return True
        import hmac

        from grove_tpu.api import naming

        auth = self.headers.get("Authorization", "")
        if clique is None:
            return any(
                hmac.compare_digest(auth, f"Bearer {s.token}")
                for s in self.manager.cluster.secrets.values()
            )
        secret = self.manager.cluster.secrets.get(
            naming.initc_sa_token_secret_name(clique.pcs_name)
        )
        if secret is None:
            return False
        return hmac.compare_digest(auth, f"Bearer {secret.token}")

    def _respond(self, code: int, body: str, ctype: str = "text/plain"):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):  # quiet
        pass


def _serve_admission_review(handler: "_ProbeHandler") -> None:
    """Inbound AdmissionReview v1 endpoints — the apiserver-facing webhook
    surface (webhook/register.go:34-62 analog). Served ONLY on the dedicated
    webhook port: these are called BY the apiserver, which authenticates the
    operator via the serving cert, not a bearer token — putting them on the
    (possibly plaintext, token-guarded) API port would expose an
    unauthenticated admission oracle to every workload pod."""
    from grove_tpu.api.webhook import handle_authorize, handle_mutate, handle_validate

    length = int(handler.headers.get("Content-Length", "0"))
    try:
        review = json.loads(handler.rfile.read(length).decode())
        if not isinstance(review, dict):
            raise ValueError("AdmissionReview body must be a JSON object")
    except (ValueError, TypeError) as e:
        handler._respond(400, json.dumps({"errors": [str(e)]}), "application/json")
        return
    if handler.path.endswith("authorize"):
        out = handle_authorize(
            review,
            handler.manager.admission,
            handler.manager.operator_users(),
            # Parent-PCS resolution for the disable-protection annotation
            # bypass (handler.go:89-93) — the store is the PCS cache here.
            pcs_lookup=handler.manager.cluster.podcliquesets.get,
        )
    elif handler.path.endswith("default"):
        out = handle_mutate(review, handler.manager.admission)
    else:
        out = handle_validate(review, handler.manager.admission)
    handler._respond(200, json.dumps(out), "application/json")


class _WebhookHandler(_ProbeHandler):
    """The dedicated webhook server's handler: AdmissionReview POSTs plus a
    bare /healthz — nothing else from the API surface leaks onto the
    apiserver-facing port (the reference's webhook server is likewise
    separate from metrics/health, manager.go:90-121)."""

    def do_GET(self):  # noqa: N802
        if self.path == "/healthz":
            self._respond(200, "ok")
        else:
            self._respond(404, "not found")

    def do_POST(self):  # noqa: N802
        if self.path in (
            "/webhook/v1/default",
            "/webhook/v1/validate",
            "/webhook/v1/authorize",
        ):
            _serve_admission_review(self)
        else:
            self._respond(404, "not found")

    def do_DELETE(self):  # noqa: N802
        self._respond(404, "not found")


def _require_self_signed(cert_file: str) -> None:
    """Raise CertError when a manual webhook cert is CA-issued but no
    tlsCaFile was given (issuer != subject means the leaf cannot serve as
    its own trust root in caBundle). openssl-unavailable => skip the check
    (same best-effort posture as cert generation)."""
    import subprocess

    from grove_tpu.runtime.certs import CertError

    try:
        out = subprocess.run(
            ["openssl", "x509", "-noout", "-issuer", "-subject", "-in", cert_file],
            capture_output=True,
            text=True,
        )
    except OSError:
        return
    if out.returncode != 0:
        return
    fields = dict(
        line.split("=", 1) for line in out.stdout.splitlines() if "=" in line
    )
    issuer = fields.get("issuer", "").strip()
    subject = fields.get("subject", "").strip()
    if issuer and subject and issuer != subject:
        raise CertError(
            "servers.tlsCertFile is CA-issued (issuer != subject) but "
            "servers.tlsCaFile is unset: the webhook caBundle patch would "
            "install an unverifiable leaf as trust root; set tlsCaFile to "
            "the issuing CA bundle"
        )


class _EventDeduper:
    """One control-plane event per (object, reason) per window.

    The heal paths (`_apply_child_scale_event`, `_apply_workload_event`)
    used to rely on last-value/last-spec guards alone: an external writer
    FLAPPING between two distinct bad values defeated those and re-evented
    on every relist echo. The window dedupe closes that: however the bad
    value churns, one (object, reason) pair emits at most once per window —
    the event ring records the episode, not the flap frequency."""

    def __init__(self, window_seconds: float) -> None:
        self.window_seconds = float(window_seconds)
        self.suppressed = 0
        self._last: dict[tuple[str, str], float] = {}

    def should_emit(self, now: float, obj: str, reason: str) -> bool:
        if self.window_seconds <= 0:
            return True
        key = (obj, reason)
        last = self._last.get(key)
        # A clock that moved BACKWARD past the window (virtual-time tests,
        # wall-clock step) re-arms rather than suppressing forever.
        if last is not None and 0 <= now - last < self.window_seconds:
            self.suppressed += 1
            return False
        self._last[key] = now
        if len(self._last) > 4096:  # bound the memo on pathological churn
            cutoff = now - self.window_seconds
            self._last = {k: t for k, t in self._last.items() if t >= cutoff}
        return True

    def reset(self, obj: str, reason: str) -> None:
        """End the episode early: the heal landed (echo confirmed / apply
        succeeded), so the NEXT bad write is a new episode and must event
        even inside the window."""
        self._last.pop((obj, reason), None)


class Manager:
    """Boots and runs the control plane from one OperatorConfiguration."""

    def __init__(
        self,
        config: OperatorConfiguration,
        cluster: Optional[Cluster] = None,
        log: Optional[Logger] = None,
    ):
        self.config = config
        self.log = log or new_logger(config.log.level, config.log.format)
        self.metrics = Registry()
        self.cluster = cluster or Cluster()
        self.topology = config.cluster_topology()
        self.controller = GroveController(
            cluster=self.cluster,
            topology=self.topology,
            solver_params=config.solver.solver_params(),
            priority_classes=dict(config.scheduling.priority_classes),
            queues=parse_queue_config(config.scheduling.queues) or {},
            tas_enabled=config.topology_aware_scheduling.enabled,
            max_groups=config.solver.max_groups,
            max_sets=config.solver.max_sets,
            max_pods=config.solver.max_pods,
            pad_gangs_to=config.solver.pad_gangs_to,
            portfolio=config.solver.portfolio,
            portfolio_escalation=config.solver.portfolio_escalation,
            pruning=config.solver.pruning_config(),
            mesh_cfg=config.solver.mesh_config(),
            auto_slice_enabled=config.network_acceleration.auto_slice_enabled,
            slice_resource_name=config.network_acceleration.slice_resource_name,
            initc_server_url=config.servers.advertise_url,
            initc_mode=config.cluster.initc_mode,
            defrag_enabled=config.defrag.enabled,
            defrag_threshold=config.defrag.threshold,
            defrag_interval_seconds=config.defrag.interval_seconds,
            defrag_max_concurrent=config.defrag.max_concurrent_migrations,
            defrag_cooldown_seconds=config.defrag.gang_cooldown_seconds,
            defrag_max_moves=config.defrag.max_moves_per_plan,
            defrag_min_efficiency=config.defrag.min_efficiency,
            rollout_enabled=config.rollout.enabled,
            rollout_surge_racks=config.rollout.surge_racks,
            rollout_backoff_base_seconds=config.rollout.backoff_base_seconds,
            rollout_backoff_cap_seconds=config.rollout.backoff_cap_seconds,
            rollout_deadline_seconds=config.rollout.deadline_seconds,
            revocation_eviction_lead_seconds=(
                config.cluster.revocable_eviction_lead_seconds
            ),
            tenancy_enabled=config.tenancy.enabled,
            tenancy_aging_half_life_seconds=config.tenancy.aging_half_life_seconds,
            tenancy_aging_max_boost=config.tenancy.aging_max_boost,
        )
        # Bounded event ring (controllers.eventsBuffer): long soaks must not
        # leak; overflow drops oldest + counts (grove_events_dropped_total).
        self.cluster.set_events_maxlen(config.controllers.events_buffer)
        # Heal-event window dedupe (controllers.healEventDedupeSeconds): one
        # event per (object, reason) episode, whatever the relist cadence.
        self._heal_dedupe = _EventDeduper(
            config.controllers.heal_event_dedupe_seconds
        )
        # Decision flight recorder (config section `trace`): journals solve
        # waves + disruptive actions for deterministic replay and what-if
        # counterfactuals (grove_tpu/trace; docs/design.md).
        self.trace_recorder = None
        if config.trace.enabled:
            from grove_tpu.trace.recorder import TraceRecorder

            self.trace_recorder = TraceRecorder(
                config.trace.path,
                max_records_per_file=config.trace.max_records_per_file,
                max_files=config.trace.max_files,
                queue_size=config.trace.queue_size,
                flush_interval_seconds=config.trace.flush_interval_seconds,
            )
            self.controller.recorder = self.trace_recorder
        # Deterministic fault injection (config section `faults`, env
        # override GROVE_FAULTS): installed process-wide at start() so the
        # named sites across the stack see it; every fire is journaled as a
        # flight-recorder action record and counted.
        from grove_tpu import faults as faults_mod

        self.fault_injector = faults_mod.from_config(
            config.faults, recorder=self.trace_recorder
        )
        # Graceful-degradation ladder (config section `resilience`): shared
        # control-plane state — the per-tick solves, the bind commit path,
        # and any stream/drain driver handed controller.resilience all see
        # the same breaker states. Transitions journal + log (never silent).
        self.resilience_ladder = None
        if config.resilience.enabled:
            from grove_tpu.solver.resilience import DegradationLadder

            def _ladder_event(event: str, subsystem: str) -> None:
                self.log.info(
                    f"degradation ladder {event}", subsystem=subsystem
                )
                if self.trace_recorder is not None:
                    try:
                        self.trace_recorder.capture_action(
                            time.time(), f"resilience.{event}", subsystem
                        )
                    except Exception:  # noqa: BLE001
                        pass

            self.resilience_ladder = DegradationLadder(
                config.resilience.resilience_config(), on_event=_ladder_event
            )
            self.controller.resilience = self.resilience_ladder
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._http_servers: list[http.server.ThreadingHTTPServer] = []
        # FileLease, or cluster.kubernetes.KubeLease when source=kubernetes
        # (same try_acquire/release surface).
        self._lease: Optional[FileLease] = None
        self._is_leader = not config.leader_election.enabled
        # Cellular control plane (config cells section): the partition plan
        # (grove_tpu/cells/partition.py) and the per-cell named leases
        # (runtime/lease.LeaseSet — independent renewal clocks). Built by
        # start() when cells.enabled; renewed each run-loop tick.
        self.cell_plan = None
        self.cell_leases = None
        self._backend_server = None
        self.backend_port: Optional[int] = None
        self.health_port: Optional[int] = None
        self._started = False
        self._prewarm_thread: Optional[threading.Thread] = None
        self._next_requeue: Optional[float] = None
        self.persistence = None  # wired by start() when enabled
        self.metrics_port: Optional[int] = None
        self.webhook_port: Optional[int] = None
        self._tls_paths: Optional[tuple[str, str]] = None  # (cert, key) once ensured
        self._webhook_tls_paths: Optional[tuple[str, str]] = None
        self._webhook_ca_pending = False  # boot patch failed; retry in reconcile
        self._operator_users: Optional[frozenset] = None  # cached (static)
        # Child-CR scale values already rejected (ceilings): name -> value.
        # Guards against per-replay event spam until the healing PUT lands.
        self._rejected_child_scales: dict[str, int] = {}
        # /profilez state: per-step cumulative seconds + call counts.
        self._profile: dict[str, dict[str, float]] = {}
        # Watch driver (cluster integration path): attached via attach_watch;
        # pumped before and pushed after every reconcile pass.
        self.watch = None
        # gRPC client the manager itself created (kwok node-forwarding) and
        # must close at stop(); caller-supplied clients stay caller-owned.
        self._owned_backend_client = None
        # Live-apiserver watch source (cluster.source: kubernetes); its
        # reader threads are stopped at manager stop().
        self._kube_source = None
        # Rejected-CR dedupe: name -> repr of the last spec the admission
        # chain rejected (one event per distinct bad spec, not per echo).
        self._rejected_workload_specs: dict[str, str] = {}
        # HPA utilization feed (metrics-server analog): target FQN -> current
        # average utilization normalized to the target (1.0 == at target).
        # Pushed via POST /api/v1/metrics; consumed by the autoscale step.
        self.hpa_metrics: dict[str, float] = {}
        # Admission chain (webhook analog): defaulting + validation +
        # authorizer-protected managed resources (config.authorizer).
        self.admission = AdmissionChain(
            topology=self.topology,
            authorizer=Authorizer(
                enabled=config.authorizer.enabled,
                exempt_actors=tuple(config.authorizer.exempt_actors),
            ),
            known_queues=frozenset(config.scheduling.queues),
            auto_slice_enabled=config.network_acceleration.auto_slice_enabled,
            slice_resource_name=config.network_acceleration.slice_resource_name,
        )

        self._m_reconciles = self.metrics.counter(
            "grove_reconcile_total", "Reconcile passes run"
        )
        self._m_reconcile_errors = self.metrics.counter(
            "grove_reconcile_errors_total", "Reconcile step errors"
        )
        self._m_reconcile_seconds = self.metrics.histogram(
            "grove_reconcile_duration_seconds", "Reconcile pass duration"
        )
        self._m_leader = self.metrics.gauge(
            "grove_leader", "1 when this process holds the leader lease"
        )
        # Cellular control plane (grove_tpu/cells): plan size plus per-cell
        # lease holdership and queue-pin counts, labeled by cell name.
        self._m_cell_count = self.metrics.gauge(
            "grove_cell_count", "Reconcile cells in the partition plan"
        )
        self._m_cell_lease_held = self.metrics.gauge(
            "grove_cell_lease_held",
            "1 when this process holds the named cell lease",
        )
        self._m_cell_queues = self.metrics.gauge(
            "grove_cell_queues", "Queues pinned to the cell by the plan"
        )
        self._m_gangs_admitted = self.metrics.counter(
            "grove_gangs_admitted_total", "Gangs admitted by the solver"
        )
        self._m_queue_used = self.metrics.gauge(
            "grove_queue_used", "Bound resource usage per capacity queue"
        )
        # Solve-wave dispositions (controller.solve_pass_counts): how often
        # the damper turned a reconcile into a skip or an arrivals-only
        # delta instead of a full encode+solve. A real Counter (rate()
        # works, OpenMetrics _total convention holds); the refresh incs
        # the delta against the last exported snapshot.
        self._m_solve_passes = self.metrics.counter(
            "grove_solve_passes_total",
            "Solve waves by disposition (full | delta | skipped)",
        )
        self._solve_passes_exported = {"full": 0, "delta": 0, "skipped": 0}
        # GREP-244 "TAS metrics" direction: PlacementScore distribution of
        # admitted gangs (scheduler podgang.go:176-178; 1.0 = optimal).
        # Buckets cover the score's [0,1] range, dense near the top where
        # placement-quality regressions show first.
        self._m_placement_score = self.metrics.histogram(
            "grove_placement_score",
            "PlacementScore of gangs at first admission (1.0 = optimal)",
            buckets=(0.25, 0.5, 0.75, 0.9, 0.95, 0.99, 1.0),
        )
        # Defragmentation loop observability (GREP-244 metrics direction):
        # the fragmentation score is a gauge (sampled each defrag tick), the
        # per-level stranded fractions carry level+resource labels, and the
        # migration counters export as real Counters (delta-tracked against
        # controller.defrag_counts, same discipline as solve passes).
        self._m_frag_score = self.metrics.gauge(
            "grove_fragmentation_score",
            "Cluster fragmentation score (1 - best domain free / ideal)",
        )
        self._m_frag_stranded = self.metrics.gauge(
            "grove_fragmentation_stranded",
            "Stranded free-capacity fraction per topology level and resource",
        )
        self._m_defrag_plans = self.metrics.counter(
            "grove_defrag_plans_total", "Migration plans executed"
        )
        self._m_defrag_migrations = self.metrics.counter(
            "grove_defrag_migrations_total", "Gang migrations started by defrag"
        )
        self._m_defrag_pods = self.metrics.counter(
            "grove_defrag_pods_migrated_total", "Pods rebound by defrag migrations"
        )
        self._m_defrag_migrating = self.metrics.gauge(
            "grove_defrag_migrating", "Gangs currently mid-migration"
        )
        self._defrag_exported = {"plans": 0, "migrations": 0, "pods_migrated": 0}
        # Fleet lifecycle (rollout + revocable capacity) counters, exported
        # as deltas from controller.rollout_counts / revocation_counts; the
        # gauge samples replicas currently mid-replacement.
        self._m_rollout_cutovers = self.metrics.counter(
            "grove_rollout_cutovers_total",
            "Make-before-break replica cutovers committed",
        )
        self._m_rollout_retries = self.metrics.counter(
            "grove_rollout_retries_total",
            "Deferred-replica retries scheduled by the rollout backoff",
        )
        self._m_rollout_fallbacks = self.metrics.counter(
            "grove_rollout_fallbacks_total",
            "Replicas that fell back to delete-then-recreate (deadline spent)",
        )
        self._m_rollout_replacing = self.metrics.gauge(
            "grove_rollout_replacing",
            "Rolling-update replicas currently mid-replacement",
        )
        self._m_revocation_notices = self.metrics.counter(
            "grove_revocation_notices_total", "Revocation notices observed"
        )
        self._m_revocation_migrated = self.metrics.counter(
            "grove_revocation_migrations_total",
            "Gangs rescued off revocation-pending nodes by migration",
        )
        self._m_revocation_evicted = self.metrics.counter(
            "grove_revocation_evictions_total",
            "Gangs evicted ahead of a revocation deadline (SLO-rank order)",
        )
        self._rollout_exported = {"cutovers": 0, "retries": 0, "fallbacks": 0}
        self._revocation_exported = {"notices": 0, "migrated": 0, "evicted": 0}
        # Tenancy fairness surfaces (grove_tpu/tenancy): counters are
        # delta-exported from the ledger totals (same discipline as defrag),
        # gauges sample the ledger/budget each reconcile.
        self._m_tenancy_admitted = self.metrics.counter(
            "grove_tenancy_admitted_total", "Gangs first-admitted (tenancy view)"
        )
        self._m_tenancy_borrowed = self.metrics.counter(
            "grove_tenancy_admitted_borrowing_total",
            "Admissions that rode borrowed queue capacity",
        )
        self._m_tenancy_preemptions = self.metrics.counter(
            "grove_tenancy_preemptions_total", "Gangs preempted (tenancy view)"
        )
        self._m_tenancy_reclaims = self.metrics.counter(
            "grove_tenancy_reclaims_total", "Gangs evicted by quota reclaim"
        )
        self._m_tenancy_reclaim_deferred = self.metrics.counter(
            "grove_tenancy_reclaim_deferred_total",
            "Reclaims deferred by the shared disruption budget",
        )
        self._m_tenancy_aging = self.metrics.counter(
            "grove_tenancy_aging_boosts_total", "Aging-ladder steps granted"
        )
        self._m_tenancy_tenants = self.metrics.gauge(
            "grove_tenancy_tenants", "Tenants (queues) seen by the ledger"
        )
        self._m_tenancy_disrupted = self.metrics.gauge(
            "grove_tenancy_disrupted",
            "Gangs counted against the shared disruption budget right now",
        )
        self._tenancy_exported = {
            "admitted": 0,
            "admitted_borrowing": 0,
            "preemptions": 0,
            "reclaims": 0,
            "reclaim_deferred": 0,
            "aging_boosts": 0,
        }
        # Placement-quality gauges (quality/report.py consumers): the last
        # non-empty solve wave's aggregate view, refreshed each reconcile —
        # the live-serving counterpart of the bench's quality report, so a
        # quality regression shows on /metrics before any bench run does.
        self._m_quality_admitted_ratio = self.metrics.gauge(
            "grove_placement_quality_admitted_ratio",
            "Admitted / schedulable gangs in the last non-empty solve wave",
        )
        self._m_quality_score = self.metrics.gauge(
            "grove_placement_quality_score",
            "Mean PlacementScore of gangs admitted by the last solve wave",
        )
        self._m_quality_pref = self.metrics.gauge(
            "grove_placement_quality_preferred_fraction",
            "Mean preferred-domain fraction implied by the last wave's scores",
        )
        # Flight recorder + event-ring observability (trace subsystem):
        # journal records written/dropped (delta-exported from the recorder
        # counters), replay divergences found by replay_verify (every
        # divergence is a solver-nondeterminism regression), and events the
        # bounded ring dropped.
        self._m_trace_records = self.metrics.counter(
            "grove_trace_records_total", "Flight-recorder records journaled"
        )
        self._m_trace_dropped = self.metrics.counter(
            "grove_trace_dropped_total",
            "Flight-recorder records dropped (bounded queue full)",
        )
        self._m_replay_divergence = self.metrics.counter(
            "grove_replay_divergence_total",
            "Plan divergences found by deterministic replay verification",
        )
        self._m_events_dropped = self.metrics.counter(
            "grove_events_dropped_total",
            "Control-plane events dropped by the bounded event ring",
        )
        self._trace_exported = {"recorded": 0, "dropped": 0}
        self._events_dropped_exported = 0
        # Kube wire-client throttling (cluster.kubeQps/kubeBurst token
        # bucket): requests that had to wait for a token.
        self._m_kube_throttled = self.metrics.counter(
            "grove_kube_client_throttled_total",
            "Apiserver requests delayed by the QPS/Burst token bucket",
        )
        self._kube_throttled_exported = 0
        # Candidate-pruning observability (solver/pruning.py): the last
        # pruned solve's candidate-axis size (gauge) and the exactness-
        # escalation counter (lossy rejection -> dense re-solve; delta-
        # exported from warm.prune, same discipline as solve passes).
        self._m_candidate_nodes = self.metrics.gauge(
            "grove_solver_candidate_nodes",
            "Candidate-axis size of the last pruned solve (0 = dense)",
        )
        # Mesh-shard fallback ledger (parallel/mesh.py): solves that wanted
        # a multi-device layout but ran unsharded — the observable side of
        # the solver_mesh_for/solve_layout_for no-divisible-split path.
        self._m_shard_fallbacks = self.metrics.counter(
            "grove_solver_shard_fallbacks_total",
            "Solver mesh-layout negotiations that fell back to unsharded",
        )
        self._shard_fallbacks_exported = 0
        self._m_candidate_escalations = self.metrics.counter(
            "grove_solver_candidate_escalations_total",
            "Pruned-solve rejections re-verified by a dense re-solve",
        )
        self._prune_escalations_exported = 0
        # Host<->device round-trip ledger (solver/drain.DrainStats): every
        # drain/stream feeds the warm path's cumulative dispatch/harvest
        # counters through record_drain regardless of harvest discipline,
        # so the deltas here never miss a drain between scrapes. The scan
        # discipline's whole point is this counter: O(shape classes +
        # escalations) instead of O(waves).
        self._m_drain_roundtrips = self.metrics.counter(
            "grove_drain_device_roundtrips_total",
            "Host-blocking device harvest syncs across all drains/streams",
        )
        self._m_drain_dispatches = self.metrics.counter(
            "grove_drain_dispatches_total",
            "Solve programs dispatched across all drains/streams "
            "(a scanned chunk counts once)",
        )
        self._roundtrips_exported = 0
        self._dispatches_exported = 0
        # Streaming-drain observability (solver/stream.py): pipeline depth
        # and steady-state throughput of the last streaming run (gauges cut
        # from warm.last_stream), and the measured per-gang enqueue->bound
        # distribution (samples drained from the warm path's bounded queue
        # each refresh — a stream outrunning the scrape loses oldest
        # samples, never memory).
        self._m_stream_depth = self.metrics.gauge(
            "grove_stream_depth",
            "Pipeline depth of the last streaming drain (0 = serial)",
        )
        self._m_stream_gps = self.metrics.gauge(
            "grove_stream_gangs_per_sec",
            "Steady-state admitted gangs/sec of the last streaming drain",
        )
        self._m_stream_ttb = self.metrics.histogram(
            "grove_stream_time_to_bind_seconds",
            "Per-gang enqueue->bound seconds under streaming admission",
        )
        # Host-stage timing ledger (solver/drain.DrainStats.host_stages):
        # per-stage host seconds of the last drain/stream — the measurable
        # side of the host hot-path vectorization (encode/prefilter/decode/
        # bind must stay flat as the fleet grows).
        self._m_host_stage = self.metrics.gauge(
            "grove_host_stage_seconds",
            "Host seconds by stage of the last drain/stream "
            "(encode|prefilter|dispatch|harvest|decode|bind|journal|"
            "total|hotPath)",
        )
        # Failure-domain hardening observability (faults + resilience
        # sections): ladder transitions per subsystem, injected faults,
        # bind rollbacks / stale-plan requeues / bind push retries, watch
        # reconnects+resyncs, recorder write failures. All real Counters,
        # delta-exported each reconcile against the underlying monotonic
        # sources — same discipline as the solve-pass counters.
        self._m_degradation_down = self.metrics.counter(
            "grove_degradation_step_downs_total",
            "Degradation-ladder rungs stepped down (breaker opened)",
        )
        self._m_degradation_up = self.metrics.counter(
            "grove_degradation_step_ups_total",
            "Degradation-ladder rungs stepped back up (probation passed)",
        )
        self._degradation_exported: dict = {}
        self._m_faults_injected = self.metrics.counter(
            "grove_faults_injected_total",
            "Faults fired by the deterministic injection registry",
        )
        self._faults_exported = 0
        self._m_bind_rollbacks = self.metrics.counter(
            "grove_bind_rollbacks_total",
            "Gang binds rolled back (all-or-nothing commit failed mid-gang)",
        )
        self._m_stale_requeues = self.metrics.counter(
            "grove_stale_plan_requeues_total",
            "Gangs requeued at bind time because a target node died",
        )
        self._resilience_exported = {
            "bind_rollbacks": 0,
            "stale_plan_requeues": 0,
            "solve_degraded_retries": 0,
        }
        self._m_solve_degraded = self.metrics.counter(
            "grove_solve_degraded_retries_total",
            "Serving solves retried fully degraded after a solve failure",
        )
        self._m_watch_reconnects = self.metrics.counter(
            "grove_watch_reconnects_total",
            "Watch streams resubscribed after a disconnect",
        )
        self._m_watch_resyncs = self.metrics.counter(
            "grove_watch_resyncs_total",
            "Full watch resyncs forced by resourceVersion expiry (410)",
        )
        self._m_bind_push_retries = self.metrics.counter(
            "grove_bind_retries_total",
            "Kube bind pushes retried in-call with backoff",
        )
        self._watch_exported = {"reconnects": 0, "resyncs": 0, "bindRetries": 0}
        self._m_recorder_write_errors = self.metrics.counter(
            "grove_recorder_write_errors_total",
            "Flight-recorder segment writes that failed (counting-drops mode)",
        )
        self._recorder_write_errors_exported = 0
        # Every (queue, resource) series ever emitted — re-zeroed each pass
        # when usage disappears (gauge values persist otherwise).
        self._queue_metric_keys: dict[str, set] = {}

    # --- object apply surface (admission-gated; kubectl-apply analog) -------------

    def apply_podcliqueset(self, pcs: PodCliqueSet, actor: str = "user") -> PodCliqueSet:
        """Create/update a PCS through the admission chain (defaulting +
        validation + update immutability); raises AdmissionError on reject."""
        old = self.cluster.podcliquesets.get(pcs.metadata.name)
        pcs = self.admission.admit_podcliqueset(pcs, old=old)
        self.cluster.podcliquesets[pcs.metadata.name] = pcs
        return pcs

    def delete_podcliqueset(self, name: str, actor: str = "user") -> None:
        self.cluster.delete_pcs_cascade(name)
        # CR-backed workloads must ALSO be deleted at the apiserver, or the
        # next watch relist re-emits ADDED and resurrects the workload.
        if self._kube_source is not None and actor != "apiserver":
            self._kube_source.delete_workload(name)

    def scale_target(
        self,
        target: str,
        replicas: int,
        actor: str = "user",
        now: float | None = None,
    ) -> int:
        """kubectl-scale analog: write the scale subresource of a PodClique
        or PodCliqueScalingGroup — the SAME surface the HPA component writes
        (reference: `scale` subresource on the CRs, podcliqueset.go:27;
        HPA ScaleTargetRef, components/hpa/hpa.go:249-259). Returns the
        previous effective value. Raises KeyError for an unknown target,
        ValueError for a bad count."""
        c = self.cluster
        if target in c.podcliques:
            pclq = c.podcliques[target]
            if pclq.pcsg_name:
                # Members scale WITH their group (the reference forbids
                # individual autoscaling for them, validation/podcliqueset.
                # go:240-246; expansion takes member replicas from the
                # template). Accepting this would silently do nothing and
                # leave an externally-scaled CR diverged forever.
                raise ValueError(
                    f"{target} is a scaling-group member; scale the "
                    f"PodCliqueScalingGroup {pclq.pcsg_name} instead"
                )
            spec_replicas = pclq.spec.replicas
        elif target in c.scaling_groups:
            spec_replicas = c.scaling_groups[target].spec.replicas
        else:
            raise KeyError(target)
        if replicas < 0:
            raise ValueError("replicas must be >= 0")
        # Ceiling: the HPA's user-declared max when one targets this object,
        # else the control-plane sanity bound — one reconcile materializes a
        # Pod object per replica, so an unbounded request is an OOM lever.
        ceiling = constants.MAX_SCALE_REPLICAS
        hpa = c.hpas.get(f"{target}-hpa")
        if hpa is not None:
            ceiling = min(ceiling, hpa.max_replicas)
        if replicas > ceiling:
            raise ValueError(f"replicas must be <= {ceiling} for {target}")
        previous = c.scale_overrides.get(target, spec_replicas)
        c.scale_overrides[target] = int(replicas)
        # `now` keeps virtual-time callers (tests, simulator) on one event
        # timeline; the HTTP path has no virtual clock and takes wall time.
        c.record_event(
            time.time() if now is None else now,
            target,
            f"scaled {previous} -> {replicas} (by {actor})",
        )
        return previous

    def mutate_managed(self, actor: str, kind: str, name: str, fn) -> None:
        """Apply `fn(cluster)` as `actor` touching managed resource kind/name.
        The authorizer (when enabled) blocks everyone but the operator and
        exempt actors (authorization/handler.go:60-80)."""
        self.admission.admit_managed_mutation(actor, kind, name)
        fn(self.cluster)

    def _apply_child_scale_event(self, ev, now: float) -> None:
        """PodClique/PCSG CR watch event -> the scale subresource path.

        The child CRs are operator-owned projections, but their spec.replicas
        is the reference's public scale surface (HPA ScaleTargetRef,
        hpa.go:249-259; kubectl scale pclq): an external value becomes a
        scale_target() call — the SAME path the in-process HPA and the CLI
        scale verb use (ceilings included).

        External-vs-echo is decided against what THIS process last PUSHED to
        the apiserver (source.last_projected_replicas), not against store
        state: a pending override makes the store disagree with the wire, so
        a relist replaying our own stale projection would otherwise read as
        an external write and revert a just-applied scale. A replica count
        equal to our last push is indistinguishable from our own echo (the
        inherent limit of a level-based watch) and is ignored; after an
        operator restart nothing has been pushed yet, so a CR value
        differing from the freshly-expanded spec is re-adopted — an external
        scale survives the restart."""
        if ev.type.value == "DELETED":
            return  # our own GC, or an out-of-band delete the sync heals
        spec = (ev.obj or {}).get("spec", {}) or {}
        reps = spec.get("replicas")
        if not isinstance(reps, int) or isinstance(reps, bool):
            return
        c = self.cluster
        cur = c.podcliques.get(ev.name) or c.scaling_groups.get(ev.name)
        if cur is None:
            return  # projection of an object the store no longer owns
        last = (
            self._kube_source.last_projected_replicas(ev.name)
            if self._kube_source is not None
            else None
        )
        if last is not None:
            if reps == last:
                # Our own write (live echo or relist replay). Seeing the CR
                # back at the pushed value also proves a heal PUT landed —
                # clear the rejected-value guard so a SECOND genuine write
                # of the same out-of-range value records and heals again
                # instead of being silently ignored forever. The dedupe
                # window resets with it: the landed heal ENDS the episode,
                # so the next rejection events even inside the window.
                self._rejected_child_scales.pop(ev.name, None)
                self._heal_dedupe.reset(ev.name, "cr-scale-rejected")
                return
        elif cur.spec.replicas == reps:
            return  # nothing pushed yet and the CR agrees with the store
        if c.scale_overrides.get(ev.name) == reps:
            return  # already requested; projection just hasn't caught up
        if self._rejected_child_scales.get(ev.name) == reps:
            return  # already rejected this exact value; no event spam
        try:
            self.scale_target(ev.name, reps, actor="apiserver", now=now)
            self._rejected_child_scales.pop(ev.name, None)
        except (KeyError, ValueError) as e:
            # Out-of-range external scale: surface once, don't crash the
            # pump — and heal the wire: invalidate the projection cache so
            # the next sync re-PUTs the effective manifest (the external
            # write changed the CR behind the cache's back; without this
            # kubectl would show the rejected value forever). The event is
            # additionally window-deduped per (object, reason): the value
            # guard above only stops IDENTICAL replays, so a writer flapping
            # between two bad values would otherwise event on every flip.
            self._rejected_child_scales[ev.name] = reps
            if self._heal_dedupe.should_emit(now, ev.name, "cr-scale-rejected"):
                c.record_event(now, ev.name, f"CR scale rejected: {e}")
            if self._kube_source is not None:
                self._kube_source.invalidate_child_projection(ev.name)

    def _apply_workload_event(self, ev, now: float) -> None:
        """PodCliqueSet watch event -> admission-gated apply / cascade
        delete. Rejections surface as control-plane events (the CR stays in
        the cluster; its status never progresses) rather than crashing the
        pump loop. `now` comes from the pump so CR events share the one
        event timeline (virtual time in tests, wall time in production)."""
        from grove_tpu.api import default_podcliqueset
        from grove_tpu.api.admission import AdmissionError

        name = ev.name
        if ev.type.value == "DELETED":
            if name in self.cluster.podcliquesets:
                self.delete_podcliqueset(name, actor="apiserver")
                self.cluster.record_event(
                    now, name, "workload CR deleted (apiserver watch)"
                )
            return
        try:
            # Default BEFORE the echo comparison: the stored spec is the
            # defaulted one, so comparing against the raw CR would never
            # match and every echo would take the full re-apply path.
            incoming = default_podcliqueset(PodCliqueSet.from_dict(ev.obj))
            existing = self.cluster.podcliquesets.get(name)
            if existing is not None and existing.spec == incoming.spec:
                # Status-only MODIFIED — usually the echo of our own status
                # write-back. Re-applying would replace the stored object
                # and wipe the status we just computed (write loop).
                return
            spec_key = repr(incoming.spec)
            if self._rejected_workload_specs.get(name) == spec_key:
                return  # already rejected this exact spec; don't re-event
            applied = self.apply_podcliqueset(incoming, actor="apiserver")
            self._rejected_workload_specs.pop(name, None)
            # A successful apply ends any rejection episode for this CR.
            self._heal_dedupe.reset(name, "cr-rejected")
            self._heal_dedupe.reset(name, "cr-unparseable")
            if existing is not None:
                # CR status is OURS (the operator is the status writer);
                # a spec update must not reset reconciled state.
                applied.status = existing.status
        except AdmissionError as e:
            # Async-validation reality: the reference rejects at the
            # apiserver door (inbound webhook); our chain runs in-process
            # AFTER etcd accepted the object, so a rejected edit leaves the
            # CR and the store diverged until the user fixes the CR. Record
            # ONE event per distinct rejected spec — the status write-back
            # echo would otherwise re-emit it every tick — and at most one
            # per (object, reason) window: distinct bad specs arriving in
            # quick succession are one heal episode, not an event flood.
            self._rejected_workload_specs[name] = spec_key
            if self._heal_dedupe.should_emit(now, name, "cr-rejected"):
                self.cluster.record_event(
                    now, name,
                    f"workload CR rejected: {'; '.join(str(x) for x in e.errors)}",
                )
        except Exception as e:  # malformed CR must not kill the pump
            if self._heal_dedupe.should_emit(now, name, "cr-unparseable"):
                self.cluster.record_event(
                    now, name, f"workload CR unparseable: {e}"
                )

    def attach_watch(self, source, backend=None) -> "object":
        """Feed the store from an external cluster's watch stream
        (grove_tpu/cluster/watch.py). Returns the WatchDriver."""
        from grove_tpu.cluster.watch import WatchDriver

        self.watch = WatchDriver(cluster=self.cluster, source=source, backend=backend)
        return self.watch

    # --- lifecycle ---------------------------------------------------------------

    @property
    def ready(self) -> bool:
        """readyz: started, and (when electing) leadership state known."""
        return self._started

    def profilez(self) -> dict:
        """Reconcile-step timing breakdown (pprof analog, profilingEnabled)."""
        return {
            "steps": {
                name: dict(rec) for name, rec in sorted(self._profile.items())
            }
        }

    def statusz(self) -> dict:
        from grove_tpu.version import build_info

        queues = {}
        qtree = self.controller.queue_tree
        if qtree is not None:
            # HTTP thread vs reconcile thread: queue_usage iterates the pod
            # dict, so retry the rare mid-iteration resize (same discipline
            # as the object-API bulk reads).
            for _ in range(8):
                try:
                    usage = self.controller.queue_usage()
                    break
                except RuntimeError:
                    continue
            else:
                usage = {}
            husage = qtree.hierarchical_usage(usage)
            desc = qtree.describe()
            queues = {
                qname: {
                    **doc,
                    "depth": qtree.depth(qname),
                    # Hierarchical: a parent's `used` includes descendants.
                    "used": dict(husage.get(qname, {})),
                }
                for qname, doc in desc.items()
            }
        return {
            "build": build_info(),
            "queues": queues,
            # Damper effectiveness: solve waves by disposition.
            "solvePasses": dict(self.controller.solve_pass_counts),
            # Warm-path caches (solver/warm.py): AOT executable hits/misses/
            # lowerings + prewarm count, device-resident tensor reuse,
            # per-gang encode-row reuse, candidate-pruning counters, and the
            # last drain's measured wave-harvest latencies — the measurable
            # side of the compile-amortization discipline.
            "warmPath": self.controller.warm.stats(),
            # Candidate-pruning view (solver/pruning.py): effective config +
            # the counters the grove_solver_candidate_* metrics are cut from.
            "solver": self.solver_status(),
            # Defrag loop state: last fragmentation report, plan summary,
            # in-flight migrations, monotonic counters (what `grove-tpu get
            # defrag` renders).
            "defrag": self.controller.defrag_status(),
            "rollout": self.controller.rollout_status(),
            # Tenancy: per-tenant fairness ledger, aging state, shared
            # disruption-budget view (`grove-tpu get tenancy` renders this).
            "tenancy": self.controller.tenancy_status(),
            # Cellular control plane: partition plan + per-cell lease
            # holdership and journal paths (`grove-tpu get cells` renders
            # this; grove_cell_* metrics are cut from the same state).
            "cells": self.cells_status(),
            # Placement quality of live serving solves (quality/report.py
            # discipline — what `grove-tpu get quality` renders).
            "quality": self.controller.quality_status(),
            # Flight recorder state (trace config section): journal path,
            # records written/dropped, queue depth — what `grove-tpu trace
            # info` points at and the grove_trace_* metrics are cut from.
            "trace": self.trace_status(),
            # Failure-domain hardening state (faults + resilience sections):
            # ladder breaker states + step counters, injected-fault ledger,
            # bind rollback/stale-requeue counts, watch reconnects — what
            # `grove-tpu get resilience` renders and the grove_degradation_*
            # metrics are cut from.
            "resilience": self.resilience_status(),
            # The effective ClusterTopology (config TAS levels + auto host
            # level) — what `grove-tpu get topology` renders (kubectl get
            # clustertopology analog; the kubernetes source also syncs it
            # as a CR at boot).
            "topology": self.topology.levels_doc(),
            "leader": self._is_leader,
            "backend_port": self.backend_port,
            "objects": {
                "podcliquesets": len(self.cluster.podcliquesets),
                "podcliques": len(self.cluster.podcliques),
                "podgangs": len(self.cluster.podgangs),
                "pods": len(self.cluster.pods),
                "nodes": len(self.cluster.nodes),
            },
        }

    def cells_status(self) -> dict:
        """JSON-able cellular-control-plane view for /statusz "cells" and
        `grove-tpu get cells`: the partition plan (which cell owns which
        root subtrees/queues), per-cell lease holdership, and where each
        cell's journal lives (the tail a replacement cell replays)."""
        import os as _os

        cfg = self.config.cells
        doc: dict = {"enabled": bool(cfg.enabled)}
        if not cfg.enabled or self.cell_plan is None:
            return doc
        held = self.cell_leases.held() if self.cell_leases is not None else {}
        doc.update(
            count=len(self.cell_plan.cells),
            shardBy=cfg.shard_by,
            journalRoot=cfg.journal_root,
            plan=self.cell_plan.to_doc(),
            cells={
                name: {
                    "queues": self.cell_plan.queues_of(name),
                    "domains": self.cell_plan.domains_of(name),
                    "leaseHeld": bool(held.get(name, False)),
                    "journal": _os.path.join(cfg.journal_root, name),
                }
                for name in self.cell_plan.cells
            },
        )
        return doc

    def solver_status(self) -> dict:
        """JSON-able solver view for /statusz "solver" and `grove-tpu get
        solver`: the effective pruning configuration plus its counters and
        the last drain's wave-harvest latencies (warm.stats carries the
        same counters flat; this section adds the config context)."""
        pruning = self.controller.pruning
        doc: dict = {
            "pruning": {
                "enabled": bool(pruning is not None),
            }
        }
        if pruning is not None:
            doc["pruning"].update(
                maxCandidates=int(pruning.max_candidates),
                padLadder=[int(x) for x in pruning.pad_ladder],
                minPad=int(pruning.min_pad),
                minFleet=int(pruning.min_fleet),
            )
        doc["pruning"].update(self.controller.warm.prune.stats())
        # Mesh-sharded solve view (parallel/mesh.py): the effective
        # solver.mesh block plus the shard-fallback ledger (solves that
        # wanted a multi-device layout but ran unsharded — never silent).
        mcfg = self.controller.mesh_cfg
        doc["mesh"] = {
            "enabled": bool(getattr(mcfg, "enabled", False)),
        }
        if mcfg is not None and getattr(mcfg, "enabled", False):
            doc["mesh"].update(
                minNodes=int(mcfg.min_nodes),
                maxDevices=int(mcfg.max_devices),
            )
        try:
            from grove_tpu.parallel.mesh import shard_fallbacks

            doc["mesh"]["shardFallbacks"] = shard_fallbacks()
        except Exception:  # noqa: BLE001 — status must never fail a scrape
            pass
        # Streaming-drain view (solver/stream.py): the effective
        # solver.streaming block plus the last streaming run's throughput
        # and measured time-to-bind percentiles (source of the
        # grove_stream_* metrics and the `get solver` stream rows).
        scfg = self.config.solver.streaming_config()
        doc["streaming"] = {
            "depth": int(scfg.depth),
            "waveSize": int(scfg.wave_size),
            "maxWaitS": float(scfg.max_wait_s),
            "pollS": float(scfg.poll_s),
        }
        # On-device fused drain view (solver/drain.py harvest="scan"): the
        # effective solver.scan block plus the cumulative round-trip ledger
        # (source of the grove_drain_device_roundtrips_total counter — the
        # number the scan discipline exists to shrink).
        kcfg = self.config.solver.scan_config()
        doc["scan"] = {
            "enabled": bool(kcfg.enabled),
            "maxScanLen": int(kcfg.max_scan_len),
            "minWavesPerClass": int(kcfg.min_waves_per_class),
            "affinityLookahead": int(kcfg.affinity_lookahead),
            "deviceResident": bool(kcfg.device_resident),
            "dispatchesTotal": int(
                self.controller.warm.drain_dispatches_total
            ),
            "deviceRoundtripsTotal": int(
                self.controller.warm.drain_device_roundtrips_total
            ),
        }
        if self.controller.warm.last_stream:
            doc["lastStream"] = dict(self.controller.warm.last_stream)
        if self.controller.warm.last_drain:
            doc["lastDrain"] = dict(self.controller.warm.last_drain)
        # Serving-path host-stage split of the last solve pass (encode /
        # solve / decode wall seconds) — the per-tick slice of the drain's
        # host-stage ledger.
        if self.controller.last_host_stages:
            doc["hostStages"] = dict(self.controller.last_host_stages)
        return doc

    def resilience_status(self) -> dict:
        """JSON-able failure-domain view for /statusz "resilience" and
        `grove-tpu get resilience`: the degradation ladder's breaker states
        and step counters, the fault injector's per-site fire ledger, the
        bind-path hardening counters, the watch reconnect/resync counters,
        and the recorder's counting-drops state."""
        doc: dict = {"enabled": self.resilience_ladder is not None}
        if self.resilience_ladder is not None:
            cfg = self.config.resilience
            doc["watchdogSeconds"] = float(cfg.watchdog_seconds)
            doc["probationSeconds"] = float(cfg.probation_seconds)
            doc["ladder"] = self.resilience_ladder.stats()
        doc["binds"] = dict(self.controller.resilience_counts)
        if self.fault_injector is not None:
            doc["faults"] = self.fault_injector.stats()
        ws = getattr(self._kube_source, "watch_stats", None)
        if ws is not None:
            doc["watch"] = ws()
        if self.trace_recorder is not None:
            doc["recorder"] = {
                "degraded": self.trace_recorder.degraded,
                "writeErrors": self.trace_recorder.write_errors,
            }
        return doc

    def trace_status(self) -> dict:
        """JSON-able flight-recorder state for /statusz "trace"."""
        if self.trace_recorder is None:
            return {"enabled": False}
        return {
            "enabled": True,
            **self.trace_recorder.stats(),
            "healEventsSuppressed": self._heal_dedupe.suppressed,
        }

    def replay_verify(self) -> Optional[dict]:
        """Replay this manager's own journal through the controller's warm
        path and assert bitwise plan equivalence — the in-process
        nondeterminism self-check. Divergences increment
        grove_replay_divergence_total; returns the replay report doc (None
        when tracing is off or the journal is empty). Re-solves every
        journaled wave: an operator action (`grove-tpu trace replay`, tests,
        a canary cron), not a per-reconcile step."""
        if self.trace_recorder is None:
            return None
        from grove_tpu.trace.recorder import read_journal
        from grove_tpu.trace.replay import replay_journal

        self.trace_recorder.flush()
        try:
            records = read_journal(self.trace_recorder.path)
        except FileNotFoundError:
            return None
        report = replay_journal(records, warm_path=self.controller.warm)
        if report.divergence_count:
            self._m_replay_divergence.inc(float(report.divergence_count))
            self.log.error(
                "replay divergence: solver nondeterminism regression",
                divergences=report.divergence_count,
            )
        return report.to_doc()

    def _kube_ctx(self):
        """Memoized kube connection material (shared by the lease and the
        watch source so both target the same cluster/namespace)."""
        if getattr(self, "_kube_ctx_cache", None) is None:
            from grove_tpu.cluster.kubernetes import load_kube_context

            cfg = self.config.cluster
            self._kube_ctx_cache = load_kube_context(
                cfg.kubeconfig or None,
                cfg.kube_context or None,
                cfg.kube_namespace or None,
            )
        return self._kube_ctx_cache

    def start(self) -> None:
        """Start servers + background loops (mgr.Start analog); idempotent."""
        if self._started:
            return
        cfg = self.config
        if cfg.solver.compilation_cache_dir:
            # Persistent XLA compilation cache: solver warm-up compiles are
            # reused across operator restarts (jax-idiomatic; never fatal).
            from grove_tpu.utils.platform import enable_compilation_cache

            if not enable_compilation_cache(cfg.solver.compilation_cache_dir):
                self.log.info("compilation cache unavailable")
        # Warm-path startup: record solver shape buckets to the history file
        # and prewarm the top-K historical ones on a background thread, so
        # the first solve_pending after a restart never blocks on XLA (the
        # persistent compile cache above makes those prewarm compiles disk
        # loads after the first boot on a machine).
        if cfg.solver.shape_history_path:
            self.controller.warm.executables.history_path = (
                cfg.solver.shape_history_path
            )
        if cfg.solver.prewarm_top_k > 0:
            # Non-daemon + stop-event-aware (a daemon thread killed inside an
            # XLA compile at interpreter exit aborts the process); stop()
            # joins it, waiting out at most one in-flight compile.
            self._prewarm_thread = self.controller.warm.executables.start_prewarm_thread(
                cfg.solver.prewarm_top_k, stop=self._stop
            )
            if self._prewarm_thread is not None:
                self.log.info(
                    "solver prewarm started", top_k=cfg.solver.prewarm_top_k
                )
        if self.trace_recorder is not None:
            # Flight-recorder writer thread (bounded queue drains to atomic
            # journal segments); stop() joins it after a final flush.
            self.trace_recorder.start()
            self.log.info("trace recorder started", path=cfg.trace.path)
        if self.fault_injector is not None:
            # Process-wide install: the named sites (solver dispatch, bind
            # commit, kube wire, watch stream, recorder writes) all consult
            # faults.active(). stop() clears it.
            from grove_tpu import faults as faults_mod

            faults_mod.install(self.fault_injector)
            self.log.info(
                "FAULT INJECTION ACTIVE",
                sites=",".join(sorted(self.fault_injector.specs)),
                seed=self.fault_injector.seed,
            )
        if cfg.leader_election.enabled:
            if cfg.cluster.source == "kubernetes":
                # Apiserver-backed Lease: the only store EVERY replica of a
                # k8s Deployment can see — a file lease would leave two
                # active managers on separate filesystems (the reference's
                # election is apiserver-backed too, types.go:73-104).
                from grove_tpu.cluster.kubernetes import KubeLease

                self._lease = KubeLease(
                    self._kube_ctx(),
                    lease_duration_seconds=cfg.leader_election.lease_duration_seconds,
                    renew_deadline_seconds=cfg.leader_election.renew_deadline_seconds,
                )
            else:
                self._lease = FileLease(
                    path=cfg.leader_election.lease_file,
                    lease_duration_seconds=cfg.leader_election.lease_duration_seconds,
                    renew_deadline_seconds=cfg.leader_election.renew_deadline_seconds,
                )
            self._is_leader = self._lease.try_acquire()
        self._m_leader.set(1.0 if self._is_leader else 0.0)
        if cfg.cells.enabled:
            # Cellular control plane: partition along QueueTree root-subtree
            # seams (shard_by queue; "topology" leaves queues unpinned) and
            # acquire one named lease per cell — independent renewal clocks,
            # so one stalled cell stands down alone (runtime/lease.LeaseSet).
            from grove_tpu.cells import partition_tree
            from grove_tpu.runtime.lease import LeaseSet

            tree = (
                self.controller.queue_tree
                if cfg.cells.shard_by == "queue"
                else None
            )
            self.cell_plan = partition_tree(tree, cfg.cells.count)
            self.cell_leases = LeaseSet(
                cfg.cells.lease_dir,
                lease_duration_seconds=cfg.cells.lease_duration_seconds,
                renew_deadline_seconds=cfg.cells.renew_deadline_seconds,
            )
            self._m_cell_count.set(float(len(self.cell_plan.cells)))
            for cell_name in self.cell_plan.cells:
                held = self.cell_leases.try_acquire(cell_name)
                self._m_cell_lease_held.set(1.0 if held else 0.0, cell=cell_name)
                self._m_cell_queues.set(
                    float(len(self.cell_plan.queues_of(cell_name))), cell=cell_name
                )
            self.log.info(
                "cellular control plane enabled",
                cells=len(self.cell_plan.cells),
                shardBy=cfg.cells.shard_by,
                journalRoot=cfg.cells.journal_root,
            )

        if cfg.servers.health_port >= 0:
            self.health_port = self._serve_http(cfg.servers.health_port)
        if cfg.servers.metrics_port >= 0:
            # Dedicated metrics bind (manager.go:94-96); same handler class,
            # so /metrics is the canonical path on this port.
            self.metrics_port = self._serve_http(cfg.servers.metrics_port)
        if cfg.servers.webhook_port >= 0:
            self.webhook_port = self._serve_webhook(cfg.servers.webhook_port)
        if cfg.backend.enabled:
            from grove_tpu.backend.service import create_server

            # create_server builds AND starts the gRPC server; the solver
            # section configures its bucketing + portfolio defaults.
            self._backend_server, self.backend_port = create_server(
                port=cfg.backend.port,
                max_workers=cfg.backend.max_workers,
                solver_config=cfg.solver,
                priority_classes=cfg.scheduling.priority_classes,
                metrics=self.metrics,  # sidecar solves surface on /metrics
            )
            self.log.info("backend sidecar listening", port=self.backend_port)
        if cfg.persistence.enabled:
            from grove_tpu.runtime.persistence import StatePersistence

            self.persistence = StatePersistence(
                cfg.persistence.path,
                snapshot_interval_seconds=cfg.persistence.snapshot_interval_seconds,
            )
            restored = self.persistence.restore(self.cluster)
            if restored:
                self.log.info("restored control-plane state", path=cfg.persistence.path)
        if cfg.cluster.source in ("kwok", "kubernetes"):
            # External fleets flow in through the watch path. Nodes also
            # forward to the backend sidecar when it is hosted here, so
            # external Solve RPCs see the same fleet.
            backend_client = None
            if self.backend_port is not None:
                from grove_tpu.backend.client import BackendClient

                backend_client = BackendClient(f"127.0.0.1:{self.backend_port}")
                # Manager-created, so manager-closed at stop(); a client the
                # CALLER passed to attach_watch stays the caller's to close.
                self._owned_backend_client = backend_client
        if cfg.cluster.source == "kwok":
            # Config-fabricated KWOK fleet — the binary is then a
            # self-contained e2e rig (kind-up.sh KWOK analog).
            from grove_tpu.cluster.kwok import kwok_fleet_from_config

            # Fabricated at now=0.0 so the bootstrap node events are visible
            # to the first pump under BOTH clocks: production's wall time and
            # the tests' virtual time (reconcile_once(now=0.0)).
            fleet = kwok_fleet_from_config(
                cfg.cluster, cfg.cluster_topology(), now=0.0
            )
            self.attach_watch(fleet, backend=backend_client)
            self.log.info("kwok fleet attached", nodes=cfg.cluster.kwok_nodes)
        elif cfg.cluster.source == "kubernetes":
            # Live apiserver via the list/watch wire protocol; solver
            # placements go back as pod creates + binding subresource POSTs
            # (cluster/kubernetes.py).
            from grove_tpu.cluster.kubernetes import (
                KubernetesWatchSource,
                render_pod_manifest,
            )

            ctx = self._kube_ctx()

            def _manifest(name: str):
                pod = self.cluster.pods.get(name)
                return render_pod_manifest(pod) if pod is not None else None

            source = KubernetesWatchSource(
                ctx,
                pod_label_selector=cfg.cluster.pod_label_selector or None,
                pod_manifest_for=_manifest,
                watch_workloads=cfg.cluster.watch_workloads,
                initc_kube_tokens=cfg.cluster.initc_mode == "kubernetes",
                qps=cfg.cluster.kube_qps,
                burst=cfg.cluster.kube_burst,
                # Bind retry + shared backoff pacing (resilience.* block):
                # in-call decorrelated-jitter retries on the bind push; the
                # WatchDriver's cross-tick retry set remains the outer loop.
                bind_retry_attempts=(
                    cfg.resilience.bind_max_attempts
                    if cfg.resilience.enabled
                    else 1
                ),
                backoff_base_s=cfg.resilience.backoff_base_seconds,
                backoff_cap_s=cfg.resilience.backoff_cap_seconds,
            )
            source.start()
            self._kube_source = source
            # Startup topology sync (clustertopology.go:39-51): publish the
            # config's ClusterTopology as a CR so cluster users can kubectl
            # get it; best-effort — a CRD-less cluster just logs.
            if not source.sync_cluster_topology(self.topology):
                self.log.info("ClusterTopology CR sync unavailable")
            if self.webhook_port is not None:
                # Complete the webhook configs deploy rendered with an empty
                # caBundle (the cert-controller rotator analog). Failure is
                # NOT terminal here — reconcile_once retries until it lands
                # (failurePolicy Fail means an unpatched config is a
                # cluster-wide PCS write outage).
                ca = self.webhook_ca_bundle()
                self._webhook_ca_pending = ca is None or not source.sync_webhook_ca(ca)
                if self._webhook_ca_pending:
                    self.log.error(
                        "webhook caBundle patch failed; retrying each reconcile"
                    )
            driver = self.attach_watch(source, backend=backend_client)
            # Workload CRs from the apiserver (kubectl apply -> watch ->
            # admission -> store; SURVEY §3.2-3.3) — the same chain the
            # HTTP apply path runs, so watch events can't bypass admission.
            driver.workload_sink = self._apply_workload_event
            driver.child_scale_sink = self._apply_child_scale_event
            self.log.info(
                "kubernetes cluster attached",
                server=ctx.server,
                namespace=ctx.namespace,
            )
        # Accelerator preflight AFTER the cluster source attached: a boot
        # that promises auto-slice injection against a fleet with no slice
        # resource anywhere must fail HERE, not strand gangs at solve time.
        self._accelerator_preflight()
        self._started = True
        self.log.info(
            "manager started",
            leader=self._is_leader,
            health_port=self.health_port,
            backend_port=self.backend_port,
            webhook_port=self.webhook_port,
        )

    def _accelerator_preflight(self) -> None:
        """Hard boot-time failure when networkAcceleration.autoSliceEnabled
        is set but no visible node exposes the slice resource — the MNNVL
        preflight analog (a GPU fleet without ComputeDomains fails the
        operator boot rather than silently scheduling nothing). Sources
        whose nodes only arrive later (externally-fed store with nothing in
        it yet, apiserver momentarily unreachable) skip: there is nothing
        visible to falsify, and the knob stays honest once nodes flow in."""
        na = self.config.network_acceleration
        if not na.auto_slice_enabled:
            return
        res = na.slice_resource_name
        caps: list | None = None
        if self.config.cluster.source == "kwok" and self.watch is not None:
            # The fabricated fleet's bootstrap events sit at t=0 (see
            # start()); pumping them in makes the fleet inspectable now.
            self.watch.pump(0.0)
            caps = [n.capacity for n in self.cluster.nodes.values()]
        elif self._kube_source is not None:
            caps = self._kube_source.list_node_capacities()
        elif self.cluster.nodes:
            caps = [n.capacity for n in self.cluster.nodes.values()]
        if not caps:
            return
        if not any(float(c.get(res, 0) or 0) > 0 for c in caps):
            raise RuntimeError(
                "networkAcceleration.autoSliceEnabled: no visible node "
                f"exposes the slice resource {res!r} ({len(caps)} nodes "
                "checked) — fix the fleet's device plugin or disable "
                "autoSliceEnabled"
            )

    def _bind_server(
        self, port: int, handler_base: type, tls_paths: Optional[tuple[str, str]]
    ) -> int:
        """Bind + start one HTTP(S) server: the single copy of the
        socket-wrap/bookkeeping logic both surfaces share."""
        import ssl

        cfg = self.config.servers
        handler = type("Handler", (handler_base,), {"manager": self})
        server = http.server.ThreadingHTTPServer((cfg.bind_address, port), handler)
        if tls_paths is not None:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(*tls_paths)
            # Handshake lazily in the per-connection handler thread
            # (do_handshake_on_connect=False): a slow client must not park
            # the accept loop and starve /healthz for everyone else.
            server.socket = ctx.wrap_socket(
                server.socket, server_side=True, do_handshake_on_connect=False
            )
        self._http_servers.append(server)
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        self._threads.append(t)
        return server.server_address[1]

    def _serve_http(self, port: int) -> int:
        cfg = self.config.servers
        if cfg.tls_mode != "disabled" and self._tls_paths is None:
            # Cert management (cert.go:46-98 analog): certs are ensured
            # BEFORE the port binds — a CertError fails the boot without
            # leaking a bound socket, and nothing ever serves plaintext.
            from grove_tpu.runtime.certs import ensure_serving_certs

            self._tls_paths = ensure_serving_certs(
                cfg.tls_mode,
                cfg.tls_cert_dir,
                cert_file=cfg.tls_cert_file,
                key_file=cfg.tls_key_file,
            )
        tls = self._tls_paths if cfg.tls_mode != "disabled" else None
        return self._bind_server(port, _ProbeHandler, tls)

    def _serve_webhook(self, port: int) -> int:
        """The dedicated AdmissionReview server. Always HTTPS — the
        apiserver refuses plaintext webhooks — with certs independent of
        the API surface's tlsMode: manual reuses its files, anything else
        self-signs into tlsCertDir/webhook with the configured SANs (the
        cert-controller rotator analog, cert.go:66-93)."""
        import os as _os

        from grove_tpu.runtime.certs import ensure_serving_certs

        cfg = self.config.servers
        if self._webhook_tls_paths is None:
            if cfg.tls_mode == "manual":
                self._webhook_tls_paths = ensure_serving_certs(
                    "manual",
                    cfg.tls_cert_dir,
                    cert_file=cfg.tls_cert_file,
                    key_file=cfg.tls_key_file,
                )
                if not cfg.tls_ca_file:
                    # A CA-issued leaf without tlsCaFile would be patched
                    # into caBundle as a trust root the apiserver cannot
                    # chain — with failurePolicy Fail that is a silent
                    # cluster-wide PCS write outage. Fail the boot instead.
                    _require_self_signed(cfg.tls_cert_file)
            else:
                self._webhook_tls_paths = ensure_serving_certs(
                    "auto",
                    _os.path.join(cfg.tls_cert_dir, "webhook"),
                    common_name="grove-tpu-webhook",
                    san_dns=tuple(cfg.webhook_sans),
                )
        return self._bind_server(port, _WebhookHandler, self._webhook_tls_paths)

    def operator_users(self) -> frozenset:
        """Identities the authorizer webhook treats as the reconciler
        (handler.go's reconcilerServiceAccountUserName): the in-process
        actor name plus the operator's own in-cluster ServiceAccount
        username (derived from the SA mount when running in a pod, else
        the deploy renderer's default namespace). Static for the process:
        computed once — this sits on the apiserver's failurePolicy-Fail
        admission path."""
        if self._operator_users is None:
            ns = "grove-system"
            try:
                with open(
                    "/var/run/secrets/kubernetes.io/serviceaccount/namespace"
                ) as f:
                    ns = f.read().strip() or ns
            except OSError:
                pass
            from grove_tpu.api.admission import OPERATOR_ACTOR

            self._operator_users = frozenset(
                {OPERATOR_ACTOR, f"system:serviceaccount:{ns}:grove-tpu-operator"}
            )
        return self._operator_users

    def webhook_ca_bundle(self) -> Optional[bytes]:
        """PEM bundle apiserver clients should trust for the webhook server
        — what the boot-time caBundle patch writes into the webhook configs.
        Auto mode: the self-signed serving cert doubles as the CA. Manual
        mode with a CA-issued cert: tlsCaFile names the issuing CA (a leaf
        installed as trust root verifies nothing); without it the manual
        cert is assumed self-signed."""
        if self._webhook_tls_paths is None:
            return None
        cfg = self.config.servers
        src = (
            cfg.tls_ca_file
            if cfg.tls_mode == "manual" and cfg.tls_ca_file
            else self._webhook_tls_paths[0]
        )
        try:
            with open(src, "rb") as f:
                return f.read()
        except OSError as e:
            # Must not escape: start() and the reconcile retry both treat
            # None as "still pending" — an uncaught raise here would kill
            # the run loop instead.
            self.log.error("webhook CA bundle unreadable", path=src, err=str(e))
            return None

    def reconcile_once(self, now: Optional[float] = None) -> FlowOutcome:
        """One full reconcile pass through the flow runner (testable unit).

        Steps mirror the reference's ordered component sync
        (podcliqueset/reconcilespec.go:206-221), expressed as flow.go steps;
        errors land in each PCS's status.last_errors via the recorder.
        """
        now = time.time() if now is None else now
        if self.watch is not None:
            # Same containment discipline as flow steps: a flaky watch source
            # or sidecar must degrade to a retry, never kill the run loop.
            try:
                self.watch.pump(now)
            except Exception as e:  # noqa: BLE001
                self._m_reconcile_errors.inc()
                self.log.error("watch pump failed", err=str(e))
        if self._webhook_ca_pending and self._kube_source is not None:
            # The rendered webhook configs carry failurePolicy Fail: until
            # the caBundle lands, every PCS write in the cluster bounces —
            # so the boot-time patch retries here until it succeeds (the
            # cert-controller rotator reconciles continuously; one-shot
            # best-effort would leave a cluster-wide outage behind an info
            # log).
            ca = self.webhook_ca_bundle()
            if ca is not None and self._kube_source.sync_webhook_ca(ca):
                self._webhook_ca_pending = False
                self.log.info("webhook caBundle patched")
        ctrl = self.controller

        def _timed(name, body):
            def run():
                t = time.perf_counter()
                try:
                    return body()
                finally:
                    rec = self._profile.setdefault(name, {"calls": 0, "seconds": 0.0, "last_seconds": 0.0})
                    dt = time.perf_counter() - t
                    rec["calls"] += 1
                    rec["seconds"] += dt
                    rec["last_seconds"] = dt

            return run

        def _step(name, fn):
            def body():
                fn(now)
                return continue_reconcile()

            return _timed(name, body)

        def _sync_workloads():
            """Expansion in parallel (slow-start, concurrentSyncs workers),
            store mutation serial — the store stays single-writer."""
            pcs_list = list(self.cluster.podcliquesets.values())
            workers = self.config.controllers.concurrent_syncs
            if workers > 1 and len(pcs_list) > 1:
                from random import Random

                from grove_tpu.utils.concurrent import run_concurrently_with_slow_start

                tasks = [
                    (lambda p=pcs: ctrl.compute_desired(p, rng=Random(hash(p.metadata.name) & 0xFFFF)))
                    for pcs in pcs_list
                ]
                results = run_concurrently_with_slow_start(
                    tasks, max_workers=workers, stop_on_error=False
                )
                # Apply every healthy expansion first — one poisoned PCS must
                # not starve the rest — then record failures WITHOUT stopping
                # the flow (continue_reconcile=True): solve/status/termination
                # must still run for the healthy PCSes this pass.
                errors = []
                for r in results:
                    if r.error is not None:
                        errors.append(
                            GroveError(
                                code="ERR_SYNC_RESOURCE",
                                operation="sync_workloads",
                                message=f"{pcs_list[r.index].metadata.name}: {r.error}",
                                cause=r.error,
                            )
                        )
                        continue
                    ctrl.sync_workload(pcs_list[r.index], now, desired=r.value)
                if errors:
                    return ReconcileStepResult(
                        continue_reconcile=True,
                        requeue_after_seconds=5.0,
                        errors=errors,
                    )
            else:
                for pcs in pcs_list:
                    ctrl.sync_workload(pcs, now)
            return continue_reconcile()

        def _solve():
            ctrl.solve_pending(now)
            return continue_reconcile()

        def _record(errors):
            msgs = [str(e) for e in errors]
            for pcs in self.cluster.podcliquesets.values():
                pcs.status.last_errors = list(msgs)

        t0 = time.perf_counter()
        def _autoscale(now=now):
            # metrics-server analog: utilization pushed to /api/v1/metrics
            # feeds the HPA objects; scale_overrides land in the NEXT
            # sync_workloads expansion (HPA -> scale subresource flow).
            # Consume-once: the ratio scales the CURRENT replica count, so
            # re-applying one stale push every tick would compound
            # geometrically to max/min replicas — each push is one
            # evaluation, like HPA refusing to act on stale metrics.
            if self.hpa_metrics:
                # Atomic swap, not copy-then-clear: an HTTP push landing
                # between the two would be 200-acknowledged yet discarded.
                self.hpa_metrics, metrics = {}, self.hpa_metrics
                ctrl.autoscale(metrics, now)
            return continue_reconcile()

        steps = [
            ("autoscale", _timed("autoscale", _autoscale)),
            ("sync_workloads", _timed("sync_workloads", _sync_workloads)),
            ("rolling_updates", _step("rolling_updates", ctrl.rolling_updates)),
            ("solve_pending", _timed("solve_pending", _solve)),
            ("update_statuses", _step("update_statuses", ctrl.update_statuses)),
            ("gang_termination", _step("gang_termination", ctrl.gang_termination)),
            # Defrag background loop (config section `defrag`): interval-
            # gated inside maybe_defrag, so this runs as a cheap no-op on
            # every other pass and a score/plan/execute cycle when due.
            ("defrag", _step("defrag", ctrl.maybe_defrag)),
        ]
        if self.trace_recorder is not None:
            # Trace flow step: nudge the writer to persist this pass's
            # records now — journal staleness is then bounded by the
            # reconcile cadence, not only the flush interval (a crashed
            # operator loses at most one pass of decisions).
            steps.append(
                ("trace", _step("trace", lambda _now: self.trace_recorder.request_flush()))
            )
        outcome = run_reconcile_flow(steps, error_recorder=_record)
        self._m_reconciles.inc()
        self._m_reconcile_seconds.observe(time.perf_counter() - t0)
        if outcome.has_errors:
            self._m_reconcile_errors.inc(len(outcome.errors))
            for e in outcome.errors:
                self.log.error("reconcile step failed", step=e.operation, err=str(e))
        # last_admission_scores is the ground truth of first admissions this
        # pass (both waves; solve_pending's int return counts the floors wave
        # only) — driving BOTH metrics from it keeps
        # grove_gangs_admitted_total == grove_placement_score_count by
        # construction, even when an extras wave first-admits a gang whose
        # floor was already met (stale-status edge).
        if ctrl.last_admission_scores:
            self._m_gangs_admitted.inc(len(ctrl.last_admission_scores))
            for score in ctrl.last_admission_scores:
                self._m_placement_score.observe(score)
            # Consume-once: a later pass that short-circuits before
            # solve_pending (which resets the list) must not re-observe.
            ctrl.last_admission_scores = []
        self._next_requeue = outcome.requeue_after_seconds
        for kind, count in self.controller.solve_pass_counts.items():
            delta = count - self._solve_passes_exported[kind]
            if delta > 0:
                self._m_solve_passes.inc(float(delta), kind=kind)
                self._solve_passes_exported[kind] = count
        if self.controller.defrag_enabled:
            last = self.controller.defrag_last
            if last:
                self._m_frag_score.set(float(last.get("score", 0.0)))
                for entry in last.get("report", {}).get("levels", []):
                    self._m_frag_stranded.set(
                        float(entry.get("stranded", 0.0)),
                        level=str(entry.get("level", "")),
                        resource=str(entry.get("resource", "")),
                    )
            self._m_defrag_migrating.set(
                float(len(self.controller._defrag_migrating))
            )
            counts = self.controller.defrag_counts
            for key, metric in (
                ("plans", self._m_defrag_plans),
                ("migrations", self._m_defrag_migrations),
                ("pods_migrated", self._m_defrag_pods),
            ):
                delta = counts[key] - self._defrag_exported[key]
                if delta > 0:
                    metric.inc(float(delta))
                    self._defrag_exported[key] = counts[key]
        for key, metric in (
            ("cutovers", self._m_rollout_cutovers),
            ("retries", self._m_rollout_retries),
            ("fallbacks", self._m_rollout_fallbacks),
        ):
            delta = self.controller.rollout_counts[key] - self._rollout_exported[key]
            if delta > 0:
                metric.inc(float(delta))
                self._rollout_exported[key] = self.controller.rollout_counts[key]
        self._m_rollout_replacing.set(
            float(len(self.controller._rollout_replacing))
        )
        for key, metric in (
            ("notices", self._m_revocation_notices),
            ("migrated", self._m_revocation_migrated),
            ("evicted", self._m_revocation_evicted),
        ):
            delta = (
                self.controller.revocation_counts[key]
                - self._revocation_exported[key]
            )
            if delta > 0:
                metric.inc(float(delta))
                self._revocation_exported[key] = self.controller.revocation_counts[key]
        if self.controller.tenancy_enabled:
            ledger = self.controller.tenancy_ledger
            for key, metric in (
                ("admitted", self._m_tenancy_admitted),
                ("admitted_borrowing", self._m_tenancy_borrowed),
                ("preemptions", self._m_tenancy_preemptions),
                ("reclaims", self._m_tenancy_reclaims),
                ("reclaim_deferred", self._m_tenancy_reclaim_deferred),
                ("aging_boosts", self._m_tenancy_aging),
            ):
                delta = ledger.totals[key] - self._tenancy_exported[key]
                if delta > 0:
                    metric.inc(float(delta))
                    self._tenancy_exported[key] = ledger.totals[key]
            self._m_tenancy_tenants.set(float(len(ledger.tenants)))
            self._m_tenancy_disrupted.set(
                float(self.controller.disrupted_now())
            )
        prune = self.controller.warm.prune
        self._m_candidate_nodes.set(float(prune.last_candidate_nodes))
        delta = prune.escalations - self._prune_escalations_exported
        if delta > 0:
            self._m_candidate_escalations.inc(float(delta))
            self._prune_escalations_exported = prune.escalations
        try:
            from grove_tpu.parallel.mesh import shard_fallbacks

            sf = shard_fallbacks()
            if sf > self._shard_fallbacks_exported:
                self._m_shard_fallbacks.inc(
                    float(sf - self._shard_fallbacks_exported)
                )
                self._shard_fallbacks_exported = sf
        except Exception:  # noqa: BLE001 — metrics must never break reconcile
            pass
        warm = self.controller.warm
        delta = warm.drain_device_roundtrips_total - self._roundtrips_exported
        if delta > 0:
            self._m_drain_roundtrips.inc(float(delta))
            self._roundtrips_exported = warm.drain_device_roundtrips_total
        delta = warm.drain_dispatches_total - self._dispatches_exported
        if delta > 0:
            self._m_drain_dispatches.inc(float(delta))
            self._dispatches_exported = warm.drain_dispatches_total
        if warm.last_stream:
            self._m_stream_depth.set(float(warm.last_stream.get("depth", 0)))
            self._m_stream_gps.set(
                float(warm.last_stream.get("gangsPerSec", 0.0))
            )
        # Host-stage ledger gauges, cut from the last recorded run (streams
        # take precedence when both surfaces are populated — the always-on
        # serving shape; drain_backlog fills last_drain in batch recovery).
        stage_src = warm.last_stream or warm.last_drain
        if stage_src:
            for stage, key in (
                ("encode", "hostEncodeS"),
                ("prefilter", "hostPrefilterS"),
                ("dispatch", "hostDispatchS"),
                ("harvest", "hostHarvestS"),
                ("decode", "hostDecodeS"),
                ("bind", "hostBindS"),
                ("journal", "hostJournalS"),
                ("total", "hostTotalS"),
                ("hotPath", "hostHotPathS"),
            ):
                if key in stage_src:
                    self._m_host_stage.set(
                        float(stage_src[key]), stage=stage
                    )
        samples = warm.stream_bind_samples
        if samples:
            # Drain-once: the deque is the warm path's hand-off buffer; each
            # sample lands in the histogram exactly once.
            while True:
                try:
                    self._m_stream_ttb.observe(samples.popleft())
                except IndexError:
                    break
        quality = self.controller.quality_last
        if quality:
            self._m_quality_admitted_ratio.set(
                float(quality.get("admittedRatio", 0.0))
            )
            self._m_quality_score.set(
                float(quality.get("meanPlacementScore", 0.0))
            )
            self._m_quality_pref.set(
                float(quality.get("preferredFraction", 0.0))
            )
        # Bounded-ring + flight-recorder counters (delta-exported, same
        # discipline as the solve-pass and defrag counters).
        delta = self.cluster.events_dropped - self._events_dropped_exported
        if delta > 0:
            self._m_events_dropped.inc(float(delta))
            self._events_dropped_exported = self.cluster.events_dropped
        if self.trace_recorder is not None:
            for key, metric in (
                ("recorded", self._m_trace_records),
                ("dropped", self._m_trace_dropped),
            ):
                cur = getattr(self.trace_recorder, key)
                delta = cur - self._trace_exported[key]
                if delta > 0:
                    metric.inc(float(delta))
                    self._trace_exported[key] = cur
        limiter = getattr(self._kube_source, "limiter", None)
        if limiter is not None:
            delta = limiter.throttled - self._kube_throttled_exported
            if delta > 0:
                self._m_kube_throttled.inc(float(delta))
                self._kube_throttled_exported = limiter.throttled
        # Failure-domain counters (ladder, injector, bind path, watch,
        # recorder) — delta-exported like every other monotonic source.
        if self.resilience_ladder is not None:
            for subsystem, counts in self.resilience_ladder.counters().items():
                prev = self._degradation_exported.setdefault(
                    subsystem, {"stepDowns": 0, "stepUps": 0}
                )
                for key, metric in (
                    ("stepDowns", self._m_degradation_down),
                    ("stepUps", self._m_degradation_up),
                ):
                    delta = counts[key] - prev[key]
                    if delta > 0:
                        metric.inc(float(delta), subsystem=subsystem)
                        prev[key] = counts[key]
        if self.fault_injector is not None:
            fired = self.fault_injector.total_fired()
            if fired > self._faults_exported:
                self._m_faults_injected.inc(float(fired - self._faults_exported))
                self._faults_exported = fired
        rc = self.controller.resilience_counts
        for key, metric in (
            ("bind_rollbacks", self._m_bind_rollbacks),
            ("stale_plan_requeues", self._m_stale_requeues),
            ("solve_degraded_retries", self._m_solve_degraded),
        ):
            delta = rc[key] - self._resilience_exported[key]
            if delta > 0:
                metric.inc(float(delta))
                self._resilience_exported[key] = rc[key]
        watch_stats = getattr(self._kube_source, "watch_stats", None)
        if watch_stats is not None:
            wstats = watch_stats()
            for key, metric in (
                ("reconnects", self._m_watch_reconnects),
                ("resyncs", self._m_watch_resyncs),
                ("bindRetries", self._m_bind_push_retries),
            ):
                delta = wstats[key] - self._watch_exported[key]
                if delta > 0:
                    metric.inc(float(delta))
                    self._watch_exported[key] = wstats[key]
        if self.trace_recorder is not None:
            we = self.trace_recorder.write_errors
            if we > self._recorder_write_errors_exported:
                self._m_recorder_write_errors.inc(
                    float(we - self._recorder_write_errors_exported)
                )
                self._recorder_write_errors_exported = we
        qtree = self.controller.queue_tree
        if qtree is not None:
            # Per-queue usage gauges (GREP-244 metrics direction): refreshed
            # per pass so /metrics mirrors the quota filter's view — every
            # tree level, usage hierarchical (a parent includes its
            # descendants). Every series ever emitted is re-set each pass
            # (zero when usage is gone) — gauges are persistent, so
            # skip-when-absent would freeze a drained queue at its last
            # nonzero value forever.
            husage = qtree.hierarchical_usage(self.controller.queue_usage())
            for qname, spec in qtree.specs.items():
                keys = set(spec.resources) | set(husage.get(qname, {}))
                self._queue_metric_keys.setdefault(qname, set()).update(keys)
                for rname in self._queue_metric_keys[qname]:
                    self._m_queue_used.set(
                        husage.get(qname, {}).get(rname, 0.0),
                        queue=qname,
                        resource=rname,
                    )
        if self.watch is not None:
            try:
                self.watch.push(now)
            except Exception as e:  # noqa: BLE001
                self._m_reconcile_errors.inc()
                self.log.error("watch push failed", err=str(e))
        if self.persistence is not None:
            self.persistence.maybe_snapshot(self.cluster, now)
        return outcome

    def run(self, stop_after_seconds: Optional[float] = None) -> None:
        """The hot loop: lease renewal + periodic reconcile until stopped."""
        self.start()
        cfg = self.config
        deadline = (
            time.time() + stop_after_seconds if stop_after_seconds is not None else None
        )
        while not self._stop.is_set():
            now = time.time()
            if deadline is not None and now >= deadline:
                break
            if self._lease is not None:
                self._is_leader = self._lease.try_acquire(now)
                self._m_leader.set(1.0 if self._is_leader else 0.0)
            if self.cell_leases is not None:
                # Per-cell renewal, one clock each: a cell that oversleeps
                # its renew deadline stands down alone, the others renew on.
                for cell_name in self.cell_plan.cells:
                    held = self.cell_leases.try_acquire(cell_name, now)
                    self._m_cell_lease_held.set(
                        1.0 if held else 0.0, cell=cell_name
                    )
            if self._is_leader:
                self.reconcile_once(now)
                interval = cfg.controllers.reconcile_interval_seconds
                if self._next_requeue is not None:
                    interval = min(interval, max(0.05, self._next_requeue))
            else:
                # Non-leaders retry acquisition on the retry period, not the
                # reconcile cadence (leaderElection.retryPeriodSeconds).
                interval = cfg.leader_election.retry_period_seconds
            self._stop.wait(interval)

    def stop(self) -> None:
        self._stop.set()
        if self.fault_injector is not None:
            # Clear the process-wide injector so a later manager (tests run
            # several per process) starts fault-free unless it asks.
            from grove_tpu import faults as faults_mod

            faults_mod.install(None)
        if self.trace_recorder is not None:
            # Final flush + join BEFORE servers go down, so a stop-triggered
            # journal read (tests, postmortems) sees every record.
            self.trace_recorder.stop()
        if getattr(self, "_prewarm_thread", None) is not None:
            self._prewarm_thread.join()
            self._prewarm_thread = None
        if self._kube_source is not None:
            self._kube_source.stop()
            self._kube_source = None
        if self._owned_backend_client is not None:
            self._owned_backend_client.close()
            self._owned_backend_client = None
        if self._backend_server is not None:
            self._backend_server.stop(grace=1.0)
        for server in self._http_servers:
            server.shutdown()
        if self._lease is not None:
            self._lease.release()
        if self.cell_leases is not None:
            self.cell_leases.release_all()
        if self.persistence is not None:
            self.persistence.snapshot(self.cluster)
        self.log.info("manager stopped")
