"""Control-plane persistence: snapshot/restore the whole store.

The reference survives operator restarts because every piece of control-plane
state lives in CR status in etcd — generation hashes and per-level
RollingUpdateProgress (`operator/api/core/v1alpha1/podcliqueset.go:96-118`,
`podclique.go:140-164`, `scalinggroup.go:106-129`), bindings as pod specs,
breach timestamps as conditions. This stack's store is in-memory, so the
manager snapshots it to disk (typed JSON via grove_tpu/utils/serde) and
restores on boot: a controller killed mid-rolling-update resumes exactly
where it stopped, one replica at a time.
"""

from __future__ import annotations

import json
from typing import Optional

from grove_tpu.api import pod as pod_mod
from grove_tpu.api import podgang as podgang_mod
from grove_tpu.api import resources as resources_mod
from grove_tpu.api import types as types_mod
from grove_tpu.orchestrator.store import Cluster
from grove_tpu.state import cluster as state_mod
from grove_tpu.utils import serde
from grove_tpu.utils.fsio import atomic_write_json

# v2: headless_services (derived set) replaced by typed aux-resource
# collections (services/hpas/service_accounts/roles/role_bindings/secrets).
SCHEMA_VERSION = 2

for _m in (types_mod, pod_mod, podgang_mod, state_mod, resources_mod):
    serde.register_module(_m)

# The store fields that constitute durable control-plane state. `events` is
# excluded deliberately: it is an unbounded diagnostic ring, not state the
# reconcile loop reads.
_STATE_FIELDS = (
    "nodes",
    "podcliquesets",
    "podcliques",
    "scaling_groups",
    "podgangs",
    "pods",
    "services",
    "hpas",
    "service_accounts",
    "roles",
    "role_bindings",
    "secrets",  # token material IS control-plane state (long-lived SA tokens)
    "scale_overrides",
)


def dump_cluster(cluster: Cluster) -> dict:
    return {
        "schema": SCHEMA_VERSION,
        **{f: serde.encode(getattr(cluster, f)) for f in _STATE_FIELDS},
    }


def load_cluster(doc: dict, into: Optional[Cluster] = None) -> Cluster:
    schema = doc.get("schema")
    if schema not in (1, SCHEMA_VERSION):
        raise ValueError(f"state schema {schema} not in (1, {SCHEMA_VERSION})")
    cluster = into if into is not None else Cluster()
    for f in _STATE_FIELDS:
        value = serde.decode(doc.get(f) or type(getattr(cluster, f))())
        if f == "pods":
            from grove_tpu.orchestrator.store import _PodDict

            value = _PodDict(value)  # restore the clique/gang indexes
        setattr(cluster, f, value)
    # v1 migration: aux-resource collections did not exist (loaded empty
    # above); the next sync_workload re-materializes them — including FRESH
    # SA tokens, so in-flight agents holding old credentials re-auth via
    # their next mount read, not via this restore.
    return cluster


class StatePersistence:
    """Atomic snapshot/restore of a Cluster at a filesystem path."""

    def __init__(self, path: str, snapshot_interval_seconds: float = 10.0):
        self.path = path
        self.snapshot_interval_seconds = snapshot_interval_seconds
        self._last_snapshot: float = float("-inf")

    def snapshot(self, cluster: Cluster) -> None:
        atomic_write_json(self.path, dump_cluster(cluster))

    def maybe_snapshot(self, cluster: Cluster, now: float) -> bool:
        if now - self._last_snapshot < self.snapshot_interval_seconds:
            return False
        self.snapshot(cluster)
        self._last_snapshot = now
        return True

    def restore(self, into: Cluster) -> bool:
        """Load state into the store; False when no snapshot exists yet."""
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except FileNotFoundError:
            return False
        load_cluster(doc, into=into)
        return True
