"""Operator runtime: configuration, reconcile flow, manager, boot path.

The analog of the reference's `operator/cmd` + `operator/internal/controller`
runtime layers (SURVEY.md §1 L2/L3): a validated YAML OperatorConfiguration
boots a manager that wires the store, the reconcile loop (typed step results,
requeue semantics), observability (leveled logging, metrics endpoint, health
probes), leader election, and optionally the scheduler-backend sidecar — all
from one config file.
"""

from grove_tpu.runtime.config import (
    OperatorConfiguration,
    load_operator_config,
    validate_operator_config,
)
from grove_tpu.runtime.flow import (
    ReconcileStepResult,
    continue_reconcile,
    reconcile_after,
    reconcile_with_errors,
    run_reconcile_flow,
    short_circuit,
)
from grove_tpu.runtime.manager import Manager

__all__ = [
    "Manager",
    "OperatorConfiguration",
    "ReconcileStepResult",
    "continue_reconcile",
    "load_operator_config",
    "reconcile_after",
    "reconcile_with_errors",
    "run_reconcile_flow",
    "short_circuit",
    "validate_operator_config",
]
