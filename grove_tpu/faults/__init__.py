"""Deterministic fault injection: named sites, seeded dice, journaled fires.

The chaos story of PRs 1-9 was whatever the sim tests happened to exercise
(kill_node between reconciles). This registry makes failure a FIRST-CLASS,
replayable input: code threads named *sites* through the stack —

  solver.dispatch    device dispatch raises (solver/drain._WavePipeline)
  solver.harvest     a dispatched wave hangs; the watchdog must recover
  bind.commit        the gang-bind commit fails mid-gang (controller)
  kube.request       the apiserver wire call returns 409/5xx (kubernetes.py)
  watch.disconnect   the watch stream drops (kubernetes.py reader loop)
  recorder.write     the journal segment write hits ENOSPC (trace/recorder)
  sim.node_death     schedulable chaos-script node kill (sim/simulator)
  sim.node_revocation  a revocable node gets a revocation notice with a
                     grace window (sim/simulator; spot capacity reclaim)
  cell.crash         a reconcile cell dies mid-stream; the replacement must
                     recover from its journal tail (cells/cell.py)
  cell.partition     the coordinator cannot reach a cell — cross-cell
                     borrow/reclaim routing defers (cells/coordinator.py)

— and an injector decides, per evaluation, whether the fault fires. The
decision is a pure function of (site seed, evaluation index): two runs with
the same spec see the SAME fault schedule regardless of thread interleaving
across sites, so a chaos soak is as replayable as the solver itself.

Gating: production code calls `active()`, which returns a disabled no-op
singleton unless an injector was installed from the `faults.*` config block
or the `GROVE_FAULTS` env override — the hot path pays one attribute check.
Every fire is counted per site and journaled to the flight recorder (when
one is attached) as an `action` record, so an incident trace shows the
injected fault right next to the recovery it provoked — the acceptance
contract is "every injected fault matched by a journaled action record".

GROVE_FAULTS syntax (env override, wins over config):

  GROVE_FAULTS="seed=7;solver.dispatch=error:0.5:3;recorder.write=enospc:1:2"

i.e. `;`-separated `site=kind:rate[:count[:after]]` entries plus an
optional `seed=N`. kind ∈ error|timeout|http409|http500|http503|enospc|
disconnect; rate is the per-evaluation fire probability; count caps total
fires (0 = unlimited); after skips the first N evaluations.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass

# Site kinds and the exception they surface as (see maybe_raise).
KINDS = ("error", "timeout", "http409", "http500", "http503", "enospc", "disconnect")

# The named sites threaded through the stack (docs/design.md site table).
SITES = (
    "solver.dispatch",
    "solver.harvest",
    "bind.commit",
    "kube.request",
    "watch.disconnect",
    "recorder.write",
    "sim.node_death",
    "sim.node_revocation",
    "cell.crash",
    "cell.partition",
)


class InjectedFault(RuntimeError):
    """An injected failure surfacing as a generic runtime error."""

    def __init__(self, site: str, kind: str = "error"):
        super().__init__(f"injected fault at {site} ({kind})")
        self.site = site
        self.kind = kind


@dataclass(frozen=True)
class SiteSpec:
    """One site's fault schedule (all fields validated by parse helpers)."""

    kind: str = "error"
    rate: float = 1.0  # per-evaluation fire probability
    count: int = 0  # max total fires; 0 = unlimited
    after: int = 0  # skip the first N evaluations (fault arrives "later")


class FaultInjector:
    """Seeded per-site dice + fire counters + journal hook.

    Thread-safe: sites are evaluated from the reconcile thread, the trace
    writer thread, and kube reader threads; each site's RNG stream is
    independent (seeded site-wise), so cross-site interleaving cannot
    change any site's schedule."""

    def __init__(
        self,
        specs: dict[str, SiteSpec] | None = None,
        *,
        seed: int = 0,
        recorder=None,  # trace.recorder.TraceRecorder (capture_action)
        clock=time.time,
    ) -> None:
        self.specs = dict(specs or {})
        self.seed = int(seed)
        self.recorder = recorder
        self.clock = clock
        self._lock = threading.Lock()
        self._rng: dict[str, random.Random] = {}
        self.evaluated: dict[str, int] = {}
        self.fired: dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.specs)

    def _site_rng(self, site: str) -> random.Random:
        rng = self._rng.get(site)
        if rng is None:
            # Site-wise derivation keeps each site's schedule independent of
            # every other site's evaluation count (deterministic under any
            # thread interleaving).
            rng = self._rng[site] = random.Random(f"{self.seed}:{site}")
        return rng

    def should_fire(self, site: str, **ctx) -> SiteSpec | None:
        """Evaluate one site; the spec when the fault fires, else None.
        A fire is counted AND journaled (action record `fault.injected`)."""
        spec = self.specs.get(site)
        if spec is None:
            return None
        with self._lock:
            n = self.evaluated.get(site, 0)
            self.evaluated[site] = n + 1
            if n < spec.after:
                return None
            if spec.count and self.fired.get(site, 0) >= spec.count:
                return None
            if spec.rate < 1.0 and self._site_rng(site).random() >= spec.rate:
                return None
            self.fired[site] = self.fired.get(site, 0) + 1
        if self.recorder is not None:
            try:
                self.recorder.capture_action(
                    self.clock(), "fault.injected", site, faultKind=spec.kind, **ctx
                )
            except Exception:  # noqa: BLE001 — injection must not need tracing
                pass
        return spec

    def maybe_raise(self, site: str, **ctx) -> None:
        """Raise the site's failure when its schedule fires (no-op spec-less).
        http* kinds raise whatever `exc_factory(status)` builds when the
        caller passes one in ctx (the kube client maps them to KubeApiError);
        everything else raises InjectedFault/OSError as appropriate."""
        exc_factory = ctx.pop("exc_factory", None)
        spec = self.should_fire(site, **ctx)
        if spec is None:
            return
        if spec.kind.startswith("http") and exc_factory is not None:
            raise exc_factory(int(spec.kind[4:]))
        if spec.kind == "enospc":
            raise OSError(28, f"injected ENOSPC at {site}")  # errno.ENOSPC
        if spec.kind == "disconnect":
            raise OSError(f"injected disconnect at {site}")
        raise InjectedFault(site, spec.kind)

    def maybe_timeout(self, site: str, **ctx) -> bool:
        """True when the site's schedule fires a simulated hang/timeout —
        the caller's watchdog path takes over (nothing is raised here)."""
        spec = self.should_fire(site, **ctx)
        return spec is not None and spec.kind in ("timeout", "error")

    def total_fired(self) -> int:
        with self._lock:
            return sum(self.fired.values())

    def stats(self) -> dict:
        """JSON-able injector state for /statusz resilience.faults."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "seed": self.seed,
                "sites": {
                    site: {
                        "kind": spec.kind,
                        "rate": spec.rate,
                        "count": spec.count,
                        "after": spec.after,
                        "evaluated": self.evaluated.get(site, 0),
                        "fired": self.fired.get(site, 0),
                    }
                    for site, spec in sorted(self.specs.items())
                },
            }


# Disabled singleton: the default `active()` result. Its specs dict is empty,
# so every evaluation is one dict miss — the hot-path cost of having fault
# sites compiled in at all.
_DISABLED = FaultInjector()
_active: FaultInjector = _DISABLED


def active() -> FaultInjector:
    """The process-wide injector (disabled no-op unless one was installed)."""
    return _active


def install(injector: FaultInjector | None) -> FaultInjector:
    """Install (or clear, with None) the process-wide injector; returns the
    now-active one. The manager calls this at boot from the faults config;
    tests install scoped injectors and clear them in teardown."""
    global _active
    _active = injector if injector is not None else _DISABLED
    return _active


def parse_spec_entry(site: str, doc) -> SiteSpec:
    """One config-block site entry ({kind, rate, count, after}) -> SiteSpec.
    Raises ValueError naming the field — config validation surfaces it."""
    if not isinstance(doc, dict):
        raise ValueError(f"{site}: must be a mapping")
    kind = doc.get("kind", "error")
    if kind not in KINDS:
        raise ValueError(f"{site}.kind: {kind!r} not in {'|'.join(KINDS)}")
    rate = doc.get("rate", 1.0)
    if not isinstance(rate, (int, float)) or isinstance(rate, bool) or not 0.0 <= float(rate) <= 1.0:
        raise ValueError(f"{site}.rate: must be a number in [0, 1]")
    count = doc.get("count", 0)
    after = doc.get("after", 0)
    for fname, v in (("count", count), ("after", after)):
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            raise ValueError(f"{site}.{fname}: must be an int >= 0")
    unknown = set(doc) - {"kind", "rate", "count", "after"}
    if unknown:
        raise ValueError(f"{site}: unknown field(s) {sorted(unknown)}")
    return SiteSpec(kind=kind, rate=float(rate), count=int(count), after=int(after))


def parse_env(value: str) -> tuple[dict[str, SiteSpec], int]:
    """GROVE_FAULTS string -> (specs, seed). See the module docstring for
    the syntax; raises ValueError on malformed entries (a typo'd chaos
    schedule silently not firing is the worst failure mode of a chaos rig)."""
    specs: dict[str, SiteSpec] = {}
    seed = 0
    for entry in value.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(f"GROVE_FAULTS: {entry!r} is not site=kind:rate[:count[:after]]")
        site, _, rhs = entry.partition("=")
        site = site.strip()
        if site == "seed":
            seed = int(rhs)
            continue
        parts = rhs.split(":")
        kind = parts[0] or "error"
        if kind not in KINDS:
            raise ValueError(f"GROVE_FAULTS: {site}: kind {kind!r} not in {'|'.join(KINDS)}")
        rate = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
        count = int(parts[2]) if len(parts) > 2 and parts[2] else 0
        after = int(parts[3]) if len(parts) > 3 and parts[3] else 0
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"GROVE_FAULTS: {site}: rate must be in [0, 1]")
        if count < 0 or after < 0:
            raise ValueError(f"GROVE_FAULTS: {site}: count/after must be >= 0")
        specs[site] = SiteSpec(kind=kind, rate=rate, count=count, after=after)
    return specs, seed


def from_config(cfg, *, recorder=None, env: str | None = None) -> FaultInjector | None:
    """Build the process injector from a runtime FaultsConfig, honoring the
    GROVE_FAULTS env override (env wins outright — an operator attaching a
    chaos schedule to a running config must not have to edit YAML). Returns
    None when injection is off both ways."""
    env = os.environ.get("GROVE_FAULTS", "") if env is None else env
    if env:
        specs, seed = parse_env(env)
        if specs:
            return FaultInjector(specs, seed=seed, recorder=recorder)
        return None
    if cfg is None or not getattr(cfg, "enabled", False):
        return None
    specs = {
        site: parse_spec_entry(site, doc) for site, doc in (cfg.sites or {}).items()
    }
    if not specs:
        return None
    return FaultInjector(specs, seed=int(cfg.seed), recorder=recorder)
