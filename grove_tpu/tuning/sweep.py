"""Batched config-sweep replay: score K solver configs from one trace pass.

Naive offline tuning replays the journal once per candidate config — K full
replays, K re-encodes, K solve dispatches per wave. This engine replays each
wave ONCE: the encode closure is rebuilt a single time from the wave record
(exactly as trace/replay.py does), and the K candidate weight vectors ride
the solver's existing variant axis (`core.stacked_solve_batch`, the same
vmap-over-SolverParams the portfolio path uses) through ONE warm-path AOT
executable keyed on (wave shape bucket, K). Per-config verdict planes come
back as a leading [K] axis and decode through the batched
`core.decode_bindings`.

Exactness contract (what lets sweep results be trusted as production
predictions): row k of the stacked solve is BITWISE-identical to a
single-config solve under config k — vmap batches the identical op sequence
(pinned in tests/test_tuning.py). Paths the stacked solve cannot express
bitwise fall back to the production `core.solve` for the affected row only:

  - portfolio > 1 configs (already multi-variant themselves),
  - portfolio-escalation rows (a row's base solve left valid gangs
    rejected and its config escalates — production would re-solve wider),
  - candidate-pruned rows whose lossy witness fired on a rejection
    (production re-solves dense before the rejection stands).

Those fallbacks run the exact code production runs, so every row's verdicts
equal what a plain single-config replay of the journal would produce — the
PR 4 contract extended to counterfactual configs. The row matching the
RECORDED solver fingerprint is additionally diffed against the journal's
plans: its divergence count is the replay-divergence gate (`trace replay`
exits 1 on it), surfaced so a sweep over a corrupt journal cannot quietly
recommend garbage.

Pruned waves journaled from the pipelined drain carry their candidate list;
the sweep rebuilds the exact gather (`pruning.plan_from_indices`) once and
shares it across all K rows — candidate selection is config-independent, so
the gather cost does not scale with K either.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from grove_tpu.solver.core import SolverParams, decode_bindings, solve
from grove_tpu.solver.encode import encode_gangs
from grove_tpu.trace.replay import diff_wave, nodes_from_fleet, snapshot_from_wave
from grove_tpu.utils import serde

_N_WEIGHTS = len(SolverParams._fields)


@dataclass(frozen=True)
class SweepConfig:
    """One candidate solver config in the sweep grid."""

    name: str
    weights: tuple  # floats, SolverParams field order
    portfolio: int = 1
    escalate_portfolio: int = 1

    def solver_params(self) -> SolverParams:
        return SolverParams(*(float(w) for w in self.weights))

    def matches_fingerprint(self, cfg: dict) -> bool:
        """True iff this config IS the recorded solver fingerprint — its
        sweep row must then reproduce the journal bitwise."""
        return (
            [float(w) for w in self.weights] == [float(w) for w in cfg["params"]]
            and self.portfolio == int(cfg["portfolio"])
            and self.escalate_portfolio == int(cfg["escalatePortfolio"])
        )

    def to_doc(self) -> dict:
        return {
            "name": self.name,
            "weights": {
                f: float(w) for f, w in zip(SolverParams._fields, self.weights)
            },
            "portfolio": self.portfolio,
            "escalatePortfolio": self.escalate_portfolio,
        }


def incumbent_config(records: list) -> SweepConfig:
    """The recorded solver fingerprint as a SweepConfig (from the first wave
    record — the journal's production config). Raises on a journal with no
    waves: there is nothing to tune against."""
    for rec in records:
        if rec.get("kind") == "wave":
            cfg = rec["solver"]
            return SweepConfig(
                name="incumbent",
                weights=tuple(float(w) for w in cfg["params"]),
                portfolio=int(cfg["portfolio"]),
                escalate_portfolio=int(cfg["escalatePortfolio"]),
            )
    raise ValueError("journal contains no wave records — nothing to sweep")


def default_grid(
    incumbent: SweepConfig,
    k: int,
    *,
    spread: float = 0.5,
    seed: int = 0,
) -> list[SweepConfig]:
    """K-config grid around the incumbent: row 0 is the incumbent itself
    (the safety baseline AND the replay-divergence probe), the rest are
    deterministic log-normal weight perturbations with packing-polarity
    diversity (odd rows flip w_tight's sign — the portfolio population's
    worst-fit trick, parallel/portfolio.py) and an escalation axis (every
    fourth row disables portfolio escalation, pricing the escalation knob
    against its admitted-ratio payoff)."""
    if k < 1:
        raise ValueError(f"grid size {k} < 1")
    rng = np.random.default_rng(seed)
    factors = np.exp(
        rng.normal(0.0, spread, size=(k, _N_WEIGHTS))
    ).astype(np.float64)
    factors[0, :] = 1.0
    base = np.asarray([float(w) for w in incumbent.weights], dtype=np.float64)
    stack = factors * base[None, :]
    tight_i = SolverParams._fields.index("w_tight")
    stack[1::2, tight_i] *= -1.0
    grid = [
        SweepConfig(
            name="incumbent",
            weights=incumbent.weights,
            portfolio=incumbent.portfolio,
            escalate_portfolio=incumbent.escalate_portfolio,
        )
    ]
    for i in range(1, k):
        esc = 1 if i % 4 == 3 else incumbent.escalate_portfolio
        grid.append(
            SweepConfig(
                name=f"cand-{i:02d}",
                weights=tuple(float(x) for x in stack[i]),
                portfolio=incumbent.portfolio,
                escalate_portfolio=esc,
            )
        )
    return grid


@dataclass
class ConfigTally:
    """One config's accumulated outcome over the waves it has seen."""

    config: SweepConfig
    waves: int = 0
    gangs: int = 0  # solver-valid gangs offered
    admitted: int = 0
    score_sum: float = 0.0  # placement score over admitted gangs
    solve_s: float = 0.0  # attributed share of the stacked wave cost
    escalations: int = 0  # production-semantics fallback rows (this config)
    divergences: int = 0  # vs recorded plans (fingerprint-matching rows only)
    # Per wave, in consumption order: (plan, ok_by_name, scores_by_name) —
    # retained for winner validation (bitwise vs a standalone replay).
    plans: list = field(default_factory=list)

    @property
    def admitted_ratio(self) -> float:
        return self.admitted / self.gangs if self.gangs else 0.0

    @property
    def mean_score(self) -> float:
        return self.score_sum / self.admitted if self.admitted else 0.0

    def rank_key(self) -> tuple:
        """Halving/winner order: admitted first (the gang contract), quality
        tie-break, then name for determinism."""
        return (self.admitted, self.score_sum, self.config.name)

    def to_doc(self) -> dict:
        return {
            "config": self.config.to_doc(),
            "waves": self.waves,
            "gangs": self.gangs,
            "admitted": self.admitted,
            "admittedRatio": round(self.admitted_ratio, 4),
            "meanPlacementScore": round(self.mean_score, 4),
            "solveSeconds": round(self.solve_s, 4),
            "escalations": self.escalations,
            "divergences": self.divergences,
        }


class SweepEngine:
    """Replays journal records once, scoring every active config per wave.

    Feed it record batches (whole journal, or segment-by-segment for the
    halving driver) via `consume`; drop losing configs between batches with
    `keep`. Fleet records are cached across batches, so segment-by-segment
    consumption works on flat record lists too."""

    def __init__(self, configs: list, *, warm_path=None) -> None:
        from grove_tpu.solver.warm import WarmPath

        if not configs:
            raise ValueError("sweep needs at least one config")
        names = [c.name for c in configs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate config names in grid: {names}")
        self.configs = list(configs)
        self.warm = warm_path if warm_path is not None else WarmPath()
        self.tallies: dict[str, ConfigTally] = {
            c.name: ConfigTally(c) for c in configs
        }
        self.waves_seen = 0
        self.stacked_solves = 0
        self.fallback_solves = 0  # production-semantics per-row re-solves
        self._fleets: dict[str, dict] = {}
        self._fleet_nodes: dict[str, list] = {}

    # ---- grid management ---------------------------------------------------

    def keep(self, names: set) -> None:
        """Restrict the active grid to `names` (halving): eliminated configs
        keep their tallies' aggregates for the report but stop accruing."""
        survivors = [c for c in self.configs if c.name in names]
        if not survivors:
            raise ValueError("halving eliminated every config")
        self.configs = survivors

    def _param_stack(self) -> SolverParams:
        stack = np.asarray(
            [[float(w) for w in c.weights] for c in self.configs],
            dtype=np.float32,
        )  # [K, W]
        return SolverParams(*(stack[:, i] for i in range(_N_WEIGHTS)))

    # ---- consumption -------------------------------------------------------

    def consume(self, records: list) -> None:
        """Process one batch of journal records (fleets + waves)."""
        for rec in records:
            kind = rec.get("kind")
            if kind == "fleet":
                self._fleets[rec["digest"]] = rec
                continue
            if kind != "wave":
                continue
            fleet = self._fleets.get(rec["fleet"])
            if fleet is None:
                raise ValueError(
                    f"wave {self.waves_seen} references fleet {rec['fleet']!r} "
                    "missing from this journal — cannot sweep (recorder drops? "
                    "check `grove-tpu trace info` recorderDropped)"
                )
            self._wave(rec, fleet)
            self.waves_seen += 1

    def _wave(self, rec: dict, fleet: dict) -> None:
        t0 = time.perf_counter()
        gangs = [serde.decode(d) for d in rec["gangs"]]
        pods = {n: serde.decode(d) for n, d in rec["pods"].items()}
        nodes = self._fleet_nodes.get(rec["fleet"])
        if nodes is None:
            nodes = self._fleet_nodes[rec["fleet"]] = nodes_from_fleet(fleet)
        snapshot = snapshot_from_wave(rec, fleet, nodes=nodes)
        cfg = rec["solver"]

        # One encode for all K rows — the same closure replay rebuilds.
        batch, decode = encode_gangs(
            gangs,
            pods,
            snapshot,
            max_groups=rec.get("maxGroups"),
            max_sets=rec.get("maxSets"),
            max_pods=rec.get("maxPods"),
            pad_gangs_to=rec.get("padGangsTo"),
            scheduled_gangs=set(rec.get("scheduled", [])),
            bound_nodes_by_group=rec.get("boundNodes") or None,
            reuse_nodes_by_gang=rec.get("reuseNodes") or None,
            spread_avoid_by_gang=rec.get("spreadAvoid") or None,
        )
        valid_np = np.asarray(batch.gang_valid, dtype=bool)

        free_override = None
        if rec.get("freeRows"):
            free_override = np.array(
                snapshot.capacity, dtype=np.float32, copy=True
            )
            for name, row in rec["freeRows"].items():
                if name in snapshot.node_index_map:
                    free_override[snapshot.node_index(name)] = np.asarray(
                        row, np.float32
                    )

        pruning = None
        pr = cfg.get("pruning")
        if pr and pr.get("enabled"):
            from grove_tpu.solver.pruning import PruningConfig

            pruning = PruningConfig(
                enabled=True,
                max_candidates=int(pr.get("maxCandidates", 8191)),
                pad_ladder=tuple(pr.get("padLadder", ())),
                min_pad=int(pr.get("minPad", 64)),
                min_fleet=int(pr.get("minFleet", 256)),
            )
        mesh_fp = cfg.get("mesh")

        rows = self._solve_rows(
            rec, snapshot, batch, valid_np, free_override, pruning, mesh_fp
        )
        elapsed = time.perf_counter() - t0

        per_cfg = elapsed / max(len(self.configs), 1)
        for config, (ok_row, assigned_row, score_row) in zip(self.configs, rows):
            plan = decode_bindings(ok_row, assigned_row, decode, snapshot)
            ok = dict(
                zip(decode.gang_names, (bool(x) for x in np.asarray(ok_row)))
            )
            scores = dict(
                zip(
                    decode.gang_names,
                    (float(x) for x in np.asarray(score_row)),
                )
            )
            tally = self.tallies[config.name]
            tally.waves += 1
            tally.gangs += int(valid_np.sum())
            ok_arr = np.asarray(ok_row, dtype=bool)[: len(decode.gang_names)]
            tally.admitted += int(ok_arr.sum())
            tally.score_sum += float(
                np.asarray(score_row)[: len(decode.gang_names)][ok_arr].sum()
            )
            tally.solve_s += per_cfg
            tally.plans.append((plan, ok, scores))
            if config.matches_fingerprint(cfg):
                tally.divergences += len(diff_wave(rec, plan, ok, scores))

    # ---- the per-wave K-row solve ------------------------------------------

    def _solve_rows(
        self, rec, snapshot, batch, valid_np, free_override, pruning, mesh_fp
    ) -> list:
        """One wave under every active config: [(ok [G], assigned [G, MP],
        score [G])] in config order, each row bitwise-equal to the
        production solve under that config."""
        import jax.numpy as jnp

        from grove_tpu.solver.encode import GangBatch

        cfg = rec["solver"]
        g = int(valid_np.shape[0])
        rows: list = [None] * len(self.configs)

        candidates = rec.get("candidates")
        if candidates is not None:
            # Recorded-candidate waves replay single-variant regardless of
            # portfolio (trace/replay.py's candidates branch does the same:
            # the recorded gather fixes the sub-fleet and the verdicts were
            # journaled post-escalation) — every row stacks.
            stackable = list(range(len(self.configs)))
        else:
            stackable = [
                i for i, c in enumerate(self.configs) if c.portfolio == 1
            ]
        pplan = None
        if candidates is not None and pruning is not None:
            # Pipelined pruned wave: rebuild the exact recorded gather once;
            # it is config-independent, so all K rows share it. Escalation is
            # moot (trace/replay.py): a wave whose dense re-solve changed a
            # verdict was journaled AS dense.
            from grove_tpu.solver.pruning import plan_from_indices

            pplan = plan_from_indices(
                snapshot,
                candidates,
                pruning,
                g,
                mesh_axis=int(mesh_fp.get("node", 1)) if mesh_fp else 1,
            )
        elif (
            pruning is not None
            and free_override is None
            and stackable
        ):
            # Snapshot-state pruned wave (controller path): re-cut the
            # candidate plan exactly as core.solve would — same inputs, same
            # plan — shared across rows. The recorded mesh fingerprint
            # negotiates the pad (executable shape identity with replay).
            from grove_tpu.solver.pruning import plan_candidates

            mesh_axis = 1
            if mesh_fp:
                from grove_tpu.parallel.mesh import layout_from_fingerprint

                layout = layout_from_fingerprint(
                    mesh_fp, int(np.asarray(snapshot.capacity).shape[0])
                )
                mesh_axis = layout.node_devices if layout is not None else 1
            pplan = plan_candidates(
                snapshot, batch, pruning, mesh_axis=mesh_axis
            )

        if stackable:
            pstack_full = self._param_stack()
            sel = np.asarray(stackable, dtype=np.int64)
            pstack = SolverParams(*(np.asarray(w)[sel] for w in pstack_full))
            free_np = (
                free_override
                if free_override is not None
                else np.asarray(snapshot.free, np.float32)
            )
            if pplan is not None:
                pbatch = pplan.gather_batch(batch)
                jpbatch = GangBatch(
                    *(None if x is None else jnp.asarray(x) for x in pbatch)
                )
                result = self.warm.executables.solve_stacked(
                    jnp.asarray(pplan.gather_free(free_np)),
                    jnp.asarray(pplan.capacity),
                    jnp.asarray(pplan.schedulable),
                    jnp.asarray(pplan.node_domain_id),
                    jpbatch,
                    pstack,
                    coarse_dmax=pplan.coarse_dmax(),
                )
                assigned_k = pplan.remap_assigned(np.asarray(result.assigned))
            else:
                from grove_tpu.solver.core import coarse_dmax_of

                jbatch = GangBatch(
                    *(None if x is None else jnp.asarray(x) for x in batch)
                )
                result = self.warm.executables.solve_stacked(
                    jnp.asarray(free_np),
                    jnp.asarray(snapshot.capacity),
                    jnp.asarray(snapshot.schedulable),
                    jnp.asarray(snapshot.node_domain_id),
                    jbatch,
                    pstack,
                    coarse_dmax=coarse_dmax_of(snapshot),
                )
                assigned_k = np.asarray(result.assigned)
            self.stacked_solves += 1
            ok_k = np.asarray(result.ok, dtype=bool)
            score_k = np.asarray(result.placement_score)
            recut_pruned = pplan is not None and candidates is None
            for j, i in enumerate(stackable):
                config = self.configs[i]
                needs_fallback = False
                if recut_pruned:
                    # Production would escalate a lossy pruned rejection to a
                    # dense re-solve; mirror it through core.solve itself.
                    from grove_tpu.solver.pruning import lossy_rejections

                    if lossy_rejections(pplan, valid_np, ok_k[j]).any():
                        needs_fallback = True
                if (
                    candidates is None
                    and config.escalate_portfolio > config.portfolio
                    and bool(np.any(valid_np & ~ok_k[j]))
                ):
                    # Portfolio escalation would fire in production.
                    needs_fallback = True
                if needs_fallback:
                    rows[i] = self._solve_row_production(
                        rec, snapshot, batch, free_override, pruning, config
                    )
                    tally = self.tallies[config.name]
                    tally.escalations += 1
                else:
                    rows[i] = (ok_k[j], assigned_k[j], score_k[j])

        for i, config in enumerate(self.configs):
            if rows[i] is None:
                # portfolio > 1 rows: already multi-variant, not stackable —
                # production semantics straight through core.solve.
                rows[i] = self._solve_row_production(
                    rec, snapshot, batch, free_override, pruning, config
                )
        return rows

    def _solve_row_production(
        self, rec, snapshot, batch, free_override, pruning, config: SweepConfig
    ):
        """The guaranteed-bitwise fallback: the production `core.solve` under
        this config, exactly as a standalone replay would run it (the
        candidates branch never lands here — see _solve_rows)."""
        self.fallback_solves += 1
        result = solve(
            snapshot,
            batch,
            config.solver_params(),
            free=free_override,
            portfolio=config.portfolio,
            escalate_portfolio=config.escalate_portfolio,
            warm=self.warm,
            pruning=pruning,
        )
        return (
            np.asarray(result.ok, dtype=bool),
            np.asarray(result.assigned),
            np.asarray(result.placement_score),
        )

    # ---- reporting ---------------------------------------------------------

    def to_doc(self) -> dict:
        ranked = sorted(
            self.tallies.values(), key=lambda t: t.rank_key(), reverse=True
        )
        return {
            "waves": self.waves_seen,
            "stackedSolves": self.stacked_solves,
            "fallbackSolves": self.fallback_solves,
            "configs": [t.to_doc() for t in ranked],
        }


def sweep_journal(
    records: list, configs: list, *, warm_path=None
) -> SweepEngine:
    """One-shot sweep of a whole journal under a fixed grid (no halving) —
    the what-if multi-override entry (trace/whatif.py)."""
    engine = SweepEngine(configs, warm_path=warm_path)
    engine.consume(records)
    return engine
