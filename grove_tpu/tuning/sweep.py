"""Batched config-sweep replay: score K solver configs from one trace pass.

Naive offline tuning replays the journal once per candidate config — K full
replays, K re-encodes, K solve dispatches per wave. This engine replays each
wave ONCE: the encode closure is rebuilt a single time from the wave record
(exactly as trace/replay.py does), and the K candidate weight vectors ride
the solver's existing variant axis (`core.stacked_solve_batch`, the same
vmap-over-SolverParams the portfolio path uses) through ONE warm-path AOT
executable keyed on (wave shape bucket, K). Per-config verdict planes come
back as a leading [K] axis and decode through the batched
`core.decode_bindings`.

Exactness contract (what lets sweep results be trusted as production
predictions): row k of the stacked solve is BITWISE-identical to a
single-config solve under config k — vmap batches the identical op sequence
(pinned in tests/test_tuning.py). Paths the stacked solve cannot express
bitwise fall back to the production `core.solve` for the affected row only:

  - portfolio > 1 configs (already multi-variant themselves),
  - portfolio-escalation rows (a row's base solve left valid gangs
    rejected and its config escalates — production would re-solve wider),
  - candidate-pruned rows whose lossy witness fired on a rejection
    (production re-solves dense before the rejection stands).

Those fallbacks run the exact code production runs, so every row's verdicts
equal what a plain single-config replay of the journal would produce — the
PR 4 contract extended to counterfactual configs. The row matching the
RECORDED solver fingerprint is additionally diffed against the journal's
plans: its divergence count is the replay-divergence gate (`trace replay`
exits 1 on it), surfaced so a sweep over a corrupt journal cannot quietly
recommend garbage.

Pruned waves journaled from the pipelined drain carry their candidate list;
the sweep rebuilds the exact gather (`pruning.plan_from_indices`) once and
shares it across all K rows — candidate selection is config-independent, so
the gather cost does not scale with K either.

Scanned-journal run batching: journals written by the scan/resident drain
disciplines are long runs of same-shape waves, each record carrying its
entering free (`freeRows`). Consecutive waves whose stacked-solve signature
matches (same fleet digest, resources, node pad, batch leaf shapes) are
dispatched as ONE `core.stacked_scan_solve_fn` executable — a device-side
scan over the wave axis of the K-stacked solve, each step replaying its
wave from the RECORDED entering free (no carry threads between steps, so
per-wave bitwise equality to the single-wave stacked solve is structural:
lax.scan runs the identical step computation on the identical inputs).
Run lengths pad to power-of-two with null waves (zero free, all-invalid
batch — admit nothing, score nothing), so a sweep over a scanned journal
pays O(log max_run) lowerings per shape class instead of one dispatch per
wave, keeping the whole sweep at ~one-replay cost.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from grove_tpu.solver.core import SolverParams, decode_bindings, solve
from grove_tpu.solver.encode import encode_gangs
from grove_tpu.trace.replay import diff_wave, nodes_from_fleet, snapshot_from_wave
from grove_tpu.utils import serde

_N_WEIGHTS = len(SolverParams._fields)

# Longest same-signature wave run dispatched as one stacked-scan executable.
# Runs pad to power-of-two lengths, so lowerings per shape class are bounded
# by log2 of this (the drain's warm_scan uses the same bucketing trick).
_MAX_RUN = 64


@dataclass(frozen=True)
class SweepConfig:
    """One candidate solver config in the sweep grid."""

    name: str
    weights: tuple  # floats, SolverParams field order
    portfolio: int = 1
    escalate_portfolio: int = 1

    def solver_params(self) -> SolverParams:
        return SolverParams(*(float(w) for w in self.weights))

    def matches_fingerprint(self, cfg: dict) -> bool:
        """True iff this config IS the recorded solver fingerprint — its
        sweep row must then reproduce the journal bitwise."""
        return (
            [float(w) for w in self.weights] == [float(w) for w in cfg["params"]]
            and self.portfolio == int(cfg["portfolio"])
            and self.escalate_portfolio == int(cfg["escalatePortfolio"])
        )

    def to_doc(self) -> dict:
        return {
            "name": self.name,
            "weights": {
                f: float(w) for f, w in zip(SolverParams._fields, self.weights)
            },
            "portfolio": self.portfolio,
            "escalatePortfolio": self.escalate_portfolio,
        }


def incumbent_config(records: list) -> SweepConfig:
    """The recorded solver fingerprint as a SweepConfig (from the first wave
    record — the journal's production config). Raises on a journal with no
    waves: there is nothing to tune against."""
    for rec in records:
        if rec.get("kind") == "wave":
            cfg = rec["solver"]
            return SweepConfig(
                name="incumbent",
                weights=tuple(float(w) for w in cfg["params"]),
                portfolio=int(cfg["portfolio"]),
                escalate_portfolio=int(cfg["escalatePortfolio"]),
            )
    raise ValueError("journal contains no wave records — nothing to sweep")


def default_grid(
    incumbent: SweepConfig,
    k: int,
    *,
    spread: float = 0.5,
    seed: int = 0,
) -> list[SweepConfig]:
    """K-config grid around the incumbent: row 0 is the incumbent itself
    (the safety baseline AND the replay-divergence probe), the rest are
    deterministic log-normal weight perturbations with packing-polarity
    diversity (odd rows flip w_tight's sign — the portfolio population's
    worst-fit trick, parallel/portfolio.py) and an escalation axis (every
    fourth row disables portfolio escalation, pricing the escalation knob
    against its admitted-ratio payoff)."""
    if k < 1:
        raise ValueError(f"grid size {k} < 1")
    rng = np.random.default_rng(seed)
    factors = np.exp(
        rng.normal(0.0, spread, size=(k, _N_WEIGHTS))
    ).astype(np.float64)
    factors[0, :] = 1.0
    base = np.asarray([float(w) for w in incumbent.weights], dtype=np.float64)
    stack = factors * base[None, :]
    tight_i = SolverParams._fields.index("w_tight")
    stack[1::2, tight_i] *= -1.0
    grid = [
        SweepConfig(
            name="incumbent",
            weights=incumbent.weights,
            portfolio=incumbent.portfolio,
            escalate_portfolio=incumbent.escalate_portfolio,
        )
    ]
    for i in range(1, k):
        esc = 1 if i % 4 == 3 else incumbent.escalate_portfolio
        grid.append(
            SweepConfig(
                name=f"cand-{i:02d}",
                weights=tuple(float(x) for x in stack[i]),
                portfolio=incumbent.portfolio,
                escalate_portfolio=esc,
            )
        )
    return grid


@dataclass
class ConfigTally:
    """One config's accumulated outcome over the waves it has seen."""

    config: SweepConfig
    waves: int = 0
    gangs: int = 0  # solver-valid gangs offered
    admitted: int = 0
    score_sum: float = 0.0  # placement score over admitted gangs
    solve_s: float = 0.0  # attributed share of the stacked wave cost
    escalations: int = 0  # production-semantics fallback rows (this config)
    divergences: int = 0  # vs recorded plans (fingerprint-matching rows only)
    # Per wave, in consumption order: (plan, ok_by_name, scores_by_name) —
    # retained for winner validation (bitwise vs a standalone replay).
    plans: list = field(default_factory=list)

    @property
    def admitted_ratio(self) -> float:
        return self.admitted / self.gangs if self.gangs else 0.0

    @property
    def mean_score(self) -> float:
        return self.score_sum / self.admitted if self.admitted else 0.0

    def rank_key(self) -> tuple:
        """Halving/winner order: admitted first (the gang contract), quality
        tie-break, then name for determinism."""
        return (self.admitted, self.score_sum, self.config.name)

    def to_doc(self) -> dict:
        return {
            "config": self.config.to_doc(),
            "waves": self.waves,
            "gangs": self.gangs,
            "admitted": self.admitted,
            "admittedRatio": round(self.admitted_ratio, 4),
            "meanPlacementScore": round(self.mean_score, 4),
            "solveSeconds": round(self.solve_s, 4),
            "escalations": self.escalations,
            "divergences": self.divergences,
        }


@dataclass
class _WavePrep:
    """One wave record's host-side preparation (encode + snapshot rebuild),
    done exactly once whether the wave solves alone or inside a stacked-scan
    run."""

    rec: dict
    snapshot: object
    cfg: dict
    batch: object  # GangBatch (numpy leaves)
    decode: object
    valid_np: np.ndarray
    free_override: object  # np [N, R] | None
    free_np: np.ndarray  # entering free actually solved from
    pruning: object  # PruningConfig | None
    mesh_fp: object
    prep_s: float  # host seconds spent building this prep


class SweepEngine:
    """Replays journal records once, scoring every active config per wave.

    Feed it record batches (whole journal, or segment-by-segment for the
    halving driver) via `consume`; drop losing configs between batches with
    `keep`. Fleet records are cached across batches, so segment-by-segment
    consumption works on flat record lists too."""

    def __init__(self, configs: list, *, warm_path=None) -> None:
        from grove_tpu.solver.warm import WarmPath

        if not configs:
            raise ValueError("sweep needs at least one config")
        names = [c.name for c in configs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate config names in grid: {names}")
        self.configs = list(configs)
        self.warm = warm_path if warm_path is not None else WarmPath()
        self.tallies: dict[str, ConfigTally] = {
            c.name: ConfigTally(c) for c in configs
        }
        self.waves_seen = 0
        self.stacked_solves = 0
        self.scan_stacked_solves = 0  # same-shape wave runs scanned as one
        self.fallback_solves = 0  # production-semantics per-row re-solves
        self._fleets: dict[str, dict] = {}
        self._fleet_nodes: dict[str, list] = {}

    # ---- grid management ---------------------------------------------------

    def keep(self, names: set) -> None:
        """Restrict the active grid to `names` (halving): eliminated configs
        keep their tallies' aggregates for the report but stop accruing."""
        survivors = [c for c in self.configs if c.name in names]
        if not survivors:
            raise ValueError("halving eliminated every config")
        self.configs = survivors

    def _param_stack(self) -> SolverParams:
        stack = np.asarray(
            [[float(w) for w in c.weights] for c in self.configs],
            dtype=np.float32,
        )  # [K, W]
        return SolverParams(*(stack[:, i] for i in range(_N_WEIGHTS)))

    # ---- consumption -------------------------------------------------------

    def consume(self, records: list) -> None:
        """Process one batch of journal records (fleets + waves).

        Consecutive wave records with matching stacked-solve signatures
        (scanned-journal runs) buffer and dispatch as one device-side
        stacked-scan executable; a signature break, a run reaching _MAX_RUN,
        or the end of the batch flushes. Fleet records never split a run —
        the signature carries the fleet digest, so a digest change breaks it
        anyway. Runs never span consume() calls: the halving driver may
        keep() between batches, which changes the param stack."""
        run: list[_WavePrep] = []
        run_sig = None

        def flush() -> None:
            nonlocal run, run_sig
            if len(run) >= 2:
                self._wave_run(run)
            elif run:
                self._wave_single(run[0])
            run = []
            run_sig = None

        for rec in records:
            kind = rec.get("kind")
            if kind == "fleet":
                self._fleets[rec["digest"]] = rec
                continue
            if kind != "wave":
                continue
            fleet = self._fleets.get(rec["fleet"])
            if fleet is None:
                raise ValueError(
                    f"wave {self.waves_seen} references fleet {rec['fleet']!r} "
                    "missing from this journal — cannot sweep (recorder drops? "
                    "check `grove-tpu trace info` recorderDropped)"
                )
            prep = self._prep_wave(rec, fleet)
            self.waves_seen += 1
            sig = self._run_sig(prep)
            if sig is None:
                flush()
                self._wave_single(prep)
                continue
            if run and sig != run_sig:
                flush()
            run.append(prep)
            run_sig = sig
            if len(run) >= _MAX_RUN:
                flush()
        flush()

    def _run_sig(self, prep: _WavePrep):
        """Stacked-scan run signature, or None when the wave cannot join a
        run. Eligible waves are exactly the dense stacked-solve path:
        recorded-candidate waves need their per-wave gather, and
        snapshot-state pruned waves re-cut a candidate plan from the
        entering free (per-wave by construction). Two waves with equal
        signatures rebuild identical capacity/schedulable/node_domain_id
        (same fleet digest + resources + node pad -> same build_snapshot
        inputs) and stack on the wave axis leaf-for-leaf."""
        if prep.rec.get("candidates") is not None:
            return None
        if prep.pruning is not None and prep.free_override is None:
            return None
        leaves = tuple(
            None
            if x is None
            else (tuple(np.shape(x)), str(np.asarray(x).dtype))
            for x in prep.batch
        )
        return (
            prep.rec["fleet"],
            tuple(prep.rec["resources"]),
            prep.rec["padNodesTo"],
            leaves,
        )

    def _prep_wave(self, rec: dict, fleet: dict) -> _WavePrep:
        t0 = time.perf_counter()
        gangs = [serde.decode(d) for d in rec["gangs"]]
        pods = {n: serde.decode(d) for n, d in rec["pods"].items()}
        nodes = self._fleet_nodes.get(rec["fleet"])
        if nodes is None:
            nodes = self._fleet_nodes[rec["fleet"]] = nodes_from_fleet(fleet)
        snapshot = snapshot_from_wave(rec, fleet, nodes=nodes)
        cfg = rec["solver"]

        # One encode for all K rows — the same closure replay rebuilds.
        batch, decode = encode_gangs(
            gangs,
            pods,
            snapshot,
            max_groups=rec.get("maxGroups"),
            max_sets=rec.get("maxSets"),
            max_pods=rec.get("maxPods"),
            pad_gangs_to=rec.get("padGangsTo"),
            scheduled_gangs=set(rec.get("scheduled", [])),
            bound_nodes_by_group=rec.get("boundNodes") or None,
            reuse_nodes_by_gang=rec.get("reuseNodes") or None,
            spread_avoid_by_gang=rec.get("spreadAvoid") or None,
        )
        valid_np = np.asarray(batch.gang_valid, dtype=bool)

        free_override = None
        if rec.get("freeRows"):
            free_override = np.array(
                snapshot.capacity, dtype=np.float32, copy=True
            )
            for name, row in rec["freeRows"].items():
                if name in snapshot.node_index_map:
                    free_override[snapshot.node_index(name)] = np.asarray(
                        row, np.float32
                    )

        pruning = None
        pr = cfg.get("pruning")
        if pr and pr.get("enabled"):
            from grove_tpu.solver.pruning import PruningConfig

            pruning = PruningConfig(
                enabled=True,
                max_candidates=int(pr.get("maxCandidates", 8191)),
                pad_ladder=tuple(pr.get("padLadder", ())),
                min_pad=int(pr.get("minPad", 64)),
                min_fleet=int(pr.get("minFleet", 256)),
            )
        mesh_fp = cfg.get("mesh")
        free_np = (
            free_override
            if free_override is not None
            else np.asarray(snapshot.free, np.float32)
        )
        return _WavePrep(
            rec=rec,
            snapshot=snapshot,
            cfg=cfg,
            batch=batch,
            decode=decode,
            valid_np=valid_np,
            free_override=free_override,
            free_np=free_np,
            pruning=pruning,
            mesh_fp=mesh_fp,
            prep_s=time.perf_counter() - t0,
        )

    def _wave_single(self, prep: _WavePrep) -> None:
        t0 = time.perf_counter()
        rows = self._solve_rows(
            prep.rec, prep.snapshot, prep.batch, prep.valid_np,
            prep.free_override, prep.pruning, prep.mesh_fp,
        )
        self._tally(prep, rows, prep.prep_s + time.perf_counter() - t0)

    def _tally(self, prep: _WavePrep, rows: list, elapsed: float) -> None:
        rec, snapshot, decode = prep.rec, prep.snapshot, prep.decode
        valid_np, cfg = prep.valid_np, prep.cfg
        per_cfg = elapsed / max(len(self.configs), 1)
        for config, (ok_row, assigned_row, score_row) in zip(self.configs, rows):
            plan = decode_bindings(ok_row, assigned_row, decode, snapshot)
            ok = dict(
                zip(decode.gang_names, (bool(x) for x in np.asarray(ok_row)))
            )
            scores = dict(
                zip(
                    decode.gang_names,
                    (float(x) for x in np.asarray(score_row)),
                )
            )
            tally = self.tallies[config.name]
            tally.waves += 1
            tally.gangs += int(valid_np.sum())
            ok_arr = np.asarray(ok_row, dtype=bool)[: len(decode.gang_names)]
            tally.admitted += int(ok_arr.sum())
            tally.score_sum += float(
                np.asarray(score_row)[: len(decode.gang_names)][ok_arr].sum()
            )
            tally.solve_s += per_cfg
            tally.plans.append((plan, ok, scores))
            if config.matches_fingerprint(cfg):
                tally.divergences += len(diff_wave(rec, plan, ok, scores))

    # ---- the stacked-scan run solve ----------------------------------------

    def _wave_run(self, run: list) -> None:
        """A same-signature run of journaled waves under every active config,
        solved as ONE device-side scan over the wave axis
        (warm.solve_scan_stacked). Each scan step replays its wave from the
        RECORDED entering free with no carry between steps, so row (w, k) is
        bitwise what _wave_single's stacked solve produces for wave w —
        the per-wave escalation fallbacks apply unchanged afterwards."""
        import jax.numpy as jnp

        from grove_tpu.solver.core import coarse_dmax_of
        from grove_tpu.solver.encode import GangBatch

        t0 = time.perf_counter()
        w_real = len(run)
        rows_by_wave: list = [[None] * len(self.configs) for _ in run]
        stackable = [i for i, c in enumerate(self.configs) if c.portfolio == 1]
        if stackable:
            # Power-of-two run-length bucket, padded with null waves (zero
            # free, all-invalid batch): a null step admits nothing and there
            # is no carry to disturb, so padded rows are simply discarded.
            w_pad = 1 << (w_real - 1).bit_length()

            def stack(arrs, dtype=None):
                out = np.stack([np.asarray(a) for a in arrs])
                if dtype is not None:
                    out = out.astype(dtype, copy=False)
                if w_pad > w_real:
                    out = np.concatenate(
                        [
                            out,
                            np.zeros(
                                (w_pad - w_real,) + out.shape[1:], out.dtype
                            ),
                        ]
                    )
                return out

            pstack_full = self._param_stack()
            sel = np.asarray(stackable, dtype=np.int64)
            pstack = SolverParams(*(np.asarray(w)[sel] for w in pstack_full))
            free_stack = stack([p.free_np for p in run], np.float32)
            sbatch = GangBatch(
                *(
                    None
                    if leaf0 is None
                    else jnp.asarray(stack([p.batch[i] for p in run]))
                    for i, leaf0 in enumerate(run[0].batch)
                )
            )
            snapshot = run[0].snapshot
            result = self.warm.executables.solve_scan_stacked(
                jnp.asarray(free_stack),
                jnp.asarray(snapshot.capacity),
                jnp.asarray(snapshot.schedulable),
                jnp.asarray(snapshot.node_domain_id),
                sbatch,
                pstack,
                coarse_dmax=coarse_dmax_of(snapshot),
            )
            self.scan_stacked_solves += 1
            ok_wk = np.asarray(result.ok, dtype=bool)
            assigned_wk = np.asarray(result.assigned)
            score_wk = np.asarray(result.placement_score)
            for w, prep in enumerate(run):
                for j, i in enumerate(stackable):
                    config = self.configs[i]
                    if config.escalate_portfolio > config.portfolio and bool(
                        np.any(prep.valid_np & ~ok_wk[w, j])
                    ):
                        # Portfolio escalation would fire in production —
                        # same per-row fallback the single-wave path takes.
                        rows_by_wave[w][i] = self._solve_row_production(
                            prep.rec, prep.snapshot, prep.batch,
                            prep.free_override, prep.pruning, config,
                        )
                        self.tallies[config.name].escalations += 1
                    else:
                        rows_by_wave[w][i] = (
                            ok_wk[w, j], assigned_wk[w, j], score_wk[w, j]
                        )
        for w, prep in enumerate(run):
            for i, config in enumerate(self.configs):
                if rows_by_wave[w][i] is None:
                    rows_by_wave[w][i] = self._solve_row_production(
                        prep.rec, prep.snapshot, prep.batch,
                        prep.free_override, prep.pruning, config,
                    )
        solve_s = (time.perf_counter() - t0) / w_real
        for w, prep in enumerate(run):
            self._tally(prep, rows_by_wave[w], prep.prep_s + solve_s)

    # ---- the per-wave K-row solve ------------------------------------------

    def _solve_rows(
        self, rec, snapshot, batch, valid_np, free_override, pruning, mesh_fp
    ) -> list:
        """One wave under every active config: [(ok [G], assigned [G, MP],
        score [G])] in config order, each row bitwise-equal to the
        production solve under that config."""
        import jax.numpy as jnp

        from grove_tpu.solver.encode import GangBatch

        cfg = rec["solver"]
        g = int(valid_np.shape[0])
        rows: list = [None] * len(self.configs)

        candidates = rec.get("candidates")
        if candidates is not None:
            # Recorded-candidate waves replay single-variant regardless of
            # portfolio (trace/replay.py's candidates branch does the same:
            # the recorded gather fixes the sub-fleet and the verdicts were
            # journaled post-escalation) — every row stacks.
            stackable = list(range(len(self.configs)))
        else:
            stackable = [
                i for i, c in enumerate(self.configs) if c.portfolio == 1
            ]
        pplan = None
        if candidates is not None and pruning is not None:
            # Pipelined pruned wave: rebuild the exact recorded gather once;
            # it is config-independent, so all K rows share it. Escalation is
            # moot (trace/replay.py): a wave whose dense re-solve changed a
            # verdict was journaled AS dense.
            from grove_tpu.solver.pruning import plan_from_indices

            pplan = plan_from_indices(
                snapshot,
                candidates,
                pruning,
                g,
                mesh_axis=int(mesh_fp.get("node", 1)) if mesh_fp else 1,
            )
        elif (
            pruning is not None
            and free_override is None
            and stackable
        ):
            # Snapshot-state pruned wave (controller path): re-cut the
            # candidate plan exactly as core.solve would — same inputs, same
            # plan — shared across rows. The recorded mesh fingerprint
            # negotiates the pad (executable shape identity with replay).
            from grove_tpu.solver.pruning import plan_candidates

            mesh_axis = 1
            if mesh_fp:
                from grove_tpu.parallel.mesh import layout_from_fingerprint

                layout = layout_from_fingerprint(
                    mesh_fp, int(np.asarray(snapshot.capacity).shape[0])
                )
                mesh_axis = layout.node_devices if layout is not None else 1
            pplan = plan_candidates(
                snapshot, batch, pruning, mesh_axis=mesh_axis
            )

        if stackable:
            pstack_full = self._param_stack()
            sel = np.asarray(stackable, dtype=np.int64)
            pstack = SolverParams(*(np.asarray(w)[sel] for w in pstack_full))
            free_np = (
                free_override
                if free_override is not None
                else np.asarray(snapshot.free, np.float32)
            )
            if pplan is not None:
                pbatch = pplan.gather_batch(batch)
                jpbatch = GangBatch(
                    *(None if x is None else jnp.asarray(x) for x in pbatch)
                )
                result = self.warm.executables.solve_stacked(
                    jnp.asarray(pplan.gather_free(free_np)),
                    jnp.asarray(pplan.capacity),
                    jnp.asarray(pplan.schedulable),
                    jnp.asarray(pplan.node_domain_id),
                    jpbatch,
                    pstack,
                    coarse_dmax=pplan.coarse_dmax(),
                )
                assigned_k = pplan.remap_assigned(np.asarray(result.assigned))
            else:
                from grove_tpu.solver.core import coarse_dmax_of

                jbatch = GangBatch(
                    *(None if x is None else jnp.asarray(x) for x in batch)
                )
                result = self.warm.executables.solve_stacked(
                    jnp.asarray(free_np),
                    jnp.asarray(snapshot.capacity),
                    jnp.asarray(snapshot.schedulable),
                    jnp.asarray(snapshot.node_domain_id),
                    jbatch,
                    pstack,
                    coarse_dmax=coarse_dmax_of(snapshot),
                )
                assigned_k = np.asarray(result.assigned)
            self.stacked_solves += 1
            ok_k = np.asarray(result.ok, dtype=bool)
            score_k = np.asarray(result.placement_score)
            recut_pruned = pplan is not None and candidates is None
            for j, i in enumerate(stackable):
                config = self.configs[i]
                needs_fallback = False
                if recut_pruned:
                    # Production would escalate a lossy pruned rejection to a
                    # dense re-solve; mirror it through core.solve itself.
                    from grove_tpu.solver.pruning import lossy_rejections

                    if lossy_rejections(pplan, valid_np, ok_k[j]).any():
                        needs_fallback = True
                if (
                    candidates is None
                    and config.escalate_portfolio > config.portfolio
                    and bool(np.any(valid_np & ~ok_k[j]))
                ):
                    # Portfolio escalation would fire in production.
                    needs_fallback = True
                if needs_fallback:
                    rows[i] = self._solve_row_production(
                        rec, snapshot, batch, free_override, pruning, config
                    )
                    tally = self.tallies[config.name]
                    tally.escalations += 1
                else:
                    rows[i] = (ok_k[j], assigned_k[j], score_k[j])

        for i, config in enumerate(self.configs):
            if rows[i] is None:
                # portfolio > 1 rows: already multi-variant, not stackable —
                # production semantics straight through core.solve.
                rows[i] = self._solve_row_production(
                    rec, snapshot, batch, free_override, pruning, config
                )
        return rows

    def _solve_row_production(
        self, rec, snapshot, batch, free_override, pruning, config: SweepConfig
    ):
        """The guaranteed-bitwise fallback: the production `core.solve` under
        this config, exactly as a standalone replay would run it (the
        candidates branch never lands here — see _solve_rows)."""
        self.fallback_solves += 1
        result = solve(
            snapshot,
            batch,
            config.solver_params(),
            free=free_override,
            portfolio=config.portfolio,
            escalate_portfolio=config.escalate_portfolio,
            warm=self.warm,
            pruning=pruning,
        )
        return (
            np.asarray(result.ok, dtype=bool),
            np.asarray(result.assigned),
            np.asarray(result.placement_score),
        )

    # ---- reporting ---------------------------------------------------------

    def to_doc(self) -> dict:
        ranked = sorted(
            self.tallies.values(), key=lambda t: t.rank_key(), reverse=True
        )
        return {
            "waves": self.waves_seen,
            "stackedSolves": self.stacked_solves,
            "scanStackedSolves": self.scan_stacked_solves,
            "fallbackSolves": self.fallback_solves,
            "configs": [t.to_doc() for t in ranked],
        }


def sweep_journal(
    records: list, configs: list, *, warm_path=None
) -> SweepEngine:
    """One-shot sweep of a whole journal under a fixed grid (no halving) —
    the what-if multi-override entry (trace/whatif.py)."""
    engine = SweepEngine(configs, warm_path=warm_path)
    engine.consume(records)
    return engine
