"""Successive-halving search over a solver-config grid, from one trace.

The sweep engine (tuning/sweep.py) makes scoring K configs cost ~one replay;
halving makes the K axis SHRINK while the trace plays: after each rung (a
contiguous chunk of the journal's segments) the bottom half of the surviving
grid is dropped, so late segments — where per-wave cost is K-proportional on
the stacked axis — run at K/2, K/4, ... The incumbent (recorded) config
never halves away: it is the safety baseline every candidate must beat AND
the replay-divergence probe (its row must reproduce the journal bitwise).

The winner is validated two ways before it is recommended:

1. **Bitwise replay agreement** (the PR 4 contract, extended): the winner's
   sweep-row plans must equal a plain single-config replay of the same
   journal under the winner config — a sweep solve that diverges from the
   production solve is a bug, not a recommendation.
2. **Exact-reference audit** (quality/audit.py): the winner's admitted ratio
   against the exact branch-and-bound optimum on the seeded tier-1 audit
   instances must be >= the incumbent's — tuning cannot trade admitted
   ratio for placement score.

A recommendation that fails either gate is emitted with `"valid": false`
and the failing gate named; callers (the `tune sweep` CLI, `make
bench-sweep`) treat that as exit 1.
"""

from __future__ import annotations

import math

from grove_tpu.trace.replay import diff_wave, snapshot_from_wave, solve_wave_record
from grove_tpu.tuning.sweep import (
    SweepConfig,
    SweepEngine,
    default_grid,
    incumbent_config,
)


def _wave_count(records: list) -> int:
    return sum(1 for r in records if r.get("kind") == "wave")


def _chunk_records(records: list, rungs: int) -> list[list]:
    """Split a flat record list into `rungs` contiguous chunks of roughly
    equal WAVE counts (fleet records ride with the chunk they precede; the
    engine caches fleets across chunks, so boundaries are safe)."""
    total = _wave_count(records)
    if total == 0:
        raise ValueError("journal contains no wave records — nothing to sweep")
    rungs = max(1, min(rungs, total))
    per = math.ceil(total / rungs)
    chunks: list[list] = [[]]
    waves_in_chunk = 0
    for rec in records:
        if waves_in_chunk >= per and rec.get("kind") == "wave" and len(chunks) < rungs:
            chunks.append([])
            waves_in_chunk = 0
        chunks[-1].append(rec)
        if rec.get("kind") == "wave":
            waves_in_chunk += 1
    return chunks


def successive_halving(
    records: list,
    grid: list,
    *,
    rungs: int = 3,
    min_configs: int = 2,
    warm_path=None,
) -> tuple[SweepEngine, list]:
    """Sweep `records` under `grid`, halving the surviving set between
    rungs. Returns (engine, schedule) where schedule is one doc per rung:
    the survivors that entered it and their standing when it closed."""
    engine = SweepEngine(grid, warm_path=warm_path)
    chunks = _chunk_records(records, rungs)
    schedule: list[dict] = []
    for ri, chunk in enumerate(chunks):
        entered = [c.name for c in engine.configs]
        engine.consume(chunk)
        ranked = sorted(
            (engine.tallies[n] for n in entered),
            key=lambda t: t.rank_key(),
            reverse=True,
        )
        schedule.append(
            {
                "rung": ri,
                "waves": _wave_count(chunk),
                "configs": entered,
                "ranking": [
                    {
                        "name": t.config.name,
                        "admitted": t.admitted,
                        "admittedRatio": round(t.admitted_ratio, 4),
                        "meanPlacementScore": round(t.mean_score, 4),
                    }
                    for t in ranked
                ],
            }
        )
        if ri < len(chunks) - 1 and len(entered) > min_configs:
            keep_n = max(min_configs, math.ceil(len(entered) / 2))
            survivors = {t.config.name for t in ranked[:keep_n]}
            survivors.add("incumbent")  # the baseline never halves away
            survivors &= set(entered)
            engine.keep(survivors)
    return engine, schedule


def _validate_bitwise(records: list, winner, tally, warm) -> dict:
    """Gate 1: a plain single-config replay of the journal under the winner
    config must reproduce the winner's sweep-row plans bitwise."""
    fleets: dict[str, dict] = {}
    divergences = 0
    waves = 0
    diverged: list = []
    for rec in records:
        kind = rec.get("kind")
        if kind == "fleet":
            fleets[rec["digest"]] = rec
            continue
        if kind != "wave":
            continue
        snapshot = snapshot_from_wave(rec, fleets[rec["fleet"]])
        plan, ok, scores, _s = solve_wave_record(
            rec,
            snapshot,
            warm=warm,
            params=winner.solver_params(),
            portfolio=winner.portfolio,
            escalate_portfolio=winner.escalate_portfolio,
        )
        sweep_plan, sweep_ok, sweep_scores = tally.plans[waves]
        pseudo = {"ok": sweep_ok, "plan": sweep_plan, "scores": sweep_scores}
        diffs = diff_wave(pseudo, plan, ok, scores)
        if diffs:
            divergences += len(diffs)
            if len(diverged) < 3:
                diverged.append({"wave": waves, "diffs": diffs})
        waves += 1
    out = {"waves": waves, "divergences": divergences}
    if diverged:
        out["diverged"] = diverged
    return out


def _validate_exact(winner, incumbent, seeds=None) -> dict:
    """Gate 2: winner admitted ratio vs the exact optimum must not fall
    below the incumbent's on the seeded audit instances."""
    from grove_tpu.quality.audit import AUDIT_SEEDS, audit_config

    seeds = tuple(seeds) if seeds else AUDIT_SEEDS

    def run(cfg):
        return audit_config(
            cfg.weights,
            portfolio=cfg.portfolio,
            escalate_portfolio=cfg.escalate_portfolio,
            seeds=seeds,
        )

    w = run(winner)
    inc = run(incumbent) if winner.name != incumbent.name else w
    return {
        "seeds": list(seeds),
        "winner": w.to_doc(),
        "incumbent": inc.to_doc(),
        "admittedPass": w.admitted >= inc.admitted,
    }


def recommend(
    records: list,
    *,
    grid: list | None = None,
    k: int = 16,
    rungs: int = 3,
    spread: float = 0.5,
    seed: int = 0,
    audit_seeds=None,
    warm_path=None,
) -> dict:
    """Full tuning pass: grid -> halving sweep -> validated recommendation.

    Returns the recommended-config JSON document (see module docstring for
    the gates). `grid` overrides the default grid (row 0 must then be the
    incumbent-named baseline)."""
    from grove_tpu.solver.warm import WarmPath

    warm = warm_path if warm_path is not None else WarmPath()
    incumbent = incumbent_config(records)
    if grid is None:
        grid = default_grid(incumbent, k, spread=spread, seed=seed)
    engine, schedule = successive_halving(
        records, grid, rungs=rungs, warm_path=warm
    )
    finalists = [engine.tallies[c.name] for c in engine.configs]
    winner_tally = max(finalists, key=lambda t: t.rank_key())
    winner = winner_tally.config
    incumbent_tally = engine.tallies["incumbent"]

    bitwise = _validate_bitwise(records, winner, winner_tally, warm)
    exact = _validate_exact(winner, incumbent, seeds=audit_seeds)
    replay_divergences = incumbent_tally.divergences
    valid = (
        bitwise["divergences"] == 0
        and exact["admittedPass"]
        and replay_divergences == 0
    )
    failed = []
    if bitwise["divergences"]:
        failed.append("bitwiseReplay")
    if not exact["admittedPass"]:
        failed.append("exactAudit")
    if replay_divergences:
        failed.append("journalReplay")
    doc = {
        "winner": winner.to_doc(),
        "incumbent": incumbent.to_doc(),
        "valid": valid,
        "grid": len(grid),
        "rungs": schedule,
        "sweep": engine.to_doc(),
        "winnerTally": winner_tally.to_doc(),
        "incumbentTally": incumbent_tally.to_doc(),
        "validation": {
            "bitwiseReplay": bitwise,
            "journalReplayDivergences": replay_divergences,
            "exactAudit": exact,
        },
    }
    if failed:
        doc["failedGates"] = failed
    return doc
