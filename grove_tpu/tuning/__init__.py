"""Offline solver tuning from flight-recorder traces (ROADMAP item 3).

`sweep` replays a recorded journal ONCE per wave while scoring K candidate
solver configs stacked on the solver's variant axis; `search` drives a
successive-halving schedule over a config grid and emits a validated
recommended-config document. See docs/design.md "Offline tuning".
"""

from grove_tpu.tuning.search import recommend, successive_halving
from grove_tpu.tuning.sweep import (
    SweepConfig,
    SweepEngine,
    default_grid,
    incumbent_config,
    sweep_journal,
)

__all__ = [
    "SweepConfig",
    "SweepEngine",
    "default_grid",
    "incumbent_config",
    "recommend",
    "successive_halving",
    "sweep_journal",
]
