"""Single-source version + build info (reference: `internal/version/`).

The reference injects version/commit/date at build time via ldflags
(`operator/internal/version/`); a Python package has no link step, so the
analog is: one VERSION constant here (re-exported as
``grove_tpu.__version__``), plus best-effort build metadata gathered at
call time (git commit read from the working tree if present, interpreter
and jax versions). Everything that reports a version — ``--version`` flags,
``/statusz``, the CLI — MUST come through this module; tests pin that the
surfaces agree (tests/test_runtime.py).
"""

from __future__ import annotations

import pathlib
import platform as _platform
import sys

VERSION = "0.4.0"


def _git_commit() -> str | None:
    """Resolve HEAD from the on-disk git metadata (no subprocess: this runs
    inside the operator's /statusz handler and must never block or fail)."""
    root = pathlib.Path(__file__).resolve().parent.parent
    git = root / ".git"
    try:
        head = (git / "HEAD").read_text().strip()
        if head.startswith("ref: "):
            ref = head[5:].strip()
            ref_file = git / ref
            if ref_file.exists():
                return ref_file.read_text().strip()[:12]
            packed = git / "packed-refs"
            if packed.exists():
                for line in packed.read_text().splitlines():
                    if line.endswith(" " + ref):
                        return line.split()[0][:12]
            return None
        return head[:12]
    except OSError:
        return None


def build_info() -> dict:
    """Version + build metadata dict (ldflags-injected build info analog)."""
    try:
        import jax

        jax_version = jax.__version__
    except Exception:  # jax import must never break a version query
        jax_version = None
    return {
        "version": VERSION,
        "git_commit": _git_commit(),
        "python": sys.version.split()[0],
        "platform": _platform.platform(),
        "jax": jax_version,
    }


def version_string(prog: str = "grove-tpu") -> str:
    commit = _git_commit()
    suffix = f" ({commit})" if commit else ""
    return f"{prog} {VERSION}{suffix}"
