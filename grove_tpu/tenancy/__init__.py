"""Multi-tenant SLO tiers (docs/design.md "Multi-tenant SLO tiers").

Composes the existing pieces — hierarchical capacity queues, priority
preemption, disruption-budgeted migration, the flight recorder — into a
per-tenant SLO enforcement story:

  - SLO classes (`api.constants.SLO_CLASSES`) mapped to admission order,
    borrowing eligibility, and preemptibility (slo.py);
  - deterministic priority aging so in-quota demand cannot be starved
    forever by higher-weight borrowers (aging.py);
  - a per-tenant fairness ledger surfaced via /statusz, metrics, and
    `grove-tpu get tenancy` (ledger.py).

The enforcement itself lives in the controller's admission pass
(orchestrator/controller.py); this package holds the pure policy pieces so
they are unit-testable and shared with the bench harness.
"""

from grove_tpu.tenancy.aging import aging_boost
from grove_tpu.tenancy.ledger import TenantLedger, quantile
from grove_tpu.tenancy.slo import (
    is_valid_slo_class,
    normalized_slo_class,
    slo_borrow_eligible,
    slo_rank,
    stream_order_key,
)

__all__ = [
    "aging_boost",
    "TenantLedger",
    "quantile",
    "is_valid_slo_class",
    "normalized_slo_class",
    "slo_borrow_eligible",
    "slo_rank",
    "stream_order_key",
]
