"""Per-tenant fairness ledger.

Tenant identity is the capacity queue a gang draws quota from (PodGang
.queue) — the same key the QueueTree charges, so admission accounting and
fairness accounting cannot disagree about who a gang belongs to.

The ledger is pure bookkeeping: the controller calls the note_* hooks from
decision points that are already journaled (wave records, aging / reclaim /
preemption action records), so the ledger itself never needs to be part of
the replay closure — replaying the journal rebuilds an equivalent ledger.

Bind-latency samples are kept per (tenant, SLO class) in bounded reservoirs
(newest-kept) so hundreds of churning tenants cannot grow the ledger
without bound; the p50/p99 cut from them is what the tenancy bench gates
tier ordering on.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from grove_tpu.tenancy.slo import normalized_slo_class

# Newest-kept samples per (tenant, class); enough for a stable p99 without
# unbounded growth under churn.
_LATENCY_CAP = 512


def quantile(samples: list[float], q: float) -> float:
    """Nearest-rank quantile; 0.0 on empty input. Deterministic (no
    interpolation-mode surprises across numpy versions)."""
    if not samples:
        return 0.0
    xs = sorted(samples)
    idx = min(len(xs) - 1, max(0, int(q * len(xs) + 0.5) - 1))
    return xs[idx]


@dataclass
class TenantStats:
    """One tenant's (= one queue's) cumulative counters."""

    submitted: int = 0          # gangs that entered the contender field
    admitted: int = 0           # gangs granted quota into a solve batch
    admitted_borrowing: int = 0  # admissions that rode borrowed capacity
    bound: int = 0              # gangs fully bound
    preemptions_suffered: int = 0
    preemptions_caused: int = 0
    reclaims_suffered: int = 0
    reclaims_caused: int = 0
    aging_boosts: int = 0       # aging ladder steps granted to this tenant
    # SLO class -> bounded bind-latency samples (seconds, newest kept).
    bind_latencies: dict[str, list[float]] = field(default_factory=dict)

    def admitted_ratio(self) -> float:
        return self.admitted / self.submitted if self.submitted else 0.0

    def borrowed_share(self) -> float:
        return self.admitted_borrowing / self.admitted if self.admitted else 0.0


class TenantLedger:
    """Fairness accounting across tenants; surfaced via /statusz tenancy,
    grove_tenancy_* metrics, and `grove-tpu get tenancy`."""

    def __init__(self) -> None:
        self.tenants: dict[str, TenantStats] = {}
        # Monotonic totals the manager cuts delta-exported counters from.
        self.totals: dict[str, int] = {
            "submitted": 0,
            "admitted": 0,
            "admitted_borrowing": 0,
            "bound": 0,
            "preemptions": 0,
            "reclaims": 0,
            "aging_boosts": 0,
            "reclaim_deferred": 0,
        }

    def _stats(self, tenant: str) -> TenantStats:
        st = self.tenants.get(tenant)
        if st is None:
            st = self.tenants[tenant] = TenantStats()
        return st

    def note_submitted(self, tenant: str) -> None:
        self._stats(tenant).submitted += 1
        self.totals["submitted"] += 1

    def note_admitted(self, tenant: str, borrowed: bool) -> None:
        st = self._stats(tenant)
        st.admitted += 1
        self.totals["admitted"] += 1
        if borrowed:
            st.admitted_borrowing += 1
            self.totals["admitted_borrowing"] += 1

    def note_bound(self, tenant: str, slo_class: str, latency_s: float) -> None:
        st = self._stats(tenant)
        st.bound += 1
        self.totals["bound"] += 1
        samples = st.bind_latencies.setdefault(normalized_slo_class(slo_class), [])
        samples.append(latency_s)
        if len(samples) > _LATENCY_CAP:
            del samples[: len(samples) - _LATENCY_CAP]

    def note_preemption(self, victim_tenant: str, contender_tenant: str) -> None:
        self._stats(victim_tenant).preemptions_suffered += 1
        self._stats(contender_tenant).preemptions_caused += 1
        self.totals["preemptions"] += 1

    def note_reclaim(self, victim_tenant: str, contender_tenant: str) -> None:
        self._stats(victim_tenant).reclaims_suffered += 1
        self._stats(contender_tenant).reclaims_caused += 1
        self.totals["reclaims"] += 1

    def note_aging(self, tenant: str) -> None:
        self._stats(tenant).aging_boosts += 1
        self.totals["aging_boosts"] += 1

    def note_reclaim_deferred(self) -> None:
        self.totals["reclaim_deferred"] += 1

    def tier_latencies(self) -> dict[str, list[float]]:
        """SLO class -> pooled bind-latency samples across every tenant."""
        pooled: dict[str, list[float]] = {}
        for st in self.tenants.values():
            for cls, samples in st.bind_latencies.items():
                pooled.setdefault(cls, []).extend(samples)
        return pooled

    def snapshot(self, top: int = 0) -> dict:
        """The /statusz `tenancy` doc. `top` > 0 bounds the per-tenant
        table (busiest first) so hundreds of tenants stay renderable."""
        names = sorted(
            self.tenants,
            key=lambda t: (-self.tenants[t].submitted, t),
        )
        if top > 0:
            names = names[:top]
        tenants = {}
        for name in names:
            st = self.tenants[name]
            tenants[name] = {
                "submitted": st.submitted,
                "admitted": st.admitted,
                "admittedRatio": round(st.admitted_ratio(), 4),
                "borrowedShare": round(st.borrowed_share(), 4),
                "bound": st.bound,
                "preemptionsSuffered": st.preemptions_suffered,
                "preemptionsCaused": st.preemptions_caused,
                "reclaimsSuffered": st.reclaims_suffered,
                "reclaimsCaused": st.reclaims_caused,
                "agingBoosts": st.aging_boosts,
            }
        tiers = {
            cls: {
                "samples": len(samples),
                "p50BindSeconds": round(quantile(samples, 0.50), 6),
                "p99BindSeconds": round(quantile(samples, 0.99), 6),
            }
            for cls, samples in sorted(self.tier_latencies().items())
        }
        return {
            "tenantCount": len(self.tenants),
            "totals": dict(self.totals),
            "tiers": tiers,
            "tenants": tenants,
        }
