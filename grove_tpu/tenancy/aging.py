"""Deterministic priority aging.

A pending gang's effective priority is its PriorityClass value plus an
aging boost that grows with time spent waiting. The boost follows a
half-life-doubling ladder: step k unlocks after the gang has waited
half_life * (2^k - 1) seconds, so every successive step takes twice as long
as the last —

    waited <  h        -> 0
    waited >= h        -> 1
    waited >= 3h       -> 2
    waited >= 7h       -> 3
    waited >= (2^k-1)h -> k   (capped at max_boost)

Early steps come fast enough that a low-weight tenant's in-quota demand
climbs past habitual borrowers within a few half-lives; the geometric
slow-down keeps an unschedulable gang from aging without bound and
inverting the whole priority space. The inputs are (waited, half_life,
max_boost) only — no wall clock, no randomness — so a boost computed during
a recorded run replays bitwise from the journaled inputs.
"""

from __future__ import annotations


def aging_boost(waited_s: float, half_life_s: float, max_boost: int) -> int:
    """Completed doubling periods of `half_life_s` within `waited_s`."""
    if half_life_s <= 0.0 or max_boost <= 0 or waited_s < half_life_s:
        return 0
    boost = 0
    threshold = half_life_s
    while boost < max_boost and waited_s >= threshold:
        boost += 1
        # Next step unlocks at h*(2^(k+1)-1) = threshold*2 + h.
        threshold = threshold * 2.0 + half_life_s
    return boost
