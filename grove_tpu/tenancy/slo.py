"""SLO-class semantics: the one place admission order, borrowing
eligibility, and eviction order are defined.

The three classes (api/constants.py SLO_CLASSES) form a strict tier order:

  rank 0  latency            admits first; in-quota only (never borrows, so
                             the queue reclaim verdict can never name it off
                             borrowed share); evicted last
  rank 1  standard           the default; may borrow over quota
  rank 2  batch-preemptible  admits last; may borrow; evicted FIRST when an
                             in-quota contender reclaims or a floor
                             rejection preempts

Rank is used ascending for admission (lower = earlier in the solve batch)
and descending for victim selection (higher = preferred victim), so the two
orders cannot drift apart.
"""

from __future__ import annotations

from grove_tpu.api.constants import (
    DEFAULT_SLO_CLASS,
    SLO_CLASS_LATENCY,
    SLO_CLASSES,
)

_RANK = {cls: i for i, cls in enumerate(SLO_CLASSES)}


def is_valid_slo_class(cls: str) -> bool:
    return cls in _RANK


def normalized_slo_class(cls: str | None) -> str:
    """Empty/unknown collapses to the default — the controller must never
    crash on a gang admitted before the field existed."""
    return cls if cls in _RANK else DEFAULT_SLO_CLASS


def slo_rank(cls: str | None) -> int:
    """Admission tier: 0 admits first. Unknown/legacy gangs rank standard."""
    return _RANK[normalized_slo_class(cls)]


def slo_borrow_eligible(cls: str | None) -> bool:
    """latency gangs are in-quota only: they never ride borrowed capacity,
    which is exactly what makes them unreclaimable (queues.py reclaim names
    borrowed usage first; a gang that cannot borrow cannot be the borrower
    an in-quota contender beats)."""
    return normalized_slo_class(cls) != SLO_CLASS_LATENCY


def revocation_victim_key(cls: str | None, priority: int, name: str) -> tuple:
    """Eviction order when a revocation deadline forces a node clear
    (controller._revocation_evict): batch-preemptible tiers go first
    (descending rank), then lowest effective priority, then name — the
    deterministic mirror of the admission order, so the journal shows
    low-SLO work absorbing the reclaim ahead of latency work."""
    return (-slo_rank(cls), priority, name)


def stream_order_key(priority_of=None):
    """Window-ordering key for solver.stream.drain_stream(order_key=...):
    tier first, then priority descending. The key depends only on
    template-level fields (sloClass, PriorityClass), so it is family-uniform
    and the stream driver's stable sort keeps base gangs ahead of their
    scaled siblings."""
    if priority_of is None:
        priority_of = lambda g: 0  # noqa: E731 - tier-only ordering

    def key(gang):
        return (slo_rank(getattr(gang, "slo_class", "")), -priority_of(gang))

    return key
