from grove_tpu.client.typed import FakeGroveClient, GroveApiError, GroveClient  # noqa: F401
