"""Typed object clients over the manager API — the generated-clientset analog.

The reference ships generated typed clientsets/informers/listers with fakes
(`operator/client/`, `scheduler/client/`, incl.
`scheduler/client/clientset/versioned/fake/`). Here the same two surfaces:

  GroveClient      — HTTP client over the manager's /api/v1 object API
                     (list/get for every collection, apply/delete for
                     PodCliqueSets through the admission chain)
  FakeGroveClient  — same interface over an in-process Manager, for tests
                     that don't want a socket (the fake-clientset analog)

Typed: get_* return the real dataclasses (decoded via utils/serde), not raw
dicts.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional

from grove_tpu.utils import serde

# Typed decode needs the object modules registered (same set the
# control-plane persistence uses).
from grove_tpu.api import pod as _pod
from grove_tpu.api import podgang as _podgang
from grove_tpu.api import resources as _resources
from grove_tpu.api import types as _types
from grove_tpu.state import cluster as _state

for _m in (_types, _pod, _podgang, _state, _resources):
    serde.register_module(_m)


class GroveApiError(Exception):
    def __init__(self, status: int, errors: list[str]):
        self.status = status
        self.errors = errors
        super().__init__(f"HTTP {status}: " + "; ".join(errors))


class GroveClient:
    """HTTP(S) typed client (apiserver-analog surface).

    `cafile` pins the manager's serving cert (the auto-mode self-signed cert
    doubles as the CA bundle: <tlsCertDir>/tls.crt); `token` is the bearer
    credential for authorizer-enabled managers."""

    def __init__(
        self,
        base_url: str,
        actor: str = "user",
        timeout_s: float = 10.0,
        cafile: str | None = None,
        token: str | None = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.actor = actor
        self.timeout_s = timeout_s
        self.token = token
        self._ssl_ctx = None
        if cafile is not None:
            from grove_tpu.runtime.certs import pinned_client_context

            self._ssl_ctx = pinned_client_context(cafile)

    # -- transport ------------------------------------------------------------------

    def _request(self, method: str, path: str, body: bytes | None = None) -> Any:
        req = urllib.request.Request(
            f"{self.base_url}{path}", data=body, method=method
        )
        req.add_header("X-Grove-Actor", self.actor)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                req, timeout=self.timeout_s, context=self._ssl_ctx
            ) as resp:
                return json.loads(resp.read() or b"null")
        except urllib.error.HTTPError as e:
            try:
                doc = json.loads(e.read())
                errors = doc.get("errors", [str(e)])
            except Exception:
                errors = [str(e)]
            raise GroveApiError(e.code, errors) from e

    def _list(self, kind: str) -> list[str]:
        return self._request("GET", f"/api/v1/{kind}")

    def _list_full(self, kind: str) -> dict[str, Any]:
        """One round trip for every object of a kind (?full=1) — the table
        path; per-name gets would be N+1 requests at cluster scale."""
        doc = self._request("GET", f"/api/v1/{kind}?full=1")
        return {name: serde.decode(obj) for name, obj in doc.items()}

    def _get(self, kind: str, name: str):
        return serde.decode(self._request("GET", f"/api/v1/{kind}/{name}"))

    # -- typed surface ---------------------------------------------------------------

    def list_podcliquesets(self) -> list[str]:
        return self._list("podcliquesets")

    def list_podcliquesets_full(self) -> dict[str, Any]:
        return self._list_full("podcliquesets")

    def list_podgangs_full(self) -> dict[str, Any]:
        return self._list_full("podgangs")

    def list_podcliques_full(self) -> dict[str, Any]:
        return self._list_full("podcliques")

    def list_scaling_groups_full(self) -> dict[str, Any]:
        return self._list_full("podcliquescalinggroups")

    def list_pods_full(self) -> dict[str, Any]:
        return self._list_full("pods")

    def list_nodes_full(self) -> dict[str, Any]:
        return self._list_full("nodes")

    def get_podcliqueset(self, name: str):
        return self._get("podcliquesets", name)

    def apply_podcliqueset(self, doc_or_yaml: dict | str) -> str:
        body = (
            doc_or_yaml if isinstance(doc_or_yaml, str) else json.dumps(doc_or_yaml)
        ).encode()
        return self._request("POST", "/api/v1/podcliquesets", body)["name"]

    def delete_podcliqueset(self, name: str) -> None:
        self._request("DELETE", f"/api/v1/podcliquesets/{name}")

    def list_podgangs(self) -> list[str]:
        return self._list("podgangs")

    def get_podgang(self, name: str):
        return self._get("podgangs", name)

    def list_pods(self) -> list[str]:
        return self._list("pods")

    def get_pod(self, name: str):
        return self._get("pods", name)

    def list_nodes(self) -> list[str]:
        return self._list("nodes")

    def get_node(self, name: str):
        return self._get("nodes", name)

    def list_services(self) -> list[str]:
        return self._list("services")

    def list_hpas(self) -> list[str]:
        return self._list("hpas")

    def events(self) -> list[tuple[float, str, str]]:
        return [tuple(e) for e in self._request("GET", "/api/v1/events")]

    def push_metrics(self, metrics: dict[str, float]) -> int:
        """HPA utilization feed (metrics-server analog): target FQN ->
        utilization normalized to the target (1.0 == at target)."""
        resp = self._request(
            "POST", "/api/v1/metrics", json.dumps(metrics).encode()
        )
        return resp["targets"]

    def scale(self, target: str, replicas: int) -> int:
        """kubectl-scale analog: set a PodClique/PCSG scale subresource.
        Returns the previous effective replica count."""
        resp = self._request(
            "POST",
            "/api/v1/scale",
            json.dumps({"target": target, "replicas": replicas}).encode(),
        )
        return resp["previous"]

    def statusz(self) -> dict:
        """Operator status document (build info, leadership, queue
        quota/usage, object counts)."""
        return self._request("GET", "/statusz")


class FakeGroveClient:
    """In-process fake with the same typed surface (fake-clientset analog).

    Backed by a live Manager: reads hit the store directly; applies run the
    same admission chain the HTTP path uses."""

    def __init__(self, manager, actor: str = "user"):
        self.manager = manager
        self.actor = actor

    def _coll(self, kind: str) -> dict:
        return {
            "podcliquesets": self.manager.cluster.podcliquesets,
            "podcliques": self.manager.cluster.podcliques,
            "podcliquescalinggroups": self.manager.cluster.scaling_groups,
            "podgangs": self.manager.cluster.podgangs,
            "pods": self.manager.cluster.pods,
            "nodes": self.manager.cluster.nodes,
            "services": self.manager.cluster.services,
            "hpas": self.manager.cluster.hpas,
        }[kind]

    def _list(self, kind: str) -> list[str]:
        return sorted(self._coll(kind))

    def _get(self, kind: str, name: str):
        obj = self._coll(kind).get(name)
        if obj is None:
            raise GroveApiError(404, ["not found"])
        return obj

    list_podcliquesets = lambda self: self._list("podcliquesets")  # noqa: E731
    list_podgangs = lambda self: self._list("podgangs")  # noqa: E731
    list_pods = lambda self: self._list("pods")  # noqa: E731
    list_nodes = lambda self: self._list("nodes")  # noqa: E731
    list_services = lambda self: self._list("services")  # noqa: E731
    list_hpas = lambda self: self._list("hpas")  # noqa: E731

    def _list_full(self, kind: str) -> dict:
        return dict(sorted(self._coll(kind).items()))

    list_podcliquesets_full = lambda self: self._list_full("podcliquesets")  # noqa: E731
    list_podgangs_full = lambda self: self._list_full("podgangs")  # noqa: E731
    list_podcliques_full = lambda self: self._list_full("podcliques")  # noqa: E731
    list_scaling_groups_full = lambda self: self._list_full("podcliquescalinggroups")  # noqa: E731
    list_pods_full = lambda self: self._list_full("pods")  # noqa: E731
    list_nodes_full = lambda self: self._list_full("nodes")  # noqa: E731

    def get_podcliqueset(self, name: str):
        return self._get("podcliquesets", name)

    def get_podgang(self, name: str):
        return self._get("podgangs", name)

    def get_pod(self, name: str):
        return self._get("pods", name)

    def get_node(self, name: str):
        return self._get("nodes", name)

    def push_metrics(self, metrics: dict[str, float]) -> int:
        import math as _math

        update = {str(k): float(v) for k, v in metrics.items()}
        bad = [k for k, v in update.items() if not _math.isfinite(v)]
        if bad:
            # Same contract as the HTTP path's 400 on non-finite values.
            raise GroveApiError(400, [f"non-finite utilization for {bad}"])
        self.manager.hpa_metrics.update(update)
        return len(update)

    def apply_podcliqueset(self, doc_or_yaml: dict | str) -> str:
        import yaml as _yaml

        from grove_tpu.api.admission import AdmissionError
        from grove_tpu.api.types import PodCliqueSet

        doc = (
            _yaml.safe_load(doc_or_yaml)
            if isinstance(doc_or_yaml, str)
            else doc_or_yaml
        )
        try:
            pcs = self.manager.apply_podcliqueset(
                PodCliqueSet.from_dict(doc), actor=self.actor
            )
        except AdmissionError as e:
            raise GroveApiError(422, [str(x) for x in e.errors]) from e
        return pcs.metadata.name

    def delete_podcliqueset(self, name: str) -> None:
        if name not in self.manager.cluster.podcliquesets:
            raise GroveApiError(404, ["not found"])
        self.manager.delete_podcliqueset(name, actor=self.actor)

    def statusz(self) -> dict:
        return self.manager.statusz()

    def scale(self, target: str, replicas: int) -> int:
        if not isinstance(replicas, int) or isinstance(replicas, bool):
            raise GroveApiError(400, ["replicas must be an integer"])
        try:
            return self.manager.scale_target(target, replicas, actor=self.actor)
        except KeyError:
            raise GroveApiError(404, [f"unknown scale target {target!r}"]) from None
        except ValueError as e:
            raise GroveApiError(400, [str(e)]) from None

    def events(self) -> list[tuple[float, str, str]]:
        return self.manager.cluster.recent_events(200)
