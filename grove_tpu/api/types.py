"""Core workload API: PodCliqueSet / PodClique / PodCliqueScalingGroup / ClusterTopology.

Semantic parity with the reference core API (operator/api/core/v1alpha1/):
  - PodCliqueSetSpec / TemplateSpec with cliques, startup type, terminationDelay,
    scaling-group configs (podcliqueset.go:52-58,126-159)
  - CliqueStartupType {AnyOrder, InOrder, Explicit} (podcliqueset.go:249-257)
  - PodCliqueSpec with RoleName, Replicas, MinAvailable, StartsAfter, ScaleConfig
    (podclique.go:54-79); AutoScalingConfig (podclique.go:82-101)
  - PodCliqueScalingGroupConfig with dual-purpose MinAvailable (podcliqueset.go:216-227)
  - TopologyConstraint{PackDomain} (podcliqueset.go:188-197)
  - TopologyDomain 7-level hierarchy with ordering (clustertopology.go:92-136)
  - Rolling-update progress types (podcliqueset.go:96-118, podclique.go:140-164)

These are plain dataclasses (the "CRD" layer); everything tensor-shaped lives in
grove_tpu/state. All objects round-trip from the reference's YAML shapes via
``from_dict`` so the reference sample workloads load unmodified.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field, replace as _dc_replace
from typing import Any, Optional

from grove_tpu.api.quantity import parse_quantity

# ---------------------------------------------------------------------------------
# Topology domains (clustertopology.go:92-136)
# ---------------------------------------------------------------------------------


class TopologyDomain(str, enum.Enum):
    """Seven-level topology hierarchy, broadest → narrowest.

    TPU mapping: `region`/`zone`/`datacenter` ride DCN; `block` ≈ a pod of
    slices, `rack` ≈ one slice (ICI domain), `host` ≈ one host's chips,
    `numa` ≈ chips behind one PCIe/NUMA node.
    """

    REGION = "region"
    ZONE = "zone"
    DATACENTER = "datacenter"
    BLOCK = "block"
    RACK = "rack"
    HOST = "host"
    NUMA = "numa"


# Lower value = broader scope (clustertopology.go:124-136).
TOPOLOGY_DOMAIN_ORDER: dict[TopologyDomain, int] = {
    TopologyDomain.REGION: 0,
    TopologyDomain.ZONE: 1,
    TopologyDomain.DATACENTER: 2,
    TopologyDomain.BLOCK: 3,
    TopologyDomain.RACK: 4,
    TopologyDomain.HOST: 5,
    TopologyDomain.NUMA: 6,
}


def is_domain_narrower(d: TopologyDomain, other: TopologyDomain) -> bool:
    """True if `d` is narrower (more specific) than `other` (clustertopology.go:110-112)."""
    return TOPOLOGY_DOMAIN_ORDER[d] > TOPOLOGY_DOMAIN_ORDER[other]


@dataclass
class TopologyLevel:
    """One level of the ClusterTopology: a domain bound to a node-label key."""

    domain: TopologyDomain
    node_label_key: str

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "TopologyLevel":
        return cls(
            domain=TopologyDomain(d["domain"]),
            node_label_key=d.get("nodeLabelKey") or d.get("node_label_key"),
        )


@dataclass
class ClusterTopology:
    """Cluster-scoped topology declaration (clustertopology.go:40).

    The operator auto-appends the `host` level bound to `kubernetes.io/hostname`
    if absent (internal/clustertopology/clustertopology.go:102-107).
    """

    name: str
    levels: list[TopologyLevel] = field(default_factory=list)

    def sorted_levels(self) -> list[TopologyLevel]:
        """Levels broadest → narrowest (clustertopology.go:141)."""
        return sorted(self.levels, key=lambda l: TOPOLOGY_DOMAIN_ORDER[l.domain])

    def label_key_for(self, domain: TopologyDomain) -> Optional[str]:
        for level in self.levels:
            if level.domain == domain:
                return level.node_label_key
        return None

    def with_host_level(self) -> "ClusterTopology":
        if self.label_key_for(TopologyDomain.HOST) is not None:
            return self
        return ClusterTopology(
            name=self.name,
            levels=[*self.levels, TopologyLevel(TopologyDomain.HOST, "kubernetes.io/hostname")],
        )

    def levels_doc(self) -> list[dict]:
        """The wire shape of the effective hierarchy (host level included,
        broadest first) — the ONE rendering both the synced ClusterTopology
        CR and /statusz (CLI `get topology`) use."""
        return [
            {"domain": lvl.domain.value, "nodeLabelKey": lvl.node_label_key}
            for lvl in self.with_host_level().sorted_levels()
        ]

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ClusterTopology":
        spec = d.get("spec", d)
        return cls(
            name=d.get("metadata", {}).get("name", d.get("name", "default")),
            levels=[TopologyLevel.from_dict(x) for x in spec.get("levels", [])],
        )


DEFAULT_CLUSTER_TOPOLOGY = ClusterTopology(
    name="default",
    levels=[
        TopologyLevel(TopologyDomain.ZONE, "topology.kubernetes.io/zone"),
        TopologyLevel(TopologyDomain.BLOCK, "topology.kubernetes.io/block"),
        TopologyLevel(TopologyDomain.RACK, "topology.kubernetes.io/rack"),
        TopologyLevel(TopologyDomain.HOST, "kubernetes.io/hostname"),
    ],
)


# ---------------------------------------------------------------------------------
# Shared metadata / pod template primitives
# ---------------------------------------------------------------------------------


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    uid: str = ""
    generation: int = 1
    finalizers: list[str] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    creation_timestamp: Optional[float] = None
    owner: Optional[str] = None  # FQN of owning object (controller ref analog)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            labels=dict(d.get("labels", {}) or {}),
            annotations=dict(d.get("annotations", {}) or {}),
        )


@dataclass
class Container:
    name: str
    image: str = ""
    command: list[str] = field(default_factory=list)
    args: list[str] = field(default_factory=list)
    env: dict[str, str] = field(default_factory=dict)
    # Deferred env vars (`valueFrom` downward-API/fieldRef entries) kept verbatim
    # so nothing from a loaded workload is silently dropped.
    env_value_from: dict[str, dict] = field(default_factory=dict)
    requests: dict[str, float] = field(default_factory=dict)  # base units
    limits: dict[str, float] = field(default_factory=dict)
    ports: list[int] = field(default_factory=list)
    # [{"name": ..., "mountPath": ...}] — fulfilled by the node runtime
    # against PodSpec.volumes (the kubelet contract).
    volume_mounts: list[dict] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Container":
        res = d.get("resources", {}) or {}
        requests = {k: parse_quantity(v) for k, v in (res.get("requests", {}) or {}).items()}
        limits = {k: parse_quantity(v) for k, v in (res.get("limits", {}) or {}).items()}
        env: dict[str, str] = {}
        env_value_from: dict[str, dict] = {}
        for e in d.get("env", []) or []:
            if "valueFrom" in e:
                env_value_from[e["name"]] = e["valueFrom"]
            elif "value" in e:
                env[e["name"]] = str(e["value"])
        ports = [p.get("containerPort") for p in d.get("ports", []) or [] if "containerPort" in p]
        return cls(
            name=d["name"],
            image=d.get("image", ""),
            command=list(d.get("command", []) or []),
            args=list(d.get("args", []) or []),
            env=env,
            env_value_from=env_value_from,
            requests=requests,
            limits=limits,
            ports=ports,
            volume_mounts=[dict(v) for v in d.get("volumeMounts", []) or []],
        )


@dataclass
class PodSpec:
    """The subset of corev1.PodSpec that drives placement and lifecycle."""

    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    node_selector: dict[str, str] = field(default_factory=dict)
    priority_class_name: str = ""
    restart_policy: str = "Always"
    termination_grace_period_seconds: int = 30
    scheduling_gates: list[str] = field(default_factory=list)
    hostname: str = ""
    subdomain: str = ""
    tolerations: list[dict] = field(default_factory=list)
    resource_claims: list[dict] = field(default_factory=list)  # MNNVL/ICI analog
    # Declared volumes ([{"name": ..., "secret": {"secretName": ...}}, ...]);
    # the runtime materializes them for the containers' volume_mounts.
    volumes: list[dict] = field(default_factory=list)

    def total_requests(self) -> dict[str, float]:
        """Aggregate resource requests across containers (max with init containers)."""
        total: dict[str, float] = {}
        for c in self.containers:
            for k, v in c.requests.items():
                total[k] = total.get(k, 0.0) + v
        for c in self.init_containers:
            for k, v in c.requests.items():
                total[k] = max(total.get(k, 0.0), v)
        return total

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodSpec":
        return cls(
            containers=[Container.from_dict(c) for c in d.get("containers", []) or []],
            init_containers=[Container.from_dict(c) for c in d.get("initContainers", []) or []],
            node_selector=dict(d.get("nodeSelector", {}) or {}),
            priority_class_name=d.get("priorityClassName", ""),
            restart_policy=d.get("restartPolicy", "Always") or "Always",
            # `or 30` would coerce an explicit 0 (force-immediate-kill, a
            # standard k8s idiom) back to the default — only None defaults.
            termination_grace_period_seconds=(
                30
                if d.get("terminationGracePeriodSeconds") is None
                else d["terminationGracePeriodSeconds"]
            ),
            tolerations=list(d.get("tolerations", []) or []),
            resource_claims=list(d.get("resourceClaims", []) or []),
            volumes=[dict(v) for v in d.get("volumes", []) or []],
        )


# ---------------------------------------------------------------------------------
# Workload topology constraint (podcliqueset.go:188-197)
# ---------------------------------------------------------------------------------


@dataclass
class TopologyConstraint:
    """Pack each replica instance within one domain of `pack_domain`.

    NOTE: this constrains EACH replica independently — different replicas may
    land in different domains (podcliqueset.go:190-196).

    `preferred_domain` is the soft counterpart (wire key `preferredDomain`):
    the scheduler tries to pack the replica into one domain at that level
    and degrades the gang's PlacementScore — never rejects — when it cannot
    (the Required/Preferred pair of the scheduler IR's
    TopologyPackConstraint, podgang.go:101-117). Either field may be unset;
    a constraint with both packs hard at `pack_domain` and scores soft at
    `preferred_domain` (which must be equal or narrower to mean anything).
    """

    pack_domain: Optional[TopologyDomain] = None
    preferred_domain: Optional[TopologyDomain] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> Optional["TopologyConstraint"]:
        if not d:
            return None
        pack = d.get("packDomain")
        preferred = d.get("preferredDomain")
        if pack is None and preferred is None:
            return None
        return cls(
            pack_domain=TopologyDomain(pack) if pack is not None else None,
            preferred_domain=(
                TopologyDomain(preferred) if preferred is not None else None
            ),
        )


# ---------------------------------------------------------------------------------
# Autoscaling (podclique.go:82-101)
# ---------------------------------------------------------------------------------


@dataclass
class AutoScalingConfig:
    """HPA-shaped autoscaling config: min/max replicas + metric specs."""

    max_replicas: int
    min_replicas: Optional[int] = None  # defaulted to .Replicas by webhook
    metrics: list[dict[str, Any]] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: dict[str, Any] | None) -> Optional["AutoScalingConfig"]:
        if not d:
            return None
        return cls(
            max_replicas=int(d["maxReplicas"]),
            min_replicas=int(d["minReplicas"]) if d.get("minReplicas") is not None else None,
            metrics=list(d.get("metrics", []) or []),
        )


# ---------------------------------------------------------------------------------
# PodClique (podclique.go)
# ---------------------------------------------------------------------------------


class CliqueStartupType(str, enum.Enum):
    """Startup ordering across cliques (podcliqueset.go:249-257)."""

    ANY_ORDER = "CliqueStartupTypeAnyOrder"
    IN_ORDER = "CliqueStartupTypeInOrder"
    EXPLICIT = "CliqueStartupTypeExplicit"


@dataclass
class PodCliqueSpec:
    """Spec of one clique role (podclique.go:54-79)."""

    role_name: str
    pod_spec: PodSpec
    replicas: int = 0  # defaulted to 1
    min_available: Optional[int] = None  # defaulted to replicas
    starts_after: list[str] = field(default_factory=list)
    scale_config: Optional[AutoScalingConfig] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodCliqueSpec":
        return cls(
            role_name=d.get("roleName", ""),
            pod_spec=PodSpec.from_dict(d.get("podSpec", {}) or {}),
            replicas=int(d.get("replicas", 0) or 0),
            min_available=int(d["minAvailable"]) if d.get("minAvailable") is not None else None,
            starts_after=list(d.get("startsAfter", []) or []),
            scale_config=AutoScalingConfig.from_dict(d.get("autoScalingConfig")),
        )


@dataclass
class PodCliqueTemplateSpec:
    """Named clique template inside a PodCliqueSet (podcliqueset.go:160-186)."""

    name: str
    spec: PodCliqueSpec
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    topology_constraint: Optional[TopologyConstraint] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodCliqueTemplateSpec":
        return cls(
            name=d["name"],
            spec=PodCliqueSpec.from_dict(d.get("spec", {}) or {}),
            labels=dict(d.get("labels", {}) or {}),
            annotations=dict(d.get("annotations", {}) or {}),
            topology_constraint=TopologyConstraint.from_dict(d.get("topologyConstraint")),
        )


@dataclass
class PodCliqueStatus:
    """Status rollup for a PodClique (podclique.go:104-137)."""

    replicas: int = 0
    ready_replicas: int = 0
    scheduled_replicas: int = 0
    # Pods still holding the podgang-pending scheduling gate
    # (scheduleGatedReplicas, podclique.go status).
    schedule_gated_replicas: int = 0
    updated_replicas: int = 0
    conditions: list["Condition"] = field(default_factory=list)
    current_pod_template_hash: Optional[str] = None
    current_pcs_generation_hash: Optional[str] = None
    selector: str = ""
    last_errors: list[str] = field(default_factory=list)


@dataclass
class PodClique:
    """The PodClique CR: one role's pods within one PCS replica."""

    metadata: ObjectMeta
    spec: PodCliqueSpec
    status: PodCliqueStatus = field(default_factory=PodCliqueStatus)
    # Denormalized bookkeeping (reference keeps these in labels):
    template_name: str = ""
    pcs_name: str = ""
    pcs_replica_index: int = 0
    pcsg_name: Optional[str] = None  # FQN of owning PCSG, if any
    pcsg_replica_index: Optional[int] = None
    pod_gang_name: str = ""
    topology_constraint: Optional[TopologyConstraint] = None

    @property
    def min_available(self) -> int:
        return self.spec.min_available if self.spec.min_available is not None else self.spec.replicas


# ---------------------------------------------------------------------------------
# PodCliqueScalingGroup (scalinggroup.go)
# ---------------------------------------------------------------------------------


@dataclass
class PodCliqueScalingGroupConfig:
    """Template-level scaling-group config (podcliqueset.go:200-236).

    MinAvailable is dual-purpose (scalinggroup.go:56-67): the gang-scheduling
    floor (PCSG replicas [0, minAvailable) join the base PodGang; the rest get
    scaled PodGangs) AND the gang-termination threshold.
    """

    name: str
    clique_names: list[str]
    replicas: int = 1
    min_available: int = 1
    scale_config: Optional[AutoScalingConfig] = None
    topology_constraint: Optional[TopologyConstraint] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodCliqueScalingGroupConfig":
        return cls(
            name=d["name"],
            clique_names=list(d.get("cliqueNames", []) or []),
            replicas=int(d["replicas"]) if d.get("replicas") is not None else 1,
            min_available=int(d["minAvailable"]) if d.get("minAvailable") is not None else 1,
            scale_config=AutoScalingConfig.from_dict(d.get("scaleConfig")),
            topology_constraint=TopologyConstraint.from_dict(d.get("topologyConstraint")),
        )


@dataclass
class PodCliqueScalingGroupSpec:
    """Spec of the PCSG CR materialized per PCS replica (scalinggroup.go:51-71)."""

    clique_names: list[str]
    replicas: int = 1
    min_available: int = 1

    @classmethod
    def from_config(cls, cfg: PodCliqueScalingGroupConfig) -> "PodCliqueScalingGroupSpec":
        return cls(
            clique_names=list(cfg.clique_names),
            replicas=cfg.replicas,
            min_available=cfg.min_available,
        )


@dataclass
class PodCliqueScalingGroupStatus:
    replicas: int = 0
    scheduled_replicas: int = 0
    available_replicas: int = 0
    updated_replicas: int = 0
    conditions: list["Condition"] = field(default_factory=list)
    rolling_update_progress: Optional["PCSGRollingUpdateProgress"] = None
    selector: str = ""
    last_errors: list[str] = field(default_factory=list)


@dataclass
class PodCliqueScalingGroup:
    metadata: ObjectMeta
    spec: PodCliqueScalingGroupSpec
    status: PodCliqueScalingGroupStatus = field(default_factory=PodCliqueScalingGroupStatus)
    template_name: str = ""  # config name within the PCS template
    pcs_name: str = ""
    pcs_replica_index: int = 0
    topology_constraint: Optional[TopologyConstraint] = None


# ---------------------------------------------------------------------------------
# Rolling update progress (podcliqueset.go:96-118, scalinggroup.go:106-129)
# ---------------------------------------------------------------------------------


@dataclass
class PodCliqueSetRollingUpdateProgress:
    update_started_at: float = 0.0
    update_ended_at: Optional[float] = None
    current_replica_index: Optional[int] = None
    updated_replica_indices: list[int] = field(default_factory=list)


@dataclass
class PCSGRollingUpdateProgress:
    update_started_at: float = 0.0
    update_ended_at: Optional[float] = None
    current_replica_index: Optional[int] = None
    updated_replica_indices: list[int] = field(default_factory=list)


# ---------------------------------------------------------------------------------
# PodCliqueSet (podcliqueset.go)
# ---------------------------------------------------------------------------------


@dataclass
class HeadlessServiceConfig:
    publish_not_ready_addresses: bool = True


@dataclass
class PodCliqueSetTemplateSpec:
    """The per-replica template (podcliqueset.go:126-159)."""

    cliques: list[PodCliqueTemplateSpec] = field(default_factory=list)
    startup_type: CliqueStartupType = CliqueStartupType.ANY_ORDER
    pod_clique_scaling_group_configs: list[PodCliqueScalingGroupConfig] = field(default_factory=list)
    termination_delay_seconds: float = 4 * 3600.0  # default 4h (podcliqueset.go:154)
    priority_class_name: str = ""
    # SLO tier (constants.SLO_CLASSES): admission order, borrowing
    # eligibility, preemptibility (docs/design.md "Multi-tenant SLO
    # tiers"). "" on load; defaulting fills "standard".
    slo_class: str = ""
    headless_service_config: Optional[HeadlessServiceConfig] = None
    topology_constraint: Optional[TopologyConstraint] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodCliqueSetTemplateSpec":
        term = d.get("terminationDelay")
        if term is None:
            term_s = 4 * 3600.0
        elif isinstance(term, (int, float)):
            term_s = float(term)
        else:
            term_s = _parse_duration(term)
        hs = d.get("headlessServiceConfig")
        return cls(
            cliques=[PodCliqueTemplateSpec.from_dict(c) for c in d.get("cliques", []) or []],
            # CRD JSON tag is `cliqueStartupType` (reference podcliqueset.go:133);
            # accept `startupType` as a convenience alias.
            startup_type=CliqueStartupType(
                d.get("cliqueStartupType") or d.get("startupType") or CliqueStartupType.ANY_ORDER.value
            ),
            pod_clique_scaling_group_configs=[
                PodCliqueScalingGroupConfig.from_dict(c)
                for c in d.get("podCliqueScalingGroups", d.get("podCliqueScalingGroupConfigs", [])) or []
            ],
            termination_delay_seconds=term_s,
            priority_class_name=d.get("priorityClassName", ""),
            slo_class=d.get("sloClass", ""),
            headless_service_config=(
                HeadlessServiceConfig(bool(hs.get("publishNotReadyAddresses", True))) if hs else None
            ),
            topology_constraint=TopologyConstraint.from_dict(d.get("topologyConstraint")),
        )


@dataclass
class PodCliqueSetSpec:
    replicas: int = 1
    template: PodCliqueSetTemplateSpec = field(default_factory=PodCliqueSetTemplateSpec)
    # Spread each PCS replica across this domain (replica-spread analog).
    topology_spread_domain: Optional[TopologyDomain] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodCliqueSetSpec":
        return cls(
            replicas=int(d.get("replicas", 1) or 1),
            template=PodCliqueSetTemplateSpec.from_dict(d.get("template", {}) or {}),
            topology_spread_domain=(
                TopologyDomain(d["topologySpreadDomain"]) if d.get("topologySpreadDomain") else None
            ),
        )


@dataclass
class PodGangStatusSummary:
    """Per-gang status surfaced in PCS status (podcliqueset.go:262-270)."""

    name: str
    phase: str = "Pending"
    conditions: list["Condition"] = field(default_factory=list)


@dataclass
class PodCliqueSetStatus:
    replicas: int = 0
    updated_replicas: int = 0
    available_replicas: int = 0
    observed_generation: int = 0
    current_generation_hash: Optional[str] = None
    updated_generation_hash: Optional[str] = None
    rolling_update_progress: Optional[PodCliqueSetRollingUpdateProgress] = None
    pod_gang_statuses: list[PodGangStatusSummary] = field(default_factory=list)
    conditions: list["Condition"] = field(default_factory=list)
    last_errors: list[str] = field(default_factory=list)
    selector: str = ""


@dataclass
class PodCliqueSet:
    metadata: ObjectMeta
    spec: PodCliqueSetSpec
    status: PodCliqueSetStatus = field(default_factory=PodCliqueSetStatus)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "PodCliqueSet":
        return cls(
            metadata=ObjectMeta.from_dict(d.get("metadata", {}) or {}),
            spec=PodCliqueSetSpec.from_dict(d.get("spec", {}) or {}),
        )

    def clique_template(self, name: str) -> Optional[PodCliqueTemplateSpec]:
        for c in self.spec.template.cliques:
            if c.name == name:
                return c
        return None

    def scaling_group_for_clique(self, clique_name: str) -> Optional[PodCliqueScalingGroupConfig]:
        for cfg in self.spec.template.pod_clique_scaling_group_configs:
            if clique_name in cfg.clique_names:
                return cfg
        return None

    def standalone_clique_templates(self) -> list[PodCliqueTemplateSpec]:
        """Cliques NOT belonging to any scaling group."""
        in_group = {n for cfg in self.spec.template.pod_clique_scaling_group_configs for n in cfg.clique_names}
        return [c for c in self.spec.template.cliques if c.name not in in_group]


# ---------------------------------------------------------------------------------
# Conditions
# ---------------------------------------------------------------------------------


@dataclass
class Condition:
    type: str
    status: str  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0


def get_condition(conditions: list[Condition], ctype: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == ctype:
            return c
    return None


def set_condition(conditions: list[Condition], cond: Condition, now: float = 0.0) -> list[Condition]:
    """Upsert preserving last_transition_time when status is unchanged."""
    out = []
    found = False
    for c in conditions:
        if c.type == cond.type:
            found = True
            if c.status == cond.status:
                out.append(_dc_replace(cond, last_transition_time=c.last_transition_time))
            else:
                out.append(_dc_replace(cond, last_transition_time=now))
        else:
            out.append(c)
    if not found:
        out.append(_dc_replace(cond, last_transition_time=now))
    return out


# ---------------------------------------------------------------------------------


def _parse_duration(s: str) -> float:
    """Parse Go-style duration strings: '4h', '30m', '1h30m', '90s', '100ms'."""
    if re.fullmatch(r"(?:[0-9.]+(?:ms|us|ns|h|m|s))+", s) is None:
        raise ValueError(f"invalid duration: {s!r}")
    m = re.findall(r"([0-9.]+)(ms|us|ns|h|m|s)", s)
    mult = {"h": 3600.0, "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9}
    return sum(float(v) * mult[u] for v, u in m)
