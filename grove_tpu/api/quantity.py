"""Kubernetes resource-quantity parsing (the subset Grove workloads use).

Parity target: resource requests in PodSpecs, e.g. `cpu: 10m`, `memory: 1Gi`,
`nvidia.com/gpu: 8` (reference sample workloads, operator/samples/**.yaml). We
normalize every quantity to a float in base units (cores for cpu, bytes for
memory, count for extended resources) so cluster snapshots are dense float32
tensors (see grove_tpu/state/cluster.py).
"""

from __future__ import annotations

import re

_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DECIMAL_SUFFIXES = {
    "n": 1e-9,
    "u": 1e-6,
    "m": 1e-3,
    "": 1.0,
    "k": 1e3,
    "M": 1e6,
    "G": 1e9,
    "T": 1e12,
    "P": 1e15,
    "E": 1e18,
}

_QUANTITY_RE = re.compile(r"^([+-]?[0-9.]+(?:[eE][+-]?[0-9]+)?)([A-Za-z]{0,2})$")


def parse_quantity(value: str | int | float) -> float:
    """Parse a Kubernetes quantity string into a float in base units."""
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    m = _QUANTITY_RE.match(s)
    if m is None:
        raise ValueError(f"invalid quantity: {value!r}")
    number, suffix = m.groups()
    base = float(number)
    if suffix in _BINARY_SUFFIXES:
        return base * _BINARY_SUFFIXES[suffix]
    if suffix in _DECIMAL_SUFFIXES:
        return base * _DECIMAL_SUFFIXES[suffix]
    raise ValueError(f"unknown quantity suffix {suffix!r} in {value!r}")


def format_quantity(value: float) -> str:
    """Render a float back into a compact quantity string (for status display)."""
    if value == int(value):
        return str(int(value))
    milli = value * 1000
    if milli == int(milli):
        return f"{int(milli)}m"
    return repr(value)
