"""Deterministic resource naming, parity with operator/api/common/namegen.go.

Scheme (docs/user-guide/02_pod-and-resource-naming-conventions/02_naming-conventions.md):
  headless service       <pcs>-<i>                      (namegen.go:34-36)
  PodClique (standalone) <pcs>-<i>-<clique>             (namegen.go:70-72)
  PCSG                   <pcs>-<i>-<sg>                 (namegen.go:76-78)
  PodClique (in PCSG)    <pcs>-<i>-<sg>-<j>-<clique>    (PCSG FQN as owner)
  base PodGang           <pcs>-<i>                      (namegen.go:82-84)
  scaled PodGang         <pcsgFQN>-<k>  k = j - minAvailable  (namegen.go:88-115)
  pod                    <pclqFQN>-<5char-suffix>; hostname <pclqFQN>-<idx>
"""

from __future__ import annotations

import random
import string

from grove_tpu.api.types import PodCliqueScalingGroup, PodCliqueSet

GROUP = "grove.io"

_SUFFIX_ALPHABET = string.ascii_lowercase + string.digits


def headless_service_name(pcs_name: str, replica: int) -> str:
    return f"{pcs_name}-{replica}"


def headless_service_address(pcs_name: str, replica: int, namespace: str) -> str:
    return f"{headless_service_name(pcs_name, replica)}.{namespace}.svc.cluster.local"


def pod_role_name(pcs_name: str) -> str:
    return f"{GROUP}:pcs:{pcs_name}"


def pod_role_binding_name(pcs_name: str) -> str:
    return f"{GROUP}:pcs:{pcs_name}"


def pod_service_account_name(pcs_name: str) -> str:
    return pcs_name


def initc_sa_token_secret_name(pcs_name: str) -> str:
    return f"{pcs_name}-initc-sa-token-secret"


def podclique_name(owner_name: str, owner_replica: int, clique_template_name: str) -> str:
    """Owner is the PCS (standalone cliques) or the PCSG FQN (member cliques)."""
    return f"{owner_name}-{owner_replica}-{clique_template_name}"


def scaling_group_name(pcs_name: str, pcs_replica: int, sg_config_name: str) -> str:
    return f"{pcs_name}-{pcs_replica}-{sg_config_name}"


def base_podgang_name(pcs_name: str, pcs_replica: int) -> str:
    return f"{pcs_name}-{pcs_replica}"


def scaled_podgang_name(pcsg_fqn: str, scaled_index: int) -> str:
    """scaled_index is 0-based, counted from PCSG replica minAvailable upward."""
    return f"{pcsg_fqn}-{scaled_index}"


def podgang_name_for_pcsg_replica(
    pcs: PodCliqueSet, pcs_replica: int, pcsg: PodCliqueScalingGroup, pcsg_replica: int
) -> str:
    """PCSG replicas [0, minAvailable) belong to the base gang; the rest each get
    a scaled gang indexed from 0 (namegen.go:100-115)."""
    min_available = pcsg.spec.min_available
    if pcsg_replica < min_available:
        return base_podgang_name(pcs.metadata.name, pcs_replica)
    return scaled_podgang_name(pcsg.metadata.name, pcsg_replica - min_available)


def extract_sg_name_from_fqn(pcsg_fqn: str, pcs_name: str, pcs_replica: int) -> str:
    prefix = f"{pcs_name}-{pcs_replica}-"
    return pcsg_fqn[len(prefix):]


def pod_name(pclq_fqn: str, rng: random.Random | None = None) -> str:
    """Pod object name: clique FQN + random 5-char suffix (k8s generateName style)."""
    r = rng or random
    suffix = "".join(r.choice(_SUFFIX_ALPHABET) for _ in range(5))
    return f"{pclq_fqn}-{suffix}"


def pod_hostname(pclq_fqn: str, pod_index: int) -> str:
    """Stable DNS hostname: clique FQN + stable index
    (podclique/components/pod/pod.go:262-269)."""
    return f"{pclq_fqn}-{pod_index}"
