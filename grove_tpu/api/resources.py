"""Managed auxiliary resources: Service, HPA, RBAC, SA token Secret.

The reference's PodCliqueSet controller materializes these as first-class
Kubernetes objects per PCS (ordered kinds,
`podcliqueset/reconcilespec.go:206-221`):
  - per-replica headless Service for DNS discovery
    (`components/service/service.go:137-155`)
  - HorizontalPodAutoscaler per auto-scaled PCLQ / PCSG
    (`components/hpa/hpa.go:130,249-259`)
  - ServiceAccount + Role + RoleBinding + long-lived token Secret — the
    credentials grove-initc uses to watch pods
    (`components/serviceaccount|role|rolebinding|satokensecret/`)

Here they are typed store objects with the same ownership/GC semantics; the
token Secret is LIVE credential material — the manager's HTTP API (the
apiserver analog the initc agent polls) verifies it when the authorizer is
enabled.
"""

from __future__ import annotations

import hashlib
import secrets as _secrets
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class HeadlessService:
    """ClusterIP:None discovery service per PCS replica (service.go:137-155)."""

    name: str
    namespace: str = "default"
    pcs_name: str = ""
    pcs_replica_index: int = 0
    cluster_ip: str = "None"
    publish_not_ready_addresses: bool = True
    selector: dict[str, str] = field(default_factory=dict)


@dataclass
class HorizontalPodAutoscaler:
    """HPA over a CR scale subresource (hpa.go:249-259)."""

    name: str
    namespace: str = "default"
    pcs_name: str = ""
    target_kind: str = "PodClique"  # or PodCliqueScalingGroup
    target_name: str = ""  # FQN — the scale-override key
    min_replicas: int = 1
    max_replicas: int = 1
    # The target's spec replicas at build time — the scaling baseline before
    # any override exists (avoids fuzzy FQN->template back-resolution).
    target_spec_replicas: int = 1
    metrics: list[dict[str, Any]] = field(default_factory=list)


@dataclass
class ServiceAccount:
    name: str
    namespace: str = "default"
    pcs_name: str = ""


@dataclass
class Role:
    """Minimal rules model: what the initc credential may do."""

    name: str
    namespace: str = "default"
    pcs_name: str = ""
    rules: list[dict[str, Any]] = field(
        default_factory=lambda: [
            {"apiGroup": "grove.io", "resources": ["podcliques"], "verbs": ["get", "list"]},
            {"apiGroup": "", "resources": ["pods"], "verbs": ["get", "list"]},
        ]
    )


@dataclass
class RoleBinding:
    name: str
    namespace: str = "default"
    pcs_name: str = ""
    role_name: str = ""
    service_account_name: str = ""


@dataclass
class TokenSecret:
    """Long-lived SA token the initc agent presents to the manager API
    (satokensecret component analog). The token value is generated once at
    create and persisted with the control-plane state."""

    name: str
    namespace: str = "default"
    pcs_name: str = ""
    service_account_name: str = ""
    token: str = ""

    def __post_init__(self):
        if not self.token:
            self.token = _secrets.token_hex(16)

    def fingerprint(self) -> str:
        return hashlib.sha256(self.token.encode()).hexdigest()[:12]


def build_pcs_rbac(pcs_name: str, namespace: str) -> tuple[
    ServiceAccount, Role, RoleBinding, TokenSecret
]:
    """The four per-PCS credential objects, reference-named (namegen.go)."""
    from grove_tpu.api import naming

    sa = ServiceAccount(
        name=naming.pod_service_account_name(pcs_name),
        namespace=namespace,
        pcs_name=pcs_name,
    )
    role = Role(
        name=naming.pod_role_name(pcs_name), namespace=namespace, pcs_name=pcs_name
    )
    binding = RoleBinding(
        name=naming.pod_role_binding_name(pcs_name),
        namespace=namespace,
        pcs_name=pcs_name,
        role_name=role.name,
        service_account_name=sa.name,
    )
    secret = TokenSecret(
        name=naming.initc_sa_token_secret_name(pcs_name),
        namespace=namespace,
        pcs_name=pcs_name,
        service_account_name=sa.name,
    )
    return sa, role, binding, secret
