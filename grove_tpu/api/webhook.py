"""AdmissionReview v1 wire handlers — the inbound-HTTPS webhook surface.

In the reference, the kube-apiserver POSTs `admission.k8s.io/v1`
AdmissionReview objects to the operator's webhook server: a mutating
(defaulting) handler (`webhook/admission/pcs/defaulting/handler.go`) and a
validating handler (`validation/handler.go`), registered at
`internal/webhook/register.go:34-62`. This module speaks that exact wire
format so an apiserver (or the deploy renderer's
Mutating/ValidatingWebhookConfiguration objects) can call THIS operator the
same way — no client library, just the review JSON in and out.

The semantic work stays in one place (`api/defaulting.py`,
`api/validation.py`, `api/admission.py`); this module only translates:

  - mutate: run the chain's defaulting on the incoming object and emit an
    RFC-6902 JSON patch covering exactly the fields defaulting touches
    (targeted `add` ops — never a whole-spec replace, so fields this build
    does not model survive untouched).
  - validate: run the full chain (create or update path) and translate
    AdmissionError into `allowed: false` + message.
"""

from __future__ import annotations

import base64
import copy
import json
from typing import Any

from grove_tpu.api import constants
from grove_tpu.api.admission import AdmissionChain, AdmissionError
from grove_tpu.api.types import PodCliqueSet


def _escape_pointer(token: str) -> str:
    """RFC-6901 token escaping (`/` and `~` in annotation keys)."""
    return token.replace("~", "~0").replace("/", "~1")


def _ensure_map(ops: list, doc: dict, path: str, key: str) -> dict:
    """Make sure `doc[key]` exists as a map, adding a patch op if created."""
    cur = doc.get(key)
    if not isinstance(cur, dict):
        doc[key] = {}
        ops.append({"op": "add", "path": f"{path}/{key}", "value": {}})
    return doc[key]


def _set(ops: list, parent: dict, path: str, key: str, value: Any) -> None:
    """Add/replace `parent[key] = value`, recording the patch op when the
    current wire value differs."""
    if parent.get(key) == value:
        return
    op = "replace" if key in parent else "add"
    parent[key] = value
    ops.append({"op": op, "path": f"{path}/{_escape_pointer(key)}", "value": value})


def default_patch_ops(
    doc: dict,
    chain: AdmissionChain,
    operation: str = "CREATE",
    old_doc: dict | None = None,
) -> list[dict]:
    """Compute the defaulting JSON patch for a PodCliqueSet CR document.

    Values come from the typed defaulting pass (so the semantics live only
    in `defaulting.py`/`admission.py`); this function knows the CR paths.
    The incoming `doc` is not modified.
    """
    pcs = PodCliqueSet.from_dict(copy.deepcopy(doc))
    # Defaulting only — validation/authorization belong to the validating
    # webhook; a mutating handler must still patch objects it would reject
    # so the user sees the validation message, not a patch failure.
    from grove_tpu.api.defaulting import default_podcliqueset

    default_podcliqueset(pcs)
    if operation == "CREATE":
        # Auto-annotation only on creation (defaulting/handler.go:62-65);
        # on update the live object already carries it (immutable).
        chain._default_auto_slice(pcs)
    elif isinstance(old_doc, dict):
        # UPDATE carry-forward: a whole-object PUT that omits the immutable
        # annotation must not silently drop it — the validating webhook can
        # only allow/deny, so the MUTATING webhook (which sees oldObject)
        # re-stamps it. Without this, an explicit "disabled" opt-out would
        # vanish on the next full replace and injection would switch on.
        old_val = (old_doc.get("metadata", {}) or {}).get("annotations", {}) or {}
        old_slice = old_val.get(constants.ANNOTATION_AUTO_SLICE)
        if (
            old_slice is not None
            and constants.ANNOTATION_AUTO_SLICE not in pcs.metadata.annotations
        ):
            pcs.metadata.annotations[constants.ANNOTATION_AUTO_SLICE] = old_slice

    doc = copy.deepcopy(doc)
    ops: list[dict] = []
    meta = _ensure_map(ops, doc, "", "metadata")
    if not meta.get("namespace"):
        _set(ops, meta, "/metadata", "namespace", pcs.metadata.namespace)
    want_slice = pcs.metadata.annotations.get(constants.ANNOTATION_AUTO_SLICE)
    if want_slice is not None:
        anns = _ensure_map(ops, meta, "/metadata", "annotations")
        _set(ops, anns, "/metadata/annotations", constants.ANNOTATION_AUTO_SLICE, want_slice)

    spec = _ensure_map(ops, doc, "", "spec")
    tmpl = _ensure_map(ops, spec, "/spec", "template")
    tpath = "/spec/template"

    cliques = tmpl.get("cliques") or []
    for i, cdoc in enumerate(cliques):
        typed = pcs.spec.template.cliques[i].spec
        cspec = _ensure_map(ops, cdoc, f"{tpath}/cliques/{i}", "spec")
        cpath = f"{tpath}/cliques/{i}/spec"
        if int(cspec.get("replicas") or 0) == 0:
            _set(ops, cspec, cpath, "replicas", typed.replicas)
        if cspec.get("minAvailable") is None:
            _set(ops, cspec, cpath, "minAvailable", typed.min_available)
        asc = cspec.get("autoScalingConfig")
        if isinstance(asc, dict) and asc.get("minReplicas") is None:
            _set(
                ops,
                asc,
                f"{cpath}/autoScalingConfig",
                "minReplicas",
                typed.scale_config.min_replicas,
            )
        ps = _ensure_map(ops, cspec, cpath, "podSpec")
        if not ps.get("restartPolicy"):
            _set(ops, ps, f"{cpath}/podSpec", "restartPolicy", typed.pod_spec.restart_policy)
        if ps.get("terminationGracePeriodSeconds") is None:
            _set(
                ops,
                ps,
                f"{cpath}/podSpec",
                "terminationGracePeriodSeconds",
                typed.pod_spec.termination_grace_period_seconds,
            )

    # PCSG configs: accept both CR key spellings the loader does.
    key = (
        "podCliqueScalingGroups"
        if "podCliqueScalingGroups" in tmpl
        else "podCliqueScalingGroupConfigs"
    )
    for i, gdoc in enumerate(tmpl.get(key) or []):
        typed_g = pcs.spec.template.pod_clique_scaling_group_configs[i]
        gpath = f"{tpath}/{key}/{i}"
        if gdoc.get("replicas") is None:
            _set(ops, gdoc, gpath, "replicas", typed_g.replicas)
        if gdoc.get("minAvailable") is None:
            _set(ops, gdoc, gpath, "minAvailable", typed_g.min_available)
        gsc = gdoc.get("scaleConfig") or gdoc.get("autoScalingConfig")
        if isinstance(gsc, dict) and gsc.get("minReplicas") is None:
            sub = "scaleConfig" if "scaleConfig" in gdoc else "autoScalingConfig"
            _set(ops, gsc, f"{gpath}/{sub}", "minReplicas", typed_g.scale_config.min_replicas)

    if tmpl.get("terminationDelay") is None:
        # CR field is a metav1.Duration string (podcliqueset.go:154).
        _set(ops, tmpl, tpath, "terminationDelay", "4h")
    if tmpl.get("headlessServiceConfig") is None:
        _set(
            ops,
            tmpl,
            tpath,
            "headlessServiceConfig",
            {"publishNotReadyAddresses": True},
        )
    return ops


def _review_response(uid: str, allowed: bool, message: str = "", patch: list | None = None) -> dict:
    resp: dict[str, Any] = {"uid": uid, "allowed": allowed}
    if message:
        resp["status"] = {"message": message, "code": 200 if allowed else 422}
    if patch:
        resp["patchType"] = "JSONPatch"
        resp["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "response": resp,
    }


def _review_request(body: dict) -> tuple[str, str, dict | None, dict | None]:
    req = body.get("request") or {}
    return (
        str(req.get("uid", "")),
        str(req.get("operation", "")).upper(),
        req.get("object"),
        req.get("oldObject"),
    )


def handle_mutate(body: dict, chain: AdmissionChain) -> dict:
    """Defaulting (mutating) webhook endpoint body → AdmissionReview response."""
    uid, operation, obj, old = _review_request(body)
    if operation not in ("CREATE", "UPDATE") or not isinstance(obj, dict):
        return _review_response(uid, True)
    try:
        ops = default_patch_ops(obj, chain, operation=operation, old_doc=old)
    except Exception as e:  # malformed object: let validation produce the message
        return _review_response(uid, True, message=f"defaulting skipped: {e}")
    return _review_response(uid, True, patch=ops or None)


def handle_authorize(
    body: dict,
    chain: AdmissionChain,
    operator_users: frozenset,
    pcs_lookup=None,
) -> dict:
    """Authorizer webhook endpoint (admission/pcs/authorization/handler.go:
    60-135): deny any user other than the reconciler (and configured exempt
    actors) mutating a grove-managed resource. Reference exceptions kept:
    CONNECT is always allowed; Pod DELETE is allowed for everyone (the
    kubelet's completion deletes and the GC's owner-reference cascade are
    system identities no exempt list could enumerate, handler.go:121-124);
    a parent PCS annotated grove.io/disable-managed-resource-protection:
    "true" bypasses the check for its children (handler.go:89-93,
    `pcs_lookup` resolves the parent by the part-of label). The rendered
    configuration pre-filters with an objectSelector on the managed-by
    label; this handler re-checks the label so a mis-scoped configuration
    fails closed for managed objects and open for everything else."""
    req = body.get("request") or {}
    uid = str(req.get("uid", ""))
    operation = str(req.get("operation", "")).upper()
    if operation == "CONNECT":
        # Always allowed for users with sufficient RBAC (handler.go:66-70).
        return _review_response(uid, True)
    username = str((req.get("userInfo") or {}).get("username", ""))
    kind = str((req.get("kind") or {}).get("kind", ""))
    if kind == "Pod" and operation == "DELETE":
        return _review_response(uid, True)

    def _managed(o) -> bool:
        labels = ((o or {}).get("metadata", {}) or {}).get("labels", {}) or {}
        return labels.get(constants.LABEL_MANAGED_BY) == constants.LABEL_MANAGED_BY_VALUE

    obj = req.get("object") if isinstance(req.get("object"), dict) else None
    old = req.get("oldObject") if isinstance(req.get("oldObject"), dict) else None
    # Managed if EITHER side carries the label: an UPDATE that strips the
    # managed-by label would otherwise walk straight past the check — the
    # objectSelector fires on either side and so must we.
    if not (_managed(obj) or _managed(old)):
        return _review_response(uid, True)  # not grove-managed
    if obj is None:
        obj = old  # DELETE reviews carry only oldObject
    if username in operator_users:
        return _review_response(uid, True)
    meta = (obj or {}).get("metadata", {}) or {}
    if pcs_lookup is not None:
        pcs_name = (meta.get("labels", {}) or {}).get(constants.LABEL_PART_OF, "")
        parent = pcs_lookup(pcs_name) if pcs_name else None
        if parent is not None and (
            parent.metadata.annotations.get(constants.ANNOTATION_DISABLE_PROTECTION)
            == "true"
        ):
            return _review_response(uid, True)
    try:
        chain.admit_managed_mutation(username, kind, meta.get("name", ""))
    except PermissionError as e:
        return _review_response(uid, False, message=str(e))
    return _review_response(uid, True)


def handle_validate(body: dict, chain: AdmissionChain) -> dict:
    """Validating webhook endpoint body → AdmissionReview response."""
    uid, operation, obj, old = _review_request(body)
    if operation == "DELETE":
        return _review_response(uid, True)
    if not isinstance(obj, dict):
        return _review_response(uid, False, message="request.object missing")
    try:
        new_pcs = PodCliqueSet.from_dict(copy.deepcopy(obj))
        old_pcs = (
            PodCliqueSet.from_dict(copy.deepcopy(old))
            if operation == "UPDATE" and isinstance(old, dict)
            else None
        )
        chain.admit_podcliqueset(new_pcs, old=old_pcs)
    except AdmissionError as e:
        return _review_response(uid, False, message=str(e))
    except Exception as e:
        return _review_response(uid, False, message=f"malformed PodCliqueSet: {e}")
    return _review_response(uid, True)
