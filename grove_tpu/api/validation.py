"""Admission validation, parity with the validating webhook
(operator/internal/webhook/admission/pcs/validation/podcliqueset.go and
topologyconstraints.go).

Rules (reference line numbers in parens):
  - PCS name <= 45 chars so generated pod names fit 63 (podcliqueset.go:37-39,564)
  - at least one clique (116); unique clique names + role names (138-139)
  - clique: replicas > 0 (350); 0 < minAvailable <= replicas (358-362)
  - startsAfter: non-empty names, no self-reference, unique (369-375); every
    dependency exists (303); no cycles (309)
  - clique scaleConfig: minReplicas >= minAvailable (406), maxReplicas >=
    minReplicas (409), maxReplicas >= replicas (381)
  - PCSG: unique names (236); clique names exist; no clique in two groups (238);
    replicas > 0 (209); minAvailable > 0 (215); minAvailable <= replicas (222);
    scaleConfig.minReplicas >= minAvailable (229); member cliques must not have
    individual autoscaling (podcliqueset.go API note :202)
  - terminationDelay > 0 (260)
  - topology constraints: domain must exist in the cluster topology; child
    constraints must be equal-or-narrower than parent (PCS >= PCSG >= PCLQ)
    (topologyconstraints.go)
  - update immutability: minAvailable, clique set/order under InOrder/Explicit
    startup (492-544)
"""

from __future__ import annotations

from dataclasses import dataclass

from grove_tpu.api.constants import (
    ANNOTATION_ROLLOUT_STRATEGY,
    MAX_PCS_NAME_LENGTH,
    ROLLOUT_STRATEGIES,
    SLO_CLASSES,
)
from grove_tpu.api.types import (
    ClusterTopology,
    CliqueStartupType,
    PodCliqueSet,
    TopologyConstraint,
    TopologyDomain,
    is_domain_narrower,
)


@dataclass
class ValidationError(Exception):
    field: str
    message: str

    def __str__(self) -> str:
        return f"{self.field}: {self.message}"


def validate_podcliqueset(
    pcs: PodCliqueSet, topology: ClusterTopology | None = None
) -> list[ValidationError]:
    """Full create-time validation; returns a list of errors (empty = valid)."""
    # The host level is always available (clustertopology.go:102-107).
    if topology is not None:
        topology = topology.with_host_level()
    errs: list[ValidationError] = []
    name = pcs.metadata.name
    if not name:
        errs.append(ValidationError("metadata.name", "name is required"))
    # The 45-char budget caps the COMBINED <pcs>[-<pcsg>]-<pclq> name material
    # so generated pod names `<pcs>-<i>[-<pcsg>-<j>]-<pclq>-<suffix>` fit the
    # 63-char DNS label (validation/podcliqueset.go:564-578).
    sg_of_clique = {
        cn: cfg.name
        for cfg in pcs.spec.template.pod_clique_scaling_group_configs
        for cn in cfg.clique_names
    }
    for clique in pcs.spec.template.cliques:
        parts = [name, sg_of_clique.get(clique.name, ""), clique.name]
        combined = sum(len(p) for p in parts if p)
        if combined > MAX_PCS_NAME_LENGTH:
            errs.append(
                ValidationError(
                    "metadata.name",
                    f"combined name length {combined} for clique {clique.name!r} exceeds "
                    f"{MAX_PCS_NAME_LENGTH} characters; generated pod names would not fit "
                    f"the 63-character limit",
                )
            )
    if pcs.spec.replicas < 1:
        errs.append(ValidationError("spec.replicas", "must be greater than 0"))

    tmpl = pcs.spec.template
    if not tmpl.cliques:
        errs.append(ValidationError("spec.template.cliques", "at least one PodClique must be defined"))
    if tmpl.termination_delay_seconds is not None and tmpl.termination_delay_seconds <= 0:
        errs.append(ValidationError("spec.template.terminationDelay", "must be greater than 0"))
    # sloClass: one of the fixed tenancy tiers ("" = defaulting fills
    # "standard"; an unknown tier would silently schedule as standard, so
    # reject it at admission instead).
    if tmpl.slo_class and tmpl.slo_class not in SLO_CLASSES:
        errs.append(
            ValidationError(
                "spec.template.sloClass",
                f"unknown SLO class {tmpl.slo_class!r}; must be one of {', '.join(SLO_CLASSES)}",
            )
        )
    # grove.io/rollout-strategy: the per-PCS update-strategy override must
    # name a known strategy — a typo'd value would silently fall back to the
    # global rollout.enabled default, the opposite of what was asked for.
    strategy = (pcs.metadata.annotations or {}).get(ANNOTATION_ROLLOUT_STRATEGY)
    if strategy is not None and strategy not in ROLLOUT_STRATEGIES:
        errs.append(
            ValidationError(
                f"metadata.annotations[{ANNOTATION_ROLLOUT_STRATEGY}]",
                f"unknown rollout strategy {strategy!r}; must be one of "
                + ", ".join(ROLLOUT_STRATEGIES),
            )
        )

    clique_names = [c.name for c in tmpl.cliques]
    _require_unique(errs, clique_names, "spec.template.cliques.name", "clique names must be unique")
    role_names = [c.spec.role_name for c in tmpl.cliques if c.spec.role_name]
    _require_unique(errs, role_names, "spec.template.cliques.spec.roleName", "role names must be unique")

    sg_member_cliques: set[str] = set()
    for cfg in tmpl.pod_clique_scaling_group_configs:
        sg_member_cliques.update(cfg.clique_names)

    for i, clique in enumerate(tmpl.cliques):
        fld = f"spec.template.cliques[{i}]"
        spec = clique.spec
        if spec.replicas <= 0:
            errs.append(ValidationError(f"{fld}.spec.replicas", "must be greater than 0"))
        if spec.min_available is not None:
            if spec.min_available <= 0:
                errs.append(ValidationError(f"{fld}.spec.minAvailable", "must be greater than 0"))
            elif spec.min_available > spec.replicas:
                errs.append(
                    ValidationError(f"{fld}.spec.minAvailable", "minAvailable must not be greater than replicas")
                )
        for dep in spec.starts_after:
            if not dep:
                errs.append(ValidationError(f"{fld}.spec.startsAfter", "clique dependency must not be empty"))
            elif dep == clique.name:
                errs.append(ValidationError(f"{fld}.spec.startsAfter", "clique dependency cannot refer to itself"))
            elif dep not in clique_names:
                errs.append(
                    ValidationError(
                        f"{fld}.spec.startsAfter",
                        f"unknown clique {dep!r}, all clique dependencies must be defined as cliques",
                    )
                )
        _require_unique(errs, spec.starts_after, f"{fld}.spec.startsAfter", "clique dependencies must be unique")
        if spec.scale_config is not None:
            sc = spec.scale_config
            if clique.name in sg_member_cliques:
                errs.append(
                    ValidationError(
                        f"{fld}.spec.autoScalingConfig",
                        "cliques in a PodCliqueScalingGroup cannot have individual autoscaling",
                    )
                )
            min_avail = spec.min_available if spec.min_available is not None else spec.replicas
            min_reps = sc.min_replicas if sc.min_replicas is not None else spec.replicas
            if min_reps < min_avail:
                errs.append(
                    ValidationError(
                        f"{fld}.spec.autoScalingConfig.minReplicas",
                        "must be greater than or equal to minAvailable",
                    )
                )
            if sc.max_replicas < min_reps:
                errs.append(
                    ValidationError(
                        f"{fld}.spec.autoScalingConfig.maxReplicas",
                        "must be greater than or equal to minReplicas",
                    )
                )
            if sc.max_replicas < spec.replicas:
                errs.append(
                    ValidationError(
                        f"{fld}.spec.autoScalingConfig.maxReplicas",
                        "must be greater than or equal to replicas",
                    )
                )

    errs.extend(_validate_startup_dag(pcs))
    errs.extend(_validate_scaling_groups(pcs))
    errs.extend(_validate_topology_constraints(pcs, topology))
    return errs


def _validate_scaling_groups(pcs: PodCliqueSet) -> list[ValidationError]:
    errs: list[ValidationError] = []
    tmpl = pcs.spec.template
    clique_names = {c.name for c in tmpl.cliques}
    sg_names = [cfg.name for cfg in tmpl.pod_clique_scaling_group_configs]
    _require_unique(errs, sg_names, "spec.template.podCliqueScalingGroups.name", "scaling group names must be unique")
    all_members: list[str] = []
    for i, cfg in enumerate(tmpl.pod_clique_scaling_group_configs):
        fld = f"spec.template.podCliqueScalingGroups[{i}]"
        if not cfg.clique_names:
            errs.append(ValidationError(f"{fld}.cliqueNames", "at least one clique name is required"))
        for cn in cfg.clique_names:
            if cn not in clique_names:
                errs.append(ValidationError(f"{fld}.cliqueNames", f"unknown clique {cn!r}"))
        all_members.extend(cfg.clique_names)
        if cfg.replicas <= 0:
            errs.append(ValidationError(f"{fld}.replicas", "must be greater than 0"))
        if cfg.min_available <= 0:
            errs.append(ValidationError(f"{fld}.minAvailable", "must be greater than 0"))
        if cfg.min_available > cfg.replicas:
            errs.append(ValidationError(f"{fld}.minAvailable", "minAvailable must not be greater than replicas"))
        if cfg.scale_config is not None:
            min_reps = cfg.scale_config.min_replicas if cfg.scale_config.min_replicas is not None else cfg.replicas
            if min_reps < cfg.min_available:
                errs.append(
                    ValidationError(
                        f"{fld}.scaleConfig.minReplicas",
                        "must be greater than or equal to minAvailable",
                    )
                )
            if cfg.scale_config.max_replicas < min_reps:
                errs.append(
                    ValidationError(
                        f"{fld}.scaleConfig.maxReplicas",
                        "must be greater than or equal to minReplicas",
                    )
                )
    _require_unique(
        errs,
        all_members,
        "spec.template.podCliqueScalingGroups.cliqueNames",
        "clique names must not overlap across scaling groups",
    )
    return errs


def _validate_startup_dag(pcs: PodCliqueSet) -> list[ValidationError]:
    """Cycle detection over StartsAfter (validation/podcliqueset.go:290-309)."""
    errs: list[ValidationError] = []
    tmpl = pcs.spec.template
    if tmpl.startup_type != CliqueStartupType.EXPLICIT:
        for c in tmpl.cliques:
            if c.spec.starts_after:
                errs.append(
                    ValidationError(
                        "spec.template.cliques.spec.startsAfter",
                        "startsAfter is only allowed with CliqueStartupTypeExplicit",
                    )
                )
                break
        return errs

    graph = {c.name: [d for d in c.spec.starts_after if any(x.name == d for x in tmpl.cliques)] for c in tmpl.cliques}
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}

    def dfs(node: str) -> bool:
        color[node] = GRAY
        for dep in graph[node]:
            if color[dep] == GRAY:
                return True
            if color[dep] == WHITE and dfs(dep):
                return True
        color[node] = BLACK
        return False

    for node in graph:
        if color[node] == WHITE and dfs(node):
            errs.append(
                ValidationError(
                    "spec.template.cliques.spec.startsAfter",
                    "clique must not have circular dependencies",
                )
            )
            break
    return errs


def _validate_topology_constraints(
    pcs: PodCliqueSet, topology: ClusterTopology | None
) -> list[ValidationError]:
    """Hierarchy: child (PCLQ) must be equal-or-narrower than parent (PCSG/PCS),
    and every referenced domain must exist in the ClusterTopology
    (validation/topologyconstraints.go)."""
    errs: list[ValidationError] = []
    tmpl = pcs.spec.template

    if (
        pcs.spec.topology_spread_domain is not None
        and topology is not None
        and topology.label_key_for(pcs.spec.topology_spread_domain) is None
    ):
        errs.append(
            ValidationError(
                "spec.topologySpreadDomain",
                f"topology domain {pcs.spec.topology_spread_domain.value!r} "
                "is not defined in the cluster topology",
            )
        )

    def check_domain_exists(tc: TopologyConstraint | None, fld: str) -> None:
        if tc is None or topology is None:
            return
        for dom in (tc.pack_domain, tc.preferred_domain):
            if dom is not None and topology.label_key_for(dom) is None:
                errs.append(
                    ValidationError(
                        fld,
                        f"topology domain {dom.value!r} is not defined in the cluster topology",
                    )
                )
        # A preferred level BROADER than the required pack is vacuous (the
        # required domain already confines every pod inside one preferred
        # domain) — reject it as authored confusion, like the parent check.
        if (
            tc.pack_domain is not None
            and tc.preferred_domain is not None
            and is_domain_narrower(tc.pack_domain, tc.preferred_domain)
        ):
            errs.append(
                ValidationError(
                    fld,
                    f"preferredDomain {tc.preferred_domain.value!r} must be equal to "
                    f"or narrower than packDomain {tc.pack_domain.value!r}",
                )
            )

    def check_narrower(child: TopologyConstraint | None, parent: TopologyConstraint | None, fld: str) -> None:
        if child is None or parent is None:
            return
        if child.pack_domain is None or parent.pack_domain is None:
            return  # preferred-only constraints never conflict hierarchically
        if is_domain_narrower(parent.pack_domain, child.pack_domain):
            errs.append(
                ValidationError(
                    fld,
                    f"constraint domain {child.pack_domain.value!r} must be equal to or "
                    f"narrower than the parent constraint {parent.pack_domain.value!r}",
                )
            )

    pcs_tc = tmpl.topology_constraint
    check_domain_exists(pcs_tc, "spec.template.topologyConstraint")
    sg_by_clique: dict[str, TopologyConstraint | None] = {}
    for i, cfg in enumerate(tmpl.pod_clique_scaling_group_configs):
        fld = f"spec.template.podCliqueScalingGroups[{i}].topologyConstraint"
        check_domain_exists(cfg.topology_constraint, fld)
        check_narrower(cfg.topology_constraint, pcs_tc, fld)
        for cn in cfg.clique_names:
            sg_by_clique[cn] = cfg.topology_constraint
    for i, clique in enumerate(tmpl.cliques):
        fld = f"spec.template.cliques[{i}].topologyConstraint"
        check_domain_exists(clique.topology_constraint, fld)
        parent = sg_by_clique.get(clique.name) or pcs_tc
        check_narrower(clique.topology_constraint, parent, fld)
    return errs


def validate_update(old: PodCliqueSet, new: PodCliqueSet) -> list[ValidationError]:
    """Update immutability (validation/podcliqueset.go:440-544)."""
    errs: list[ValidationError] = []
    old_tmpl, new_tmpl = old.spec.template, new.spec.template

    old_cliques = {c.name: c for c in old_tmpl.cliques}
    new_cliques = {c.name: c for c in new_tmpl.cliques}
    if set(old_cliques) != set(new_cliques):
        errs.append(
            ValidationError("spec.template.cliques", "cliques cannot be added or removed on update")
        )
    if new_tmpl.startup_type != old_tmpl.startup_type:
        errs.append(ValidationError("spec.template.startupType", "field is immutable"))
    if new_tmpl.startup_type in (CliqueStartupType.IN_ORDER, CliqueStartupType.EXPLICIT):
        old_order = [c.name for c in old_tmpl.cliques]
        new_order = [c.name for c in new_tmpl.cliques]
        if old_order != new_order and set(old_order) == set(new_order):
            errs.append(
                ValidationError(
                    "spec.template.cliques",
                    "clique order cannot be changed when StartupType is InOrder or Explicit",
                )
            )
    for name, new_c in new_cliques.items():
        old_c = old_cliques.get(name)
        if old_c is None:
            continue
        if new_c.spec.min_available != old_c.spec.min_available:
            errs.append(ValidationError(f"spec.template.cliques[{name}].spec.minAvailable", "field is immutable"))
        if new_c.spec.role_name != old_c.spec.role_name:
            errs.append(ValidationError(f"spec.template.cliques[{name}].spec.roleName", "field is immutable"))

    old_sgs = {c.name: c for c in old_tmpl.pod_clique_scaling_group_configs}
    new_sgs = {c.name: c for c in new_tmpl.pod_clique_scaling_group_configs}
    if set(old_sgs) != set(new_sgs):
        errs.append(
            ValidationError(
                "spec.template.podCliqueScalingGroups",
                "scaling groups cannot be added or removed on update",
            )
        )
    for name, new_sg in new_sgs.items():
        old_sg = old_sgs.get(name)
        if old_sg is None:
            continue
        if new_sg.min_available != old_sg.min_available:
            errs.append(
                ValidationError(f"spec.template.podCliqueScalingGroups[{name}].minAvailable", "field is immutable")
            )
        if new_sg.clique_names != old_sg.clique_names:
            errs.append(
                ValidationError(f"spec.template.podCliqueScalingGroups[{name}].cliqueNames", "field is immutable")
            )
    return errs


def _require_unique(errs: list[ValidationError], items: list[str], field_name: str, message: str) -> None:
    seen: set[str] = set()
    for item in items:
        if item in seen:
            errs.append(ValidationError(field_name, f"{message}: {item!r}"))
            return
        seen.add(item)
