"""Lightweight Pod object — the unit the orchestrator creates and the solver places.

The reference uses corev1.Pod built by the PodClique pod component
(operator/internal/controller/podclique/components/pod/pod.go:68,135-172,232-269):
scheduling gate `grove.io/podgang-pending-creation`, GROVE_* env vars, stable
hostname `<pclq>-<idx>` + subdomain, startup-ordering init container. We keep the
same observable fields plus a dense resource-request vector filled in by
grove_tpu/state when snapshotting.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from grove_tpu.api.types import Condition, PodSpec


class PodPhase(str, enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    spec: PodSpec = field(default_factory=PodSpec)
    # Grove bookkeeping (labels in the reference; first-class here):
    pclq_fqn: str = ""
    podgang_name: str = ""
    base_podgang_name: Optional[str] = None  # set for pods of scaled gangs
    pod_index: int = 0  # stable hostname index (internal/index/tracker.go)
    pod_template_hash: str = ""
    env: dict[str, str] = field(default_factory=dict)
    # Lifecycle:
    phase: PodPhase = PodPhase.PENDING
    conditions: list[Condition] = field(default_factory=list)
    node_name: Optional[str] = None
    scheduling_gates: list[str] = field(default_factory=list)
    deletion_timestamp: Optional[float] = None
    started_at: Optional[float] = None
    ready: bool = False
    # Container terminated erroneously and keeps restarting: the pod stays
    # bound and active (restartPolicy Always) but is neither ready nor
    # "starting" (utils/kubernetes/pod.go:95-112 HasPodTerminatedErroneously).
    crashlooping: bool = False

    @property
    def is_gated(self) -> bool:
        return bool(self.scheduling_gates)

    @property
    def is_scheduled(self) -> bool:
        return self.node_name is not None

    @property
    def is_active(self) -> bool:
        """Not terminal and not being deleted — counts toward replica math."""
        return (
            self.deletion_timestamp is None
            and self.phase not in (PodPhase.SUCCEEDED, PodPhase.FAILED)
        )

    @property
    def hostname(self) -> str:
        return f"{self.pclq_fqn}-{self.pod_index}"
