"""The admission gateway: defaulting → validation → authorization.

In-process analog of the reference's webhook chain
(`operator/internal/webhook/register.go:34-62`): a mutation enters through
`admit_*` and passes the defaulting webhook
(`admission/pcs/defaulting/podcliqueset.go:35-108`), the validating webhook
(`admission/pcs/validation/`), and — when enabled — the authorizer
(`admission/pcs/authorization/handler.go:60-80`), which blocks actors other
than the operator (and configured exempt actors) from mutating resources the
operator manages: PodCliques, PodCliqueScalingGroups, PodGangs, and Pods
owned by a PodCliqueSet. Users create/update/delete PodCliqueSets; everything
below them belongs to the reconciler.
"""

from __future__ import annotations

from dataclasses import dataclass

from grove_tpu.api import constants
from grove_tpu.api.defaulting import default_podcliqueset
from grove_tpu.api.types import ClusterTopology, PodCliqueSet
from grove_tpu.api.validation import (
    ValidationError,
    validate_podcliqueset,
    validate_update,
)

# The reconciler's own identity; always allowed to touch managed resources.
OPERATOR_ACTOR = "system:grove-operator"

# Kinds the operator owns end-to-end (authorization/handler.go exempt list is
# the inverse: these kinds are protected FROM everyone else).
MANAGED_KINDS = ("PodClique", "PodCliqueScalingGroup", "PodGang", "Pod")


class AdmissionError(Exception):
    """Mutation rejected by the admission chain."""

    def __init__(self, errors: list):
        self.errors = list(errors)
        super().__init__("; ".join(str(e) for e in self.errors))


@dataclass
class Authorizer:
    """authorizer webhook analog (types.go:211-220, handler.go:60-80)."""

    enabled: bool = False
    exempt_actors: tuple[str, ...] = ()

    def check(self, actor: str, kind: str, name: str) -> None:
        """Raise PermissionError for a non-exempt actor mutating a managed kind."""
        if not self.enabled or kind not in MANAGED_KINDS:
            return
        if actor == OPERATOR_ACTOR or actor in self.exempt_actors:
            return
        raise PermissionError(
            f"actor {actor!r} may not mutate managed resource {kind}/{name} "
            f"(grove authorizer; exempt actors: {list(self.exempt_actors)})"
        )


@dataclass
class AdmissionChain:
    """defaulting + validation + authorization, invoked at apply time."""

    topology: ClusterTopology | None = None
    authorizer: Authorizer = None  # type: ignore[assignment]
    # Configured capacity queue names (scheduling.queues); None = don't
    # check (e.g. the CLI's config-less dry run). A workload naming an
    # unknown queue is rejected at the door — a typo'd queue would
    # otherwise silently run unquoted.
    known_queues: frozenset | None = None
    # networkAcceleration.autoSliceEnabled (the MNNVL webhook's feature
    # gate, mnnvl/webhook.go:33-169). None = config unknown (CLI dry run
    # without --config): the annotation value is still checked but the
    # feature-enabled cross-check is skipped.
    auto_slice_enabled: bool | None = None
    slice_resource_name: str = constants.DEFAULT_SLICE_RESOURCE

    def __post_init__(self):
        if self.authorizer is None:
            self.authorizer = Authorizer()

    def admit_podcliqueset(
        self,
        pcs: PodCliqueSet,
        old: PodCliqueSet | None = None,
    ) -> PodCliqueSet:
        """Default + validate a PCS create/update; returns the mutated object.

        `old` triggers update-path immutability checks
        (validation/podcliqueset.go:440-508)."""
        pcs = default_podcliqueset(pcs)
        if old is None:
            # Auto-annotation is applied only on creation
            # (defaulting/handler.go:62-65).
            self._default_auto_slice(pcs)
        errors = validate_podcliqueset(pcs, self.topology)
        errors += self._validate_auto_slice(pcs, old)
        if old is not None:
            errors += validate_update(old, pcs)
        queue = pcs.metadata.annotations.get(constants.ANNOTATION_QUEUE, "")
        if queue and self.known_queues is not None and queue not in self.known_queues:
            errors = errors + [
                ValidationError(
                    f"metadata.annotations[{constants.ANNOTATION_QUEUE}]",
                    f"unknown queue {queue!r} (configured: "
                    f"{sorted(self.known_queues) or 'none'})",
                )
            ]
        if errors:
            raise AdmissionError(errors)
        return pcs

    def _requests_slice(self, pcs: PodCliqueSet) -> bool:
        """hasGPURequirement analog (mnnvl/webhook.go:~57): any clique
        template requesting the slice resource."""
        for tmpl in pcs.spec.template.cliques:
            if (
                tmpl.spec.pod_spec.total_requests().get(self.slice_resource_name, 0.0)
                > 0
            ):
                return True
        return False

    def _default_auto_slice(self, pcs: PodCliqueSet) -> None:
        """MutateAutoMNNVL analog (mnnvl/webhook.go:33-66): when the feature
        is globally enabled and the workload requests the slice resource,
        stamp grove.io/auto-slice: enabled — unless the user already set the
        annotation (explicit values, including "disabled", are never
        overridden)."""
        if not self.auto_slice_enabled:
            return
        if constants.ANNOTATION_AUTO_SLICE in pcs.metadata.annotations:
            return
        if not self._requests_slice(pcs):
            return
        pcs.metadata.annotations[constants.ANNOTATION_AUTO_SLICE] = (
            constants.AUTO_SLICE_ENABLED
        )

    def _validate_auto_slice(self, pcs: PodCliqueSet, old: PodCliqueSet | None) -> list:
        """auto-slice annotation validation, mirroring the MNNVL webhook:

        CREATE (ValidateMetadataOnCreate, mnnvl/webhook.go:69-118): value
        must be enabled|disabled; "enabled" while the feature is off is an
        error (the injection would silently never happen). The feature
        cross-check is create-only — flipping the feature off later must not
        brick updates to workloads that were auto-stamped while it was on.

        UPDATE (ValidateMetadataOnUpdate, webhook.go:120-169): the
        annotation is immutable — changing the value or adding it after
        creation is forbidden. One replace-semantics accommodation: the
        reference relies on apiserver merge-patch to carry the stamped
        annotation through user applies that never mention it; this
        surface's applies are whole-object, so an absent annotation on
        update is carried forward from `old` rather than treated as an
        explicit removal."""
        path = f"metadata.annotations[{constants.ANNOTATION_AUTO_SLICE}]"
        value = pcs.metadata.annotations.get(constants.ANNOTATION_AUTO_SLICE)
        if old is not None:
            old_value = old.metadata.annotations.get(constants.ANNOTATION_AUTO_SLICE)
            if value is None and old_value is not None:
                pcs.metadata.annotations[constants.ANNOTATION_AUTO_SLICE] = old_value
                return []
            if value is not None and old_value is None:
                return [
                    ValidationError(
                        path, "annotation cannot be added after creation (immutable)"
                    )
                ]
            if value != old_value:
                return [
                    ValidationError(
                        path,
                        f"annotation is immutable (was {old_value!r}, got {value!r})",
                    )
                ]
            return []
        if value is None:
            return []
        errors = []
        if value not in (constants.AUTO_SLICE_ENABLED, constants.AUTO_SLICE_DISABLED):
            errors.append(
                ValidationError(
                    path,
                    f"must be {constants.AUTO_SLICE_ENABLED!r} or "
                    f"{constants.AUTO_SLICE_DISABLED!r}, got {value!r}",
                )
            )
        elif value == constants.AUTO_SLICE_ENABLED and self.auto_slice_enabled is False:
            errors.append(
                ValidationError(
                    path,
                    "TPU slice injection requested but "
                    "networkAcceleration.autoSliceEnabled is false",
                )
            )
        return errors

    def admit_managed_mutation(self, actor: str, kind: str, name: str) -> None:
        self.authorizer.check(actor, kind, name)
