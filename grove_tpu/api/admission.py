"""The admission gateway: defaulting → validation → authorization.

In-process analog of the reference's webhook chain
(`operator/internal/webhook/register.go:34-62`): a mutation enters through
`admit_*` and passes the defaulting webhook
(`admission/pcs/defaulting/podcliqueset.go:35-108`), the validating webhook
(`admission/pcs/validation/`), and — when enabled — the authorizer
(`admission/pcs/authorization/handler.go:60-80`), which blocks actors other
than the operator (and configured exempt actors) from mutating resources the
operator manages: PodCliques, PodCliqueScalingGroups, PodGangs, and Pods
owned by a PodCliqueSet. Users create/update/delete PodCliqueSets; everything
below them belongs to the reconciler.
"""

from __future__ import annotations

from dataclasses import dataclass

from grove_tpu.api import constants
from grove_tpu.api.defaulting import default_podcliqueset
from grove_tpu.api.types import ClusterTopology, PodCliqueSet
from grove_tpu.api.validation import (
    ValidationError,
    validate_podcliqueset,
    validate_update,
)

# The reconciler's own identity; always allowed to touch managed resources.
OPERATOR_ACTOR = "system:grove-operator"

# Kinds the operator owns end-to-end (authorization/handler.go exempt list is
# the inverse: these kinds are protected FROM everyone else).
MANAGED_KINDS = ("PodClique", "PodCliqueScalingGroup", "PodGang", "Pod")


class AdmissionError(Exception):
    """Mutation rejected by the admission chain."""

    def __init__(self, errors: list):
        self.errors = list(errors)
        super().__init__("; ".join(str(e) for e in self.errors))


@dataclass
class Authorizer:
    """authorizer webhook analog (types.go:211-220, handler.go:60-80)."""

    enabled: bool = False
    exempt_actors: tuple[str, ...] = ()

    def check(self, actor: str, kind: str, name: str) -> None:
        """Raise PermissionError for a non-exempt actor mutating a managed kind."""
        if not self.enabled or kind not in MANAGED_KINDS:
            return
        if actor == OPERATOR_ACTOR or actor in self.exempt_actors:
            return
        raise PermissionError(
            f"actor {actor!r} may not mutate managed resource {kind}/{name} "
            f"(grove authorizer; exempt actors: {list(self.exempt_actors)})"
        )


@dataclass
class AdmissionChain:
    """defaulting + validation + authorization, invoked at apply time."""

    topology: ClusterTopology | None = None
    authorizer: Authorizer = None  # type: ignore[assignment]
    # Configured capacity queue names (scheduling.queues); None = don't
    # check (e.g. the CLI's config-less dry run). A workload naming an
    # unknown queue is rejected at the door — a typo'd queue would
    # otherwise silently run unquoted.
    known_queues: frozenset | None = None

    def __post_init__(self):
        if self.authorizer is None:
            self.authorizer = Authorizer()

    def admit_podcliqueset(
        self,
        pcs: PodCliqueSet,
        old: PodCliqueSet | None = None,
    ) -> PodCliqueSet:
        """Default + validate a PCS create/update; returns the mutated object.

        `old` triggers update-path immutability checks
        (validation/podcliqueset.go:440-508)."""
        pcs = default_podcliqueset(pcs)
        errors = validate_podcliqueset(pcs, self.topology)
        if old is not None:
            errors += validate_update(old, pcs)
        queue = pcs.metadata.annotations.get(constants.ANNOTATION_QUEUE, "")
        if queue and self.known_queues is not None and queue not in self.known_queues:
            errors = errors + [
                ValidationError(
                    f"metadata.annotations[{constants.ANNOTATION_QUEUE}]",
                    f"unknown queue {queue!r} (configured: "
                    f"{sorted(self.known_queues) or 'none'})",
                )
            ]
        if errors:
            raise AdmissionError(errors)
        return pcs

    def admit_managed_mutation(self, actor: str, kind: str, name: str) -> None:
        self.authorizer.check(actor, kind, name)
