"""Scheduler IR: the PodGang contract between orchestrator and placement backend.

Semantic parity with the reference scheduler API (scheduler/api/core/v1alpha1/podgang.go):
  - PodGangSpec{PodGroups, TopologyConstraint, TopologyConstraintGroupConfigs,
    PriorityClassName, ReuseReservationRef} (podgang.go:51-72)
  - PodGroup{PodReferences, MinReplicas, TopologyConstraint} (podgang.go:75-89)
  - TopologyPackConstraint{Required, Preferred} holding *node-label keys*
    (translated from workload-level domain names) (podgang.go:101-117)
  - Phases Pending/Starting/Running (podgang.go:143-150)
  - Conditions Scheduled/Ready/Unhealthy/DisruptionTarget (podgang.go:155-168)
  - PlacementScore (0,1] with 1.0 = optimal (podgang.go:170-179)

This is the tensorizable boundary: everything below this IR is dense-tensor
work in grove_tpu/state + grove_tpu/solver.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

from grove_tpu.api.types import Condition


@dataclass(frozen=True)
class NamespacedName:
    namespace: str
    name: str

    def __str__(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class TopologyPackConstraint:
    """Hard/soft packing constraint as node-label keys (podgang.go:101-117)."""

    required: Optional[str] = None  # e.g. "topology.kubernetes.io/rack"
    preferred: Optional[str] = None  # e.g. "kubernetes.io/hostname"


@dataclass
class IRTopologyConstraint:
    """IR-level constraint wrapper (podgang.go:94-99)."""

    pack_constraint: Optional[TopologyPackConstraint] = None


@dataclass
class PodGroup:
    """Pods sharing one template within a gang (podgang.go:75-89).

    MinReplicas is the gang floor: scheduling of the gang is all-or-nothing for
    MinReplicas of each group; pods beyond it are best-effort.
    """

    name: str
    pod_references: list[NamespacedName] = field(default_factory=list)
    min_replicas: int = 0
    topology_constraint: Optional[IRTopologyConstraint] = None


@dataclass
class TopologyConstraintGroupConfig:
    """Constraint over a strict subset of PodGroups (podgang.go:120-128).

    Used for PCSG-level packing: all pods of one PCSG replica (spanning its
    member-clique PodGroups) must pack into one domain.
    """

    name: str
    pod_group_names: list[str] = field(default_factory=list)
    topology_constraint: Optional[IRTopologyConstraint] = None


class PodGangPhase(str, enum.Enum):
    PENDING = "Pending"
    STARTING = "Starting"
    RUNNING = "Running"
    FAILED = "Failed"
    SUCCEEDED = "Succeeded"


@dataclass
class PodGangSpec:
    pod_groups: list[PodGroup] = field(default_factory=list)
    topology_constraint: Optional[IRTopologyConstraint] = None
    topology_constraint_group_configs: list[TopologyConstraintGroupConfig] = field(default_factory=list)
    priority_class_name: str = ""
    reuse_reservation_ref: Optional[NamespacedName] = None
    # Replica spread (PCS topologySpreadDomain translated to a node-label
    # key, like pack constraints): base gangs of sibling PCS replicas prefer
    # domains at this level that no sibling occupies (soft; w_spread).
    spread_key: Optional[str] = None


@dataclass
class PodGangStatus:
    phase: PodGangPhase = PodGangPhase.PENDING
    conditions: list[Condition] = field(default_factory=list)
    # Fraction of scheduled placement quality, (0,1], 1.0 = optimal
    # (podgang.go:176-178).
    placement_score: Optional[float] = None
    # Per-group count of pods bound to nodes (used by gate-removal logic:
    # podclique/components/pod/syncflow.go:303-345 checks
    # ScheduledReplicas >= MinReplicas for every group of the base gang).
    scheduled_replicas: dict[str, int] = field(default_factory=dict)
    # Latch: the gang achieved Scheduled at least once. Distinguishes a gang
    # that LOST its placement (Unhealthy, podgang.go:155-168) from one that
    # never had any (merely Pending) — the live Scheduled condition flips back
    # to False in both cases.
    ever_scheduled: bool = False


@dataclass
class PodGang:
    """The gang CR handed to the placement backend."""

    name: str
    namespace: str = "default"
    spec: PodGangSpec = field(default_factory=PodGangSpec)
    status: PodGangStatus = field(default_factory=PodGangStatus)
    # Bookkeeping mirrored from labels in the reference:
    pcs_name: str = ""
    # Capacity queue (grove.io/queue annotation; "" = unquoted). The KAI
    # Queue analog — quota enforcement is the controller's pre-solve
    # admission filter (orchestrator/controller.py _solve_wave).
    queue: str = ""
    # SLO tier (spec.template.sloClass, api/constants.py SLO_CLASSES):
    # admission order, borrowing eligibility, preemptibility. "" ranks as
    # "standard" for gangs admitted before the field existed.
    slo_class: str = ""
    pcs_replica_index: int = 0
    # For scaled gangs: the base gang that must schedule first
    # (grove.io/base-podgang label; podclique/components/pod/syncflow.go:347-387).
    base_podgang_name: Optional[str] = None
    # 0-based scaled-gang index (pcsg_replica - minAvailable); -1 for base gangs.
    scaled_index: int = -1

    @property
    def is_scaled(self) -> bool:
        return self.base_podgang_name is not None

    def total_min_replicas(self) -> int:
        return sum(g.min_replicas for g in self.spec.pod_groups)

    def total_pods(self) -> int:
        return sum(len(g.pod_references) for g in self.spec.pod_groups)

    def is_base_gang_scheduled(self) -> bool:
        """All groups have ScheduledReplicas >= MinReplicas (syncflow.go:303-345)."""
        return all(
            self.status.scheduled_replicas.get(g.name, 0) >= g.min_replicas
            for g in self.spec.pod_groups
        )
