"""Workload API surface: core types, scheduler IR, naming, defaulting, validation."""

from grove_tpu.api.types import (  # noqa: F401
    AutoScalingConfig,
    CliqueStartupType,
    ClusterTopology,
    Condition,
    Container,
    DEFAULT_CLUSTER_TOPOLOGY,
    HeadlessServiceConfig,
    ObjectMeta,
    PodClique,
    PodCliqueScalingGroup,
    PodCliqueScalingGroupConfig,
    PodCliqueScalingGroupSpec,
    PodCliqueScalingGroupStatus,
    PodCliqueSet,
    PodCliqueSetSpec,
    PodCliqueSetStatus,
    PodCliqueSetTemplateSpec,
    PodCliqueSpec,
    PodCliqueStatus,
    PodCliqueTemplateSpec,
    PodSpec,
    TopologyConstraint,
    TopologyDomain,
    TopologyLevel,
    TOPOLOGY_DOMAIN_ORDER,
    get_condition,
    is_domain_narrower,
    set_condition,
)
from grove_tpu.api.pod import Pod, PodPhase  # noqa: F401
from grove_tpu.api.podgang import (  # noqa: F401
    IRTopologyConstraint,
    NamespacedName,
    PodGang,
    PodGangPhase,
    PodGangSpec,
    PodGangStatus,
    PodGroup,
    TopologyConstraintGroupConfig,
    TopologyPackConstraint,
)
from grove_tpu.api.defaulting import default_podcliqueset  # noqa: F401
from grove_tpu.api.validation import ValidationError, validate_podcliqueset, validate_update  # noqa: F401
