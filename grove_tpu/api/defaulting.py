"""Admission defaulting, parity with the defaulting webhook
(operator/internal/webhook/admission/pcs/defaulting/podcliqueset.go:35-108).

Applied in place on a freshly loaded PodCliqueSet before validation:
  - namespace -> "default"
  - clique replicas 0 -> 1; minAvailable -> replicas;
    scaleConfig.minReplicas -> replicas
  - PCSG config replicas -> 1 (kubebuilder default), minAvailable -> 1,
    scaleConfig.minReplicas -> PCSG replicas
  - terminationDelay -> 4h; headlessServiceConfig.publishNotReadyAddresses -> true
  - podSpec restartPolicy -> Always, terminationGracePeriodSeconds -> 30
"""

from __future__ import annotations

from grove_tpu.api.constants import DEFAULT_SLO_CLASS
from grove_tpu.api.types import (
    AutoScalingConfig,
    HeadlessServiceConfig,
    PodCliqueSet,
)


def default_podcliqueset(pcs: PodCliqueSet) -> PodCliqueSet:
    """Mutates and returns pcs (analog of defaultPodCliqueSet, defaulting/podcliqueset.go:35)."""
    if not pcs.metadata.namespace:
        pcs.metadata.namespace = "default"
    tmpl = pcs.spec.template

    for clique in tmpl.cliques:
        spec = clique.spec
        if spec.replicas == 0:
            spec.replicas = 1
        if spec.min_available is None:
            spec.min_available = spec.replicas
        if spec.scale_config is not None and spec.scale_config.min_replicas is None:
            spec.scale_config.min_replicas = spec.replicas
        ps = spec.pod_spec
        if not ps.restart_policy:
            ps.restart_policy = "Always"
        if ps.termination_grace_period_seconds is None:
            ps.termination_grace_period_seconds = 30

    for cfg in tmpl.pod_clique_scaling_group_configs:
        # replicas/minAvailable carry kubebuilder default 1 (podcliqueset.go:212-227);
        # the dataclass already defaults both to 1 on load.
        if cfg.scale_config is not None and cfg.scale_config.min_replicas is None:
            cfg.scale_config.min_replicas = cfg.replicas

    if tmpl.termination_delay_seconds is None:
        tmpl.termination_delay_seconds = 4 * 3600.0
    if not tmpl.slo_class:
        tmpl.slo_class = DEFAULT_SLO_CLASS
    if tmpl.headless_service_config is None:
        tmpl.headless_service_config = HeadlessServiceConfig(publish_not_ready_addresses=True)
    return pcs


def effective_min_replicas(scale_config: AutoScalingConfig | None, replicas: int) -> int:
    if scale_config is None or scale_config.min_replicas is None:
        return replicas
    return scale_config.min_replicas
