"""Shared label keys, environment variable names, conditions and finalizers.

Behavioral parity with the reference's common API helpers
(operator/api/common/labels.go:21-45, operator/api/common/constants/constants.go:32-122).
"""

# --- Label keys (labels.go:21-45) ------------------------------------------------

LABEL_MANAGED_BY = "app.kubernetes.io/managed-by"
LABEL_MANAGED_BY_VALUE = "grove-tpu-operator"
LABEL_PART_OF = "app.kubernetes.io/part-of"  # value: PodCliqueSet name
LABEL_COMPONENT = "app.kubernetes.io/component"

LABEL_PODCLIQUE = "grove.io/podclique"
LABEL_PODGANG = "grove.io/podgang"
LABEL_BASE_PODGANG = "grove.io/base-podgang"  # set on pods of *scaled* gangs
LABEL_PCS_REPLICA_INDEX = "grove.io/podcliqueset-replica-index"
LABEL_PCSG_REPLICA_INDEX = "grove.io/podcliquescalinggroup-replica-index"
LABEL_POD_TEMPLATE_HASH = "grove.io/pod-template-hash"
LABEL_PCS_GENERATION_HASH = "grove.io/podcliqueset-generation-hash"
LABEL_POD_GANG_NAME = LABEL_PODGANG
LABEL_SCALING_GROUP = "grove.io/podcliquescalinggroup"
LABEL_POD_INDEX = "grove.io/pod-index"

# Component values used to select managed resources per kind.
COMPONENT_PCLQ_POD = "pclq-pod"
COMPONENT_HEADLESS_SERVICE = "pcs-headless-service"
COMPONENT_HPA = "pcs-hpa"
COMPONENT_PODGANG = "pcs-podgang"
COMPONENT_PODCLIQUE = "pcs-podclique"
COMPONENT_PCSG = "pcs-podcliquescalinggroup"
COMPONENT_SERVICE_ACCOUNT = "pcs-service-account"
COMPONENT_ROLE = "pcs-role"
COMPONENT_ROLE_BINDING = "pcs-role-binding"
COMPONENT_SA_TOKEN_SECRET = "pcs-sa-token-secret"
COMPONENT_COMPUTE_DOMAIN = "pcs-compute-domain"

# --- Scheduling gate (podclique/components/pod/pod.go:68) ------------------------

POD_GANG_SCHEDULING_GATE = "grove.io/podgang-pending-creation"

# --- Environment variables injected into pods (constants/constants.go:53-67) -----

ENV_PCS_NAME = "GROVE_PCS_NAME"
ENV_PCS_INDEX = "GROVE_PCS_INDEX"
ENV_PCLQ_NAME = "GROVE_PCLQ_NAME"
ENV_PCLQ_POD_INDEX = "GROVE_PCLQ_POD_INDEX"
ENV_HEADLESS_SERVICE = "GROVE_HEADLESS_SERVICE"
ENV_PCSG_NAME = "GROVE_PCSG_NAME"
ENV_PCSG_INDEX = "GROVE_PCSG_INDEX"

# --- Condition types (constants/constants.go:88-122) -----------------------------

CONDITION_MIN_AVAILABLE_BREACHED = "MinAvailableBreached"
CONDITION_POD_CLIQUE_SCHEDULED = "PodCliqueScheduled"
CONDITION_UPDATE_IN_PROGRESS = "UpdateInProgress"

# PodGang conditions (scheduler/api/core/v1alpha1/podgang.go:155-168)
PODGANG_CONDITION_SCHEDULED = "Scheduled"
PODGANG_CONDITION_READY = "Ready"
PODGANG_CONDITION_UNHEALTHY = "Unhealthy"
PODGANG_CONDITION_DISRUPTION_TARGET = "DisruptionTarget"

# --- Finalizers (constants/constants.go:32-39) -----------------------------------

FINALIZER_PCS = "grove.io/podcliqueset-protection"
FINALIZER_PCLQ = "grove.io/podclique-protection"
FINALIZER_PCSG = "grove.io/podcliquescalinggroup-protection"

# --- Annotations -----------------------------------------------------------------

ANNOTATION_MNNVL = "grove.io/network-acceleration"  # analog: TPU slice acceleration
ANNOTATION_ICI_DOMAIN = "grove.io/ici-domain"  # TPU-native: pin gang to ICI domain
# Per-workload TPU-slice injection opt-in/out (the grove.io/auto-mnnvl
# analog, mnnvl/helpers.go:29-34): defaulted to "enabled" at admission when
# the feature is on and a clique requests the slice resource; users may
# pre-set it to either value (webhook.go:33-66).
ANNOTATION_AUTO_SLICE = "grove.io/auto-slice"
AUTO_SLICE_ENABLED = "enabled"
AUTO_SLICE_DISABLED = "disabled"
# The ONE default for the TPU-slice device resource name (the GPU-request
# analog, mnnvl/helpers.go hasGPURequirement): config, admission chain, and
# the config-less CLI dry run must agree or they check different resources.
DEFAULT_SLICE_RESOURCE = "google.com/tpu"
# Capacity queue this workload's gangs draw quota from (the KAI Queue
# analog, e2e/yaml/queues.yaml; scheduling.queues in the operator config).
ANNOTATION_QUEUE = "grove.io/queue"
# Set "true" on a PodCliqueSet to bypass the authorizer's managed-resource
# protection for its children (constants.go:43-45).
ANNOTATION_DISABLE_PROTECTION = "grove.io/disable-managed-resource-protection"
# Per-PCS rolling-update strategy (docs/design.md "Fleet lifecycle"):
# "make-before-break" plans the replacement generation onto free capacity
# and cuts over atomically; "recreate" pins the delete-then-recreate seed
# behavior. Unset defers to the operator config's `rollout.enabled`.
ANNOTATION_ROLLOUT_STRATEGY = "grove.io/rollout-strategy"
ROLLOUT_STRATEGY_MAKE_BEFORE_BREAK = "make-before-break"
ROLLOUT_STRATEGY_RECREATE = "recreate"
ROLLOUT_STRATEGIES = (ROLLOUT_STRATEGY_MAKE_BEFORE_BREAK, ROLLOUT_STRATEGY_RECREATE)

# SLO classes (spec.template.sloClass; tenancy subsystem, docs/design.md
# "Multi-tenant SLO tiers"). The class maps to admission order, borrowing
# eligibility, and preemptibility: `latency` admits first and never borrows
# (so reclaim cannot name it off borrowed share), `batch-preemptible` is
# evicted first when an in-quota contender reclaims.
SLO_CLASS_LATENCY = "latency"
SLO_CLASS_STANDARD = "standard"
SLO_CLASS_BATCH = "batch-preemptible"
SLO_CLASSES = (SLO_CLASS_LATENCY, SLO_CLASS_STANDARD, SLO_CLASS_BATCH)
DEFAULT_SLO_CLASS = SLO_CLASS_STANDARD

# Default PodCliqueSet name budget: pod names must fit the 63-char DNS label after
# the operator appends `-<i>-[<pcsg>-<j>-]<pclq>-<5char suffix>`
# (webhook/admission/pcs/validation/podcliqueset.go:37-39,564).
MAX_PCS_NAME_LENGTH = 45
MAX_K8S_NAME_LENGTH = 63

# Control-plane event ring: the object API serves at most this many recent
# events; clients (CLI --tail) validate against the same bound.
EVENTS_BUFFER = 200

# Ceiling for the scale subresource (kubectl-scale analog): the operator
# materializes one in-memory Pod per replica, so an unbounded scale request
# could OOM the control plane in one reconcile. HPA maxReplicas (when an HPA
# targets the object) is the tighter, user-declared bound.
MAX_SCALE_REPLICAS = 10_000
