"""Streaming drain: the always-on admission loop under live arrival traffic.

`drain_backlog` answers "a backlog arrived at once"; this module answers the
BandPilot-shaped question (PAPERS.md): a scheduler that solves CONTINUOUSLY
while gangs keep arriving — bursty, diurnally modulated, heavy-tailed,
multi-tenant traffic (sim/workloads.arrival_process). The loop batches
queued arrivals into shape-bucketed waves and feeds them to the SAME
double-buffered pipeline engine as the drain (solver/drain._WavePipeline):
while wave N solves on device, the host encodes wave N+1 from fresh arrivals
and decodes/binds wave N-depth — the drain never syncs except at retirement.

Four disciplines, one dispatch chain (identical admissions by construction —
the chain is the same; test-pinned):

  resident   scan + chained retirement: NOTHING retires until the trace is
             exhausted — scan chunks chain device-side over the whole run
             and the host harvests every verdict in ONE batched device_get
             at the end, so device round-trips collapse to O(1 +
             escalations). Saturated mode only (`solver.scan.deviceResident`);
             first ladder rung, stepping down to scan.
  scan       pipeline + device-side fusion: consecutive same-shape-class
             waves (across windows, saturated mode) dispatch as ONE
             lax.scan chunk — O(shape classes) host round-trips instead of
             O(waves). Window composition is untouched, so admitted sets
             stay bitwise-equal to both baselines.
  pipeline   retire wave N-depth while wave N is in flight (the steady-state
             serving shape; ~chained-drain throughput, measured latencies)
  serial     retire every wave before forming the next (the wave-at-a-time
             baseline the pipelined mode is benchmarked against)

Class-affine window forming (`solver.scan.affinityLookahead`, saturated
mode only): planned waves from up to (1 + L) consecutive windows buffer and
reorder by (rank, shape class) before dispatch — rank 0 before rank 1,
classes in first-appearance order, each class's gang-axis pad canonicalized
up to the class max within the group — so same-class RUNS form under mixed
arrival traffic and the scan actually fuses. Window composition is
untouched (forming only reorders dispatch of already-planned waves), the
reorder is a pure function of the requested scan config (ladder state and
harvest discipline never affect it), and rank order still guarantees every
base dispatches before any scaled gang — so all four disciplines at the
same look-ahead see the identical wave sequence and admitted sets stay
bitwise-equal to serial. L=0 (or paced mode) is bitwise the unformed
window-at-a-time order.

Two clocks:

  saturated  (pace=False) arrivals are consumed flat-out in arrival order —
             wave composition is a pure function of (arrival order,
             wave_size), so serial and pipelined runs see IDENTICAL waves
             and their admitted sets must match exactly. The throughput
             measurement: steady-state gangs/sec is admitted/wall.
  paced      (pace=True) arrivals become visible at their trace offsets in
             wall time; a wave forms when wave_size gangs are queued, the
             oldest has waited max_wait_s, or the trace is exhausted.
             Time-to-bind (enqueue->bound) is MEASURED per gang against its
             arrival instant — the latency-under-load configuration. Wave
             composition depends on wall time, so paced runs are not the
             parity gate.

Ordering invariant: the arrival list must place a base gang before every
gang scaled from it (`sim.workloads.expand_arrivals` guarantees this);
within a window `plan_waves` enforces base-rank-first, and across windows
the ok_global device chain resolves the verdict.

The engine journals committed waves to an attached flight recorder with
monotonic `stream-NNNNNN` ids in commit order; trace replay stays bitwise
on the overlapped path (tests/test_stream.py pins it).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from grove_tpu.solver.core import SolverParams
from grove_tpu.solver.drain import (
    DrainStats,
    ScanConfig,
    WaveFault,
    _WavePipeline,
    plan_waves,
)


@dataclass(frozen=True)
class StreamConfig:
    """`solver.streaming` config block (runtime/config.py validates the
    YAML shape; this is the solver-side value object)."""

    # Pipeline depth: waves allowed in flight before the host blocks on the
    # oldest. 2 = classic double buffering (one solving, one encoding, one
    # retiring). Ignored by the serial discipline.
    depth: int = 2
    # Max gangs per formed window; also the plan_waves wave size. Smaller
    # waves bind arrivals sooner (lower time-to-bind), bigger waves amortize
    # per-wave dispatch better (higher throughput).
    wave_size: int = 64
    # Paced mode: how long the oldest queued gang may wait for companions
    # before a partial wave dispatches anyway.
    max_wait_s: float = 0.05
    # Paced mode: idle poll granularity while waiting for arrivals.
    poll_s: float = 0.005


@dataclass
class StreamStats:
    """One streaming run, as measured (wall seconds unless noted)."""

    offered: int = 0  # gangs fed from the arrival trace
    admitted: int = 0
    pods_bound: int = 0
    waves: int = 0
    windows: int = 0  # arrival windows formed (each plans >= 1 wave)
    wall_s: float = 0.0
    gangs_per_sec: float = 0.0  # admitted / wall — steady-state throughput
    depth: int = 0
    mode: str = "pipeline"  # pipeline | serial
    paced: bool = False
    # Per-ADMITTED-gang enqueue->bound seconds, in commit order. Under
    # pacing this is the real time-to-bind against the arrival instant;
    # saturated runs measure pull->bound (queueing excluded by design —
    # a saturated backlog's queueing delay is an artifact of the replay
    # rate, not of the scheduler).
    bind_latencies: list = field(default_factory=list)
    # The engine's phase/cache/escalation breakdown for this run.
    drain: DrainStats = field(default_factory=DrainStats)

    def bind_percentiles(self, qs=(50.0, 99.0)) -> dict | None:
        """Measured time-to-bind percentiles; None when nothing was bound
        (same no-fabrication contract as DrainStats.latency_percentiles)."""
        if not self.bind_latencies:
            return None
        import numpy as np

        return {
            float(q): float(np.percentile(self.bind_latencies, q)) for q in qs
        }

    def to_doc(self) -> dict:
        doc = {
            "streamGangs": self.offered,
            "streamAdmitted": self.admitted,
            "streamPodsBound": self.pods_bound,
            "streamWaves": self.waves,
            "streamWallS": round(self.wall_s, 4),
            "gangsPerSec": round(self.gangs_per_sec, 2),
            "depth": self.depth,
            "mode": self.mode,
            "paced": self.paced,
            "shardDevices": self.drain.shard_devices,
        }
        # Host-stage timing ledger of the underlying engine run — the
        # lastStream rows carry the same per-stage split as lastDrain, so
        # streaming host overhead is a recorded number on /statusz too.
        doc.update(self.drain.host_stages())
        pct = self.bind_percentiles((50.0, 99.0))
        if pct is not None:
            doc["bindP50S"] = round(pct[50.0], 4)
            doc["bindP99S"] = round(pct[99.0], 4)
        # Fault-recovery ledger: only present when something actually fired
        # (a healthy stream's lastStream rows stay unchanged).
        res = self.drain.resilience_doc()
        if any(res.values()):
            doc.update(res)
        return doc


def drain_stream(
    arrivals: list,
    pods_by_name: dict,
    snapshot,
    *,
    config: StreamConfig | None = None,
    params: SolverParams | None = None,
    warm_path=None,  # solver.warm.WarmPath; None = the process-shared one
    pruning=None,  # solver.pruning.PruningConfig; None/disabled = dense
    recorder=None,  # trace.recorder.TraceRecorder; journals committed waves
    pipeline: bool = True,  # False = wave-serial baseline
    scan=None,  # None | True | ScanConfig: fuse same-class wave runs on device
    pace: bool = False,  # True = honor arrival offsets in wall time
    donate: bool | None = None,
    mesh=None,  # None | parallel.mesh.SolveLayout | parallel.mesh.MeshConfig
    faults=None,  # faults.FaultInjector; None = the process-installed one
    resilience=None,  # None | ResilienceConfig | DegradationLadder (shared)
    order_key=None,  # None | callable(PodGang) -> sort key; tenancy ordering
) -> tuple[dict[str, dict[str, str]], StreamStats]:
    """Admit a live arrival trace; returns ({gang: {pod: node}}, StreamStats).

    `arrivals` is a list of (t_offset_seconds, PodGang) sorted by offset,
    base gangs before their scaled gangs (sim.workloads.expand_arrivals
    builds it from an ArrivalEvent trace). See the module docstring for the
    pipeline/serial and saturated/paced semantics.

    Warm path: shapes are AOT-compiled lazily on FIRST encounter (counted in
    stats.drain.compile_s — a cold stream pays XLA inline; prewarm from
    shape history and a warm-up run both make the steady state compile-free,
    and the in-flight compile tracking in solver/warm.py dedupes against a
    concurrently running prewarm thread). Everything else — executable
    cache, encode-row reuse, candidate pruning with exactness escalation,
    flight-recorder journaling — behaves exactly as in drain_backlog.

    `mesh`: mesh-sharded solves, same semantics as drain_backlog — the
    engine's free carry chains node-sharded between waves, fallbacks are
    counted, journaled waves record the mesh fingerprint.

    `resilience`: the graceful-degradation ladder (solver/resilience.py).
    The always-on loop is where the ladder EARNS its keep: between windows
    the driver reconciles the engine against the breaker states — an open
    `mesh` rung strips the layout (bitwise-equal unsharded), an open
    `pruning` rung solves dense (admitted-equal by the escalation pin), an
    open `pipeline` rung retires serially — and a wave failure past the
    engine's own watchdog/retry budget charges the first active rung, so
    repeated failures walk the loop down to the boring-but-correct
    configuration and probation walks it back up. Admitted sets are
    invariant across every rung (the PR 5-7 equivalence family), so chaos
    changes latency, never placements. Pass a shared DegradationLadder to
    let the controller/manager see (and export) the same breaker state.

    `faults`: deterministic fault injector threaded through the engine's
    named sites (grove_tpu/faults) — chaos runs replay bit-for-bit.

    `scan`: the on-device fused-drain discipline (fusion requires
    `pipeline`). True uses ScanConfig defaults; a ScanConfig tunes
    maxScanLen / minWavesPerClass / affinityLookahead / deviceResident. In
    saturated mode the driver buffers CONSECUTIVE same-shape-class planned
    waves across windows and dispatches each run as lax.scan chunks
    through the engine (`submit_scan`) — window/wave composition is
    untouched, only dispatch fuses, so admitted sets stay bitwise-equal to
    the pipelined and serial baselines while host round-trips drop to
    O(shape classes). Class-affine forming (`affinityLookahead` > 0,
    saturated only) reorders planned waves across a bounded window
    look-ahead so same-class runs actually form under mixed traffic; the
    reorder is a pure function of the requested config — a serial run
    (pipeline=False) given the same `scan` applies the identical forming,
    which is what keeps admitted sets bitwise-comparable. With
    `deviceResident` the saturated loop retires nothing until the trace is
    exhausted and harvests everything in ONE batched device_get —
    device_roundtrips == 1 + escalations. Paced runs never hold an arrival
    back for fusion or forming (each window flushes), so pacing
    degenerates to the pipelined discipline unless a single window plans a
    fusable run. Under a ladder, "resident" is the FIRST rung (falls back
    to scanned-but-pipelined retirement), "scan" the second: a failure
    steps the loop down to per-wave pipelined dispatch (bitwise-equal),
    probation steps it back.

    `order_key`: optional key callable; when given, the backlog of queued
    arrivals is STABLE-sorted by it before each window is sliced, so e.g.
    a tenancy tier key (slo_rank, -priority) lets latency-class gangs jump
    ahead of batch work that arrived earlier. The key must be
    family-uniform (identical for a base gang and its scaled siblings —
    true for anything derived from the template, like sloClass), so the
    stable sort preserves the base-before-scaled arrival invariant the
    encoder depends on. Paced-mode batching waits still key off the
    oldest ARRIVAL in the queue, not the sorted head.
    """
    from grove_tpu.solver import warm as warm_mod
    from grove_tpu.solver.resilience import ladder_for

    cfg = config or StreamConfig()
    params = params or SolverParams()
    wp = warm_path if warm_path is not None else warm_mod.default_warm_path()
    if pruning is not None and not getattr(pruning, "enabled", False):
        pruning = None
    if donate is None:
        donate = warm_mod.donation_default()
    ladder = ladder_for(resilience)
    if cfg.depth < 1:
        raise ValueError(f"streaming depth must be >= 1, got {cfg.depth}")
    if cfg.wave_size < 1:
        raise ValueError(f"streaming waveSize must be >= 1, got {cfg.wave_size}")
    layout = None
    shard_fallback = 0
    if mesh is not None:
        from grove_tpu.parallel.mesh import MeshConfig, resolve_layout

        layout = resolve_layout(mesh, int(snapshot.free.shape[0]))
        requested = not isinstance(mesh, MeshConfig) or mesh.enabled
        if layout is None and requested:
            shard_fallback = 1

    # Fusion (base_scan) needs the pipelined engine; class-affine FORMING
    # (affine) deliberately does not — it is a pure function of the
    # requested scan config, so a serial baseline handed the same config
    # sees the identical wave sequence (the bitwise parity contract).
    requested_scan = None
    if scan is not None:
        requested_scan = ScanConfig() if scan is True else scan
        if not requested_scan.enabled:
            requested_scan = None
    base_scan = requested_scan if pipeline else None
    affine = None
    if (
        requested_scan is not None
        and not pace
        and int(requested_scan.affinity_lookahead) > 0
    ):
        affine = requested_scan
    # Device-resident saturated drain: retire nothing until the trace is
    # exhausted, then ONE batched harvest. First ladder rung.
    resident_req = (
        base_scan is not None and base_scan.device_resident and not pace
    )

    gangs_all = [g for _, g in arrivals]
    stats = StreamStats(
        offered=len(gangs_all),
        depth=cfg.depth if pipeline else 0,
        mode=(
            "resident"
            if resident_req
            else "scan"
            if base_scan is not None
            else ("pipeline" if pipeline else "serial")
        ),
        paced=bool(pace),
    )
    dstats = stats.drain
    dstats.gangs = len(gangs_all)
    dstats.harvest = stats.mode if pipeline else "wave"
    dstats.depth = stats.depth
    dstats.shard_fallbacks = shard_fallback
    if not gangs_all:
        return {}, stats

    exec0 = (wp.executables.hits, wp.executables.misses, wp.executables.lowerings)
    avail: dict[str, float] = {}  # gang name -> wall instant it became visible
    engine_box: list = []

    def on_commit(members, wave_bindings, stamp):
        wall = engine_box[0].t0 + stamp
        for g in members:
            if g.name in wave_bindings:
                stats.bind_latencies.append(max(0.0, wall - avail[g.name]))

    # Ladder-effective starting configuration + engine watchdog/retry arms.
    base_lag = cfg.depth if pipeline else 0
    base_layout, base_pruning = layout, pruning
    scan_cfg = base_scan
    watchdog_s = None
    max_wave_retries = 0
    if ladder is not None:
        watchdog_s = ladder.config.watchdog_seconds
        max_wave_retries = ladder.config.max_wave_retries
        if scan_cfg is not None and not ladder.allows("scan"):
            scan_cfg = None
        if not ladder.allows("mesh"):
            layout = None
        if not ladder.allows("pruning"):
            pruning = None
        if not ladder.allows("pipeline"):
            pass  # applied via retire_lag below

    def _effective_lag(scan_armed: bool) -> int | None:
        """Where the host blocks, by ladder state: serial (0) when the
        pipeline rung is open, fully resident (None — retire only at the
        final flush) when requested and both the resident rung and the
        scan dispatch are up, else the pipelined depth."""
        if ladder is not None and not ladder.allows("pipeline"):
            return 0
        if (
            resident_req
            and scan_armed
            and (ladder is None or ladder.allows("resident"))
        ):
            return None
        return base_lag

    engine = _WavePipeline(
        gangs=gangs_all,
        pods_by_name=pods_by_name,
        snapshot=snapshot,
        params=params,
        warm_path=wp,
        stats=dstats,
        pruning=pruning,
        donate=bool(donate),
        retire_lag=_effective_lag(scan_cfg is not None),
        recorder=recorder,
        wave_prefix="stream",
        record_stamps=True,
        on_commit=on_commit,
        layout=layout,
        faults=faults,
        watchdog_s=watchdog_s,
        max_wave_retries=max_wave_retries,
        scan=scan_cfg,
    )
    engine_box.append(engine)

    def _active_rungs() -> tuple:
        """The rungs currently at full config — the ones a new failure can
        step down (ladder attribution order is resilience.SUBSYSTEMS)."""
        active = []
        if resident_req and engine.retire_lag is None and engine.scan is not None:
            active.append("resident")
        if engine.scan is not None:
            active.append("scan")
        if engine.layout is not None:
            active.append("mesh")
        if engine.pruning is not None:
            active.append("pruning")
        if engine.retire_lag != 0:
            active.append("pipeline")
        return tuple(active)

    def _reconcile_ladder() -> None:
        """Engine config <- breaker states: step open rungs down, step
        probation-expired rungs back up (half-open trial — the next wave
        runs at full config; its outcome closes or re-opens the breaker)."""
        try:
            # Layout transitions flush the in-flight waves first (their
            # carries chain on the old buffers); a hung wave can block the
            # transition — stay on the current layout this round and let
            # the retirement path own retrying the hang.
            if engine.layout is not None and not ladder.allows("mesh"):
                engine.strip_layout()
            elif (
                engine.layout is None
                and base_layout is not None
                and ladder.allows("mesh")
            ):
                engine.adopt_layout(base_layout)
        except WaveFault as e:
            if e.fatal:
                raise
        engine.set_scan(base_scan if ladder.allows("scan") else None)
        engine.set_pruning(
            base_pruning if ladder.allows("pruning") else None
        )
        engine.set_retire_lag(_effective_lag(engine.scan is not None))

    def _charge(e: WaveFault) -> None:
        """A wave failed past the engine's own retry budget: charge the
        first active rung, step the engine down, or give up when the ladder
        has no rung left to sacrifice."""
        if ladder is None or e.fatal:
            raise e
        if ladder.record_failure(active=_active_rungs()) is None:
            raise e  # bottom of the ladder and still failing
        _reconcile_ladder()

    def _retire_down(to_lag: bool) -> None:
        """Retire waves (down to the pipeline depth, or everything for the
        final flush) under the ladder: a retirement failure leaves the wave
        at the queue head, steps the ladder down, and retries with fresh
        watchdog budget — a hung wave degrades the loop, it never loses a
        gang. Under the resident discipline retire_due() is never true (the
        lag is None), so the trace drains with zero mid-run retirement and
        the final flush pays ONE batched harvest for the whole run."""
        if not to_lag and engine.retire_lag is None:
            engine.harvest_inflight()
        while engine.retire_due() if to_lag else engine.inflight:
            try:
                engine._retire_next()
                if ladder is not None:
                    ladder.record_success()
            except WaveFault as e:
                _charge(e)

    def _submit(ws) -> None:
        # Dispatch phase (retire=False: a failure here unambiguously means
        # the wave was NOT enqueued, so the loop resubmits the SAME wave
        # under the stepped-down config — arrivals are never dropped).
        while True:
            try:
                if ladder is not None:
                    _reconcile_ladder()
                # Lazy AOT warm-up of first-seen shapes (compile-only; the
                # executable cache + in-flight tracking dedupe process-wide).
                tc = time.perf_counter()
                if engine.warm_shape(ws):
                    dstats.compile_s += time.perf_counter() - tc
                engine.submit(ws, retire=False)
                if ladder is not None:
                    ladder.record_success()
                break
            except WaveFault as e:
                _charge(e)
        _retire_down(to_lag=True)

    # Fusion buffer: consecutive same-shape-class planned waves awaiting a
    # scanned dispatch. Only ever non-empty while engine.scan is armed and
    # the loop is saturated; buffered waves are NOT in flight yet, so the
    # final flush below owns draining it before retirement.
    run_buf: list = []

    def _submit_run(run: list) -> None:
        """Dispatch a same-class run fused (`submit_scan`); a failure past
        the engine's retry budget charges the ladder (the "scan" rung goes
        first) and resubmits exactly the not-yet-enqueued tail — per-wave
        once the rung is open — so arrivals are never dropped and the
        dispatch order matches the per-wave disciplines bitwise."""
        pending = run
        while pending:
            if ladder is not None:
                _reconcile_ladder()
            if engine.scan is None or len(pending) < max(
                1, int(engine.scan.min_waves_per_class)
            ):
                for ws in pending:
                    _submit(ws)
                return
            try:
                tc = time.perf_counter()
                warmed = engine.warm_shape(pending[0])
                warmed = engine.warm_scan(pending) or warmed
                if warmed:
                    dstats.compile_s += time.perf_counter() - tc
                engine.submit_scan(pending, retire=False)
                if ladder is not None:
                    ladder.record_success()
                pending = []
            except WaveFault as e:
                rest = e.pending if e.pending is not None else pending
                _charge(e)  # raises when no ladder / bottom of the ladder
                pending = rest
            _retire_down(to_lag=True)

    def _flush_run() -> None:
        if run_buf:
            run, run_buf[:] = list(run_buf), []
            _submit_run(run)

    def _dispatch_planned(planned: list) -> None:
        """Feed planned waves to the engine in the order given: buffered
        into cross-window fused runs while the scan dispatch is armed
        (saturated), per-wave otherwise."""
        if engine.scan is not None and not pace:
            # Saturated scan: buffer consecutive same-class waves across
            # windows; a class change (or a full chunk) flushes the run
            # as one scanned dispatch. Composition untouched — only WHEN
            # the host dispatches changes, never what a wave contains.
            for ws in planned:
                if run_buf and (
                    run_buf[0][1:] != ws[1:]
                    or len(run_buf)
                    >= max(1, int(engine.scan.max_scan_len))
                ):
                    _flush_run()
                run_buf.append(ws)
        else:
            _flush_run()  # scan stepped down (or paced): drain the buffer
            for ws in planned:
                _submit(ws)

    def _affine_order(group: list) -> list:
        """Class-affine reorder of one look-ahead group of planned waves:
        rank 0 before rank 1 (every base still dispatches before any
        scaled gang — the only cross-wave dependency), shape classes in
        first-appearance order within each rank, each class's waves
        contiguous in window order, and the gang-axis pad canonicalized UP
        to the class max across the group (pad-up is binding-neutral —
        padded slots are invalid gangs that never touch the carry — and it
        lets one class formed from different windows share one executable
        and one scan run). A single-window group reproduces plan_waves'
        own emission order bitwise, so look-ahead 0 is the unformed
        baseline."""
        buckets: dict = {}
        for ws in group:
            rank = 0 if ws[0][0].base_podgang_name is None else 1
            buckets.setdefault((rank, ws[1]), []).append(ws)
        out: list = []
        for rank in (0, 1):
            for (r, _shape), members in buckets.items():
                if r != rank:
                    continue
                pad = max(ws[2] for ws in members)
                out.extend((ws[0], ws[1], pad) for ws in members)
        return out

    # Class-affine look-ahead group: planned waves from up to
    # (1 + affinityLookahead) consecutive windows awaiting reorder. A pure
    # function of the REQUESTED scan config — never of ladder state or
    # harvest discipline — so every discipline at the same look-ahead sees
    # the identical dispatch sequence (the parity contract).
    wave_buf: list = []
    buf_windows = 0
    lookahead = int(affine.affinity_lookahead) if affine is not None else 0

    def _flush_group() -> None:
        nonlocal buf_windows
        if not wave_buf:
            return
        group, wave_buf[:] = list(wave_buf), []
        buf_windows = 0
        _dispatch_planned(_affine_order(group))

    t0 = time.perf_counter()
    engine.t0 = t0
    queue: list = []
    i, n = 0, len(arrivals)
    while i < n or queue:
        now = time.perf_counter()
        if pace:
            while i < n and arrivals[i][0] <= now - t0:
                off, g = arrivals[i]
                queue.append(g)
                avail[g.name] = t0 + off
                i += 1
        else:
            while i < n and len(queue) < cfg.wave_size:
                g = arrivals[i][1]
                queue.append(g)
                avail[g.name] = now
                i += 1
        ready = len(queue) >= cfg.wave_size or (i >= n and bool(queue))
        if pace and queue and not ready:
            # Batching window: the oldest queued gang only waits so long.
            # (Under order_key the sorted head need not be the oldest —
            # always anchor the wait on the earliest arrival still queued.)
            oldest = (
                min(avail[g.name] for g in queue)
                if order_key is not None
                else avail[queue[0].name]
            )
            ready = (now - oldest) >= cfg.max_wait_s
        if ready:
            if order_key is not None and len(queue) > 1:
                queue.sort(key=order_key)  # stable: FIFO within equal keys
            window, queue = queue[: cfg.wave_size], queue[cfg.wave_size :]
            stats.windows += 1
            planned = plan_waves(window, cfg.wave_size)
            if affine is not None:
                # Class-affine forming: buffer this window's planned waves
                # and dispatch the whole look-ahead group reordered once
                # (1 + lookahead) windows are in hand.
                wave_buf.extend(planned)
                buf_windows += 1
                if buf_windows >= 1 + lookahead:
                    _flush_group()
            else:
                _dispatch_planned(planned)
        elif pace:
            if engine.inflight:
                # Host idle until the next arrival: retire the oldest
                # in-flight wave now instead of sleeping on it later.
                try:
                    engine._retire_next()
                except WaveFault as e:
                    _charge(e)
            else:
                next_due = (t0 + arrivals[i][0]) if i < n else now
                time.sleep(min(cfg.poll_s, max(0.0, next_due - now)))
    _flush_group()  # trace exhausted: dispatch any partial look-ahead group
    _flush_run()  # ... and any fused run still buffering
    _retire_down(to_lag=False)
    stats.wall_s = time.perf_counter() - t0
    dstats.total_s = stats.wall_s
    stats.waves = dstats.waves
    stats.admitted = dstats.admitted
    stats.pods_bound = dstats.pods_bound
    stats.gangs_per_sec = (
        stats.admitted / stats.wall_s if stats.wall_s > 0 else 0.0
    )
    dstats.exec_cache_hits = wp.executables.hits - exec0[0]
    dstats.exec_cache_misses = wp.executables.misses - exec0[1]
    dstats.lowerings = wp.executables.lowerings - exec0[2]
    if dstats.pruned_waves:
        wp.prune.pruned_solves += dstats.pruned_waves
        wp.prune.escalations += dstats.escalations
        wp.prune.escalations_adopted += dstats.escalations_adopted
        wp.prune.last_candidate_nodes = dstats.candidate_nodes
        wp.prune.last_candidate_pad = dstats.candidate_pad
        wp.prune.last_fleet_nodes = int(snapshot.free.shape[0])
    wp.record_drain(dstats)
    wp.record_stream(stats.to_doc(), stats.bind_latencies)
    return engine.bindings, stats
