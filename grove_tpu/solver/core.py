"""The TPU placement engine: batched all-or-nothing gang bin-packing.

Replaces the reference's per-pod Filter/Score/Permit scheduler cycle (KAI,
behind scheduler/api PodGang) with one jitted program:

  lax.scan over gangs (sequential commit — later gangs see earlier placements)
    stage 1: pack-set domain commitment, broad→narrow (lax.scan over sets):
             per-domain feasibility via segment_sum (capacity + slot counts),
             best-fit domain choice; a required set with no feasible domain
             rejects the whole gang
    stage 2: group count-allocation (lax.scan over groups): per-node slot
             counts, score = preferred-domain bonus + gang locality + bin-pack
             tightness, sorted-cumsum greedy take
    stage 3: counts → per-pod node ids (vmapped searchsorted)
    stage 4: all-or-nothing: capacity update applied only if every group met
             its floor (PodGroup.MinReplicas, scheduler podgang.go:80-84) and
             no required pack-set failed; otherwise the gang is rejected whole
             (GS "all pods scheduled or none" semantics,
             operator/e2e/tests/gang_scheduling_test.go GS1)

Filter predicates are boolean masks; Score is a vectorized cost; Permit is the
masked take — the design stated in BASELINE.json's north star.

Everything is static-shaped: gangs/groups/sets/pods are padded per bucket
(solver/encode.py), nodes padded by the snapshot. Runs identically on CPU
(tests) and TPU (bench): no data-dependent Python control flow, f32
throughout (resource quantities need exactness to ~1e-3 of a core, far inside
f32; the MXU-heavy parts are the [MG,N,R] slot/score tensors).
"""

from __future__ import annotations

import threading
from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from grove_tpu.solver.encode import GangBatch

SLOT_CAP = 1 << 20  # slots for a zero-request group (effectively unbounded)
_EPS = 1e-6


class SolverParams(NamedTuple):
    """Score weights (Score plugin analog)."""

    w_tight: jnp.float32 = 1.0  # bin-pack: prefer nodes with less free capacity
    w_pref: jnp.float32 = 4.0  # preferred-domain bonus per matching pack-set
    w_reuse: jnp.float32 = 2.0  # gang locality: prefer nodes this gang already uses
    w_reserve: jnp.float32 = 8.0  # keep non-members out of committed pack domains
    # Replica-spread repulsion (PCS topologySpreadDomain): penalty for nodes
    # whose spread-level domain already hosts a sibling replica's base gang.
    # Soft by design — spread yields to Required packs and to feasibility.
    w_spread: jnp.float32 = 1.5


class SolveResult(NamedTuple):
    assigned: jax.Array  # i32 [G, MP] node index or -1
    ok: jax.Array  # bool [G] gang admitted whole
    placement_score: jax.Array  # f32 [G] quality in (0,1], 1.0 = optimal
    free_after: jax.Array  # f32 [N, R]
    # Updated global verdict bitmap (pipelined-wave chaining): present iff the
    # caller passed ok_global; this batch's verdicts scattered at each gang's
    # batch.global_index. Feed it to the next wave's solve so cross-wave
    # base-gang gating resolves on-device with no host round-trip.
    ok_global: jax.Array | None = None


def _reuse_of(batch: GangBatch, n: int) -> jax.Array:
    """ReuseReservationRef node seed [G, N]; zeros when the batch predates the
    field (older pickled batches) or carries none."""
    if batch.reuse_nodes is None:
        return jnp.zeros((batch.gang_valid.shape[0], n), dtype=bool)
    return batch.reuse_nodes


def _apply_global_deps(batch: GangBatch, ok_global: jax.Array | None) -> jax.Array:
    """gang_valid with cross-batch base-gang verdicts folded in."""
    if ok_global is None:
        return batch.gang_valid
    t = ok_global.shape[0]
    dg = batch.depends_global
    ext_ok = jnp.where(dg >= 0, ok_global[jnp.clip(dg, 0, t - 1)], True)
    return batch.gang_valid & ext_ok


def _scatter_global_ok(
    batch: GangBatch, ok: jax.Array, ok_global: jax.Array | None
) -> jax.Array | None:
    """Write this batch's verdicts into the global bitmap at global_index."""
    if ok_global is None:
        return None
    t = ok_global.shape[0]
    gidx = batch.global_index
    return ok_global.at[jnp.clip(gidx, 0, t - 1)].max(ok & (gidx >= 0))


def _group_slots(free: jax.Array, group_req: jax.Array) -> jax.Array:
    """Per-node pod capacity for each group's request vector.

    free [N,R], group_req [MG,R] -> i32 [MG,N].
    """
    pos = group_req > 0  # [MG, R]
    ratio = jnp.floor((free[None, :, :] + _EPS) / jnp.maximum(group_req[:, None, :], 1e-9))
    ratio = jnp.where(pos[:, None, :], ratio, jnp.inf)
    slots = ratio.min(axis=-1)  # [MG, N]
    slots = jnp.where(jnp.isinf(slots), SLOT_CAP, slots)
    return jnp.clip(slots, 0, SLOT_CAP).astype(jnp.int32)


def _domain_sum(values: jax.Array, seg: jax.Array, n: int) -> jax.Array:
    """Sum `values` [N, ...] per domain ordinal; unlabeled nodes spill to a
    dropped padding segment."""
    return jax.ops.segment_sum(values, seg, num_segments=n + 1)[:n]


def _coarse_onehot_stack(node_domain_id: jax.Array, coarse_dmax: int) -> jax.Array:
    """[Lc, Dm, N] f32 one-hot domain membership for the coarse (non-host)
    topology levels.

    TPU scatter serializes per update row, so `segment_sum` over 5k nodes
    costs ~milliseconds inside the solve loop (measured: it was ~95% of the
    round-2 bench's 55s). Domain aggregation as a one-hot matmul instead
    rides the MXU: [Dm, N] @ [N, C] is microseconds at Dm<=few hundred. The
    host level (one domain per node, ordinal == node index by construction,
    state/cluster.py) needs no aggregation at all — it selects the masked
    per-node rows directly."""
    levels = node_domain_id.shape[0]
    lc = max(levels - 1, 1)
    ords = jnp.arange(coarse_dmax, dtype=node_domain_id.dtype)
    return (node_domain_id[:lc, None, :] == ords[None, :, None]).astype(jnp.float32)


def _place_gang(
    free,
    used_carry,
    gang,
    *,
    schedulable,
    node_domain_id,
    cap_scale,
    params,
    coarse_onehot=None,  # [Lc, Dm, N] f32; None = segment-sum fallback
    spread_avoid=None,  # bool [N]: nodes sibling replicas occupy (see w_spread)
):
    """Place one gang against `free`; pure function of its inputs."""
    n, r = free.shape
    levels = node_domain_id.shape[0]
    group_req = gang["group_req"]  # [MG, R]
    group_total = gang["group_total"]  # [MG]
    group_required = gang["group_required"]  # [MG]
    group_valid = gang["group_valid"]  # [MG]
    set_member = gang["set_member"]  # [MS, MG]
    set_req_level = gang["set_req_level"]  # [MS]
    set_pref_level = gang["set_pref_level"]  # [MS]
    set_valid = gang["set_valid"]  # [MS]
    set_pinned = gang["set_pinned"]  # [MS] forced domain ordinal, -1 = free
    mg = group_req.shape[0]
    ms = set_member.shape[0]
    mp_bound = gang["pod_group"].shape[0]  # max pods this gang can place

    def seg_of(level):
        dom = node_domain_id[jnp.clip(level, 0, levels - 1)]  # [N]
        return jnp.where(dom >= 0, dom, n), dom

    # Hoisted loop invariants for stage 1: free capacity does NOT change while
    # committing domains, so per-node slots, per-node fused feature rows, and
    # per-level segment ids are computed once per gang, not once per set.
    slots_all = _group_slots(free, group_req)  # [MG, N]
    # nodeSelector eligibility (encode.GangBatch.group_node_ok): ineligible
    # nodes offer zero slots for the group, which flows into every
    # feasibility aggregate below. Present only when a pod in the batch
    # carries a selector — the common case compiles without this input.
    eligible = gang.get("group_node_ok")  # bool [MG, N] or None
    if eligible is not None:
        slots_all = jnp.where(eligible, slots_all, 0)
    seg_all, dom_all = jax.vmap(lambda lv: seg_of(lv))(jnp.arange(levels))  # [L, N] x2
    # Fused per-node feature rows: [free (R) | slots (MG) | 1] — one
    # segment-sum yields domain free, domain slots, and domain node-count
    # together instead of three reductions.
    ones_col = jnp.ones((free.shape[0], 1), dtype=jnp.float32)
    feat = jnp.concatenate([free, slots_all.T.astype(jnp.float32), ones_col], axis=1)


    def _joint_slots_ok(dom_slots, members):
        """Joint slot feasibility for a set's member groups [N_dom].

        Per-group slot floors are independently satisfiable yet jointly
        impossible when groups COMPETE for the same nodes (4 pods of two
        2-pod groups vs 3 one-pod nodes: each group sees 3 >= 2, together
        they need 4). When every member group shares one request vector the
        joint check is exact: min member slots >= summed floors. For
        heterogeneous members it stays optimistic (per-group only) — a
        conservative joint bound would wrongly reject feasible mixes."""
        membersf = members.astype(jnp.float32)  # [MG]
        any_member = members.any()
        req_lo = jnp.where(members[:, None], group_req, jnp.inf).min(axis=0)  # [R]
        req_hi = jnp.where(members[:, None], group_req, -jnp.inf).max(axis=0)
        homogeneous = any_member & ((req_hi - req_lo) <= _EPS).all()
        joint_need = (group_required.astype(jnp.float32) * membersf).sum()
        min_slots = jnp.where(
            members[None, :], dom_slots, jnp.inf
        ).min(axis=-1)  # [N_dom]
        return jnp.where(homogeneous, min_slots >= joint_need, True)

    def agg_by_domain(vals, level):
        """Per-domain sums of pre-masked per-node rows `vals` [N, C] at
        `level`, padded to [N, C] rows (ordinal -> row; rows >= D are zero).

        Matmul path (see _coarse_onehot_stack): scatter-free. Host level is
        the identity — domain ordinal == node index by snapshot construction.
        """
        if coarse_onehot is None:
            seg = seg_all[jnp.clip(level, 0, levels - 1)]
            return _domain_sum(vals, seg, n)
        lc_count = coarse_onehot.shape[0]
        dm = coarse_onehot.shape[1]
        oh = coarse_onehot[jnp.clip(level, 0, lc_count - 1)]  # [Dm, N]
        coarse = jnp.matmul(oh, vals, precision=jax.lax.Precision.HIGHEST)
        coarse = jnp.pad(coarse, ((0, n - dm), (0, 0)))
        host_vals = jnp.where(dom_all[levels - 1][:, None] >= 0, vals, 0.0)
        return jnp.where(level == levels - 1, host_vals, coarse)

    def dom_tables(ok_nodes, level):
        """Masked domain aggregates at `level`: (free [D,R], slots [D,MG],
        count [D])."""
        table = agg_by_domain(jnp.where(ok_nodes[:, None], feat, 0.0), level)
        return table[:, :r], table[:, r : r + mg], table[:, r + mg]

    # Hoisted nested-feasibility inputs (free does not change during stage 1,
    # and domains strictly nest — build_snapshot derives domain identity from
    # label PATHS — so a narrower set's per-domain feasibility over all
    # schedulable nodes is valid inside any committed ancestor domain; the
    # per-set eligibility masks only select domains wholly in or out):
    #   tables_L  [L, N, C]  per-level domain aggregates, schedulable nodes
    #   feas2_all [MS, N]    per narrow set: its domains' aggregate feasibility
    # This removes the per-(set, narrow-set) re-aggregations that dominated
    # the round-2 TPU profile (413ms of 493ms per 256-gang scan).
    tables_L = jax.vmap(
        lambda lv: agg_by_domain(jnp.where(schedulable[:, None], feat, 0.0), lv)
    )(jnp.arange(levels))  # [L, N, C]
    # Replica-spread penalty, hoisted (the avoid set is fixed during this
    # gang): 1.0 on nodes whose spread-level domain contains ANY avoided
    # node. Domain granularity, not node granularity — an availability
    # spread means "a different rack/zone", not "a different host".
    spread_pen = None
    if spread_avoid is not None:
        s_lvl = gang["spread_level"]
        lvl_c = jnp.clip(s_lvl, 0, levels - 1)
        used_cnt = agg_by_domain(
            spread_avoid[:, None].astype(jnp.float32), lvl_c
        )[:, 0]  # [N] domain-ordinal rows
        s_dom = dom_all[lvl_c]  # [N] node -> ordinal at the spread level
        spread_pen = jnp.where(
            (s_lvl >= 0) & (s_dom >= 0),
            jnp.take(used_cnt, jnp.clip(s_dom, 0, n - 1)) > 0.5,
            False,
        ).astype(jnp.float32)

    def _set_dom_feasible(s2):
        lvl2c = jnp.clip(set_req_level[s2], 0, levels - 1)
        member2 = set_member[s2] & group_valid  # [MG]
        demand2 = (
            group_req * (group_required * member2).astype(jnp.float32)[:, None]
        ).sum(0)  # [R]
        t2 = tables_L[lvl2c]  # [N, C]
        return (
            (t2[:, :r] >= demand2[None, :] - _EPS).all(axis=-1)
            & (
                (t2[:, r : r + mg] >= group_required[None, :]) | ~member2[None, :]
            ).all(axis=-1)
            & _joint_slots_ok(t2[:, r : r + mg], member2)
        )  # [N] domain rows at lvl2

    feas2_all = jax.vmap(_set_dom_feasible)(jnp.arange(ms))  # [MS, N]
    # Per-node view of each narrow set's domain feasibility (one batched
    # gather instead of one per (set, narrow-set) pair).
    lvl2c_all = jnp.clip(set_req_level, 0, levels - 1)  # [MS]
    dom2_all = dom_all[lvl2c_all]  # [MS, N] node -> its lvl2 domain ordinal
    node_feas2_all = jnp.where(
        dom2_all >= 0,
        jnp.take_along_axis(feas2_all, jnp.clip(dom2_all, 0, n - 1), axis=1),
        False,
    )  # [MS, N]

    # ---- Stage 1: commit a domain per pack-set, broadest first --------------
    def commit_set(carry, s):
        committed_req, committed_pref, fail = carry
        member = set_member[s]  # [MG]
        req_level = set_req_level[s]
        pref_level = set_pref_level[s]
        active = set_valid[s]

        # Node eligibility from previously committed sets sharing a group.
        overlap = (set_member & member[None, :]).any(axis=-1)  # [MS]

        def mask_from(c_req, lvl, ov):
            dom = node_domain_id[jnp.clip(lvl, 0, levels - 1)]
            return jnp.where((c_req >= 0) & ov, dom == c_req, True)

        masks = jax.vmap(mask_from)(committed_req, set_req_level, overlap)  # [MS, N]
        node_ok = schedulable & masks.all(axis=0)  # [N]

        memberf = member & group_valid  # [MG]
        demand = (group_req * (group_required * memberf).astype(jnp.float32)[:, None]).sum(0)  # [R]

        def nested_feasible(level, ok_nodes):
            """[N_dom at `level`]: every NARROWER required set sharing a group
            must have some feasible domain nested inside the candidate.

            Without this, best-fit aggregate feasibility happily commits e.g.
            a block whose total capacity fits the gang but whose racks are all
            too fragmented for the rack-packed group — the narrow set then
            fails and the whole gang is rejected despite feasible blocks
            elsewhere (hierarchical bin-packing myopia).

            Uses the per-gang hoisted feas2_all/node_feas2_all: one mask and
            ONE aggregation (batched over narrow sets) per call."""
            active2 = (
                set_valid
                & (set_req_level > level)
                & (set_member & member[None, :]).any(axis=-1)
            )  # [MS]
            witness = (node_feas2_all & ok_nodes[None, :]).astype(jnp.float32)
            nested_cnt = agg_by_domain(witness.T, level)  # [N_dom, MS]
            return (
                (nested_cnt > 0.5) | ~active2[None, :]
            ).all(axis=-1)  # [N_dom]

        # Nodes inside domains committed by earlier DISJOINT sets (no shared
        # group). Stage 1 commits against un-decremented free, so two
        # same-level sibling sets would otherwise both pick the one best-fit
        # domain and collide in stage 2 (the whole gang then rejects even
        # though distinct domains fit — TAS-4/TAS-15 shape). Penalizing, not
        # forbidding: sharing stays possible when it is the only option.
        def _taken_mask(c_req, lvl, ov, act):
            dom = node_domain_id[jnp.clip(lvl, 0, levels - 1)]
            return act & ~ov & (c_req >= 0) & (dom == c_req)

        def pick_domain(level, extra_node_mask, check_nested=False):
            """Best-fit feasible domain at `level` among nodes passing masks.

            `check_nested` (required picks only — a failed preferred pick
            cannot reject the gang) adds the hierarchical feasibility guard."""
            ok_nodes = node_ok & extra_node_mask
            dom_free, dom_slots, dom_count = dom_tables(ok_nodes, level)
            feas_cap = (dom_free >= demand[None, :] - _EPS).all(axis=-1)
            feas_slots = ((dom_slots >= group_required[None, :]) | ~memberf[None, :]).all(axis=-1)
            feasible = (
                feas_cap
                & feas_slots
                & _joint_slots_ok(dom_slots, memberf)
                & (dom_count > 0)
            )
            if check_nested:
                feasible = feasible & nested_feasible(level, ok_nodes)
            taken_node = jax.vmap(_taken_mask)(
                committed_req, set_req_level, overlap, set_valid
            ).any(axis=0)  # [N]
            taken_frac = agg_by_domain(
                jnp.where(ok_nodes & taken_node, 1.0, 0.0)[:, None], level
            )[:, 0] / jnp.maximum(dom_count, 1.0)
            # Best fit on normalized free (raw sums would let memory bytes
            # drown cpu/chip counts).
            norm_free = (dom_free / cap_scale[None, :]).sum(axis=-1)
            score = jnp.where(
                feasible,
                -norm_free - params.w_reserve * taken_frac,
                -jnp.inf,
            )
            if spread_pen is not None:
                # Replica spread must steer the DOMAIN choice, not just the
                # stage-2 node scoring: best-fit actively prefers the tighter
                # domain, which is exactly the one the sibling already
                # occupies. The margin must dominate every other score term —
                # norm_free (<= n*r) plus w_reserve * taken_frac
                # (<= w_reserve) — so any feasible domain with no avoided
                # nodes beats any with them, while infeasible domains stay
                # -inf (spread remains soft).
                touched = agg_by_domain(
                    jnp.where(ok_nodes, spread_pen, 0.0)[:, None], level
                )[:, 0] > 0.5
                big = n * r + params.w_reserve + 2.0
                score = score - jnp.where(params.w_spread > 0, big, 0.0) * touched
            return jnp.argmax(score), feasible.any()

        # Incremental re-solve pin: bound pods of this set already sit in a
        # domain; the remainder must land there too (or the gang fails) —
        # a required co-location guarantee covers the whole gang.
        req_dom = node_domain_id[jnp.clip(req_level, 0, levels - 1)]
        pinned = set_pinned[s]
        pin_mask = jnp.where(pinned >= 0, req_dom == pinned, jnp.ones((n,), dtype=bool))
        has_req = active & (req_level >= 0)
        req_choice, req_any = pick_domain(req_level, pin_mask, check_nested=True)
        new_req = jnp.where(has_req & req_any, req_choice, -1)
        fail = fail | (has_req & ~req_any)

        # Preferred: choose within the (possibly just-committed) required domain.
        inside_req = jnp.where(new_req >= 0, req_dom == new_req, True)
        has_pref = active & (pref_level >= 0)
        pref_choice, pref_any = pick_domain(pref_level, inside_req)
        new_pref = jnp.where(has_pref & pref_any, pref_choice, -1)

        committed_req = committed_req.at[s].set(new_req)
        committed_pref = committed_pref.at[s].set(new_pref)
        return (committed_req, committed_pref, fail), None

    init = (
        jnp.full((ms,), -1, dtype=jnp.int32),
        jnp.full((ms,), -1, dtype=jnp.int32),
        jnp.asarray(False),
    )
    (committed_req, committed_pref, set_fail), _ = jax.lax.scan(
        commit_set, init, jnp.arange(ms)
    )

    # ---- Stage 2: allocate counts per group, honoring commitments -----------
    # Two phases so best-effort extras can never starve a later group's floor:
    # phase 0 places exactly the required counts (the gang guarantee), phase 1
    # tops up the remaining best-effort pods from leftover capacity.
    def alloc_group(carry, xs):
        free_g, used, ok = carry
        g, phase = xs
        valid = group_valid[g]
        req = group_req[g]  # [R]
        total = jnp.where(phase == 0, group_required[g], group_total[g] - group_required[g])
        required = jnp.where(phase == 0, group_required[g], 0)

        def set_mask(c_req, lvl, memb):
            dom = node_domain_id[jnp.clip(lvl, 0, levels - 1)]
            return jnp.where(memb & (c_req >= 0), dom == c_req, True)

        masks = jax.vmap(set_mask)(committed_req, set_req_level, set_member[:, g])  # [MS, N]
        node_ok = schedulable & masks.all(axis=0)
        if eligible is not None:
            # nodeSelector: allocation must honor it too — stage-2 recomputes
            # slots from LIVE free, so the stage-1 slots_all mask alone would
            # not constrain the take.
            node_ok = node_ok & eligible[g]

        slots = _group_slots(free_g, req[None, :])[0]  # [N]
        slots = jnp.where(node_ok, jnp.minimum(slots, total), 0)

        def pref_hit(c_pref, lvl, memb):
            dom = node_domain_id[jnp.clip(lvl, 0, levels - 1)]
            return (memb & (c_pref >= 0) & (dom == c_pref)).astype(jnp.float32)

        pref_bonus = jax.vmap(pref_hit)(committed_pref, set_pref_level, set_member[:, g]).sum(0)  # [N]

        def reserved_hit(c_req, lvl, memb):
            """Node sits in a domain committed to a set this group is NOT in."""
            dom = node_domain_id[jnp.clip(lvl, 0, levels - 1)]
            return (~memb & (c_req >= 0) & (dom == c_req)).astype(jnp.float32)

        reserved = jax.vmap(reserved_hit)(committed_req, set_req_level, set_member[:, g]).sum(0)
        norm_free = (free_g / cap_scale[None, :]).mean(axis=-1)  # [N] in ~[0,1]
        score = (
            params.w_pref * pref_bonus
            + params.w_reuse * used.astype(jnp.float32)
            - params.w_tight * norm_free
            - params.w_reserve * reserved
        )
        if spread_pen is not None:
            score = score - params.w_spread * spread_pen
        # Top-k instead of a full argsort over N nodes: a group places at most
        # MP pods and every usable node contributes >= 1 slot, so the best MP
        # nodes always suffice. O(N log k) vs O(N log N) — the full sort was
        # the hottest op in the whole solve at 5k nodes.
        k = min(n, mp_bound)
        masked_score = jnp.where(slots > 0, score, -jnp.inf)
        top_score, order = jax.lax.top_k(masked_score, k)  # [k]
        slots_top = jnp.where(jnp.isfinite(top_score), slots[order], 0)
        csum = jnp.cumsum(slots_top)
        take_top = jnp.clip(total - (csum - slots_top), 0, slots_top)
        counts = jnp.zeros((n,), dtype=jnp.int32).at[order].set(take_top)
        counts = jnp.where(valid, counts, 0)
        placed = counts.sum()
        ok = ok & ((placed >= required) | ~valid)
        free_g = free_g - counts.astype(jnp.float32)[:, None] * req[None, :]
        used = used | (counts > 0)
        return (free_g, used, ok), counts

    order = gang["group_order"]  # [MG] permutation: constrained groups first
    group_ids = jnp.concatenate([order, order])
    phases = jnp.concatenate([jnp.zeros((mg,), jnp.int32), jnp.ones((mg,), jnp.int32)])
    (free2, used2, groups_ok), counts2 = jax.lax.scan(
        alloc_group, (free, used_carry, jnp.asarray(True)), (group_ids, phases)
    )  # counts2 [2*MG, N] in scan order
    counts = (
        jnp.zeros((mg, free.shape[0]), dtype=jnp.int32)
        .at[order].set(counts2[:mg])
        .at[order].add(counts2[mg:])
    )  # [MG, N] floor + best-effort, back in group-index order

    gang_ok = gang["gang_valid"] & groups_ok & ~set_fail

    # ---- Stage 3: counts -> per-pod node assignment --------------------------
    ccum = jnp.cumsum(counts, axis=1)  # [MG, N]
    placed_per_group = counts.sum(axis=1)  # [MG]

    def pod_node(pg, pr):
        gidx = jnp.clip(pg, 0, mg - 1)
        idx = jnp.searchsorted(ccum[gidx], pr, side="right")
        live = (pg >= 0) & (pr < placed_per_group[gidx]) & gang_ok
        return jnp.where(live, idx, -1)

    assigned = jax.vmap(pod_node)(gang["pod_group"], gang["pod_rank"])  # [MP]

    # ---- Stage 4: placement quality (podgang.go:176-178, 1.0 = optimal) ------
    def pref_frac(c_pref, lvl, memb):
        dom = node_domain_id[jnp.clip(lvl, 0, levels - 1)]
        in_dom = (dom == c_pref).astype(jnp.float32)  # [N]
        cnt = (counts * memb[:, None]).astype(jnp.float32)  # [MG, N]
        tot = cnt.sum()
        hits = (cnt * in_dom[None, :]).sum()
        frac = jnp.where(tot > 0, hits / jnp.maximum(tot, 1.0), 1.0)
        active = (lvl >= 0)
        return jnp.where(active & (c_pref >= 0), frac, jnp.where(active, 0.0, 1.0))

    fracs = jax.vmap(pref_frac)(committed_pref, set_pref_level, set_member.astype(jnp.float32))
    has_pref = set_valid & (set_pref_level >= 0)
    mean_frac = jnp.where(
        has_pref.any(),
        (jnp.where(has_pref, fracs, 0.0).sum()) / jnp.maximum(has_pref.sum(), 1),
        1.0,
    )
    placement_score = jnp.where(gang_ok, 0.5 + 0.5 * mean_frac, 0.0)

    free_out = jnp.where(gang_ok, free2, free)
    used_out = jnp.where(gang_ok, used2, used_carry)
    return free_out, used_out, assigned, gang_ok, placement_score


def solve_batch_impl(
    free0: jax.Array,  # f32 [N, R]
    capacity: jax.Array,  # f32 [N, R]
    schedulable: jax.Array,  # bool [N]
    node_domain_id: jax.Array,  # i32 [L, N]
    batch: GangBatch,
    params: SolverParams = SolverParams(),
    ok_global: jax.Array | None = None,  # bool [T] cross-wave verdict bitmap
    coarse_dmax: int | None = None,  # static max domains over non-host levels
) -> SolveResult:
    """Sequentially commit every gang in the batch (priority order = batch order).

    `coarse_dmax` enables the scatter-free matmul aggregation path (see
    _coarse_onehot_stack) — pass int(snapshot.num_domains[:-1].max()); the
    solve() wrapper does. None falls back to segment-sum (fine on CPU).

    This is the UNJITTED implementation — `solve_batch` below is the default
    jitted entry; solver/warm.py re-jits it for the AOT executable cache
    (with and without wave-carry donation) so the warm path and the default
    path trace the one function."""
    n = free0.shape[0]
    g = batch.gang_valid.shape[0]
    cap_scale = jnp.maximum(capacity.max(axis=0), 1e-9)  # [R]
    gang_valid0 = _apply_global_deps(batch, ok_global)
    coarse_onehot = (
        None if coarse_dmax is None else _coarse_onehot_stack(node_domain_id, coarse_dmax)
    )

    has_spread = batch.spread_level is not None

    def step(carry, xs):
        free, ok_vec, family_used = carry
        gang_slices, gi = xs
        # Scaled gangs wait for their base gang (syncflow.go:347-387): the base
        # gang sits earlier in the batch, so its verdict is already in ok_vec.
        dep = gang_slices["depends_on"]
        dep_ok = jnp.where(dep >= 0, ok_vec[jnp.clip(dep, 0, g - 1)], True)
        gang_slices = dict(gang_slices)
        gang_slices["gang_valid"] = gang_slices["gang_valid"] & dep_ok
        # Per-gang locality seed: the previous incarnation's nodes
        # (ReuseReservationRef, podgang.go:65-71) attract via w_reuse.
        used0 = gang_slices["reuse"]
        avoid = None
        if has_spread:
            # Read-before-write: a base gang sees domains occupied by sibling
            # replicas placed EARLIER (in-batch, via the family row) or
            # already live in the store (spread_avoid seed) — never its own.
            fam = gang_slices["spread_family"]
            ridx = jnp.clip(fam, 0, g - 1)
            avoid = gang_slices["spread_avoid"] | (family_used[ridx] & (fam >= 0))
        free_out, _, assigned, ok, score = _place_gang(
            free,
            used0,
            gang_slices,
            schedulable=schedulable,
            node_domain_id=node_domain_id,
            cap_scale=cap_scale,
            params=params,
            coarse_onehot=coarse_onehot,
            spread_avoid=avoid,
        )
        ok_vec = ok_vec.at[gi].set(ok)
        if has_spread:
            placed_mask = (
                jnp.zeros((n,), dtype=bool)
                .at[jnp.clip(assigned, 0, n - 1)]
                .max((assigned >= 0) & ok)
            )
            family_used = family_used.at[ridx].set(
                jnp.where(fam >= 0, family_used[ridx] | placed_mask, family_used[ridx])
            )
        return (free_out, ok_vec, family_used), (assigned, ok, score)

    gang_dict = {
        "group_req": batch.group_req,
        "group_total": batch.group_total,
        "group_required": batch.group_required,
        "group_valid": batch.group_valid,
        "set_member": batch.set_member,
        "set_req_level": batch.set_req_level,
        "set_pref_level": batch.set_pref_level,
        "set_valid": batch.set_valid,
        "set_pinned": batch.set_pinned,
        "pod_group": batch.pod_group,
        "pod_rank": batch.pod_rank,
        "gang_valid": gang_valid0,
        "group_order": batch.group_order,
        "depends_on": batch.depends_on,
        "index": jnp.arange(g, dtype=jnp.int32),
        "reuse": _reuse_of(batch, n),
    }
    if batch.group_node_ok is not None:
        gang_dict["group_node_ok"] = batch.group_node_ok
    if has_spread:
        gang_dict["spread_level"] = batch.spread_level
        gang_dict["spread_family"] = batch.spread_family
        gang_dict["spread_avoid"] = batch.spread_avoid
    fam_init = jnp.zeros((g, n) if has_spread else (1, 1), dtype=bool)
    (free_final, _, _), (assigned, ok, score) = jax.lax.scan(
        step, (free0, jnp.zeros((g,), dtype=bool), fam_init), (gang_dict, jnp.arange(g))
    )
    return SolveResult(
        assigned=assigned,
        ok=ok,
        placement_score=score,
        free_after=free_final,
        ok_global=_scatter_global_ok(batch, ok, ok_global),
    )


solve_batch = partial(jax.jit, static_argnames=("coarse_dmax",))(solve_batch_impl)


def stacked_solve_batch_impl(
    free0: jax.Array,
    capacity: jax.Array,
    schedulable: jax.Array,
    node_domain_id: jax.Array,
    batch: GangBatch,
    params_stack: SolverParams,  # each leaf [K]
    coarse_dmax: int | None = None,
) -> SolveResult:
    """Solve the SAME wave under K weight variants at once; every SolveResult
    leaf gains a leading [K] axis (assigned [K, G, MP], ok [K, G], ...).

    This is the config-sweep workhorse (grove_tpu/tuning): unlike
    `portfolio_solve_batch` it keeps ALL K results instead of selecting a
    winner — the offline sweep scores each variant independently against the
    recorded trace. Row k is BITWISE-identical to a single `solve_batch` call
    with `params_stack` row k (vmap batches the identical op sequence; the
    sweep's replay-agreement contract rests on this, pinned in
    tests/test_tuning.py), so sweep verdicts can never diverge from what the
    production solver would have done under that config.

    `ok_global` is deliberately absent: the sweep replays journaled waves,
    and replay resolves cross-wave dependencies on the host exactly like
    trace/replay.py (scheduled_gangs in the encode closure)."""
    axes = SolverParams(*(0 for _ in SolverParams._fields))
    return jax.vmap(
        lambda p: solve_batch_impl(
            free0,
            capacity,
            schedulable,
            node_domain_id,
            batch,
            p,
            None,
            coarse_dmax=coarse_dmax,
        ),
        in_axes=(axes,),
    )(params_stack)


stacked_solve_batch = partial(jax.jit, static_argnames=("coarse_dmax",))(
    stacked_solve_batch_impl
)


# Mesh-sharded solve entries, one jitted variant per (donate, layout): the
# SAME solve_batch_impl trace, with every output pinned by an explicit
# sharding constraint — free_after stays node-sharded (the drain's wave
# carry chains shard-to-shard with zero resharding), verdict/assignment/
# score/ok_global outputs are replicated (host fetches and the cross-wave
# bitmap cost one small transfer, not a gather). Inputs take their sharding
# from the arrays at lowering time (parallel/mesh.SolveLayout places them),
# so GSPMD sees the node axis split end to end and inserts the collectives
# for the per-domain segment reductions and the stage-2 top-k.
_SHARDED_JIT: dict[tuple, object] = {}
_SHARDED_JIT_LOCK = threading.Lock()


def sharded_solve_fn(layout, donate: bool = False):
    """jitted solve_batch_impl whose result layout is pinned to `layout`.

    Process-wide memo per (donate, layout key) — the AOT executable cache
    (solver/warm.py) lowers through this function, so a sharded shape
    lowered by the prewarm thread and one lowered by a live solve are the
    one traced function, exactly like the dense path."""
    key = (bool(donate), layout.key())
    with _SHARDED_JIT_LOCK:
        cached = _SHARDED_JIT.get(key)
        if cached is not None:
            return cached

    rep = layout.replicated()
    free_sh = layout.free_sharding()

    def impl(
        free0,
        capacity,
        schedulable,
        node_domain_id,
        batch,
        params=SolverParams(),
        ok_global=None,
        coarse_dmax=None,
    ):
        res = solve_batch_impl(
            free0,
            capacity,
            schedulable,
            node_domain_id,
            batch,
            params,
            ok_global,
            coarse_dmax=coarse_dmax,
        )
        c = jax.lax.with_sharding_constraint
        return SolveResult(
            assigned=c(res.assigned, rep),
            ok=c(res.ok, rep),
            placement_score=c(res.placement_score, rep),
            free_after=c(res.free_after, free_sh),
            ok_global=None if res.ok_global is None else c(res.ok_global, rep),
        )

    jitted = jax.jit(
        impl,
        static_argnames=("coarse_dmax",),
        # Same wave-carry donation contract as the dense variants
        # (solver/warm.py _jitted_solve): free0 (arg 0) + ok_global (arg 6).
        donate_argnums=(0, 6) if donate else (),
    )
    with _SHARDED_JIT_LOCK:
        return _SHARDED_JIT.setdefault(key, jitted)


class ScanSolveResult(NamedTuple):
    """One scanned shape-class: per-wave verdict planes stacked on a leading
    [W] wave axis, plus the final carry. `free_in`/`okg_in` are the ENTERING
    carry per step (present iff retain=True) — byte-identical to what the
    per-wave drain retains in rec["free_in"]/rec["okg_in"], so journaling and
    retire-time dense escalation read the same values the serial path would."""

    assigned: jax.Array  # i32 [W, G, MP]
    ok: jax.Array  # bool [W, G]
    placement_score: jax.Array  # f32 [W, G]
    free_in: jax.Array | None  # f32 [W, N, R] entering free per step
    okg_in: jax.Array | None  # bool [W, T] entering verdict bitmap per step
    free_after: jax.Array  # f32 [N, R] final carry
    ok_global: jax.Array  # bool [T] final verdict bitmap


# One jitted scan wrapper per (pruned, retain, donate, layout): the wave loop
# as a device program. The carry (free, ok_global) threads step-to-step with
# pinned shardings (the SNIPPETS pjit-chaining idiom: constrain the carry so
# the chain never reshards), the stacked GangBatch rides the scanned xs axis,
# and the verdict planes come back as stacked ys — ONE dispatch and ONE
# harvest round-trip for the whole shape class.
_SCAN_JIT: dict[tuple, object] = {}
_SCAN_JIT_LOCK = threading.Lock()


def scan_solve_fn(layout=None, retain: bool = False, donate: bool = False):
    """jitted `lax.scan` of solve_batch_impl over a stacked wave axis.

    Signature of the returned callable:
      (free0 [N,R], capacity [N,R], schedulable [N], node_domain_id [L,N],
       stacked_batch (GangBatch, each leaf [W,...]), params,
       ok_global [T], *, coarse_dmax) -> ScanSolveResult

    Step w runs solve_batch_impl on wave w's batch with the carry exactly as
    the serial drain would thread it — bitwise-identical verdicts by
    construction (same traced step function, same op order). `retain=True`
    additionally emits the entering (free, ok_global) per step so lossy-pruned
    waves can escalate dense at retire time and the journal stays per-wave.
    Process-wide memo like `sharded_solve_fn`; the AOT executable cache
    lowers through this function."""
    key = ("dense", bool(retain), bool(donate), None if layout is None else layout.key())
    with _SCAN_JIT_LOCK:
        cached = _SCAN_JIT.get(key)
        if cached is not None:
            return cached

    rep = None if layout is None else layout.replicated()
    free_sh = None if layout is None else layout.free_sharding()

    def impl(
        free0,
        capacity,
        schedulable,
        node_domain_id,
        stacked_batch,
        params=SolverParams(),
        ok_global=None,
        coarse_dmax=None,
    ):
        c = jax.lax.with_sharding_constraint

        def step(carry, wave_batch):
            free, okg = carry
            res = solve_batch_impl(
                free,
                capacity,
                schedulable,
                node_domain_id,
                wave_batch,
                params,
                okg,
                coarse_dmax=coarse_dmax,
            )
            free_out, okg_out = res.free_after, res.ok_global
            if layout is not None:
                # Pin the carry every step: node axis stays sharded, the
                # small planes replicated — zero resharding across the chain.
                free_out = c(free_out, free_sh)
                okg_out = c(okg_out, rep)
            ys = (res.assigned, res.ok, res.placement_score)
            if retain:
                ys = ys + (free, okg)
            return (free_out, okg_out), ys

        (free_final, okg_final), ys = jax.lax.scan(step, (free0, ok_global), stacked_batch)
        if layout is not None:
            ys = tuple(c(y, rep) for y in ys[:3]) + ys[3:]
        return ScanSolveResult(
            assigned=ys[0],
            ok=ys[1],
            placement_score=ys[2],
            free_in=ys[3] if retain else None,
            okg_in=ys[4] if retain else None,
            free_after=free_final,
            ok_global=okg_final,
        )

    jitted = jax.jit(
        impl,
        static_argnames=("coarse_dmax",),
        # Same wave-carry donation contract as the per-wave variants:
        # free0 (arg 0) + ok_global (arg 6) feed the next class's carry.
        donate_argnums=(0, 6) if donate else (),
    )
    with _SCAN_JIT_LOCK:
        return _SCAN_JIT.setdefault(key, jitted)


def scan_pruned_solve_fn(layout=None, retain: bool = False, donate: bool = False):
    """Candidate-pruned scan: per step, gather the FLEET free carry onto that
    wave's candidate axis, solve there, scatter free_after back — the fleet
    carry is what threads step-to-step, so the chain composes with dense
    waves and the retained `free_in` is fleet-shaped (what escalation and the
    journal need).

    Signature of the returned callable:
      (free0 [N,R], cand_idx i32 [W,CP], capacity_p [W,CP,R],
       schedulable_p [W,CP], node_domain_id_p [W,L,CP],
       stacked_batch (candidate-axis GangBatch, each leaf [W,...]), params,
       ok_global [T], *, coarse_dmax) -> ScanSolveResult

    `cand_idx` rows use the CandidatePlan._padded_idx convention: pad slots
    point past the fleet axis, so gathers fill 0.0 and scatters drop."""
    key = ("pruned", bool(retain), bool(donate), None if layout is None else layout.key())
    with _SCAN_JIT_LOCK:
        cached = _SCAN_JIT.get(key)
        if cached is not None:
            return cached

    rep = None if layout is None else layout.replicated()
    free_sh = None if layout is None else layout.free_sharding()

    def impl(
        free0,
        cand_idx,
        capacity_p,
        schedulable_p,
        node_domain_id_p,
        stacked_batch,
        params=SolverParams(),
        ok_global=None,
        coarse_dmax=None,
    ):
        c = jax.lax.with_sharding_constraint

        def step(carry, xs):
            free, okg = carry
            idx, cap_w, sched_w, ndid_w, wave_batch = xs
            free_p = free.at[idx].get(mode="fill", fill_value=0.0)
            res = solve_batch_impl(
                free_p,
                cap_w,
                sched_w,
                ndid_w,
                wave_batch,
                params,
                okg,
                coarse_dmax=coarse_dmax,
            )
            free_out = free.at[idx].set(
                res.free_after, mode="drop", unique_indices=True
            )
            okg_out = res.ok_global
            if layout is not None:
                free_out = c(free_out, free_sh)
                okg_out = c(okg_out, rep)
            ys = (res.assigned, res.ok, res.placement_score)
            if retain:
                ys = ys + (free, okg)
            return (free_out, okg_out), ys

        (free_final, okg_final), ys = jax.lax.scan(
            step,
            (free0, ok_global),
            (cand_idx, capacity_p, schedulable_p, node_domain_id_p, stacked_batch),
        )
        if layout is not None:
            ys = tuple(c(y, rep) for y in ys[:3]) + ys[3:]
        return ScanSolveResult(
            assigned=ys[0],
            ok=ys[1],
            placement_score=ys[2],
            free_in=ys[3] if retain else None,
            okg_in=ys[4] if retain else None,
            free_after=free_final,
            ok_global=okg_final,
        )

    jitted = jax.jit(
        impl,
        static_argnames=("coarse_dmax",),
        # free0 (arg 0) + ok_global (arg 7) under the pruned signature.
        donate_argnums=(0, 7) if donate else (),
    )
    with _SCAN_JIT_LOCK:
        return _SCAN_JIT.setdefault(key, jitted)


class StackedScanResult(NamedTuple):
    """A run of journaled waves solved under K configs each: every verdict
    plane gains leading [W, K] axes. No carry threads between steps — the
    sweep replays RECORDED waves, each from its journaled entering free
    (cross-wave dependencies were resolved on the host at record time), so
    the scan is pure batching: step w row k is bitwise-identical to a
    single stacked_solve_batch call on wave w (the sweep's replay-agreement
    contract, pinned in tests/test_tuning.py)."""

    assigned: jax.Array  # i32 [W, K, G, MP]
    ok: jax.Array  # bool [W, K, G]
    placement_score: jax.Array  # f32 [W, K, G]


def stacked_scan_solve_fn():
    """jitted `lax.scan` of stacked_solve_batch_impl over a journaled wave
    axis — the tuning sweep's run batcher.

    Signature of the returned callable:
      (free_stack [W,N,R], capacity [N,R], schedulable [N],
       node_domain_id [L,N], stacked_batch (GangBatch, each leaf [W,...]),
       params_stack (SolverParams, each leaf [K]), *, coarse_dmax)
      -> StackedScanResult

    Each step solves wave w from its RECORDED entering free under all K
    sweep configs; a run of W same-shape journaled waves costs ONE dispatch
    instead of W per-wave stacked solves, which is what keeps a sweep over
    a scanned journal at ~stacked-replay cost. Pad the wave axis with NULL
    waves (zero free, all-invalid batch) to bucket run lengths — null steps
    admit nothing and there is no carry to disturb. Process-wide memo like
    scan_solve_fn; the AOT executable cache lowers through this function."""
    key = ("stacked",)
    with _SCAN_JIT_LOCK:
        cached = _SCAN_JIT.get(key)
        if cached is not None:
            return cached

    def impl(
        free_stack,
        capacity,
        schedulable,
        node_domain_id,
        stacked_batch,
        params_stack,
        coarse_dmax=None,
    ):
        def step(_, xs):
            free_w, wave_batch = xs
            res = stacked_solve_batch_impl(
                free_w,
                capacity,
                schedulable,
                node_domain_id,
                wave_batch,
                params_stack,
                coarse_dmax=coarse_dmax,
            )
            return 0, (res.assigned, res.ok, res.placement_score)

        _, ys = jax.lax.scan(step, 0, (free_stack, stacked_batch))
        return StackedScanResult(
            assigned=ys[0], ok=ys[1], placement_score=ys[2]
        )

    jitted = jax.jit(impl, static_argnames=("coarse_dmax",))
    with _SCAN_JIT_LOCK:
        return _SCAN_JIT.setdefault(key, jitted)


def coarse_dmax_of(snapshot) -> int | None:
    """Static bound on domains per non-host level, selecting the aggregation
    strategy for the backend the solve will run on:

    - TPU (or any accelerator): the one-hot matmul path. TPU scatter applies
      update rows serially, so `segment_sum` over 5k nodes inside the solve
      loop cost ~milliseconds per gang (the round-2 bench burned ~95% of its
      55s there); a [Dm, N] @ [N, C] matmul rides the MXU instead. Host level
      (one domain per node, ordinal == node index) aggregates by identity.
    - CPU: None — segment_sum is a cheap serial loop there, while the one-hot
      matmul is ~100x the FLOPs (measured 4x end-to-end bench regression).
    """
    if jax.default_backend() == "cpu":
        return None
    nd = np.asarray(snapshot.num_domains)
    if nd.shape[0] <= 1:
        return 1
    return max(int(nd[:-1].max()), 1)


def solve(
    snapshot,
    batch: GangBatch,
    params: SolverParams = SolverParams(),
    free: jax.Array | None = None,
    schedulable: jax.Array | None = None,
    ok_global: jax.Array | None = None,
    portfolio: int = 1,
    escalate_portfolio: int = 1,
    warm=None,  # solver.warm.WarmPath: AOT executables + device-resident state
    donate: bool = False,
    pruning=None,  # solver.pruning.PruningConfig: candidate-pruned solve path
    mesh=None,  # parallel.mesh.SolveLayout: node-sharded solve across devices
) -> SolveResult:
    """Convenience wrapper: snapshot (numpy) -> device -> solve_batch.

    `free`/`schedulable` override the snapshot's (wave chaining: pass the
    previous result's free_after); `ok_global` threads the cross-wave verdict
    bitmap (see solve_batch).

    `warm` (a solver.warm.WarmPath) routes the single-variant solve through
    the AOT executable cache (observable hit/miss/lowering counters, prewarm)
    and keeps the snapshot's node tensors device-resident across calls via
    content-digest memoization — the per-tick serving paths pass their own.
    `donate=True` additionally donates the free/ok_global wave carry (only
    safe when the caller forfeits those buffers — the drain's chaining loop);
    never combined with cached `free` buffers (solve() only donates when the
    caller passed an explicit `free` override it owns).

    `portfolio` > 1 solves the batch under P score-weight variants (base +
    polarity-diverse perturbations, parallel/portfolio.py) and keeps the
    winner by (admitted count, quality) — the multi-chip quality path
    (solver.portfolio config knob): on a multi-device mesh the variants ride
    the portfolio axis; on one device they vmap into a single batched
    program.

    `pruning` (a solver.pruning.PruningConfig with enabled=True) routes the
    single-variant solve through the candidate-pruned path: a cheap host
    pre-filter gathers the nodes that could possibly serve any gang in the
    batch onto a compact pow2 candidate axis and runs the UNCHANGED
    solve_batch on the sub-fleet (the AOT cache then keys on the candidate
    pad, not the fleet pad). Exactness escalation: a gang rejected on the
    pruned fleet whose prune was lossy (its feasible-domain witness clipped
    by the candidate budget — solver/pruning.py) re-solves dense before the
    rejection stands; escalations are counted on `warm.prune`, never
    silent. Pruning only applies to the snapshot-state single-variant solve
    (free/schedulable overrides and portfolio solves pass through dense).

    `mesh` (a parallel.mesh.SolveLayout) shards the single-variant solve
    across the device mesh: node-axis tensors split over the layout's node
    axis, GSPMD inserting the segment-reduction collectives; verdicts come
    back replicated and the free carry stays node-sharded. Bitwise-equal to
    the unsharded solve (pinned by tests/test_mesh.py), so sharding is a
    pure throughput choice. Pruned solves shard the CANDIDATE axis — the
    candidate pad is negotiated mesh-divisible (solver/pruning.py), so the
    layout never forces a dense fallback. Portfolio (> 1) solves ignore it:
    they negotiate their own (portfolio, node) mesh in portfolio_solve.

    `escalate_portfolio` > portfolio: when the single-variant solve leaves
    VALID gangs rejected, re-solve the same batch once under P=escalate
    variants and keep that winner. Rejection under contention is sometimes a
    packing artifact (the bin-packing trap: best-fit doubles small gangs and
    strands a later floor — sim/workloads.binpack_trap_backlog) that a
    polarity-diverse portfolio fixes; slot-0 elitism guarantees the escalated
    result never admits fewer than the base. Uncontended solves (no valid
    rejections — the common case) pay nothing, which is why escalation is on
    by default in the serving path while `solver.portfolio` stays 1 for
    latency (round-4 verdict weak #6).

    (A speculative parallel-commit path existed through round 3; it was
    deleted after losing to the sequential scan in every measured regime —
    on-chip at the bench shape and a CPU G x contention sweep where its
    per-round re-placement multiplier grew the gap with G. See git history
    for scripts/sweep_speculative.py.)
    """
    if warm is not None:
        # Device-resident node state: uploads memoized by content digest, so
        # an unchanged capacity/topology/free tensor re-uses its device
        # buffer across ticks instead of paying a fresh host->device copy.
        free0, capacity, sched, node_domain_id = warm.device.snapshot_arrays(
            snapshot, free=free, schedulable=schedulable
        )
    else:
        free0 = jnp.asarray(snapshot.free if free is None else free)
        capacity = jnp.asarray(snapshot.capacity)
        sched = jnp.asarray(snapshot.schedulable if schedulable is None else schedulable)
        node_domain_id = jnp.asarray(snapshot.node_domain_id)
    jbatch = GangBatch(*(None if x is None else jnp.asarray(x) for x in batch))
    cdmax = coarse_dmax_of(snapshot)

    def _psolve(width: int) -> SolveResult:
        from grove_tpu.parallel.portfolio import portfolio_solve

        return portfolio_solve(
            free0, capacity, sched, node_domain_id, jbatch, params, width,
            ok_global, coarse_dmax=cdmax,
        )

    result = None
    pruned_ok = None  # pruned verdicts, kept to grade an escalated re-solve
    if (
        pruning is not None
        and getattr(pruning, "enabled", False)
        and portfolio == 1
        and free is None
        and schedulable is None
    ):
        from grove_tpu.solver import pruning as pruning_mod

        pstats = warm.prune if warm is not None else None
        plan = pruning_mod.plan_candidates(
            snapshot, batch, pruning,
            mesh_axis=mesh.node_devices if mesh is not None else 1,
        )
        if plan is None:
            if pstats is not None:
                pstats.dense_fallbacks += 1
        else:
            pbatch = plan.gather_batch(batch)
            jpbatch = GangBatch(
                *(None if x is None else jnp.asarray(x) for x in pbatch)
            )
            if warm is not None:
                cap_p = warm.device.device_array(plan.capacity, jnp.float32)
                sched_p = warm.device.device_array(plan.schedulable)
                ndid_p = warm.device.device_array(plan.node_domain_id, jnp.int32)
            else:
                cap_p = jnp.asarray(plan.capacity)
                sched_p = jnp.asarray(plan.schedulable)
                ndid_p = jnp.asarray(plan.node_domain_id)
            free_p = plan.gather_free(free0, layout=mesh)
            if warm is not None:
                presult = warm.executables.solve(
                    free_p, cap_p, sched_p, ndid_p, jpbatch, params, ok_global,
                    coarse_dmax=plan.coarse_dmax(), layout=mesh,
                )
            elif mesh is not None:
                free_p, cap_p, sched_p, ndid_p, jpbatch, okg_p = (
                    mesh.shard_solve_args(
                        free_p, cap_p, sched_p, ndid_p, jpbatch, ok_global
                    )
                )
                presult = sharded_solve_fn(mesh)(
                    free_p, cap_p, sched_p, ndid_p, jpbatch, params, okg_p,
                    coarse_dmax=plan.coarse_dmax(),
                )
            else:
                presult = solve_batch(
                    free_p, cap_p, sched_p, ndid_p, jpbatch, params, ok_global,
                    coarse_dmax=plan.coarse_dmax(),
                )
            if pstats is not None:
                pstats.pruned_solves += 1
                pstats.last_candidate_nodes = plan.count
                pstats.last_candidate_pad = plan.pad
                pstats.last_fleet_nodes = plan.fleet_pad
            pruned_ok = np.asarray(presult.ok, dtype=bool)
            valid_np = np.asarray(
                _apply_global_deps(jbatch, ok_global), dtype=bool
            )
            if pruning_mod.lossy_rejections(plan, valid_np, pruned_ok).any():
                # Exactness escalation: the prune may have cost this gang
                # its domain aggregates — the rejection only stands if the
                # DENSE solver agrees. Fall through to the dense dispatch.
                if pstats is not None:
                    pstats.escalations += 1
            else:
                result = SolveResult(
                    assigned=plan.remap_assigned(presult.assigned),
                    ok=presult.ok,
                    placement_score=presult.placement_score,
                    free_after=plan.scatter_free(
                        free0, presult.free_after, layout=mesh
                    ),
                    ok_global=presult.ok_global,
                )
    if result is None:
        if portfolio > 1:
            result = _psolve(portfolio)
        elif warm is not None:
            # Donation only when the caller owns the carry: a cached `free`
            # buffer (free is None -> device-cache owned) must survive the
            # call.
            result = warm.executables.solve(
                free0, capacity, sched, node_domain_id, jbatch, params,
                ok_global,
                coarse_dmax=cdmax, donate=bool(donate and free is not None),
                layout=mesh,
            )
        elif mesh is not None:
            free_s, cap_s, sched_s, ndid_s, jbatch_s, okg_s = (
                mesh.shard_solve_args(
                    free0, capacity, sched, node_domain_id, jbatch, ok_global
                )
            )
            result = sharded_solve_fn(mesh)(
                free_s, cap_s, sched_s, ndid_s, jbatch_s, params, okg_s,
                coarse_dmax=cdmax,
            )
        else:
            result = solve_batch(
                free0, capacity, sched, node_domain_id, jbatch, params,
                ok_global,
                coarse_dmax=cdmax,
            )
        if pruned_ok is not None and warm is not None:
            # Escalated re-solve: did the full fleet actually change any
            # verdict, or did it confirm the pruned rejection?
            if bool(
                np.any(np.asarray(result.ok, dtype=bool) != pruned_ok)
            ):
                warm.prune.escalations_adopted += 1
    if escalate_portfolio > portfolio:
        ok = np.asarray(result.ok, dtype=bool)
        # Fold ok_global: a gang whose cross-wave base dependency already
        # failed is rejected by construction — no weight variant can admit
        # it, so it must not trigger (and pay for) an escalated solve.
        valid = np.asarray(_apply_global_deps(jbatch, ok_global), dtype=bool)
        if bool(np.any(valid & ~ok)):
            # params_population(p) draws its perturbation matrix row-major
            # from one seeded rng, so population(escalate) extends
            # population(portfolio) — the escalated winner can never admit
            # fewer than the result it replaces.
            return _psolve(escalate_portfolio)
    return result


def decode_assignments(result: SolveResult, decode_info, snapshot) -> dict[str, dict[str, str]]:
    """SolveResult -> {gang name: {pod name: node name}} for admitted gangs."""
    return decode_bindings(result.ok, result.assigned, decode_info, snapshot)


def decode_bindings(ok, assigned, decode_info, snapshot) -> dict[str, dict[str, str]]:
    """(ok [G], assigned [G, MP]) -> {gang: {pod: node}} — the array-level
    decode; callers that retained only these two arrays (the drain keeps
    results' chaining buffers off-device) use this directly.

    Vectorized: the valid (gang, slot) pairs are cut with one mask over the
    decode info's cached slot arrays (encode.GangDecodeInfo.slot_arrays) and
    node names gather through the snapshot's memoized name array, so the
    host cost is O(admitted pods) — no per-slot Python over the [G, MP]
    table. Output is identical to the retained loop oracle
    (_decode_bindings_reference; GROVE_HOST_REFERENCE=1 routes through it,
    tests/test_hostpath.py pins equality)."""
    from grove_tpu.solver.encode import host_vectorized

    if not host_vectorized():
        return _decode_bindings_reference(ok, assigned, decode_info, snapshot)
    out: dict[str, dict[str, str]] = {}
    g_real = len(decode_info.gang_names)
    if g_real == 0:
        return out
    if g_real * len(decode_info.pod_names[0]) < 1024:
        # Crossover: below ~1k slots the loop beats the batch decode's
        # constant numpy overhead (measured ~30us floor vs a ~60ns/slot
        # loop). Identical output either way — a pure cost dispatch.
        return _decode_bindings_reference(ok, assigned, decode_info, snapshot)
    assigned = np.asarray(assigned)
    ok = np.asarray(ok)
    ok_real = ok[:g_real].astype(bool, copy=False)
    admitted = np.flatnonzero(ok_real)
    for gi in admitted.tolist():
        out[decode_info.gang_names[gi]] = {}
    if admitted.size == 0:
        return out
    slot_gang, slot_col, slot_pod = decode_info.slot_arrays()
    live = ok_real[slot_gang] & (assigned[slot_gang, slot_col] >= 0)
    sg = slot_gang[live]
    pods = slot_pod[live].tolist()
    nodes = snapshot.node_names_arr()[assigned[sg, slot_col[live]]].tolist()
    # slot arrays are row-major, so each admitted gang's pairs form one
    # contiguous segment: two searchsorted cuts per gang, dicts zipped from
    # the segment — Python work proportional to admitted pods only.
    starts = np.searchsorted(sg, admitted, side="left")
    ends = np.searchsorted(sg, admitted, side="right")
    for j, gi in enumerate(admitted.tolist()):
        s, e = int(starts[j]), int(ends[j])
        if e > s:
            out[decode_info.gang_names[gi]] = dict(
                zip(pods[s:e], nodes[s:e])
            )
    return out


def _decode_bindings_reference(
    ok, assigned, decode_info, snapshot
) -> dict[str, dict[str, str]]:
    """The retained per-slot loop decode: the parity oracle for the
    vectorized decode_bindings (and the GROVE_HOST_REFERENCE=1 bench
    baseline). Semantics frozen — do not optimize."""
    assigned = np.asarray(assigned)
    ok = np.asarray(ok)
    out: dict[str, dict[str, str]] = {}
    for gi, gang_name in enumerate(decode_info.gang_names):
        if not ok[gi]:
            continue
        bindings: dict[str, str] = {}
        for slot, pod_name in enumerate(decode_info.pod_names[gi]):
            if not pod_name:
                continue
            node_idx = int(assigned[gi, slot])
            if node_idx >= 0:
                bindings[pod_name] = snapshot.node_names[node_idx]
        out[gang_name] = bindings
    return out
