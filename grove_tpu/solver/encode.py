"""Encode PodGangs into the dense, padded batch the solver consumes.

Shapes are static per (MG, MS, MP) bucket so XLA compiles once per bucket
(SURVEY.md §7 "ragged shapes" discipline):
  G  gangs in the batch          MG max PodGroups per gang
  MS max pack-sets per gang      MP max pods per gang
  N  nodes                       R  resource kinds
  L  topology levels

A *pack-set* is one packing constraint instance: (subset of groups, level) —
"all pods of these groups must land in ONE domain at this level". Gang-level
TopologyConstraint covers all groups (scheduler podgang.go:55-57), each
TopologyConstraintGroupConfig covers its subset (podgang.go:120-128), each
PodGroup constraint covers itself (podgang.go:84-88). Sets are ordered
broadest→narrowest so domain commitment can proceed top-down.

Every pod of a PodGroup shares one template (podgang.go:75 "share the same
PodTemplateSpec"), so a group is encoded as (request-vector, total, required)
and placement is count allocation, not per-pod assignment.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import NamedTuple

import numpy as np

from grove_tpu.api.pod import Pod
from grove_tpu.api.podgang import PodGang
from grove_tpu.state.cluster import ClusterSnapshot, pod_request_vector


def host_vectorized() -> bool:
    """Selects the vectorized host hot path (decode / pre-filter / encode
    fill). GROVE_HOST_REFERENCE=1 routes through the retained loop
    implementations instead — the bench A/B switch that turns the host-stage
    speedup into a recorded number (and the oracle the parity tests pin the
    vectorized paths against, tests/test_hostpath.py). Read per call: the
    bench flips it mid-process."""
    return os.environ.get("GROVE_HOST_REFERENCE", "0") != "1"


class GangBatch(NamedTuple):
    """Dense solver input; all arrays are numpy (device put happens in solve)."""

    group_req: np.ndarray  # f32 [G, MG, R] per-pod request of each group
    group_total: np.ndarray  # i32 [G, MG] pods referenced
    group_required: np.ndarray  # i32 [G, MG] gang floor (min_replicas, clamped)
    group_valid: np.ndarray  # bool [G, MG]
    set_member: np.ndarray  # bool [G, MS, MG]
    set_req_level: np.ndarray  # i32 [G, MS] topology level index, -1 = none
    set_pref_level: np.ndarray  # i32 [G, MS] topology level index, -1 = none
    set_valid: np.ndarray  # bool [G, MS]
    # Domain pin for incremental re-solve: when part of a gang is already bound
    # (pod replacement mid-gang), a required pack-set MUST stay in the domain
    # the bound pods occupy — the constraint covers the whole gang, not just
    # the re-solved remainder. -1 = unpinned.
    set_pinned: np.ndarray  # i32 [G, MS] domain ordinal at set_req_level
    pod_group: np.ndarray  # i32 [G, MP] group index of each pod slot, -1 pad
    pod_rank: np.ndarray  # i32 [G, MP] rank of pod within its group
    gang_valid: np.ndarray  # bool [G]
    # Allocation order over groups: required-pack-constrained groups first so
    # unconstrained groups can't consume a committed domain's capacity, then
    # biggest demand first (classic first-fit-decreasing).
    group_order: np.ndarray  # i32 [G, MG] permutation of group indices
    # Scaled gangs only schedule once their base gang is scheduled
    # (grove.io/base-podgang; podclique/components/pod/syncflow.go:347-387).
    # Index of the base gang within this batch (must be earlier), -1 = no dep.
    depends_on: np.ndarray  # i32 [G]
    # Cross-batch chaining (pipelined waves): each gang's slot in a
    # caller-defined global gang table, and the base gang's slot there when
    # the base was solved in an EARLIER batch. The solver resolves these
    # against the `ok_global` verdict bitmap it carries between waves, so
    # wave k+1 can be encoded and dispatched before wave k's results reach
    # the host. -1 = unset / no cross-batch dependency.
    global_index: np.ndarray  # i32 [G]
    depends_global: np.ndarray  # i32 [G]
    # ReuseReservationRef bias (podgang.go:65-71): nodes the gang's previous
    # incarnation occupied. Seeds the solver's per-gang locality (w_reuse), so
    # a rolling-updated gang prefers its old placement when capacity allows.
    reuse_nodes: np.ndarray = None  # bool [G, N]
    # Per-group node eligibility from pod nodeSelector (we ARE the scheduler,
    # so selector semantics are enforced here, not delegated): bool [G, MG, N]
    # or None when no pod in the batch carries a selector — the common case
    # pays nothing.
    group_node_ok: np.ndarray = None
    # Replica spread (PCS topologySpreadDomain): base gangs of one PCS repel
    # the spread-level domains sibling replicas occupy (w_spread). All three
    # are None unless some gang in the batch carries a spread constraint.
    spread_level: np.ndarray = None  # i32 [G] topology level index, -1 = none
    spread_family: np.ndarray = None  # i32 [G] batch slot of family root, -1
    spread_avoid: np.ndarray = None  # bool [G, N] sibling nodes live in store

    @property
    def n_gangs(self) -> int:
        return self.group_req.shape[0]


@dataclass
class GangDecodeInfo:
    """Host-side mapping from batch slots back to object names."""

    gang_names: list[str]
    # per gang, per pod slot: pod name ("" for padding)
    pod_names: list[list[str]]
    group_names: list[list[str]]
    # Lazily-built batch-decode index arrays (see slot_arrays); cached so a
    # decode_info consulted more than once (escalated re-decode, replay
    # diffing) pays the build exactly once.
    _slots: tuple | None = field(default=None, repr=False, compare=False)

    def slot_arrays(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(slot_gang i32 [S], slot_col i32 [S], slot_pod object [S]) over the
        NON-EMPTY pod-name slots, row-major (sorted by gang). One C-level
        pass over the [G, MP] name table replaces the per-slot Python walk
        the loop decode paid per wave; decode_bindings gathers admitted
        (gang, slot) pairs against these."""
        if self._slots is None:
            if self.pod_names:
                pod_arr = np.asarray(self.pod_names, dtype=object)  # [G, MP]
                gi, sj = np.nonzero(pod_arr != "")
                self._slots = (
                    gi.astype(np.int32),
                    sj.astype(np.int32),
                    pod_arr[gi, sj],
                )
            else:
                empty = np.zeros((0,), dtype=np.int32)
                self._slots = (empty, empty, np.zeros((0,), dtype=object))
        return self._slots


def _level_index(snapshot: ClusterSnapshot, label_key: str | None) -> int:
    """Node-label key (IR constraint) → topology level index in the snapshot."""
    if label_key is None:
        return -1
    for li, domain in enumerate(snapshot.level_domains):
        level = snapshot.topology.label_key_for(domain)
        if level == label_key:
            return li
    return -1


def next_pow2(v: int) -> int:
    """Next power of two >= v (min 1) — THE bucketing rounding, shared by the
    sidecar's shape buckets and the drain planner so policy cannot diverge."""
    return max(1, 1 << (max(v, 1) - 1).bit_length())


_BLOCKING_EFFECTS = ("NoSchedule", "NoExecute")

# Shared rank table for the vectorized pod-slot fill: slicing a prebuilt
# arange is ~10x cheaper than allocating one per group, and group sizes are
# bounded by the pod bucket. Grown on demand for outsized gangs.
_RANKS = np.arange(4096, dtype=np.int32)


def _ranks(n: int) -> np.ndarray:
    global _RANKS
    if n > _RANKS.shape[0]:
        _RANKS = np.arange(max(n, 2 * _RANKS.shape[0]), dtype=np.int32)
    return _RANKS[:n]


def _tolerates(tolerations: list[dict], taint: dict) -> bool:
    """k8s toleration-vs-taint match: key equal (or empty key + Exists),
    operator Equal -> values equal, effect empty-or-equal."""
    for tol in tolerations:
        op = tol.get("operator", "Equal")
        key = tol.get("key", "")
        if key and key != taint.get("key"):
            continue
        if not key and op != "Exists":
            continue
        if op == "Equal" and tol.get("value", "") != taint.get("value", ""):
            continue
        eff = tol.get("effect", "")
        if eff and eff != taint.get("effect", ""):
            continue
        return True
    return False


def node_tolerated(tolerations: list[dict], taints: list[dict]) -> bool:
    """True iff every scheduling-blocking taint on the node is tolerated
    (PreferNoSchedule is soft and never blocks)."""
    return all(
        _tolerates(tolerations, t)
        for t in taints
        if t.get("effect") in _BLOCKING_EFFECTS
    )


def pack_set_count(gang: PodGang) -> int:
    """Number of pack-sets this gang encodes to (shape-bucketing input)."""
    tc = gang.spec.topology_constraint
    n = 1 if tc is not None and tc.pack_constraint is not None else 0
    n += sum(
        1
        for gc in gang.spec.topology_constraint_group_configs
        if gc.topology_constraint is not None
        and gc.topology_constraint.pack_constraint is not None
    )
    n += sum(
        1
        for grp in gang.spec.pod_groups
        if grp.topology_constraint is not None
        and grp.topology_constraint.pack_constraint is not None
    )
    return n


def gang_shape(gang: PodGang) -> tuple[int, int, int]:
    """(groups, pack-sets, pods) — the encode-shape signature. Batching gangs
    of one shape class instead of padding everything to the global maxima
    keeps small gangs on small compiled programs (measured 3.5x on the bench
    backlog's frontend class)."""
    return (len(gang.spec.pod_groups), pack_set_count(gang), gang.total_pods())


# GangBatch fields that depend only on one gang's spec (+ snapshot epoch +
# bound-node pins) — exactly the rows the encode-row cache may reuse.
# Everything else (depends_on, global_index, depends_global, base-gang
# gating, reuse/spread seeds) depends on batch composition and is always
# recomputed.
_ROW_FIELDS = (
    "group_req",
    "group_total",
    "group_required",
    "group_valid",
    "set_member",
    "set_req_level",
    "set_pref_level",
    "set_valid",
    "set_pinned",
    "pod_group",
    "pod_rank",
    "group_order",
)


def _encode_cross_batch_fields(
    batch: GangBatch,
    gi: int,
    gang: PodGang,
    gang_index: dict[str, int],
    scheduled_gangs: set[str],
    global_index_of: dict[str, int] | None,
) -> None:
    """Batch-positional fields: global table slot + the base-gang gate.
    Runs for cached AND freshly-encoded gangs — a cached gang's base may sit
    at a different batch index (or in a different wave) this time."""
    if global_index_of is not None:
        batch.global_index[gi] = global_index_of.get(gang.name, -1)
    if gang.base_podgang_name is not None:
        base_idx = gang_index.get(gang.base_podgang_name, -1)
        if 0 <= base_idx < gi:
            batch.depends_on[gi] = base_idx
        elif (
            global_index_of is not None
            and gang.base_podgang_name in global_index_of
        ):
            # Base solved in an earlier wave: resolve the verdict on-device
            # via the solver's ok_global bitmap (pipelined chaining).
            batch.depends_global[gi] = global_index_of[gang.base_podgang_name]
        elif gang.base_podgang_name not in scheduled_gangs:
            # Base gang missing and not yet scheduled: gate this gang out.
            batch.gang_valid[gi] = False


def _seed_reuse_row(
    reuse_arr: np.ndarray | None,
    gi: int,
    gang: PodGang,
    reuse_nodes_by_gang: dict[str, list[int]] | None,
    snapshot: ClusterSnapshot,
    g_count: int,
) -> np.ndarray | None:
    """ReuseReservationRef seed row; lazily materializes the [G, N] tensor."""
    for node_idx in (reuse_nodes_by_gang or {}).get(gang.name, []):
        if 0 <= node_idx < snapshot.capacity.shape[0]:
            if reuse_arr is None:
                reuse_arr = np.zeros((g_count, snapshot.capacity.shape[0]), dtype=bool)
            reuse_arr[gi, node_idx] = True
    return reuse_arr


def encode_gangs(
    gangs: list[PodGang],
    pods_by_name: dict[str, Pod],
    snapshot: ClusterSnapshot,
    *,
    max_groups: int | None = None,
    max_sets: int | None = None,
    max_pods: int | None = None,
    pad_gangs_to: int | None = None,
    scheduled_gangs: set[str] | None = None,
    bound_nodes_by_group: dict[str, dict[str, list[int]]] | None = None,
    global_index_of: dict[str, int] | None = None,
    reuse_nodes_by_gang: dict[str, list[int]] | None = None,
    spread_avoid_by_gang: dict[str, list[int]] | None = None,
    row_cache=None,  # solver.warm.EncodeRowCache (duck-typed)
    row_keys: list | None = None,  # per-gang spec digests incl. snapshot epoch
) -> tuple[GangBatch, GangDecodeInfo]:
    """Flatten gang CRs into the padded batch + decode info.

    `scheduled_gangs`: names of gangs already scheduled in earlier solves. A
    scaled gang whose base gang is neither in this batch (at an earlier index)
    nor in `scheduled_gangs` is marked invalid — it must wait, mirroring the
    base-gang gate (podclique/components/pod/syncflow.go:347-387).

    `bound_nodes_by_group`: gang name -> group name -> node indices of pods of
    that group already bound in earlier solves. Used to pin required pack-sets
    to the domain the bound pods occupy (incremental re-solve must not split a
    co-location guarantee across domains).

    `reuse_nodes_by_gang`: gang name -> snapshot node indices its previous
    incarnation occupied (ReuseReservationRef, podgang.go:65-71); seeds the
    solver's w_reuse locality bonus toward the old placement.

    `row_cache`/`row_keys`: incremental encode reuse (solver/warm.py). Each
    gang's dense rows are dirty-tracked under (row_keys[gi], resource axis,
    bound-node signature) at the effective bucket dims: a gang whose key
    matches a previous encode skips the Python spec walk and copies its rows
    from the cache. The caller's row key MUST include a snapshot epoch
    (ClusterSnapshot.encode_epoch()) — selector/toleration rows and pack-set
    pins read node labels/taints/domains. Cross-batch fields (depends_on,
    global_index, depends_global, base-gang gating, reuse/spread seeds) are
    always recomputed; they depend on batch composition, not the gang spec.

    `global_index_of`: gang name -> slot in a caller-defined global gang table
    (pipelined-wave chaining). When set, each gang's `global_index` is filled,
    and a base-gang dependency on a gang OUTSIDE this batch becomes a
    `depends_global` reference resolved on-device against the solver's
    `ok_global` bitmap — instead of requiring the host-side `scheduled_gangs`
    verdict at encode time. Bases in neither the batch nor the table still
    fall back to the `scheduled_gangs` check.
    """
    g_count = pad_gangs_to if pad_gangs_to is not None else len(gangs)
    if g_count < len(gangs):
        raise ValueError("pad_gangs_to smaller than gang count")
    r = len(snapshot.resource_names)

    def _sets_of(gang: PodGang):
        """Return ((member group indices, req_level, pref_level, pin_names)
        broad→narrow, schedulable). `pin_names` are the ORIGINAL member group
        names the pin lookup consults — None means the whole gang, so bound
        groups dropped from an incremental sub-gang still anchor the pin.
        A REQUIRED key that doesn't resolve to a snapshot topology level makes
        the gang unschedulable — a hard co-location guarantee must never be
        silently dropped (expansion already nullifies constraints for domains
        missing from the ClusterTopology; skew between expansion and snapshot
        is an error, not a waiver)."""
        group_idx = {grp.name: k for k, grp in enumerate(gang.spec.pod_groups)}
        raw: list[tuple[list[int], int, int, list[str] | None]] = []
        unresolved_required = False

        def levels_of(pc) -> tuple[int, int]:
            nonlocal unresolved_required
            req = _level_index(snapshot, pc.required)
            if pc.required is not None and req < 0:
                unresolved_required = True
            return req, _level_index(snapshot, pc.preferred)

        if gang.spec.topology_constraint and gang.spec.topology_constraint.pack_constraint:
            req, pref = levels_of(gang.spec.topology_constraint.pack_constraint)
            raw.append((list(range(len(gang.spec.pod_groups))), req, pref, None))
        for gc in gang.spec.topology_constraint_group_configs:
            if gc.topology_constraint and gc.topology_constraint.pack_constraint:
                members = [group_idx[n] for n in gc.pod_group_names if n in group_idx]
                if members:
                    req, pref = levels_of(gc.topology_constraint.pack_constraint)
                    raw.append((members, req, pref, list(gc.pod_group_names)))
        for k, grp in enumerate(gang.spec.pod_groups):
            if grp.topology_constraint and grp.topology_constraint.pack_constraint:
                req, pref = levels_of(grp.topology_constraint.pack_constraint)
                raw.append(([k], req, pref, [grp.name]))
        # Drop sets with neither level resolvable.
        raw = [s for s in raw if s[1] >= 0 or s[2] >= 0]
        # Broadest required level first (-1 required sorts last).
        raw.sort(key=lambda s: (s[1] if s[1] >= 0 else 10**6))
        return raw, not unresolved_required

    mg = max_groups or max((len(g.spec.pod_groups) for g in gangs), default=1) or 1
    mp = max_pods or max((g.total_pods() for g in gangs), default=1) or 1
    # Encode-row reuse: resolve cache entries BEFORE the spec walk so hits
    # can skip _sets_of entirely (the stored n_sets feeds the ms default).
    bound_map = bound_nodes_by_group or {}
    row_entries: list = [None] * len(gangs)
    row_full_keys: list = [None] * len(gangs)
    if row_cache is not None and row_keys is not None:
        if len(row_keys) != len(gangs):
            raise ValueError("row_keys length must match gangs")
        for gi, gang in enumerate(gangs):
            bound = bound_map.get(gang.name)
            # () == tuple(sorted(...)) of an empty map — the common unbound
            # case skips the generator machinery, key value unchanged.
            bound_sig = (
                tuple(
                    sorted((grp, tuple(idxs)) for grp, idxs in bound.items())
                )
                if bound
                else ()
            )
            row_full_keys[gi] = (row_keys[gi], r, bound_sig)
            row_entries[gi] = row_cache.peek(row_full_keys[gi])
    # _sets_of memo per (spec digest, snapshot epoch) — exactly the caller's
    # row key, which already folds in everything _sets_of reads (constraint
    # tree from the spec, level resolution from the snapshot). A gang whose
    # full-row entry was demoted (bucket drift) or never stored still skips
    # the constraint walk when its spec+snapshot recur.
    vectorized = host_vectorized()
    sets_memo_peek = sets_memo_put = None
    if vectorized and row_cache is not None and row_keys is not None:
        sets_memo_peek = getattr(row_cache, "peek_sets", None)
        sets_memo_put = getattr(row_cache, "put_sets", None)

    def _sets_resolve(gi: int, gang: PodGang):
        if sets_memo_peek is not None:
            hit = sets_memo_peek(row_keys[gi])
            if hit is not None:
                return hit
        out = _sets_of(gang)
        if sets_memo_put is not None:
            sets_memo_put(row_keys[gi], out)
        return out

    sets_and_ok = [
        None if row_entries[gi] is not None else _sets_resolve(gi, g)
        for gi, g in enumerate(gangs)
    ]
    ms = max_sets or max(
        (
            row_entries[gi]["n_sets"]
            if row_entries[gi] is not None
            else len(sets_and_ok[gi][0])
            for gi in range(len(gangs))
        ),
        default=1,
    ) or 1
    # Demote hits whose bucket dims drifted — the stored rows are shaped by
    # the bucket they were encoded under.
    for gi in range(len(gangs)):
        if row_entries[gi] is not None and row_entries[gi]["dims"] != (mg, ms, mp):
            row_entries[gi] = None
            sets_and_ok[gi] = _sets_resolve(gi, gangs[gi])
    all_sets = [None if s is None else s[0] for s in sets_and_ok]
    sets_resolvable = [None if s is None else s[1] for s in sets_and_ok]

    batch = GangBatch(
        group_req=np.zeros((g_count, mg, r), dtype=np.float32),
        group_total=np.zeros((g_count, mg), dtype=np.int32),
        group_required=np.zeros((g_count, mg), dtype=np.int32),
        group_valid=np.zeros((g_count, mg), dtype=bool),
        set_member=np.zeros((g_count, ms, mg), dtype=bool),
        set_req_level=np.full((g_count, ms), -1, dtype=np.int32),
        set_pref_level=np.full((g_count, ms), -1, dtype=np.int32),
        set_valid=np.zeros((g_count, ms), dtype=bool),
        set_pinned=np.full((g_count, ms), -1, dtype=np.int32),
        pod_group=np.full((g_count, mp), -1, dtype=np.int32),
        pod_rank=np.zeros((g_count, mp), dtype=np.int32),
        gang_valid=np.zeros((g_count,), dtype=bool),
        group_order=np.tile(np.arange(mg, dtype=np.int32), (g_count, 1)),
        depends_on=np.full((g_count,), -1, dtype=np.int32),
        global_index=np.full((g_count,), -1, dtype=np.int32),
        depends_global=np.full((g_count,), -1, dtype=np.int32),
        # reuse_nodes stays None unless some gang carries a reuse seed —
        # like group_node_ok/spread_*, the dense [G, N] tensor (and its
        # host->device transfer per wave: ~wave_size x nodes bools) only
        # materializes when the feature is in play; solve_batch zero-fills
        # on device for None (core._reuse_of).
    )
    decode = GangDecodeInfo(gang_names=[], pod_names=[], group_names=[])
    gang_index = {g.name: i for i, g in enumerate(gangs)}
    scheduled_gangs = scheduled_gangs or set()
    selector_masks: np.ndarray | None = None  # bool [G, MG, N], lazy
    reuse_arr: np.ndarray | None = None  # bool [G, N], lazy
    # One O(N) label scan per UNIQUE selector / toleration set, not per
    # group — gang families share templates, and this runs on the per-Solve
    # encode hot path.
    selector_rows: dict[tuple, np.ndarray] = {}
    toleration_rows: dict[tuple, np.ndarray] = {}
    # Nodes carrying scheduling-blocking taints; empty on the common
    # untainted cluster, keeping the mask tensor unmaterialized. Memoized
    # on the snapshot: per-wave rescans were the dominant node-linear term
    # in the drain's host encode (8x-scale profile).
    tainted_idx = snapshot.tainted_node_indices(_BLOCKING_EFFECTS)
    # Normalize per resource before summing — raw units are incomparable
    # (cpu cores ~1 vs memory bytes ~1e10 vs TPU chips ~4). Memoized on the
    # snapshot (immutable capacity): one O(N) column max per snapshot, not
    # one per wave.
    cap_scale = snapshot.cap_scale()

    # Row-cache hits applied BATCHED (vectorized path): one stacked fancy
    # assignment per field over all hit gangs, instead of |fields| numpy row
    # copies per gang — the hit path is the steady-state encode, so its
    # per-gang Python floor is what the wave loop pays forever. Misses store
    # their rows the same way (miss_puts, extracted after the loop).
    hit_rows: list[tuple[int, dict]] = []
    miss_puts: list[tuple] = []
    for gi, gang in enumerate(gangs):
        entry = row_entries[gi]
        if entry is not None:
            # Encode-row cache hit: the spec (and the snapshot epoch baked
            # into the key) is unchanged since the rows were built — copy
            # them in and skip the Python spec walk.
            row_cache.hits += 1
            decode.gang_names.append(gang.name)
            if vectorized:
                # The entry's name lists are private to the cache (built
                # fresh at put) and every consumer reads decode info —
                # share them instead of copying per hit.
                decode.pod_names.append(entry["pod_names"])
                decode.group_names.append(entry["group_names"])
            else:
                decode.pod_names.append(list(entry["pod_names"]))
                decode.group_names.append(list(entry["group_names"]))
            batch.gang_valid[gi] = entry["resolvable"]
            if vectorized:
                hit_rows.append((gi, entry))
            else:
                for fname in _ROW_FIELDS:
                    getattr(batch, fname)[gi] = entry[fname]
            if entry["sel_rows"]:
                if selector_masks is None:
                    selector_masks = np.ones(
                        (g_count, mg, snapshot.capacity.shape[0]), dtype=bool
                    )
                for k, sel_row in entry["sel_rows"].items():
                    selector_masks[gi, k] = sel_row
            _encode_cross_batch_fields(
                batch,
                gi,
                gang,
                gang_index,
                scheduled_gangs,
                global_index_of,
            )
            reuse_arr = _seed_reuse_row(
                reuse_arr, gi, gang, reuse_nodes_by_gang, snapshot, g_count
            )
            continue
        if row_cache is not None and row_full_keys[gi] is not None:
            row_cache.misses += 1
        if len(gang.spec.pod_groups) > mg:
            raise ValueError(f"gang {gang.name}: {len(gang.spec.pod_groups)} groups > bucket {mg}")
        if gang.total_pods() > mp:
            raise ValueError(f"gang {gang.name}: {gang.total_pods()} pods > bucket {mp}")
        decode.gang_names.append(gang.name)
        pod_names: list[str] = []
        group_names: list[str] = []
        miss_sel_rows: dict[int, np.ndarray] = {}
        batch.gang_valid[gi] = sets_resolvable[gi]
        reuse_arr = _seed_reuse_row(
            reuse_arr, gi, gang, reuse_nodes_by_gang, snapshot, g_count
        )
        _encode_cross_batch_fields(
            batch, gi, gang, gang_index, scheduled_gangs, global_index_of
        )
        slot = 0
        for k, grp in enumerate(gang.spec.pod_groups):
            group_names.append(grp.name)
            refs = [ref.name for ref in grp.pod_references]
            batch.group_total[gi, k] = len(refs)
            batch.group_required[gi, k] = min(grp.min_replicas, len(refs))
            batch.group_valid[gi, k] = True
            if refs:
                first = pods_by_name.get(refs[0])
                if first is None:
                    raise ValueError(
                        f"gang {gang.name}: pod {refs[0]!r} referenced by group "
                        f"{grp.name!r} not found in pods_by_name"
                    )
                batch.group_req[gi, k] = pod_request_vector(first, snapshot.resource_names)
                selector = first.spec.node_selector
                if selector or tainted_idx:
                    # nodeSelector + taint semantics (we ARE the scheduler):
                    # a node is eligible iff its labels are a superset of the
                    # selector AND every blocking taint is tolerated. Pods of
                    # one group share a template, so the first pod speaks for
                    # the group. Lazily materialized — no selector and no
                    # tainted node means no [G, MG, N] tensor at all.
                    if selector_masks is None:
                        selector_masks = np.ones(
                            (g_count, mg, snapshot.capacity.shape[0]), dtype=bool
                        )
                    row = np.ones((snapshot.capacity.shape[0],), dtype=bool)
                    if selector:
                        key = tuple(sorted(selector.items()))
                        sel_row = selector_rows.get(key)
                        if sel_row is None:
                            sel_row = np.fromiter(
                                (
                                    all(lbl.get(sk) == sv for sk, sv in key)
                                    for lbl in snapshot.node_labels
                                ),
                                dtype=bool,
                                count=len(snapshot.node_labels),
                            )
                            selector_rows[key] = sel_row
                        row = row & sel_row
                    if tainted_idx:
                        tols = first.spec.tolerations
                        tkey = tuple(
                            tuple(sorted(t.items())) for t in tols
                        )
                        tol_row = toleration_rows.get(tkey)
                        if tol_row is None:
                            tol_row = np.ones(
                                (snapshot.capacity.shape[0],), dtype=bool
                            )
                            for i in tainted_idx:
                                tol_row[i] = node_tolerated(
                                    tols, snapshot.node_taints[i]
                                )
                            toleration_rows[tkey] = tol_row
                        row = row & tol_row
                    selector_masks[gi, k] = row
                    miss_sel_rows[k] = row
            if vectorized:
                # Per-pod slot fill as two numpy slice writes: the per-pod
                # Python loop was the dominant miss-path term for big gangs
                # (cost grew with MP, the heavy-tailed train-gang axis).
                nr = len(refs)
                batch.pod_group[gi, slot : slot + nr] = k
                batch.pod_rank[gi, slot : slot + nr] = _ranks(nr)
                pod_names.extend(refs)
                slot += nr
            else:
                for rank, ref in enumerate(refs):
                    batch.pod_group[gi, slot] = k
                    batch.pod_rank[gi, slot] = rank
                    pod_names.append(ref)
                    slot += 1
        if len(all_sets[gi]) > ms:
            raise ValueError(
                f"gang {gang.name}: {len(all_sets[gi])} pack-sets > bucket {ms}"
            )
        gang_bound = bound_map.get(gang.name, {})
        req_constrained: set[int] = set()
        for si, (members, req_l, pref_l, pin_names) in enumerate(all_sets[gi]):
            batch.set_valid[gi, si] = True
            batch.set_req_level[gi, si] = req_l
            batch.set_pref_level[gi, si] = pref_l
            for k in members:
                batch.set_member[gi, si, k] = True
                if req_l >= 0:
                    req_constrained.add(k)
            if req_l >= 0 and gang_bound:
                # Pin to the domain the already-bound member pods live in.
                # pin_names carries ORIGINAL member names: a fully-bound group
                # dropped from an incremental sub-gang still anchors the pin.
                lookup = gang_bound.keys() if pin_names is None else pin_names
                for name in lookup:
                    for node_idx in gang_bound.get(name, []):
                        dom = int(snapshot.node_domain_id[req_l, node_idx])
                        if dom >= 0:
                            batch.set_pinned[gi, si] = dom
                            break
                    if batch.set_pinned[gi, si] >= 0:
                        break
        if vectorized:
            # One row-wise reduction: elementwise ops and the per-row sum
            # order are identical to the per-group loop, so the sort keys
            # (and therefore group_order) are bitwise-unchanged.
            demand = (
                batch.group_total[gi]
                * (batch.group_req[gi] / cap_scale[None, :]).sum(axis=1)
            ).tolist()
        else:
            demand = [
                float(batch.group_total[gi, k] * (batch.group_req[gi, k] / cap_scale).sum())
                for k in range(mg)
            ]
        batch.group_order[gi] = np.array(
            sorted(range(mg), key=lambda k: (k not in req_constrained, -demand[k])),
            dtype=np.int32,
        )
        pod_names += [""] * (mp - len(pod_names))
        decode.pod_names.append(pod_names)
        decode.group_names.append(group_names)
        if row_cache is not None and row_full_keys[gi] is not None:
            if vectorized:
                # Deferred: the rows of every miss gang are extracted with
                # ONE stacked fancy-index copy per field after the loop,
                # instead of |fields| numpy row copies per gang here.
                miss_puts.append(
                    (gi, len(all_sets[gi]), pod_names, group_names, miss_sel_rows)
                )
            else:
                rows = {
                    fname: getattr(batch, fname)[gi].copy() for fname in _ROW_FIELDS
                }
                rows.update(
                    dims=(mg, ms, mp),
                    n_sets=len(all_sets[gi]),
                    resolvable=bool(sets_resolvable[gi]),
                    pod_names=list(pod_names),
                    group_names=list(group_names),
                    sel_rows=miss_sel_rows,
                )
                row_cache.put(row_full_keys[gi], rows)

    if hit_rows:
        # Entries written by the batched put path carry their shared field
        # stacks (_stacks/_row): hits grouped by stack identity apply with
        # ONE fancy-index gather+assign per (group, field) — re-encoding a
        # recurring wave is 12 slab copies, not 12 copies per gang. Entries
        # from the loop put path (reference mode) fall back to np.stack.
        groups: dict[int, tuple] = {}
        loose: list[tuple[int, dict]] = []
        for gi, entry in hit_rows:
            sd = entry.get("_stacks")
            if sd is None:
                loose.append((gi, entry))
                continue
            rec = groups.setdefault(id(sd), (sd, [], []))
            rec[1].append(gi)
            rec[2].append(entry["_row"])
        for sd, gis, js in groups.values():
            gi_arr = np.asarray(gis, dtype=np.intp)
            j_arr = np.asarray(js, dtype=np.intp)
            for fname in _ROW_FIELDS:
                getattr(batch, fname)[gi_arr] = sd[fname][j_arr]
        if loose:
            idx = np.fromiter(
                (gi for gi, _ in loose), dtype=np.intp, count=len(loose)
            )
            for fname in _ROW_FIELDS:
                getattr(batch, fname)[idx] = np.stack(
                    [entry[fname] for _, entry in loose]
                )
    if miss_puts:
        midx = np.fromiter(
            (m[0] for m in miss_puts), dtype=np.intp, count=len(miss_puts)
        )
        # One contiguous copy per field for ALL miss gangs; each stored row
        # is a view into the stack (the stack is owned by the entries
        # collectively and never written after this point).
        stacks = {f: getattr(batch, f)[midx].copy() for f in _ROW_FIELDS}
        for j, (gi, n_sets, pod_names_j, group_names_j, sel_rows_j) in enumerate(
            miss_puts
        ):
            rows = {f: stacks[f][j] for f in _ROW_FIELDS}
            rows.update(
                dims=(mg, ms, mp),
                n_sets=n_sets,
                resolvable=bool(sets_resolvable[gi]),
                pod_names=list(pod_names_j),
                group_names=list(group_names_j),
                sel_rows=sel_rows_j,
                # Shared-stack handle for the grouped hit application.
                _stacks=stacks,
                _row=j,
            )
            row_cache.put(row_full_keys[gi], rows)

    if selector_masks is not None:
        batch = batch._replace(group_node_ok=selector_masks)
    if reuse_arr is not None:
        batch = batch._replace(reuse_nodes=reuse_arr)

    # Replica spread: base gangs whose spec carries a resolvable spread_key
    # get a level, a family root (first base sibling of the same PCS in this
    # batch), and an avoid seed (nodes sibling replicas already occupy in the
    # store, from the caller). Scaled gangs never spread — they follow their
    # base. No spread in the batch → all three stay None (no cost).
    spread_active = [
        gi
        for gi, gang in enumerate(gangs)
        if gang.spec.spread_key is not None
        and gang.base_podgang_name is None
        and _level_index(snapshot, gang.spec.spread_key) >= 0
    ]
    if spread_active:
        n_nodes = snapshot.capacity.shape[0]
        spread_level = np.full((g_count,), -1, dtype=np.int32)
        spread_family = np.full((g_count,), -1, dtype=np.int32)
        spread_avoid = np.zeros((g_count, n_nodes), dtype=bool)
        family_root: dict[str, int] = {}
        for gi in spread_active:
            gang = gangs[gi]
            spread_level[gi] = _level_index(snapshot, gang.spec.spread_key)
            fam_key = gang.pcs_name or gang.name
            spread_family[gi] = family_root.setdefault(fam_key, gi)
            for node_idx in (spread_avoid_by_gang or {}).get(gang.name, []):
                if 0 <= node_idx < n_nodes:
                    spread_avoid[gi, node_idx] = True
        batch = batch._replace(
            spread_level=spread_level,
            spread_family=spread_family,
            spread_avoid=spread_avoid,
        )
    return batch, decode
