"""Candidate-node pruning: solve on the sub-fleet that could matter.

Every solve in `solver/core.py` is dense over the full padded node axis —
`_place_gang`'s domain tables, slot counts, and top-k picks are all O(N) per
gang per set, so a 4-8x larger fleet makes every wave 4-8x slower even though
a gang can only ever land on a handful of racks (the Tesserae observation:
placement policies scale when the search is restricted to a structured
candidate set, and the Turbo-Charged Mapper line prunes the search space
BEFORE the solve, not during it).

This module adds that pre-filter as a wrapper around the UNCHANGED solver:

1. **Candidate selection** (`plan_candidates`, host numpy, cheap): a node is
   a candidate iff it is schedulable AND has enough free capacity to host at
   least one pod of some group in the batch (the smallest-group-request
   test) AND sits inside a pack domain that can feasibly serve some gang's
   required floor demand (gangs without required pack-sets disable the
   domain test — their pods can land anywhere eligible). The candidate list
   is clipped to `max_candidates` (budget) and padded to a pow2 ladder
   bucket (`solver.pruning` config), so recurring workloads land on a SMALL
   stable executable shape regardless of fleet size.

2. **Gather/scatter** (`CandidatePlan`): node tensors, domain ids (remapped
   to compact per-level ordinals; the host level keeps its ordinal==index
   invariant), and the batch's node-axis fields (reuse/selector/spread
   seeds, pack-set pins) are gathered onto the candidate axis; the existing
   `solve_batch` runs unchanged on the sub-fleet; decode scatters node
   ordinals back through the gather map. One pad row carries the FULL
   fleet's per-resource capacity maxima so `cap_scale` (score
   normalization) matches the dense solve, and stays unschedulable so it
   can never host a pod.

3. **Exactness escalation**: pruning is an approximation — nodes outside
   the candidate set still contribute free capacity to the dense solver's
   domain aggregates and best-fit scores. Each gang therefore carries a
   LOSSY witness: True iff some excluded schedulable node had free capacity
   in a resource the gang demands (or its pack-set pin's domain lost all
   its nodes to the prune). The invariant callers enforce (core.solve, the
   drain): a gang REJECTED on the pruned fleet whose witness is lossy is
   re-solved dense before the rejection stands — so no gang is ever
   rejected because of pruning, and every pruned admission carries its own
   feasibility certificate (a concrete capacity-respecting placement on
   real nodes). Escalations are counted (`PruneStats`), never silent.

The warm-path AOT cache keys on array shapes, so pruned solves key on the
CANDIDATE pad instead of the fleet pad — executables stop growing with
fleet size, and a 4x fleet with the same workload re-uses the 1x
executables byte-for-byte (pinned by tests/test_pruning.py and the
`GROVE_BENCH_SCENARIO=scale` sweep).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from grove_tpu.solver.encode import GangBatch, host_vectorized, next_pow2

_EPS = 1e-6


def _level_domain_free(
    sched_free: np.ndarray, node_domain_id: np.ndarray, lvl: int
) -> np.ndarray:
    """f64 [D, R] aggregate schedulable free per domain ordinal at `lvl`.

    np.bincount per resource column instead of the oracle's np.add.at:
    bincount's C loop walks the data in the same sequential order add.at
    does, so the f64 accumulation is BITWISE-identical (pinned in
    tests/test_hostpath.py) at ~10x less cost — ufunc.at is unbuffered and
    pays per-element dispatch."""
    dom = node_domain_id[lvl]
    d = int(dom.max(initial=-1)) + 1
    r = sched_free.shape[1]
    acc = np.zeros((d, r), dtype=np.float64)
    if d > 0:
        valid = dom >= 0
        dv = dom[valid]
        sf = sched_free[valid]
        for j in range(r):
            acc[:, j] = np.bincount(dv, weights=sf[:, j], minlength=d)[:d]
    return acc


def _grow_mask(acc: np.ndarray, shape: tuple) -> np.ndarray:
    """Zero-padded growth for the pre-filter's per-level accumulator.

    np.resize TILES the old values when growing — a recycled True would mark
    an arbitrary domain feasible and silently widen the candidate set (a
    correctness-preserving but policy-wrong keep). The padded tail must be
    False: a domain nobody proved feasible is not feasible.
    tests/test_hostpath.py pins the regression."""
    grown = np.zeros(shape, dtype=bool)
    grown[: acc.shape[0]] = acc
    return grown


@dataclass(frozen=True)
class PruningConfig:
    """`solver.pruning` config block (runtime/config.py validates the YAML
    shape; this is the solver-side value object)."""

    enabled: bool = False
    # Candidate budget: at most this many nodes enter the pruned solve; the
    # rest are clipped (clipping marks affected gangs lossy, so a clipped
    # rejection always escalates to a dense re-solve). Default pairs with
    # the 8192 bucket: 8191 candidates + the cap-anchor pad row.
    max_candidates: int = 8191
    # Pow2 pad ladder for the candidate axis; () = every power of two from
    # `min_pad` up. An explicit ladder caps executable diversity further.
    pad_ladder: tuple = ()
    # Smallest candidate bucket — tiny fleets share one executable shape.
    min_pad: int = 64
    # Fleets below this many snapshot rows never prune (the dense solve is
    # already cheap; the gather would be pure overhead).
    min_fleet: int = 256


@dataclass
class PruneStats:
    """Process-visible pruning counters (a WarmPath carries one; /statusz
    warmPath and `grove-tpu get solver` render them)."""

    pruned_solves: int = 0
    dense_fallbacks: int = 0  # pruning requested but not worthwhile
    escalations: int = 0  # lossy rejection -> dense re-solve
    escalations_adopted: int = 0  # dense re-solve changed a verdict
    last_candidate_nodes: int = 0
    last_candidate_pad: int = 0
    last_fleet_nodes: int = 0

    def stats(self) -> dict:
        return {
            "pruneSolves": self.pruned_solves,
            "pruneDenseFallbacks": self.dense_fallbacks,
            "pruneEscalations": self.escalations,
            "pruneEscalationsAdopted": self.escalations_adopted,
            "pruneCandidateNodes": self.last_candidate_nodes,
            "pruneCandidatePad": self.last_candidate_pad,
            "pruneFleetNodes": self.last_fleet_nodes,
        }


@dataclass
class CandidatePlan:
    """One batch's candidate axis: gather map, remapped topology, pruned
    static node tensors, and the per-gang lossy witness."""

    idx: np.ndarray  # i32 [count] fleet ordinals of the candidates
    count: int
    pad: int  # candidate bucket (> count; one row is the cap anchor)
    fleet_pad: int  # the dense node axis this plan was cut from
    clipped: bool  # candidate budget truncated the eligible set
    gang_lossy: np.ndarray  # bool [G] prune could have cost this gang
    capacity: np.ndarray  # f32 [pad, R] gathered + cap-anchor pad row
    schedulable: np.ndarray  # bool [pad]
    node_domain_id: np.ndarray  # i32 [L, pad] remapped compact ordinals
    num_domains: np.ndarray  # i32 [L] domain count per level on the sub-fleet
    # per level: original ordinal -> remapped ordinal (pin translation)
    _remap: list = field(default_factory=list)

    # ---- gather ------------------------------------------------------------

    def gather_free(self, free, layout=None):
        """Fleet free [N, R] -> candidate free [pad, R] (pad rows zero).
        Works on numpy (host path) and jax arrays (device-chained drain).
        `layout` (parallel.mesh.SolveLayout) keeps a sharded fleet carry
        sharded through the gather (out_shardings-pinned jit)."""
        if isinstance(free, np.ndarray):
            out = np.zeros((self.pad, free.shape[1]), dtype=np.float32)
            out[: self.count] = free[self.idx]
            return out
        if layout is not None:
            return layout.gather_rows(free, self._padded_idx())
        import jax.numpy as jnp

        idx = jnp.asarray(self._padded_idx())
        # mode="fill": the pad rows' out-of-range index reads as zero — no
        # phantom row concat per wave on the chained device carry.
        return free.at[idx].get(mode="fill", fill_value=0.0)

    def scatter_free(self, fleet_free, pruned_free, layout=None):
        """Write the pruned solve's free_after back into the fleet axis
        (device op; pad rows drop via out-of-range scatter)."""
        idx = self._padded_idx()
        if isinstance(fleet_free, np.ndarray):
            out = np.array(fleet_free, copy=True)
            out[self.idx] = np.asarray(pruned_free)[: self.count]
            return out
        if layout is not None:
            return layout.scatter_rows(fleet_free, idx, pruned_free)
        import jax.numpy as jnp

        return fleet_free.at[jnp.asarray(idx)].set(
            pruned_free, mode="drop", unique_indices=True
        )

    def _padded_idx(self) -> np.ndarray:
        """[pad] gather/scatter map; pad rows point past the fleet axis so
        gathers fill 0 and scatters drop."""
        out = np.full((self.pad,), self.fleet_pad, dtype=np.int32)
        out[: self.count] = self.idx
        return out

    def gather_batch(self, batch: GangBatch) -> GangBatch:
        """Gather the batch's node-axis fields onto the candidate axis and
        translate pack-set pins to the remapped domain ordinals."""
        reuse = batch.reuse_nodes
        node_ok = batch.group_node_ok
        avoid = batch.spread_avoid
        if reuse is not None:
            reuse = self._gather_bool_axis(np.asarray(reuse))
        if node_ok is not None:
            node_ok = self._gather_bool_axis(np.asarray(node_ok))
        if avoid is not None:
            avoid = self._gather_bool_axis(np.asarray(avoid))
        pinned = np.asarray(batch.set_pinned)
        if (pinned >= 0).any():
            pinned = self._remap_pins(pinned, np.asarray(batch.set_req_level))
        return batch._replace(
            reuse_nodes=reuse,
            group_node_ok=node_ok,
            spread_avoid=avoid,
            set_pinned=pinned,
        )

    def _gather_bool_axis(self, arr: np.ndarray) -> np.ndarray:
        out = np.zeros(arr.shape[:-1] + (self.pad,), dtype=bool)
        out[..., : self.count] = arr[..., self.idx]
        return out

    def _remap_pins(self, pinned: np.ndarray, req_level: np.ndarray) -> np.ndarray:
        """Translate fleet domain ordinals to candidate ordinals; a pinned
        domain with NO candidate nodes maps to `count` (matches nothing, so
        the pin fails closed — the affected gang is already marked lossy)."""
        out = np.array(pinned, copy=True)
        it = np.nonzero(pinned >= 0)
        for gi, si in zip(*it):
            lvl = int(req_level[gi, si])
            if not 0 <= lvl < len(self._remap):
                continue
            out[gi, si] = self._remap[lvl].get(int(pinned[gi, si]), self.count)
        return out

    def remap_assigned(self, assigned):
        """Candidate ordinals -> fleet ordinals (decode scatters through the
        gather map); numpy or jax."""
        if isinstance(assigned, np.ndarray):
            safe = np.clip(assigned, 0, self.count - 1)
            return np.where(assigned >= 0, self.idx[safe], -1)
        import jax.numpy as jnp

        idx = jnp.asarray(self.idx)
        safe = jnp.clip(assigned, 0, self.count - 1)
        return jnp.where(assigned >= 0, idx[safe], -1)

    def coarse_dmax(self) -> Optional[int]:
        """Static domain bound for the pruned axis, mirroring
        core.coarse_dmax_of: the matmul aggregation path on accelerators,
        segment-sum (None) on CPU."""
        import jax

        if jax.default_backend() == "cpu":
            return None
        if self.num_domains.shape[0] <= 1:
            return 1
        return max(int(self.num_domains[:-1].max()), 1)


def candidate_pad(
    count: int, cfg: PruningConfig, mesh_axis: int = 1
) -> Optional[int]:
    """Smallest ladder bucket holding `count` candidates PLUS the cap-anchor
    pad row; None when no ladder entry fits.

    `mesh_axis` > 1 (mesh-sharded solve, parallel/mesh.py) rounds the bucket
    up to a mesh-divisible size — NamedSharding needs the candidate axis
    divisible by the node-axis device count, and negotiating that HERE (in
    the pad, once) is what keeps `solve_layout_for` from silently falling
    back to one device at bench scale. Pow2 buckets with pow2 device counts
    are already divisible, so the round-up only moves exotic combinations
    (and pads with zero rows, which the solver masks anyway)."""
    need = count + 1
    if cfg.pad_ladder:
        for v in sorted(int(x) for x in cfg.pad_ladder):
            if v >= need:
                return _mesh_pad(v, mesh_axis)
        return None
    return _mesh_pad(next_pow2(max(need, cfg.min_pad)), mesh_axis)


def _mesh_pad(pad: int, mesh_axis: int) -> int:
    if mesh_axis <= 1 or pad % mesh_axis == 0:
        return pad
    from grove_tpu.parallel.mesh import mesh_divisible_pad

    return mesh_divisible_pad(pad, mesh_axis)


def _eligible_nodes(
    free: np.ndarray, schedulable: np.ndarray, batch: GangBatch
) -> tuple[np.ndarray, bool]:
    """(eligible mask [N], any_zero_request): a node is eligible iff it can
    host >= 1 pod of SOME valid group (elementwise on that group's positive
    requests). A valid group with no positive request at all can land on any
    schedulable node, which disables the capacity prune entirely."""
    gv = np.asarray(batch.gang_valid)[:, None] & np.asarray(batch.group_valid)
    reqs = np.asarray(batch.group_req)[gv]  # [K, R]
    if reqs.size == 0:
        return np.asarray(schedulable, bool).copy(), False
    reqs = np.unique(reqs, axis=0)
    if (reqs <= 0).all(axis=1).any():
        return np.asarray(schedulable, bool).copy(), True
    fits = (
        (free[None, :, :] + _EPS >= reqs[:, None, :]) | (reqs[:, None, :] <= 0)
    ).all(axis=-1)  # [K, N]
    return np.asarray(schedulable, bool) & fits.any(axis=0), False


def _domain_useful(
    free: np.ndarray,
    schedulable: np.ndarray,
    node_domain_id: np.ndarray,
    batch: GangBatch,
) -> tuple[np.ndarray, np.ndarray]:
    """(useful-by-domain mask [N], pin_absent_lossy [G]).

    A node passes iff SOME valid gang's broadest required pack-set could be
    served by the node's domain at that set's level: the domain's aggregate
    free (over schedulable nodes) covers the set's member floor demand, and
    a pinned set only accepts its pinned domain. Gangs with NO required
    pack-set disable the filter (their pods may land on any eligible node).
    Conservative by construction — aggregate feasibility over-approximates
    the solver's joint checks, so this can only keep too many nodes, never
    too few.

    Vectorized over [G, MS]/[G, D, R]: broadest-required-set selection is a
    masked argmin, member floor demand one broadcast reduction, and each
    level's domain feasibility a single [G_l, D, R] comparison — no per-gang
    Python in the wave loop. Bitwise-equal to the retained loop oracle
    (_domain_useful_reference; GROVE_HOST_REFERENCE=1 routes through it,
    tests/test_hostpath.py pins equality), so the conservative contract is
    unchanged by construction."""
    if not host_vectorized():
        return _domain_useful_reference(free, schedulable, node_domain_id, batch)
    g, ms = np.asarray(batch.set_valid).shape
    n = free.shape[0]
    gang_valid = np.asarray(batch.gang_valid)
    set_valid = np.asarray(batch.set_valid)
    set_req = np.asarray(batch.set_req_level)
    set_pin = np.asarray(batch.set_pinned)
    set_member = np.asarray(batch.set_member)
    group_req = np.asarray(batch.group_req)
    group_required = np.asarray(batch.group_required)
    group_valid = np.asarray(batch.group_valid)
    levels = node_domain_id.shape[0]
    pin_lossy = np.zeros((g,), dtype=bool)

    resolvable = set_valid & (set_req >= 0) & (set_req < levels)  # [G, MS]
    has_req = resolvable.any(axis=1)
    if bool((gang_valid & ~has_req).any()):
        # Some valid gang has NO resolvable required set: filter disabled.
        return np.ones((n,), dtype=bool), pin_lossy
    active = gang_valid & has_req
    if not bool(active.any()):
        # No valid gang carried a resolvable required set: filter is moot.
        return np.ones((n,), dtype=bool), pin_lossy

    # Broadest required set per gang: first index of the minimum level among
    # resolvable sets (argmin keeps the earliest on ties, matching the loop
    # oracle's Python min over ascending set indices).
    rows = np.arange(g)
    keyed = np.where(resolvable, set_req, levels + 1)
    si_sel = np.argmin(keyed, axis=1)  # [G]
    lvl_sel = keyed[rows, si_sel]  # [G]; valid only where `active`
    members = set_member[rows, si_sel] & group_valid  # [G, MG]
    weights = (group_required * members).astype(np.float64)  # [G, MG]
    # Member floor demand, one broadcast reduction over the group axis —
    # the same elementwise products and per-gang summation order as the
    # oracle's per-gang sum, so the aggregates are bitwise-identical.
    demand = (group_req * weights[:, :, None]).sum(axis=1)  # [G, R] f64
    pins = set_pin[rows, si_sel]  # [G]

    sched_free = np.where(schedulable[:, None], np.maximum(free, 0.0), 0.0)
    useful = np.zeros((n,), dtype=bool)
    for lvl in np.unique(lvl_sel[active]).tolist():
        lvl = int(lvl)
        df = _level_domain_free(sched_free, node_domain_id, lvl)  # [D, R]
        d = df.shape[0]
        sel = active & (lvl_sel == lvl)
        # Single [K, D, R] feasibility reduction at this level, over the
        # UNIQUE demand rows (clone gangs share one row; the comparison per
        # unique row is the exact comparison the per-gang form would run,
        # so expanding through the inverse map is bitwise-identical).
        uniq_dem, inv = np.unique(
            demand[sel], axis=0, return_inverse=True
        )
        ok_ud = (df[None, :, :] + _EPS >= uniq_dem[:, None, :]).all(
            axis=-1
        )  # [K, D]
        ok_gd = ok_ud[inv]  # [G_l, D]
        p = pins[sel]
        pinned = p >= 0
        if bool(pinned.any()):
            # A pinned set accepts only its pinned domain (a pin outside
            # [0, D) matches no column — fails closed, like the oracle).
            cols = np.arange(d)
            ok_gd = np.where(
                pinned[:, None], ok_gd & (cols[None, :] == p[:, None]), ok_gd
            )
        dom_ok = ok_gd.any(axis=0)  # [D] OR over this level's gangs
        dom = node_domain_id[lvl]
        valid = dom >= 0
        hit = np.zeros((n,), dtype=bool)
        hit[valid] = dom_ok[np.clip(dom[valid], 0, max(d - 1, 0))]
        useful |= hit
    return useful, pin_lossy


def _domain_useful_reference(
    free: np.ndarray,
    schedulable: np.ndarray,
    node_domain_id: np.ndarray,
    batch: GangBatch,
) -> tuple[np.ndarray, np.ndarray]:
    """The retained per-gang loop pre-filter: the parity oracle for the
    vectorized _domain_useful (and the GROVE_HOST_REFERENCE=1 bench
    baseline). Semantics frozen — do not optimize. The one deliberate
    divergence from the seed loop is the defensive accumulator-growth
    branch: np.resize tiled old values into the grown tail (recycled Trues
    marked arbitrary domains feasible); _grow_mask zero-pads instead."""
    g, ms = np.asarray(batch.set_valid).shape
    n = free.shape[0]
    gang_valid = np.asarray(batch.gang_valid)
    set_valid = np.asarray(batch.set_valid)
    set_req = np.asarray(batch.set_req_level)
    set_pin = np.asarray(batch.set_pinned)
    set_member = np.asarray(batch.set_member)
    group_req = np.asarray(batch.group_req)
    group_required = np.asarray(batch.group_required)
    group_valid = np.asarray(batch.group_valid)
    levels = node_domain_id.shape[0]

    sched_free = np.where(schedulable[:, None], np.maximum(free, 0.0), 0.0)
    dom_free: dict[int, np.ndarray] = {}

    def dom_free_at(lvl: int) -> np.ndarray:
        # The seed's np.add.at aggregation, kept verbatim: the vectorized
        # path's bincount aggregate is pinned bitwise-equal to this.
        if lvl not in dom_free:
            dom = node_domain_id[lvl]
            d = int(dom.max(initial=-1)) + 1
            acc = np.zeros((d + 1, free.shape[1]), dtype=np.float64)
            valid = dom >= 0
            np.add.at(acc, dom[valid], sched_free[valid])
            dom_free[lvl] = acc[:d]
        return dom_free[lvl]

    useful = np.zeros((n,), dtype=bool)
    pin_lossy = np.zeros((g,), dtype=bool)
    any_unconstrained = False
    # Per (level) OR of feasible domains, then one [N] gather per level.
    level_dom_ok: dict[int, np.ndarray] = {}
    for gi in range(g):
        if not gang_valid[gi]:
            continue
        req_sets = [
            si
            for si in range(ms)
            if set_valid[gi, si] and 0 <= set_req[gi, si] < levels
        ]
        if not req_sets:
            any_unconstrained = True
            continue
        # Broadest required set (sets are encoded broad->narrow; the level
        # index orders broad->narrow too).
        si = min(req_sets, key=lambda s: set_req[gi, s])
        lvl = int(set_req[gi, si])
        members = set_member[gi, si] & group_valid[gi]
        demand = (
            group_req[gi] * (group_required[gi] * members).astype(np.float64)[:, None]
        ).sum(axis=0)  # [R]
        df = dom_free_at(lvl)
        ok = (df + _EPS >= demand[None, :]).all(axis=-1)  # [D]
        pin = int(set_pin[gi, si])
        if pin >= 0:
            mask = np.zeros_like(ok)
            if pin < ok.shape[0]:
                mask[pin] = ok[pin]
            ok = mask
        acc = level_dom_ok.setdefault(lvl, np.zeros_like(ok))
        if acc.shape[0] < ok.shape[0]:  # defensive; same level, same D
            acc = _grow_mask(acc, ok.shape)
            level_dom_ok[lvl] = acc
        level_dom_ok[lvl] = acc | ok
    if any_unconstrained:
        return np.ones((n,), dtype=bool), pin_lossy
    for lvl, ok in level_dom_ok.items():
        dom = node_domain_id[lvl]
        valid = dom >= 0
        hit = np.zeros((n,), dtype=bool)
        hit[valid] = ok[np.clip(dom[valid], 0, ok.shape[0] - 1)]
        useful |= hit
    if not level_dom_ok:
        # No valid gang carried a resolvable required set: filter is moot.
        return np.ones((n,), dtype=bool), pin_lossy
    return useful, pin_lossy


def plan_candidates(
    snapshot, batch: GangBatch, cfg: PruningConfig, mesh_axis: int = 1
) -> Optional[CandidatePlan]:
    """Cut the candidate axis for one batch against `snapshot`'s CURRENT
    free state (or any state whose free is <= it — a drain computes plans
    from the initial snapshot: free only shrinks while draining, so the
    initial candidates are a superset of every later wave's).

    Returns None when pruning is not worthwhile: fleet below `min_fleet`,
    candidate bucket not smaller than the fleet axis, or no valid gangs."""
    free = np.asarray(snapshot.free, dtype=np.float32)
    schedulable = np.asarray(snapshot.schedulable, dtype=bool)
    node_domain_id = np.asarray(snapshot.node_domain_id)
    n = free.shape[0]
    if n < cfg.min_fleet:
        return None
    gang_valid = np.asarray(batch.gang_valid)
    if not gang_valid.any():
        return None

    eligible, zero_req = _eligible_nodes(free, schedulable, batch)
    dom_useful, pin_lossy = _domain_useful(free, schedulable, node_domain_id, batch)
    useful = eligible & dom_useful
    cand = np.flatnonzero(useful)
    clipped = False
    budget = max(1, int(cfg.max_candidates))
    if cand.shape[0] > budget:
        cand = cand[:budget]
        clipped = True
    count = int(cand.shape[0])
    if count == 0:
        return None  # nothing can place; the dense solve rejects cheaply
    pad = candidate_pad(count, cfg, mesh_axis)
    if pad is None or pad >= n:
        return None

    # Lossy witness: an excluded schedulable node with free capacity in a
    # resource the gang demands could have changed the dense solve's domain
    # aggregates or scores — that gang's REJECTION must not stand un-checked.
    kept = np.zeros((n,), dtype=bool)
    kept[cand] = True
    excluded = schedulable & ~kept
    lossy_res = (free > _EPS) & excluded[:, None]  # [N, R]
    lossy_by_res = lossy_res.any(axis=0)  # [R]
    gv = gang_valid[:, None] & np.asarray(batch.group_valid)  # [G, MG]
    demand_pos = (np.asarray(batch.group_req) > 0) & gv[:, :, None]  # [G, MG, R]
    gang_demands = demand_pos.any(axis=1)  # [G, R]
    gang_lossy = (gang_demands & lossy_by_res[None, :]).any(axis=-1)
    if zero_req and excluded.any():
        # Zero-request groups can land on ANY schedulable node, so every
        # exclusion is potentially theirs.
        gang_lossy = gang_lossy | gv.any(axis=1)
    gang_lossy = (gang_lossy | pin_lossy) & gang_valid

    return _assemble_plan(snapshot, cand, pad, clipped, gang_lossy)


def _assemble_plan(
    snapshot, cand: np.ndarray, pad: int, clipped: bool, gang_lossy: np.ndarray
) -> CandidatePlan:
    """Derive the gathered static tensors + compact domain remap for a fixed
    candidate list — pure function of (snapshot, cand, pad), shared by the
    live cut (`plan_candidates`) and replay reconstruction
    (`plan_from_indices`)."""
    node_domain_id = np.asarray(snapshot.node_domain_id)
    schedulable = np.asarray(snapshot.schedulable, dtype=bool)
    n = int(np.asarray(snapshot.capacity).shape[0])
    count = int(cand.shape[0])
    # Remap per-level domain ordinals to a compact range over the candidates;
    # host level (last) keeps ordinal == row index by construction.
    levels = node_domain_id.shape[0]
    ndid_p = np.full((levels, pad), -1, dtype=np.int32)
    num_domains = np.zeros((levels,), dtype=np.int32)
    remap: list[dict] = []
    for li in range(levels):
        ids = node_domain_id[li, cand]
        if li == levels - 1:
            rows = np.arange(count, dtype=np.int32)
            ndid_p[li, :count] = np.where(ids >= 0, rows, -1)
            num_domains[li] = int((ids >= 0).sum())
            remap.append({})
            continue
        uniq = np.unique(ids[ids >= 0])
        table = {int(v): i for i, v in enumerate(uniq.tolist())}
        ndid_p[li, :count] = np.where(
            ids >= 0, np.searchsorted(uniq, np.clip(ids, 0, None)), -1
        )
        num_domains[li] = len(table)
        remap.append(table)

    cap = np.asarray(snapshot.capacity, dtype=np.float32)
    cap_p = np.zeros((pad, cap.shape[1]), dtype=np.float32)
    cap_p[:count] = cap[cand]
    # Cap anchor: the dense solver normalizes scores by the FULL fleet's
    # per-resource capacity maxima (including unschedulable nodes); carry
    # them on the first pad row so pruned scores use the same scale. The
    # row stays unschedulable/zero-free, so it can never host a pod or
    # perturb any masked aggregate.
    cap_p[count] = cap.max(axis=0)
    sched_p = np.zeros((pad,), dtype=bool)
    sched_p[:count] = schedulable[cand]

    return CandidatePlan(
        idx=cand.astype(np.int32),
        count=count,
        pad=pad,
        fleet_pad=n,
        clipped=clipped,
        gang_lossy=gang_lossy,
        capacity=cap_p,
        schedulable=sched_p,
        node_domain_id=ndid_p,
        num_domains=num_domains,
        _remap=remap,
    )


def plan_from_indices(
    snapshot, indices, cfg: PruningConfig, n_gangs: int, mesh_axis: int = 1
) -> CandidatePlan:
    """Rebuild a CandidatePlan from a journaled candidate-node list
    (trace/replay.py): live plans are cut against the free state at DISPATCH
    time, which a wave record does not carry — replaying with the recorded
    list reproduces the exact gather the recorded solve ran on. The lossy
    witness is moot at replay (the recorded verdicts already absorbed any
    escalation), so it is all-False. `mesh_axis` must be the RECORDED mesh's
    node-axis size (the wave record's mesh fingerprint) so the rebuilt pad
    matches the pad the live solve ran with."""
    cand = np.asarray(indices, dtype=np.int32)
    pad = candidate_pad(int(cand.shape[0]), cfg, mesh_axis)
    if pad is None:
        raise ValueError(
            f"recorded candidate list ({cand.shape[0]} nodes) does not fit "
            f"the recorded pad ladder {cfg.pad_ladder!r}"
        )
    return _assemble_plan(
        snapshot, cand, pad, False, np.zeros((n_gangs,), dtype=bool)
    )


def lossy_rejections(plan: CandidatePlan, gang_valid, ok) -> np.ndarray:
    """bool [G]: gangs whose pruned rejection requires a dense re-solve."""
    return (
        np.asarray(gang_valid, bool)
        & ~np.asarray(ok, bool)
        & plan.gang_lossy
    )
