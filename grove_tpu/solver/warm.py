"""The warm path: everything that makes the SECOND solve cheap.

Round-5 bench anatomy (BENCH_r05.json): the 10k-pod solve itself is 0.85s,
but `compile_s=4.32` — a 5x cold-start tax paid on every controller restart,
sidecar spawn, and bench run — and `device_wait_s` is dominated by re-uploading
node tensors per solve. The Grove reference keeps its scheduler hot across
reconcile ticks; this module is the JAX equivalent of that steady state:

1. **AOT executable cache** (`ExecutableCache`): `jax.jit(solve_batch)
   .lower(...).compile()` keyed by the full input signature — gang-shape
   bucket, gang pad, node pad, topology depth, optional-feature presence
   (reuse/nodeSelector/spread), global-table width, portfolio width,
   `coarse_dmax`, donation — so two snapshots with different node pads or
   domain bounds can never alias to one executable, and a second solve of the
   same key never re-lowers (`lowerings` counts actual XLA work; tests pin
   it). Shape descriptors are recorded to a history file so a fresh process
   can PREWARM the top-K historical buckets on a background thread at startup
   — `drain_backlog` and `solve_pending` then never block on XLA.

2. **Device-resident cluster state** (`SnapshotDeviceCache`): node tensors
   (`capacity`, `schedulable`, `node_domain_id`, `free`) are device-put once
   per content digest and reused across solves/ticks instead of re-uploaded
   per call. Solves that chain waves donate the `free`/`ok_global` carry
   (donate_argnums) so the updated capacity is an in-place device buffer, not
   a fresh upload + fetch per wave.

3. **Incremental encode reuse** (`EncodeRowCache`): the host-side dense
   encode is dirty-tracked per gang. A gang whose SPEC HASH (not object
   identity — the per-tick drivers rebuild sub-gang objects every pass, so
   identity is always fresh; the spec digest is what actually determines the
   encoded rows) and snapshot epoch are unchanged reuses its dense rows from
   the previous tick instead of re-walking the spec in Python.

Donation invariants (tested in tests/test_drain.py):
- Only the wave-carry arguments (`free0`, `ok_global`) are ever donated —
  `capacity`/`schedulable`/`node_domain_id` are reused across waves and
  must survive the call.
- A donated buffer is dead after the call: callers immediately rebind the
  carry to the result (`free_arr = result.free_after`), and the host-side
  `snapshot.free` is never consulted again mid-chain (it is a property
  recomputed from capacity - allocated, so the donated device buffer never
  aliases host memory in the first place).
- Donation defaults OFF on CPU (no-op there) and ON on accelerators.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from grove_tpu.solver.core import SolveResult, SolverParams, solve_batch_impl
from grove_tpu.solver.encode import GangBatch
from grove_tpu.solver.pruning import PruneStats
from grove_tpu.utils.fsio import atomic_write_json

# jitted solve_batch variants, shared process-wide so every ExecutableCache
# (controller, sidecar, drain) lowers through the same traced function.
# Keys: bool (donate flag, single-config) | "stacked" (K-variant sweep).
_JITTED: dict[Any, Any] = {}
_JITTED_LOCK = threading.Lock()


def _jitted_stacked():
    """jitted stacked_solve_batch_impl (the K-variant config-sweep solve),
    memoized process-wide like the single-config variants. Never donated
    (the sweep owns no wave carry) and never mesh-sharded (the offline sweep
    runs on whatever host replays the journal)."""
    import jax

    from grove_tpu.solver.core import stacked_solve_batch_impl

    key = "stacked"
    with _JITTED_LOCK:
        if key not in _JITTED:
            _JITTED[key] = jax.jit(
                stacked_solve_batch_impl, static_argnames=("coarse_dmax",)
            )
        return _JITTED[key]


def _jitted_stacked_scan():
    """jitted device-side scan of the K-variant stacked solve over a
    journaled wave axis (core.stacked_scan_solve_fn) — memoized process-wide
    in core._SCAN_JIT like the carry-threading scan variants. Never donated
    (each wave replays from its recorded entering free; there is no carry)
    and never mesh-sharded (the offline sweep runs wherever the journal is
    replayed)."""
    from grove_tpu.solver.core import stacked_scan_solve_fn

    return stacked_scan_solve_fn()


def _jitted_solve(donate: bool, layout=None):
    import jax

    if layout is not None:
        # Mesh-sharded variant: the same traced solve_batch_impl with its
        # outputs pinned to the layout (free carry node-sharded, verdicts
        # replicated). core.sharded_solve_fn memoizes per (donate, layout
        # key) process-wide, exactly like _JITTED does for dense.
        from grove_tpu.solver.core import sharded_solve_fn

        return sharded_solve_fn(layout, donate)
    key = bool(donate)
    with _JITTED_LOCK:
        if key not in _JITTED:
            _JITTED[key] = jax.jit(
                solve_batch_impl,
                static_argnames=("coarse_dmax",),
                # Wave-carry donation: free0 (arg 0) and ok_global (arg 6).
                donate_argnums=(0, 6) if donate else (),
            )
        return _JITTED[key]


def _jitted_scan(pruned: bool, retain: bool, donate: bool, layout=None):
    """jitted device-side wave scan (core.scan_solve_fn /
    scan_pruned_solve_fn) — already memoized process-wide per (pruned,
    retain, donate, layout key) in core._SCAN_JIT, so every ExecutableCache
    lowers through the one traced function, like _jitted_solve."""
    from grove_tpu.solver.core import scan_pruned_solve_fn, scan_solve_fn

    fn = scan_pruned_solve_fn if pruned else scan_solve_fn
    return fn(layout, retain=retain, donate=donate)


def donation_default() -> bool:
    """Donate the wave carry by default on accelerators only: CPU PJRT
    ignores donation (harmless but pointless), and keeping the CPU default
    off makes test behavior byte-identical to the undonated path."""
    import jax

    return jax.default_backend() != "cpu"


def _canon(
    free0, capacity, schedulable, node_domain_id, batch, params, ok_global,
    layout=None,
):
    """Normalize every leaf to a committed, strongly-typed device array so
    the cache key (and the compiled executable's input avals) never depend on
    whether the caller passed numpy, python floats, or device arrays.

    With `layout` (parallel.mesh.SolveLayout), every leaf is additionally
    device_put with its layout sharding — a no-op for arrays already
    resident in that layout (the drain's chained carry, the content-digest
    device cache), so steady-state sharded solves upload nothing."""
    import jax
    import jax.numpy as jnp

    free0 = jnp.asarray(free0, jnp.float32)
    capacity = jnp.asarray(capacity, jnp.float32)
    schedulable = jnp.asarray(schedulable, bool)
    node_domain_id = jnp.asarray(node_domain_id, jnp.int32)
    batch = GangBatch(*(None if x is None else jnp.asarray(x) for x in batch))
    params = SolverParams(*(jnp.asarray(w, jnp.float32) for w in params))
    if ok_global is not None:
        ok_global = jnp.asarray(ok_global, bool)
    if layout is not None:
        free0, capacity, schedulable, node_domain_id, batch, ok_global = (
            layout.shard_solve_args(
                free0, capacity, schedulable, node_domain_id, batch, ok_global
            )
        )
        rep = layout.replicated()
        params = SolverParams(*(jax.device_put(w, rep) for w in params))
    return free0, capacity, schedulable, node_domain_id, batch, params, ok_global


def _exec_key(
    args: tuple, coarse_dmax: Optional[int], donate: bool, layout=None,
    stacked: bool = False, scan: Optional[tuple] = None,
) -> tuple:
    """Full executable identity: pytree structure (covers optional-feature
    presence) + every leaf's (shape, dtype) (covers node pad, gang pad,
    bucket dims, global-table width, portfolio width — and, for the sweep's
    stacked variant, K via the params leaf shapes) + the statics + the mesh
    layout (a sharded executable demands its input layout — an unsharded
    solve of the same shapes must never alias to it) + the stacked flag (a
    K-stacked solve and a portfolio-shaped single solve must never alias) +
    the scan tag (("dense"|"pruned", retain) for the device-side wave scan —
    the scan LENGTH bucket rides in on the stacked batch leaf shapes, but
    retain changes the output arity without changing any input aval, so it
    must be in the key explicitly)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(args)
    return (
        bool(donate),
        bool(stacked),
        scan,
        coarse_dmax,
        None if layout is None else layout.key(),
        str(treedef),
        tuple((tuple(l.shape), str(l.dtype)) for l in leaves),
    )


def _exec_desc(
    args: tuple, coarse_dmax: Optional[int], donate: bool, layout=None
) -> Optional[dict]:
    """JSON-able shape-bucket descriptor (the prewarm history record); None
    for signatures prewarm cannot reconstruct (portfolio-stacked params)."""
    free0, _, _, node_domain_id, batch, params, ok_global = args
    if params[0].ndim != 0:
        return None  # portfolio-stacked weights ride the legacy jit path
    n, r = free0.shape
    return {
        "mesh": None
        if layout is None
        else [layout.portfolio_devices, layout.node_devices],
        "n": int(n),
        "r": int(r),
        "levels": int(node_domain_id.shape[0]),
        "g": int(batch.gang_valid.shape[0]),
        "mg": int(batch.group_req.shape[1]),
        "ms": int(batch.set_member.shape[1]),
        "mp": int(batch.pod_group.shape[1]),
        "t": None if ok_global is None else int(ok_global.shape[0]),
        "reuse": batch.reuse_nodes is not None,
        "node_ok": batch.group_node_ok is not None,
        "spread": batch.spread_level is not None,
        "coarse_dmax": coarse_dmax,
        "donate": bool(donate),
        "portfolio": 1,
    }


def _layout_from_desc(desc: dict):
    """Rebuild a recorded mesh layout for prewarm, or None for dense
    descriptors. Raises when the current runtime cannot host the recorded
    mesh (fewer devices than the history was written on) — the prewarm loop
    skips such entries instead of compiling a wrong-layout executable."""
    mesh_shape = desc.get("mesh")
    if not mesh_shape:
        return None
    import jax

    from grove_tpu.parallel.mesh import solve_layout_for

    p, k = int(mesh_shape[0]), int(mesh_shape[1])
    if p != 1:
        raise ValueError(f"unsupported prewarm mesh shape {mesh_shape}")
    if len(jax.devices()) < k:
        raise ValueError(
            f"recorded mesh needs {k} devices, have {len(jax.devices())}"
        )
    layout = solve_layout_for(
        desc["n"], jax.devices()[:k], count_fallback=False
    )
    if layout is None or layout.node_devices != k:
        raise ValueError(f"cannot rebuild {k}-device layout for n={desc['n']}")
    return layout


def _args_from_desc(desc: dict, layout=None) -> tuple:
    """Descriptor -> abstract (ShapeDtypeStruct) solver arguments, good for
    `jit.lower(...)` without any concrete data. With `layout`, node-axis
    avals carry their NamedShardings so the prewarmed executable is the
    sharded one, byte-for-byte the key a live sharded solve will look up."""
    import jax
    import jax.numpy as jnp

    def S(shape, dtype, sharding=None):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    f32, i32, b = jnp.float32, jnp.int32, jnp.bool_
    n, r, lv = desc["n"], desc["r"], desc["levels"]
    g, mg, ms, mp = desc["g"], desc["mg"], desc["ms"], desc["mp"]
    rep = None if layout is None else layout.replicated()

    def nsh(axis_index, ndim):
        return None if layout is None else layout.node_sharding(axis_index, ndim)

    batch = GangBatch(
        group_req=S((g, mg, r), f32, rep),
        group_total=S((g, mg), i32, rep),
        group_required=S((g, mg), i32, rep),
        group_valid=S((g, mg), b, rep),
        set_member=S((g, ms, mg), b, rep),
        set_req_level=S((g, ms), i32, rep),
        set_pref_level=S((g, ms), i32, rep),
        set_valid=S((g, ms), b, rep),
        set_pinned=S((g, ms), i32, rep),
        pod_group=S((g, mp), i32, rep),
        pod_rank=S((g, mp), i32, rep),
        gang_valid=S((g,), b, rep),
        group_order=S((g, mg), i32, rep),
        depends_on=S((g,), i32, rep),
        global_index=S((g,), i32, rep),
        depends_global=S((g,), i32, rep),
        reuse_nodes=S((g, n), b, nsh(1, 2)) if desc["reuse"] else None,
        group_node_ok=S((g, mg, n), b, nsh(2, 3)) if desc["node_ok"] else None,
        spread_level=S((g,), i32, rep) if desc["spread"] else None,
        spread_family=S((g,), i32, rep) if desc["spread"] else None,
        spread_avoid=S((g, n), b, nsh(1, 2)) if desc["spread"] else None,
    )
    params = SolverParams(*(S((), f32, rep) for _ in SolverParams._fields))
    ok_global = None if desc["t"] is None else S((desc["t"],), b, rep)
    return (
        S((n, r), f32, nsh(0, 2)),
        S((n, r), f32, nsh(0, 2)),
        S((n,), b, nsh(0, 1)),
        S((lv, n), i32, nsh(1, 2)),
        batch,
        params,
        ok_global,
    )


def _canon_scan(
    free0, capacity, schedulable, node_domain_id, stacked_batch, params,
    ok_global, layout=None,
):
    """_canon for the device-side wave scan: node tensors + ok_global are
    per-class (unstacked), the GangBatch leaves carry the leading [W] wave
    axis and stay replicated under a mesh layout (the node-sharded thing is
    the CARRY; the per-wave gang tensors are small)."""
    import jax
    import jax.numpy as jnp

    free0 = jnp.asarray(free0, jnp.float32)
    capacity = jnp.asarray(capacity, jnp.float32)
    schedulable = jnp.asarray(schedulable, bool)
    node_domain_id = jnp.asarray(node_domain_id, jnp.int32)
    batch = GangBatch(
        *(None if x is None else jnp.asarray(x) for x in stacked_batch)
    )
    params = SolverParams(*(jnp.asarray(w, jnp.float32) for w in params))
    ok_global = jnp.asarray(ok_global, bool)
    if layout is not None:
        nsh, rep = layout.node_sharding, layout.replicated()
        free0 = jax.device_put(free0, nsh(0, 2))
        capacity = jax.device_put(capacity, nsh(0, 2))
        schedulable = jax.device_put(schedulable, nsh(0, 1))
        node_domain_id = jax.device_put(node_domain_id, nsh(1, 2))
        batch = GangBatch(
            *(None if x is None else jax.device_put(x, rep) for x in batch)
        )
        params = SolverParams(*(jax.device_put(w, rep) for w in params))
        ok_global = jax.device_put(ok_global, rep)
    return free0, capacity, schedulable, node_domain_id, batch, params, ok_global


def _canon_scan_pruned(
    free0, cand_idx, capacity_p, schedulable_p, node_domain_id_p,
    stacked_batch, params, ok_global, layout=None,
):
    """_canon for the pruned wave scan: the fleet free carry is dense (and
    node-sharded under a layout); the per-wave gather maps, pruned node
    tensors, and batch leaves all carry the leading [W] axis."""
    import jax
    import jax.numpy as jnp

    free0 = jnp.asarray(free0, jnp.float32)
    cand_idx = jnp.asarray(cand_idx, jnp.int32)
    capacity_p = jnp.asarray(capacity_p, jnp.float32)
    schedulable_p = jnp.asarray(schedulable_p, bool)
    node_domain_id_p = jnp.asarray(node_domain_id_p, jnp.int32)
    batch = GangBatch(
        *(None if x is None else jnp.asarray(x) for x in stacked_batch)
    )
    params = SolverParams(*(jnp.asarray(w, jnp.float32) for w in params))
    ok_global = jnp.asarray(ok_global, bool)
    if layout is not None:
        rep = layout.replicated()
        free0 = jax.device_put(free0, layout.node_sharding(0, 2))
        cand_idx = jax.device_put(cand_idx, rep)
        capacity_p = jax.device_put(capacity_p, rep)
        schedulable_p = jax.device_put(schedulable_p, rep)
        node_domain_id_p = jax.device_put(node_domain_id_p, rep)
        batch = GangBatch(
            *(None if x is None else jax.device_put(x, rep) for x in batch)
        )
        params = SolverParams(*(jax.device_put(w, rep) for w in params))
        ok_global = jax.device_put(ok_global, rep)
    return (
        free0, cand_idx, capacity_p, schedulable_p, node_domain_id_p, batch,
        params, ok_global,
    )


def _scan_avals(args, scan_len: int, layout=None) -> tuple:
    """Single-wave canonical solver args -> abstract scan arguments: the
    GangBatch leaves gain a leading [scan_len] axis, node tensors and
    ok_global pass through shape-identical. Good for `jit.lower` (the warm
    pre-pass compiles the scan executable without stacking any real data)."""
    import jax

    free0, capacity, schedulable, node_domain_id, batch, params, ok_global = args
    rep = None if layout is None else layout.replicated()

    def nsh(axis, ndim):
        return None if layout is None else layout.node_sharding(axis, ndim)

    def plain(x, sh=None):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype, sharding=sh)

    def stack(x):
        if x is None:
            return None
        return jax.ShapeDtypeStruct(
            (int(scan_len),) + tuple(x.shape), x.dtype, sharding=rep
        )

    return (
        plain(free0, nsh(0, 2)),
        plain(capacity, nsh(0, 2)),
        plain(schedulable, nsh(0, 1)),
        plain(node_domain_id, nsh(1, 2)),
        GangBatch(*(stack(x) for x in batch)),
        SolverParams(*(plain(w, rep) for w in params)),
        plain(ok_global, rep),
    )


def _scan_pruned_avals(args, fleet_shape: tuple, scan_len: int, layout=None) -> tuple:
    """Single-wave canonical PRUNED solver args (candidate axis) + the dense
    fleet-carry shape -> abstract scan-pruned arguments for `jit.lower`."""
    import jax
    import jax.numpy as jnp

    _free_p, capacity_p, schedulable_p, node_domain_id_p, batch, params, ok_global = args
    w = int(scan_len)
    rep = None if layout is None else layout.replicated()

    def plain(x, sh=None):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype, sharding=sh)

    def stack(x):
        if x is None:
            return None
        return jax.ShapeDtypeStruct((w,) + tuple(x.shape), x.dtype, sharding=rep)

    cand_pad = int(capacity_p.shape[0])
    free_sh = None if layout is None else layout.node_sharding(0, 2)
    return (
        jax.ShapeDtypeStruct(tuple(fleet_shape), jnp.float32, sharding=free_sh),
        jax.ShapeDtypeStruct((w, cand_pad), jnp.int32, sharding=rep),
        stack(capacity_p),
        stack(schedulable_p),
        stack(node_domain_id_p),
        GangBatch(*(stack(x) for x in batch)),
        SolverParams(*(plain(p, rep) for p in params)),
        plain(ok_global, rep),
    )


def _scan_desc(
    args: tuple, coarse_dmax: Optional[int], donate: bool, layout, scan: tuple
) -> Optional[dict]:
    """Prewarm history descriptor for a DENSE scan signature: the per-wave
    shape-bucket fields (leading wave axis stripped) + the scan length and
    retain flag. Pruned scans are not recorded — their per-wave candidate
    gather maps are backlog-specific, so a historical descriptor could not
    reconstruct them."""
    if scan[0] != "dense":
        return None
    free0, _, _, node_domain_id, batch, params, ok_global = args
    if params[0].ndim != 0:
        return None
    n, r = free0.shape
    return {
        "mesh": None
        if layout is None
        else [layout.portfolio_devices, layout.node_devices],
        "n": int(n),
        "r": int(r),
        "levels": int(node_domain_id.shape[0]),
        "g": int(batch.gang_valid.shape[1]),
        "mg": int(batch.group_req.shape[2]),
        "ms": int(batch.set_member.shape[2]),
        "mp": int(batch.pod_group.shape[2]),
        "t": int(ok_global.shape[0]),
        "reuse": batch.reuse_nodes is not None,
        "node_ok": batch.group_node_ok is not None,
        "spread": batch.spread_level is not None,
        "coarse_dmax": coarse_dmax,
        "donate": bool(donate),
        "portfolio": 1,
        "scan": int(batch.gang_valid.shape[0]),
        "retain": bool(scan[1]),
    }


def _scan_args_from_desc(desc: dict, layout=None) -> tuple:
    """Scan descriptor -> abstract scan arguments (the single-wave avals
    from _args_from_desc with the batch leaves stacked to [scan])."""
    args = _args_from_desc(desc, layout)
    return _scan_avals(args, int(desc["scan"]), layout)


class ExecutableCache:
    """In-process AOT executable cache for the batched solver.

    `jax.jit`'s own trace cache already memoizes by shape, but it is opaque:
    no hit/miss observability, no way to compile a shape BEFORE traffic
    arrives, and nothing persists a shape's popularity across processes.
    This cache lowers/compiles explicitly (`lowerings` counts real XLA
    work), records each shape bucket's use count to `history_path`, and
    `start_prewarm_thread` compiles the top-K historical buckets at startup
    from ShapeDtypeStructs — no concrete data needed.
    """

    def __init__(self, history_path: str = "") -> None:
        self._entries: dict[tuple, Any] = {}
        self._lock = threading.Lock()
        self.history_path = history_path
        self.hits = 0
        self.misses = 0
        self.lowerings = 0  # actual .lower().compile() invocations
        self.prewarmed = 0
        self.inflight_waits = 0  # lookups that waited on an in-flight compile
        # In-flight shape tracking: key -> threading.Event for compiles in
        # progress. The background prewarm thread and a live caller (the
        # streaming drain warming a just-arrived shape) race for the same
        # key; without this, both pay the FULL XLA lowering and one result
        # is discarded. The second arrival now waits on the first compile
        # instead — prewarm genuinely covers streaming shapes.
        self._inflight: dict[tuple, threading.Event] = {}
        # use counts per shape descriptor, persisted alongside new shapes
        self._history: dict[str, dict] = {}
        self._history_loaded = False

    # ---- solving -----------------------------------------------------------

    def solve(
        self,
        free0,
        capacity,
        schedulable,
        node_domain_id,
        batch: GangBatch,
        params: SolverParams = SolverParams(),
        ok_global=None,
        *,
        coarse_dmax: Optional[int] = None,
        donate: bool = False,
        layout=None,  # parallel.mesh.SolveLayout: mesh-sharded executable
    ) -> SolveResult:
        """solve_batch through the AOT cache. With donate=True the caller
        forfeits `free0` and `ok_global` after the call (wave carry). With
        `layout`, the executable is the mesh-sharded variant (inputs placed
        per layout, free carry returned node-sharded) and the cache keys on
        the mesh shape in addition to the shape bucket."""
        args = _canon(
            free0, capacity, schedulable, node_domain_id, batch, params,
            ok_global, layout=layout,
        )
        compiled = self._get_or_compile(args, coarse_dmax, donate, layout)
        return compiled(*args)

    def solve_stacked(
        self,
        free0,
        capacity,
        schedulable,
        node_domain_id,
        batch: GangBatch,
        params_stack: SolverParams,  # each leaf [K]
        *,
        coarse_dmax: Optional[int] = None,
    ) -> SolveResult:
        """core.stacked_solve_batch through the AOT cache: one wave solved
        under K weight variants, every result leaf gaining a leading [K]
        axis. The executable keys on (wave shape bucket, K) — the K rides in
        on the params leaf shapes — so a config sweep amortizes ONE lowering
        per (shape bucket, surviving-config count) across the whole trace,
        exactly like the single-config warm path does per shape bucket."""
        args = _canon(
            free0, capacity, schedulable, node_domain_id, batch, params_stack,
            None,
        )[:6]  # stacked signature carries no ok_global
        compiled = self._get_or_compile(
            args, coarse_dmax, False, None, stacked=True
        )
        return compiled(*args)

    def solve_scan_stacked(
        self,
        free_stack,  # f32 [W, N, R] — each wave's RECORDED entering free
        capacity,
        schedulable,
        node_domain_id,
        stacked_batch: GangBatch,  # each leaf [W, ...]
        params_stack: SolverParams,  # each leaf [K]
        *,
        coarse_dmax: Optional[int] = None,
    ):
        """core.stacked_scan_solve_fn through the AOT cache: a run of W
        same-shape journaled waves solved under K sweep configs as ONE
        executable (verdict planes gain leading [W, K] axes). No carry
        threads between steps — every wave replays from its recorded
        entering free, so the run's cost stays ~one stacked replay while
        paying one dispatch instead of W. The executable keys on (W, wave
        shape bucket, K) via the leaf shapes plus the stacked+scan flags."""
        import jax.numpy as jnp

        args = (
            jnp.asarray(free_stack, jnp.float32),
            jnp.asarray(capacity, jnp.float32),
            jnp.asarray(schedulable, bool),
            jnp.asarray(node_domain_id, jnp.int32),
            GangBatch(
                *(None if x is None else jnp.asarray(x) for x in stacked_batch)
            ),
            SolverParams(*(jnp.asarray(w, jnp.float32) for w in params_stack)),
        )
        compiled = self._get_or_compile(
            args, coarse_dmax, False, None, stacked=True, scan=("stacked",)
        )
        return compiled(*args)

    def solve_scan(
        self,
        free0,
        capacity,
        schedulable,
        node_domain_id,
        stacked_batch: GangBatch,  # each leaf [W, ...]
        params: SolverParams = SolverParams(),
        ok_global=None,
        *,
        coarse_dmax: Optional[int] = None,
        retain: bool = False,
        donate: bool = False,
        layout=None,
    ):
        """core.scan_solve_fn through the AOT cache: a whole shape-class of
        waves dispatched as ONE executable, the (free, ok_global) carry
        threaded on-device. Returns a ScanSolveResult (verdict planes stacked
        on the leading [W] wave axis). The cache keys on the scan length via
        the stacked leaf shapes plus the ("dense", retain) scan tag."""
        args = _canon_scan(
            free0, capacity, schedulable, node_domain_id, stacked_batch,
            params, ok_global, layout=layout,
        )
        compiled = self._get_or_compile(
            args, coarse_dmax, donate, layout, scan=("dense", bool(retain))
        )
        return compiled(*args)

    def solve_scan_pruned(
        self,
        free0,  # DENSE fleet carry [N, R]
        cand_idx,  # i32 [W, CP] per-wave padded gather maps
        capacity_p,  # f32 [W, CP, R]
        schedulable_p,  # bool [W, CP]
        node_domain_id_p,  # i32 [W, L, CP]
        stacked_batch: GangBatch,  # candidate-axis leaves, each [W, ...]
        params: SolverParams = SolverParams(),
        ok_global=None,
        *,
        coarse_dmax: Optional[int] = None,
        retain: bool = False,
        donate: bool = False,
        layout=None,
    ):
        """core.scan_pruned_solve_fn through the AOT cache: per scan step the
        fleet carry is gathered onto that wave's candidate axis, solved, and
        scattered back — the dense fleet free is what threads on-device."""
        args = _canon_scan_pruned(
            free0, cand_idx, capacity_p, schedulable_p, node_domain_id_p,
            stacked_batch, params, ok_global, layout=layout,
        )
        compiled = self._get_or_compile(
            args, coarse_dmax, donate, layout, scan=("pruned", bool(retain))
        )
        return compiled(*args)

    def ensure_compiled_scan(
        self,
        avals: tuple,  # from _scan_avals / _scan_pruned_avals
        *,
        coarse_dmax: Optional[int] = None,
        retain: bool = False,
        donate: bool = False,
        layout=None,
        pruned: bool = False,
    ) -> bool:
        """Compile-only warm-up of a scan executable from abstract arguments
        (the drain's warm pre-pass knows the per-wave shapes and scan length
        before any data is stacked). Returns True when this paid a lowering."""
        before = self.lowerings
        self._get_or_compile(
            avals, coarse_dmax, donate, layout,
            scan=("pruned" if pruned else "dense", bool(retain)),
        )
        return self.lowerings != before

    def ensure_compiled(
        self,
        free0,
        capacity,
        schedulable,
        node_domain_id,
        batch: GangBatch,
        params: SolverParams = SolverParams(),
        ok_global=None,
        *,
        coarse_dmax: Optional[int] = None,
        donate: bool = False,
        layout=None,
    ) -> bool:
        """Compile-only warm-up (no execution, no device traffic beyond the
        constant upload XLA does at compile). Returns True when this call
        paid a lowering, False on a cache hit."""
        before = self.lowerings
        args = _canon(
            free0, capacity, schedulable, node_domain_id, batch, params,
            ok_global, layout=layout,
        )
        self._get_or_compile(args, coarse_dmax, donate, layout)
        return self.lowerings != before

    def _get_or_compile(
        self, args: tuple, coarse_dmax, donate: bool, layout=None,
        stacked: bool = False, scan: Optional[tuple] = None,
    ):
        key = _exec_key(args, coarse_dmax, donate, layout, stacked, scan)
        while True:
            with self._lock:
                compiled = self._entries.get(key)
                if compiled is None:
                    pending = self._inflight.get(key)
                    if pending is None:
                        # Claim the compile: others wait instead of lowering.
                        self._inflight[key] = threading.Event()
            if compiled is not None:
                self.hits += 1
                if not stacked:
                    self._record(args, coarse_dmax, donate, layout, new=False, scan=scan)
                return compiled
            if pending is None:
                break
            # Another thread (prewarm, or a concurrent serving path) is
            # lowering this exact shape right now — wait for its result
            # rather than paying a duplicate XLA compile.
            self.inflight_waits += 1
            pending.wait()
        try:
            self.lowerings += 1
            if stacked and scan is not None:
                jitted = _jitted_stacked_scan()
            elif stacked:
                jitted = _jitted_stacked()
            elif scan is not None:
                jitted = _jitted_scan(scan[0] == "pruned", scan[1], donate, layout)
            else:
                jitted = _jitted_solve(donate, layout)
            compiled = (
                jitted.lower(*args, coarse_dmax=coarse_dmax).compile()
            )
            with self._lock:
                self._entries.setdefault(key, compiled)
            self.misses += 1
        finally:
            with self._lock:
                ev = self._inflight.pop(key, None)
            if ev is not None:
                ev.set()
        if not stacked:
            self._record(args, coarse_dmax, donate, layout, new=True, scan=scan)
        return compiled

    # ---- shape history + prewarm -------------------------------------------

    def _record(
        self, args: tuple, coarse_dmax, donate: bool, layout=None, *,
        new: bool, scan: Optional[tuple] = None,
    ) -> None:
        if not self.history_path:
            return
        if scan is not None:
            desc = _scan_desc(args, coarse_dmax, donate, layout, scan)
        else:
            desc = _exec_desc(args, coarse_dmax, donate, layout)
        if desc is None:
            return
        hkey = json.dumps(desc, sort_keys=True)
        with self._lock:
            entry = self._history.setdefault(hkey, {"count": 0, "desc": desc})
            entry["count"] += 1
        if new:
            self._save_history()

    def _save_history(self) -> None:
        try:
            with self._lock:
                merged = dict(self._history)
            # Merge with what other processes wrote; counts take the max so
            # concurrent writers can only under-count, never explode.
            for hkey, entry in self._load_history_file().items():
                if hkey in merged:
                    merged[hkey]["count"] = max(
                        merged[hkey]["count"], entry.get("count", 0)
                    )
                else:
                    merged[hkey] = entry
            # Shared atomic-write primitive (utils/fsio): temp file + rename,
            # temp cleaned on failure — concurrent writers can't tear the
            # file, and a failed write never leaves droppings behind.
            atomic_write_json(self.history_path, {"version": 1, "shapes": merged})
        except OSError:
            pass  # history is an optimization; never fatal

    def _load_history_file(self) -> dict:
        try:
            with open(self.history_path) as f:
                doc = json.load(f)
            shapes = doc.get("shapes", {})
            return shapes if isinstance(shapes, dict) else {}
        except (OSError, ValueError):
            return {}

    def prewarm_from_history(self, top_k: int, stop=None) -> int:
        """Compile the top-K most-used historical shape buckets (by recorded
        count). Returns the number of NEW executables compiled. `stop` (a
        threading.Event) aborts between compiles — a shutting-down process
        must not keep lowering."""
        shapes = self._load_history_file()
        with self._lock:
            for hkey, entry in shapes.items():
                if hkey not in self._history:
                    self._history[hkey] = entry
        ranked = sorted(shapes.values(), key=lambda e: -e.get("count", 0))
        compiled = 0
        for entry in ranked[: max(0, top_k)]:
            if stop is not None and stop.is_set():
                break
            desc = entry.get("desc")
            if not isinstance(desc, dict) or desc.get("portfolio", 1) != 1:
                continue
            try:
                layout = _layout_from_desc(desc)
                scan = None
                if desc.get("scan"):
                    scan = ("dense", bool(desc.get("retain", False)))
                    args = _scan_args_from_desc(desc, layout)
                else:
                    args = _args_from_desc(desc, layout)
                key = _exec_key(
                    args, desc.get("coarse_dmax"), desc.get("donate", False),
                    layout, scan=scan,
                )
                with self._lock:
                    if key in self._entries:
                        continue
                    # In-flight claim, same protocol as _get_or_compile: a
                    # serving path warming this shape RIGHT NOW (streaming
                    # drain, first tick) must not pay a duplicate lowering —
                    # whoever claims second waits for the first.
                    pending = self._inflight.get(key)
                    if pending is None:
                        self._inflight[key] = threading.Event()
                if pending is not None:
                    self.inflight_waits += 1
                    pending.wait()
                    continue
                try:
                    self.lowerings += 1
                    if scan is not None:
                        jitted = _jitted_scan(
                            False, scan[1], bool(desc.get("donate", False)), layout
                        )
                    else:
                        jitted = _jitted_solve(bool(desc.get("donate", False)), layout)
                    exe = (
                        jitted
                        .lower(*args, coarse_dmax=desc.get("coarse_dmax"))
                        .compile()
                    )
                    with self._lock:
                        self._entries.setdefault(key, exe)
                finally:
                    with self._lock:
                        ev = self._inflight.pop(key, None)
                    if ev is not None:
                        ev.set()
                compiled += 1
                self.prewarmed += 1
            except Exception:  # noqa: BLE001 — a stale descriptor must not kill prewarm
                continue
        return compiled

    def start_prewarm_thread(self, top_k: int, stop=None) -> Optional[threading.Thread]:
        """Background prewarm of the top-K historical shape buckets so the
        first drain/solve never blocks on XLA. None when there is no history
        to prewarm from.

        NON-daemon on purpose: a daemon thread killed mid-XLA-compile at
        interpreter shutdown aborts the whole process ("terminate called
        without an active exception") — the e2e SIGTERM contract pins a
        clean exit 0. The `stop` event bounds the wait to at most one
        in-flight compile; the owner joins the thread in its stop path."""
        if top_k <= 0 or not self.history_path:
            return None
        if not self._load_history_file():
            return None
        t = threading.Thread(
            target=self.prewarm_from_history,
            args=(top_k, stop),
            daemon=False,
            name="grove-solver-prewarm",
        )
        t.start()
        return t

    def stats(self) -> dict:
        return {
            "execHits": self.hits,
            "execMisses": self.misses,
            "lowerings": self.lowerings,
            "prewarmed": self.prewarmed,
            "inflightWaits": self.inflight_waits,
            "executables": len(self._entries),
        }


class SnapshotDeviceCache:
    """Device-resident cluster state across solves and ticks.

    Node tensors are device-put once per CONTENT DIGEST and reused — the
    per-tick drivers rebuild numpy snapshots every pass, but capacity,
    schedulability, and topology rarely change, so the uploads (the round-5
    `device_wait_s` term) collapse to digest checks. `free` is cached the
    same way: a tick where nothing bound or released reuses the previous
    tick's device buffer. Cached buffers are never donated (donation is for
    the drain's throwaway wave carry only)."""

    def __init__(self, max_entries: int = 16) -> None:
        self._cache: OrderedDict[tuple, Any] = OrderedDict()
        self._max = max_entries
        self.hits = 0
        self.misses = 0

    def device_array(self, arr, dtype=None, sharding=None):
        """Device-put `arr` (numpy), memoized by content digest; a jax.Array
        input passes through untouched (already resident). `sharding` (a
        NamedSharding) is part of the key: a mesh-sharded drain caches the
        SHARDED copy of each static tensor, so repeated waves neither
        re-upload nor reshard."""
        import jax
        import jax.numpy as jnp

        if isinstance(arr, jax.Array):
            return arr
        arr = np.asarray(arr)
        key = (
            arr.shape,
            str(arr.dtype),
            sharding,
            hashlib.blake2b(
                np.ascontiguousarray(arr).tobytes(), digest_size=16
            ).digest(),
        )
        cached = self._cache.get(key)
        if cached is not None:
            self._cache.move_to_end(key)
            self.hits += 1
            return cached
        dev = jnp.asarray(arr, dtype)
        if sharding is not None:
            dev = jax.device_put(dev, sharding)
        self._cache[key] = dev
        while len(self._cache) > self._max:
            self._cache.popitem(last=False)
        self.misses += 1
        return dev

    def snapshot_arrays(self, snapshot, free=None, schedulable=None):
        """(free, capacity, schedulable, node_domain_id) on device, cached.
        `free`/`schedulable` overrides (wave chaining) pass through when they
        are already device arrays."""
        import jax.numpy as jnp

        cap = self.device_array(snapshot.capacity, jnp.float32)
        ndid = self.device_array(snapshot.node_domain_id, jnp.int32)
        sched = self.device_array(
            snapshot.schedulable if schedulable is None else schedulable
        )
        f = self.device_array(
            snapshot.free if free is None else free, jnp.float32
        )
        return f, cap, sched, ndid

    def stats(self) -> dict:
        return {
            "deviceHits": self.hits,
            "deviceMisses": self.misses,
            "deviceEntries": len(self._cache),
        }


class EncodeRowCache:
    """Per-gang dense-encode row reuse (dirty tracking by spec hash).

    Key = (caller row key, resource axis, bound-node signature); the caller
    row key MUST fold in a snapshot epoch (`ClusterSnapshot.encode_epoch()`)
    — selector/toleration rows read node labels and taints, and pack-set
    pins read the domain map, so rows are only valid against the snapshot
    they were encoded for. Entries additionally carry their bucket dims
    (mg, ms, mp); a lookup under different dims is a miss (the row arrays
    are shaped by the bucket)."""

    def __init__(self, max_entries: int = 8192) -> None:
        self._rows: OrderedDict[tuple, dict] = OrderedDict()
        self._max = max_entries
        self.hits = 0
        self.misses = 0
        # _sets_of memo (encode_gangs): constraint-tree walks keyed by the
        # caller row key (spec digest + snapshot epoch). Kept SEPARATE from
        # the full-row entries: rows are additionally keyed by bucket dims
        # and bound-node signature, so a bucket drift or fresh pin demotes
        # the rows while the (dims-independent) set structure stays valid.
        self._sets: OrderedDict[tuple, tuple] = OrderedDict()
        self.sets_hits = 0
        self.sets_misses = 0

    def peek(self, key: tuple) -> Optional[dict]:
        entry = self._rows.get(key)
        if entry is not None:
            self._rows.move_to_end(key)
        return entry

    def put(self, key: tuple, entry: dict) -> None:
        self._rows[key] = entry
        self._rows.move_to_end(key)
        while len(self._rows) > self._max:
            self._rows.popitem(last=False)

    def peek_sets(self, key: tuple) -> Optional[tuple]:
        entry = self._sets.get(key)
        if entry is not None:
            self._sets.move_to_end(key)
            self.sets_hits += 1
        else:
            self.sets_misses += 1
        return entry

    def put_sets(self, key: tuple, entry: tuple) -> None:
        self._sets[key] = entry
        self._sets.move_to_end(key)
        while len(self._sets) > self._max:
            self._sets.popitem(last=False)

    def stats(self) -> dict:
        return {
            "encodeHits": self.hits,
            "encodeMisses": self.misses,
            "encodeEntries": len(self._rows),
            "encodeSetsHits": self.sets_hits,
            "encodeSetsMisses": self.sets_misses,
        }


# Per-pod digest-signature memo (gang_row_digest): the signature walk
# (total_requests + sorted selector/toleration tuples) was ~60% of the cold
# drain encode at bench scale, and it recurs every tick/drain for pods whose
# OBJECTS are stable (the store keeps Pod objects; only sub-GANG wrappers
# are rebuilt per pass). Keyed by (id(pod), id(pod.spec)) with a weakref
# guard: a dead pod's recycled id can never serve a stale signature, and a
# replaced spec object misses by key. In-place mutation of a live spec's
# containers/selector/tolerations would be invisible — nothing in the
# codebase does that (specs are replaced wholesale), and the encode-row
# cache already relies on the same convention via the digest.
_POD_SIG_MEMO: dict[tuple, tuple] = {}
_POD_SIG_MAX = 131072


def _pod_sig(pod, memo: bool = True) -> tuple:
    import weakref

    spec = pod.spec
    if memo:
        key = (id(pod), id(spec))
        hit = _POD_SIG_MEMO.get(key)
        if hit is not None and hit[0]() is pod:
            return hit[1]
    sig = (
        tuple(sorted(spec.total_requests().items())),
        tuple(sorted((spec.node_selector or {}).items())),
        tuple(tuple(sorted(t.items())) for t in (spec.tolerations or [])),
    )
    if memo:
        try:
            if len(_POD_SIG_MEMO) >= _POD_SIG_MAX:
                _POD_SIG_MEMO.clear()
            _POD_SIG_MEMO[key] = (weakref.ref(pod), sig)
        except TypeError:
            pass  # un-weakref-able pod stand-ins (tests): just recompute
    return sig


# Whole-gang digest memo: keyed by id(gang), guarded by a weakref on the
# gang PLUS a cheap spec fingerprint covering every scalar the digest reads
# (constraint tree, group names/floors) and identity stand-ins for the
# expensive parts it skips (the pod_references list object + endpoints, the
# first pod object + spec per group). The digest proper walks every pod
# reference name — O(pods) per gang per call, a real per-drain tax once
# everything else is vectorized — so the memo's job is to skip exactly that
# walk while still honoring the SPEC-HASH contract: any in-place scalar or
# structural spec mutation flips the guard and recomputes (test-pinned by
# test_warm.test_gang_row_digest_tracks_spec_not_identity). The one
# invisible mutation is replacing an INTERIOR element of the same
# pod_references list object in place — nothing in the codebase edits ref
# lists element-wise; expansion rebuilds them wholesale.
_GANG_DIGEST_MEMO: dict[int, tuple] = {}
_GANG_DIGEST_MAX = 65536


def _pc_levels(obj):
    tc = getattr(obj, "topology_constraint", None)
    p = getattr(tc, "pack_constraint", None) if tc else None
    return (p.required, p.preferred) if p else None


def _digest_guard(gang, pods_by_name: dict) -> tuple:
    """Cheap (O(groups)) fingerprint of everything gang_row_digest reads,
    with identity stand-ins for its O(pods) parts."""
    groups = []
    for grp in gang.spec.pod_groups:
        refs = grp.pod_references
        pod = pods_by_name.get(refs[0].name) if refs else None
        groups.append(
            (
                grp.name,
                grp.min_replicas,
                _pc_levels(grp),
                len(refs),
                id(refs),
                id(refs[0]) if refs else None,
                id(refs[-1]) if refs else None,
                None if pod is None else (id(pod), id(pod.spec)),
            )
        )
    return (
        gang.name,
        gang.base_podgang_name,
        gang.spec.spread_key,
        _pc_levels(gang.spec),
        tuple(
            (gc.name, tuple(gc.pod_group_names), _pc_levels(gc))
            for gc in gang.spec.topology_constraint_group_configs
        ),
        tuple(groups),
    )


def gang_row_digest(gang, pods_by_name: dict) -> tuple:
    """Hashable digest of everything the dense encode reads from ONE gang:
    identity, constraints at all three levels, per-group refs/floors, and
    the first pod's request vector/selector/tolerations (pods of a group
    share one template, so the first pod speaks for the group — exactly the
    encode's own rule). Spec hash, not object identity: the per-tick drivers
    rebuild sub-gang objects every pass, so identity is always 'dirty'."""
    import weakref

    from grove_tpu.solver.encode import host_vectorized

    memo = host_vectorized()  # hoisted: one env read per gang, not per pod
    if memo:
        mkey = id(gang)
        guard = _digest_guard(gang, pods_by_name)
        hit = _GANG_DIGEST_MEMO.get(mkey)
        if hit is not None and hit[0]() is gang and hit[1] == guard:
            return hit[2]

    def pod_sig(name: str):
        pod = pods_by_name.get(name)
        if pod is None:
            return None
        return _pod_sig(pod, memo)

    digest = (
        gang.name,
        gang.base_podgang_name,
        gang.spec.spread_key,
        _pc_levels(gang.spec),
        tuple(
            (gc.name, tuple(gc.pod_group_names), _pc_levels(gc))
            for gc in gang.spec.topology_constraint_group_configs
        ),
        tuple(
            (
                grp.name,
                grp.min_replicas,
                _pc_levels(grp),
                tuple(r.name for r in grp.pod_references),
                pod_sig(grp.pod_references[0].name) if grp.pod_references else None,
            )
            for grp in gang.spec.pod_groups
        ),
    )
    if memo:
        try:
            if len(_GANG_DIGEST_MEMO) >= _GANG_DIGEST_MAX:
                _GANG_DIGEST_MEMO.clear()
            _GANG_DIGEST_MEMO[mkey] = (weakref.ref(gang), guard, digest)
        except TypeError:
            pass  # un-weakref-able gang stand-ins (tests): just recompute
    return digest


@dataclass
class WarmPath:
    """One bundle of the three warm-path caches, owned per serving path
    (controller, sidecar) or shared across drains (module default)."""

    executables: ExecutableCache = field(default_factory=ExecutableCache)
    encode_rows: EncodeRowCache = field(default_factory=EncodeRowCache)
    device: SnapshotDeviceCache = field(default_factory=SnapshotDeviceCache)
    # Candidate-pruning counters (solver/pruning.py): pruned solves,
    # exactness escalations, last candidate-axis size — surfaced through
    # stats() so /statusz warmPath and `grove-tpu get solver` carry them.
    prune: PruneStats = field(default_factory=PruneStats)
    # Last drain seen through this warm path (drain_backlog reports at
    # exit): measured wave-harvest p50/p99 when the drain ran with
    # harvest="wave" or "pipeline", so the latency distribution is visible
    # OUTSIDE the bench (/statusz warmPath, `grove-tpu get solver`).
    last_drain: dict = field(default_factory=dict)
    # Last streaming drain (solver/stream.py reports at exit): steady-state
    # throughput + measured time-to-bind percentiles, the source for the
    # grove_stream_* metrics and the `get solver` stream rows.
    last_stream: dict = field(default_factory=dict)
    # Unexported per-gang time-to-bind samples (seconds), drained by the
    # manager's metrics refresh into the grove_stream_time_to_bind_seconds
    # histogram. Bounded: a stream outrunning the scrape loses oldest
    # samples, never memory.
    stream_bind_samples: object = None  # collections.deque, lazy
    # Cumulative round-trip ledger across EVERY drain/stream through this
    # warm path — all harvest disciplines (chained/wave/pipeline/scan) and
    # both drivers feed it through record_drain uniformly, so the
    # grove_drain_device_roundtrips_total counter (manager delta export)
    # never under-counts when several drains land between scrapes or the
    # resilience ladder changes the discipline mid-run.
    drain_dispatches_total: int = 0
    drain_device_roundtrips_total: int = 0

    def record_drain(self, stats) -> None:
        """Fold one DrainStats into the observable surface."""
        self.drain_dispatches_total += stats.dispatches
        self.drain_device_roundtrips_total += stats.device_roundtrips
        doc = {
            "drainWaves": stats.waves,
            "drainGangs": stats.gangs,
            "drainAdmitted": stats.admitted,
            "drainHarvest": stats.harvest,
            "drainTotalS": round(stats.total_s, 4),
        }
        # Host-stage timing ledger (DrainStats.host_stages): per-stage host
        # seconds of the last drain — /statusz warmPath, `get solver`
        # lastDrain rows, and the grove_host_stage_seconds gauges read it.
        doc.update(stats.host_stages())
        # Measured per-gang percentiles; None for chained drains, empty
        # drains, and drains in which no wave admitted anything (the
        # percentile helper owns the 0-/1-wave edge cases — a fabricated
        # 0.0 or inf here used to leak into /statusz and the bench JSON).
        pct = stats.latency_percentiles((50.0, 99.0))
        if pct is not None:
            doc["waveP50S"] = round(pct[50.0], 4)
            doc["waveP99S"] = round(pct[99.0], 4)
        self.last_drain = doc

    def record_stream(self, doc: dict, bind_latencies=()) -> None:
        """Fold one StreamStats doc into the observable surface and queue
        its per-gang time-to-bind samples for histogram export."""
        from collections import deque

        self.last_stream = dict(doc)
        if self.stream_bind_samples is None:
            self.stream_bind_samples = deque(maxlen=65536)
        self.stream_bind_samples.extend(float(x) for x in bind_latencies)

    def stats(self) -> dict:
        out = {}
        out.update(self.executables.stats())
        out.update(self.encode_rows.stats())
        out.update(self.device.stats())
        out.update(self.prune.stats())
        # Mesh-shard fallbacks (parallel/mesh.py ledger): solves that wanted
        # a multi-device layout but ran unsharded. Process-wide by design —
        # the fallback happens in layout negotiation, before any WarmPath is
        # in hand.
        try:
            from grove_tpu.parallel.mesh import shard_fallbacks

            out["shardFallbacks"] = shard_fallbacks()
        except Exception:  # noqa: BLE001 — stats must never fail a scrape
            pass
        out.update(self.last_drain)
        # Cumulative (NOT last-drain) round-trip totals — the counter
        # sources; the last_drain doc above carries the per-drain numbers.
        out["dispatchesTotal"] = self.drain_dispatches_total
        out["deviceRoundtripsTotal"] = self.drain_device_roundtrips_total
        return out


_DEFAULT_WARM_PATH: Optional[WarmPath] = None
_DEFAULT_LOCK = threading.Lock()


def default_warm_path() -> WarmPath:
    """Process-wide shared WarmPath: repeated drains in one process (the
    bench's cold/warm pair, back-to-back backlogs in a long-lived operator)
    share executables and encode rows automatically."""
    global _DEFAULT_WARM_PATH
    with _DEFAULT_LOCK:
        if _DEFAULT_WARM_PATH is None:
            _DEFAULT_WARM_PATH = WarmPath()
        return _DEFAULT_WARM_PATH
